"""Benchmark harness (BASELINE.md / BASELINE.json target).

Covers the five BASELINE.json configs plus a synthetic scale sweep:

(a/b) LinearRegression Lasso fit on dataset-full.csv (the headline metric:
      maxIter=40, regParam=1, elasticNetParam=1; single-chip mesh = config a,
      the same packed psum path sharded = config b, exercised in CI and the
      multichip dryrun),
(c)   elastic-net general path (FISTA, regParam=0.3, elasticNetParam=0.5),
(d)   LogisticRegression on the DQ-filtered rows (per-iteration-psum loop),
(e)   CrossValidator grid (regParam × elasticNetParam, grid-parallel cell
      sharding) vs sklearn GridSearchCV(refit=True) — timed as the fused
      device-complete CV program (fold Gramians → every cell solved →
      winner selected → best model refit, one dispatch, no host reads;
      the same program CrossValidator.fit runs, which then adds exactly
      one host read to materialize the packed result),
(sweep) the masked-Gramian data pass at n ∈ {1e5, 1e6, 1e7} × d ∈ {16, 128,
      512} (HBM-bounded subset), XLA vs compiled Pallas, with on-device
      numerics assertions — the MXU/HBM throughput story behind every fit.

Baselines are **measured CPU** stand-ins (sklearn / numpy, documented per
config): the reference publishes no numbers (SURVEY.md §6) and no JVM is
available, so sklearn-CPU — a C-optimized solver without Spark's RPC
barriers — is a strictly faster proxy than the Spark stack it stands in
for. ``vs_baseline`` = baseline_seconds / device_seconds.

Prints exactly ONE JSON line on stdout (driver contract); the per-config
results, sweep table, and pallas-vs-XLA table ride inside it. Per-config
lines are echoed to stderr for human reading.

Measurement hygiene: on the axon-tunneled TPU the FIRST device→host fetch
(``int()``/``float()``/``np.asarray`` on a device array) permanently
switches the process into a synchronous dispatch mode (~67 ms/call floor
afterwards; measured — ``block_until_ready`` alone does not trigger it).
ALL timing loops therefore run before ANY host read: device results and
on-device diff scalars are collected, and only after the last timing loop
does the host read anything. Data for the sweep is generated ON DEVICE
(jax.random) so multi-GB operands never cross the tunnel.
"""

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

GOLDEN_RMSE_FULL = 1.805140  # SURVEY.md §2.3, dataset-full Lasso
# BENCH_SMOKE=1: tiny sweep + few reps, for CI validation of the harness
# itself on CPU (real numbers come from the TPU run).
SMOKE = os.environ.get("BENCH_SMOKE") == "1"
REPS = 3 if SMOKE else 30
SWEEP_REPS = 2 if SMOKE else 5
# (rows, features) — sizes chosen to fit v5e HBM (16 GB) with headroom;
# the 1e7×128 / 1e7×512 cells would be 5–20 GB and are deliberately absent
# (documented cap, not silent truncation).
SWEEP_SHAPES = [(100_000, 16), (100_000, 128)] if SMOKE else \
    [(100_000, 16), (1_000_000, 16), (10_000_000, 16),
     (100_000, 128), (1_000_000, 128), (1_000_000, 512)]
CPU_SWEEP_SHAPES = {(100_000, 16), (1_000_000, 16), (100_000, 128)}


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def make_median_time(jax):
    """Timing loop: each rep blocks on ITS OWN ``fn()`` result — blocking on
    a stale array measures only async dispatch enqueue (µs), not the
    computation. Opaque (non-pytree) results pass through block_until_ready
    untouched, which is correct for the synchronous CPU baselines."""
    def median_time(fn, reps):
        jax.block_until_ready(fn())   # warm: compile cached after
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            times.append(time.perf_counter() - t0)
        return statistics.median(times)
    return median_time


def main():
    # The driver contract is ONE JSON line; a wedged tunnel must yield an
    # honest backend=cpu result, not an infinite hang (shared probe helper).
    from sparkdq4ml_tpu.utils.debug import backend_initializes

    if (os.environ.get("BENCH_SKIP_PROBE") != "1"
            and not backend_initializes()):
        log("accelerator backend failed to initialize (wedged tunnel?); "
            "falling back to CPU — results will carry backend=cpu")
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np

    import sparkdq4ml_tpu as dq
    from sparkdq4ml_tpu.config import config
    from sparkdq4ml_tpu.models import VectorAssembler
    from sparkdq4ml_tpu.models.classification import fused_logistic_fit_packed
    from sparkdq4ml_tpu.ops import pallas_kernels
    from sparkdq4ml_tpu.parallel.distributed import (fused_linear_fit_packed,
                                                     pack_design, place_packed,
                                                     unpack_fit_result)

    path = os.path.join(REPO, "data", "dataset-full.csv")
    session = dq.TpuSession.builder().app_name("bench").master("local[*]").get_or_create()
    log(f"devices: {jax.devices()}")
    backend = jax.default_backend()

    # ---- build the DQ-cleaned frame (no host reads of device arrays) ----
    dq.register_builtin_rules()
    df = (session.read.format("csv").option("inferSchema", "true")
          .option("header", "false").load(path))
    df = df.with_column_renamed("_c0", "guest").with_column_renamed("_c1", "price")
    df = df.with_column("price_no_min", dq.call_udf("minimumPriceRule", dq.col("price")))
    df.create_or_replace_temp_view("price")
    df = session.sql("SELECT cast(guest as int) guest, price_no_min AS price "
                     "FROM price WHERE price_no_min > 0")
    df = df.with_column("price_correct_correl",
                        dq.call_udf("priceCorrelationRule", dq.col("price"), dq.col("guest")))
    df.create_or_replace_temp_view("price")
    df = session.sql("SELECT guest, price_correct_correl AS price "
                     "FROM price WHERE price_correct_correl > 0")
    df = df.with_column("label", df.col("price"))
    df = VectorAssembler(["guest"], "features").transform(df)

    X = jnp.asarray(df._column_values("features"))
    y = jnp.asarray(df._column_values("label"))
    mask = df.mask
    mesh = None if session.mesh.devices.size <= 1 else session.mesh
    Zd = place_packed(pack_design(X, y, mask), mesh)

    # =====================================================================
    # PHASE 1 — every device timing loop, before ANY device→host read
    # =====================================================================

    median_time = make_median_time(jax)

    # (a) headline: Lasso fit, one packed dispatch
    fit_a = fused_linear_fit_packed(mesh, "fista", 40, 1e-6, True, True)
    hyper_a = jnp.asarray([1.0, 1.0], Zd.dtype)
    result_a = jax.block_until_ready(fit_a(Zd, hyper_a))
    t_a = median_time(lambda: fit_a(Zd, hyper_a), REPS)

    # (c) elastic-net general path (FISTA, mixed penalty, 100 iters)
    fit_c = fused_linear_fit_packed(mesh, "fista", 100, 1e-6, True, True)
    hyper_c = jnp.asarray([0.3, 0.5], Zd.dtype)
    t_c = median_time(lambda: fit_c(Zd, hyper_c), REPS)

    # (d) logistic on DQ rows: per-iteration psum FISTA loop
    yb = (y > jnp.median(y)).astype(Zd.dtype)   # device-side label build
    Zb = place_packed(pack_design(X, yb, mask), mesh)
    fit_d = fused_logistic_fit_packed(mesh, 100, 1e-6, True, True)
    hyper_d = jnp.asarray([0.01, 0.0], Zd.dtype)
    t_d = median_time(lambda: fit_d(Zb, hyper_d), REPS)

    # (e) CrossValidator grid: the fused device-complete CV program
    from sparkdq4ml_tpu.models import LinearRegression
    from sparkdq4ml_tpu.models.evaluation import RegressionEvaluator
    from sparkdq4ml_tpu.models.tuning import (ParamGridBuilder,
                                              cv_device_program)

    grid_reg, grid_en, folds = [0.1, 0.5, 1.0], [0.0, 0.5, 1.0], 3
    grid = (ParamGridBuilder().add_grid("reg_param", grid_reg)
            .add_grid("elastic_net_param", grid_en).build())
    cv_prog, cv_args, _, _ = cv_device_program(
        df, LinearRegression(max_iter=40, tol=1e-6), grid, "rmse", folds,
        7, mesh, RegressionEvaluator("rmse").is_larger_better())
    t_e = median_time(lambda: cv_prog(*cv_args), REPS)

    # (sweep) masked-Gramian pass: XLA vs compiled Pallas, data on device
    @jax.jit
    def xla_gram(Z):
        return Z.T @ Z

    # bf16-STORED variant: rows live in HBM at half the bytes and the MXU
    # is bf16-native; accumulation stays f32 (preferred_element_type)
    @jax.jit
    def xla_gram_bf16(Zh):
        return jax.lax.dot_general(
            Zh, Zh, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    sweep_rows = []        # timings (host floats, no device reads)
    pallas_diffs = []      # on-device |A_p - A_x| max scalars, read later
    pallas_mode = "on" if backend == "tpu" else "interpret"
    for (n, d) in SWEEP_SHAPES:
        key = jax.random.PRNGKey(n + d)
        Z = jax.random.normal(key, (n, d + 2), jnp.float32)
        Z = jax.block_until_ready(Z)
        gb = n * (d + 2) * 4 / 1e9

        t_x = median_time(lambda: xla_gram(Z), SWEEP_REPS)

        Zh = jax.block_until_ready(Z.astype(jnp.bfloat16))
        t_h = median_time(lambda: xla_gram_bf16(Zh), SWEEP_REPS)
        gb_h = n * (d + 2) * 2 / 1e9

        t_p = None
        best_block = None
        # Off-TPU the Pallas interpreter executes element-by-element — the
        # numerics cross-check at full sweep sizes would run for hours, so
        # it only runs compiled (TPU) or on the SMOKE shapes.
        if backend == "tpu" or SMOKE:
            config.pallas = pallas_mode
            try:
                A_p = pallas_kernels.packed_gram_pallas(Z)
                if backend == "tpu":
                    # Row-tile autotune: bigger tiles amortize grid/DMA
                    # overhead; all candidates fit VMEM double-buffered.
                    for blk in (512, 1024, 2048, 4096):
                        if blk > n:
                            continue
                        t_b = median_time(
                            lambda: pallas_kernels.packed_gram_pallas(
                                Z, block_rows=blk), SWEEP_REPS)
                        if t_p is None or t_b < t_p:
                            t_p, best_block = t_b, blk
                A_x = xla_gram(Z)
                scale = jnp.maximum(jnp.max(jnp.abs(A_x)), 1.0)
                pallas_diffs.append(
                    ((n, d), jnp.max(jnp.abs(A_p - A_x)) / scale))
            finally:
                config.pallas = "off"

        sweep_rows.append({
            "rows": n, "features": d,
            "xla_ms": round(t_x * 1e3, 3),
            "xla_gbps": round(gb / t_x, 1),
            "bf16_ms": round(t_h * 1e3, 3),
            "bf16_gbps": round(gb_h / t_h, 1),
            "bf16_rows_speedup": round(t_x / t_h, 2),
            "pallas_ms": round(t_p * 1e3, 3) if t_p else None,
            "pallas_gbps": round(gb / t_p, 1) if t_p else None,
            "pallas_block": best_block,
        })
        del Z, Zh

    # =====================================================================
    # PHASE 2 — host reads, CPU baselines, assertions
    # =====================================================================
    n_rows = df.count()
    log(f"DQ-clean rows: {n_rows} (expect 1024)")
    result = unpack_fit_result(result_a, 1)
    coef = float(result.coefficients[0])
    intercept = float(result.intercept)
    d_host = df.to_pydict()
    yv = d_host["label"].astype(np.float64)
    xv = d_host["guest"].astype(np.float64)
    rmse = float(np.sqrt(np.mean((yv - (coef * xv + intercept)) ** 2)))
    drift = abs(rmse - GOLDEN_RMSE_FULL) / GOLDEN_RMSE_FULL
    log(f"fit: coef={coef:.6f} intercept={intercept:.6f} rmse={rmse:.6f} "
        f"drift={drift*100:.4f}% (budget 1%)")
    if drift > 0.01:
        log("ERROR: RMSE drift exceeds the 1% acceptance budget")
        sys.exit(1)

    # pallas numerics: assert before reporting any pallas number
    for (shape, diff_dev) in pallas_diffs:
        diff = float(diff_dev)
        log(f"pallas-vs-xla rel diff @ {shape}: {diff:.2e}")
        if not diff < 5e-5:
            log(f"ERROR: pallas Gramian diverges from XLA at {shape}")
            sys.exit(1)

    # CPU baselines --------------------------------------------------------
    # sklearn is a strictly faster Spark-CPU proxy; without it, a pure-numpy
    # ISTA stands in for (a) and c/d report no baseline rather than dying
    # (the driver contract — one JSON line — must survive a missing dep).
    Xh = xv.reshape(-1, 1)
    sx, sy = Xh.std(ddof=1), yv.std(ddof=1)
    Xs = (Xh - Xh.mean()) / sx
    ys = (yv - yv.mean()) / sy
    yb_h = (yv > np.median(yv)).astype(np.float64)

    try:
        from sklearn.linear_model import (ElasticNet, Lasso,
                                          LogisticRegression as SkLogit)
        have_sklearn = True
    except ImportError:
        have_sklearn = False

    if have_sklearn:
        base_a = "sklearn Lasso(cd) maxIter=40"
        t_a_cpu = median_time(
            lambda: Lasso(alpha=1.0 / sy, max_iter=40, tol=1e-6).fit(Xs, ys),
            REPS)
        t_c_cpu = median_time(
            lambda: ElasticNet(alpha=0.3 / sy, l1_ratio=0.5, max_iter=100,
                               tol=1e-6).fit(Xs, ys), REPS)
        t_d_cpu = median_time(
            lambda: SkLogit(C=100.0, max_iter=100, tol=1e-6).fit(Xs, yb_h),
            REPS)
    else:
        base_a = "numpy ISTA maxIter=40"

        def ista():
            w = 0.0
            h = float(Xs[:, 0] @ Xs[:, 0]) / len(ys)
            c0 = float(Xs[:, 0] @ ys) / len(ys)
            lam = 1.0 / sy
            for _ in range(40):
                g = h * w - c0
                w = np.sign(w - g / h) * max(abs(w - g / h) - lam / h, 0.0)

        t_a_cpu = median_time(ista, REPS)
        t_c_cpu = t_d_cpu = None

    # CPU gram GB/s context for the sweep's smaller cells
    for row in sweep_rows:
        shape = (row["rows"], row["features"])
        if shape in CPU_SWEEP_SHAPES:
            rng = np.random.default_rng(0)
            Zc = rng.standard_normal((shape[0], shape[1] + 2),
                                     dtype=np.float32)
            t_cpu = median_time(lambda: Zc.T @ Zc, SWEEP_REPS)
            row["cpu_gbps"] = round(
                shape[0] * (shape[1] + 2) * 4 / 1e9 / t_cpu, 1)

    # (e) baseline: sklearn GridSearchCV, same 3x3 grid / folds / family,
    # refit=True to match the in-program best-model refit
    t_e_cpu = None
    if have_sklearn:
        from sklearn.model_selection import GridSearchCV

        def cpu_grid():
            GridSearchCV(ElasticNet(max_iter=40, tol=1e-6),
                         {"alpha": [r / sy for r in grid_reg],
                          "l1_ratio": grid_en},
                         cv=folds, scoring="neg_root_mean_squared_error",
                         n_jobs=1, refit=True).fit(Xs, ys)

        t_e_cpu = median_time(cpu_grid, REPS)

    # =====================================================================
    # PHASE 3 — report
    # =====================================================================
    def cfg(name, t_dev, baseline_name, t_cpu):
        return {"config": name, "device_ms": round(t_dev * 1e3, 4),
                "baseline": baseline_name if t_cpu else "unavailable",
                "baseline_ms": round(t_cpu * 1e3, 4) if t_cpu else None,
                "vs_baseline": round(t_cpu / t_dev, 2) if t_cpu else None}

    configs = [
        cfg("a_linear_lasso_dataset_full", t_a, base_a, t_a_cpu),
        cfg("c_elasticnet_fista_path", t_c,
            "sklearn ElasticNet(cd) maxIter=100", t_c_cpu),
        cfg("d_logistic_dq_rows", t_d,
            "sklearn LogisticRegression(lbfgs) maxIter=100", t_d_cpu),
        cfg("e_crossvalidator_grid", t_e,
            f"sklearn GridSearchCV(ElasticNet) {len(grid)}x{folds} refit",
            t_e_cpu),
    ]
    for c in configs:
        log(json.dumps(c))
    for row in sweep_rows:
        log(json.dumps(row))

    print(json.dumps({
        "metric": "linear_regression_fit_wallclock_dataset_full",
        "value": round(t_a * 1e3, 4),
        "unit": "ms",
        "vs_baseline": round(t_a_cpu / t_a, 3),
        "configs": configs,
        "sweep": sweep_rows,
        "pallas_max_rel_diff": max((float(d) for _, d in pallas_diffs),
                                   default=None),
        "backend": backend,
    }))


if __name__ == "__main__":
    main()
