"""Benchmark harness (BASELINE.md / BASELINE.json target).

Covers the five BASELINE.json configs plus a synthetic scale sweep:

(a/b) LinearRegression Lasso fit on dataset-full.csv (the headline metric:
      maxIter=40, regParam=1, elasticNetParam=1; single-chip mesh = config a,
      the same packed psum path sharded = config b, exercised in CI and the
      multichip dryrun),
(c)   elastic-net general path (FISTA, regParam=0.3, elasticNetParam=0.5),
(d)   LogisticRegression on the DQ-filtered rows (per-iteration-psum loop),
      plus a 1e6×16 scale variant (d_scale) where barrier elimination —
      not solver iteration counts — dominates,
(e)   CrossValidator grid (regParam × elasticNetParam, grid-parallel cell
      sharding) vs sklearn GridSearchCV(refit=True) — timed as the fused
      device-complete CV program (fold Gramians → every cell solved →
      winner selected → best model refit, one dispatch, no host reads;
      the same program CrossValidator.fit runs, which then adds exactly
      one host read to materialize the packed result),
(dq)  the DQ phase itself (`App.java:52-95`): CSV parse throughput
      (native C++ tokenizer vs pure-Python) on a ~1e6-row synthetic file,
      and the fused rules+filter pass (XLA, on device) vs vectorized numpy,
(ingest) streaming native CSV ingest (native/csvparse.cpp): scalar vs
      SIMD vs SIMD+chunk-parallel-threads vs the full streaming pipeline
      (bounded chunks + prefetch overlapping parse with device transfer),
      end-to-end through read_csv at 1e5/1e6/1e7 rows, bit-parity
      asserted and the golden DQ+Lasso numbers driven through the
      streaming reader,
(serving) closed-loop multi-tenant serving (serve/): 32 concurrent
      clients driving the headline DQ+Lasso query through the QueryServer,
      sustained QPS + p50/p99 latency, shared plan/jit cache on vs off,
      cross-tenant program-reuse pin, golden numbers asserted per query,
      plus a real-socket arm (serve/net.py + the resilient client, frame
      and HTTP framings mixed) whose QPS/latency delta vs the in-process
      arm prices the wire overhead,
(sweep) the masked-Gramian data pass at n ∈ {1e5, 1e6, 1e7} × d ∈ {16, 128,
      512} (HBM-bounded subset), XLA vs compiled Pallas, with on-device
      numerics assertions — the MXU/HBM throughput story behind every fit.
      On TPU each cell also reports its roofline fractions: ``hbm_frac``
      (achieved GB/s ÷ chip HBM peak) and ``mfu`` (achieved FLOP/s ÷ chip
      bf16 matmul peak; f32 cells use the same denominator, so their mfu
      is a conservative lower bound).

Baselines are **measured CPU** stand-ins (sklearn / numpy, documented per
config): the reference publishes no numbers (SURVEY.md §6) and no JVM is
available, so sklearn-CPU — a C-optimized solver without Spark's RPC
barriers — is a strictly faster proxy than the Spark stack it stands in
for. ``vs_baseline`` = baseline_seconds / device_seconds.

Prints exactly ONE JSON line on stdout (driver contract); the per-config
results, sweep table, and pallas-vs-XLA table ride inside it. Per-config
lines are echoed to stderr for human reading.

Measurement hygiene: on the axon-tunneled TPU, ``block_until_ready`` does
NOT wait for device execution (measured live in round 5: a 5e11-FLOP
matmul "completed" in 0.2 ms), so wall-clock loops around dispatches time
the enqueue — the round-2 capture's numbers and the first round-5 capture
(mfu 1.32, hbm_frac 35.9) were artifacts of exactly this. On TPU every
device op is therefore timed by ``make_chain_timer``: K data-dependent
iterations inside ONE jitted fori_loop (optimization_barrier against
fusion/DCE, carry-fed perturbation against loop hoisting), one host read
per call, minus the measured ~66 ms dispatch+sync floor, divided by K —
per-iteration times validated to scale exactly linearly with input size.
On CPU (and for the sklearn/numpy baselines) plain blocking loops remain
correct. Data for the sweep is generated ON DEVICE (jax.random) so
multi-GB operands never cross the tunnel.
"""

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

GOLDEN_RMSE_FULL = 1.805140  # SURVEY.md §2.3, dataset-full Lasso
# BENCH_SMOKE=1: tiny sweep + few reps, for CI validation of the harness
# itself on CPU (real numbers come from the TPU run).
SMOKE = os.environ.get("BENCH_SMOKE") == "1"
REPS = 3 if SMOKE else 30
SWEEP_REPS = 2 if SMOKE else 5
# (rows, features) — sizes chosen to fit v5e HBM (16 GB) with headroom;
# the 1e7×128 / 1e7×512 cells would be 5–20 GB and are deliberately absent
# (documented cap, not silent truncation).
SWEEP_SHAPES = [(100_000, 16), (100_000, 128)] if SMOKE else \
    [(100_000, 16), (1_000_000, 16), (10_000_000, 16),
     (100_000, 128), (1_000_000, 128), (1_000_000, 512)]
CPU_SWEEP_SHAPES = {(100_000, 16), (1_000_000, 16), (100_000, 128)}

# Public per-chip peaks (vendor spec sheets), keyed by device_kind prefix:
# (HBM GB/s, bf16 dense matmul TFLOP/s). Drives the hbm_frac / mfu roofline
# fractions; unknown kinds (incl. "cpu") report no fractions.
ROOFLINE = {
    "TPU v4": (1228.0, 275.0),
    "TPU v5 lite": (819.0, 197.0),    # v5e
    "TPU v5e": (819.0, 197.0),
    "TPU v5p": (2765.0, 459.0),
    "TPU v6 lite": (1640.0, 918.0),   # v6e / Trillium
    "TPU v6e": (1640.0, 918.0),
}


def roofline_for(device_kind: str):
    for prefix, peaks in ROOFLINE.items():
        if device_kind.startswith(prefix):
            return peaks
    return None


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def make_median_time(jax):
    """Timing loop: each rep blocks on ITS OWN ``fn()`` result — blocking on
    a stale array measures only async dispatch enqueue (µs), not the
    computation. Opaque (non-pytree) results pass through block_until_ready
    untouched, which is correct for the synchronous CPU baselines."""
    def median_time(fn, reps):
        jax.block_until_ready(fn())   # warm: compile cached after
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            times.append(time.perf_counter() - t0)
        return statistics.median(times)
    return median_time


def make_chain_timer(jax, jnp, log):
    """Tunnel-proof device timing.

    On the axon-tunneled TPU, ``block_until_ready`` does NOT wait for
    execution (measured live: a 5e11-FLOP matmul "completes" in 0.2 ms —
    2.6 PFLOP/s on a 197 TFLOP/s chip), so wall-clock loops around
    dispatches time the enqueue, not the computation; the round-4 capture
    gap hid this and the first round-5 capture reported mfu 1.32 /
    hbm_frac 35.9 — physically impossible. The fix measures K
    DATA-DEPENDENT iterations inside ONE jitted fori_loop with ONE host
    read at the end:

    * the consumed scalar from iteration i perturbs one input element of
      iteration i+1 by ``s*1e-30`` (an in-place one-element update on the
      loop carry), so XLA's loop-invariant code motion cannot hoist the op;
    * ``lax.optimization_barrier`` around the op's outputs stops XLA from
      fusing the consumption INTO the op (which would elide the output
      writes) or dead-code-eliminating unconsumed outputs;
    * the one host read per call lands the process in the tunnel's
      synchronous mode (~66 ms/dispatch); that fixed floor is measured on
      an empty program and subtracted, and dividing by K amortizes the
      remainder.

    Validated on-chip: per-iteration time scales exactly linearly in rows
    (1.37 ms → 13.7 ms for 10×) at a plausible 53 GB/s effective.
    """
    @jax.jit
    def _tiny(x):
        return x + 1.0

    x0 = jnp.zeros(())
    float(_tiny(x0))            # first host read → sync mode, deliberately

    def _measure_floor(reps=8):
        floors = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(_tiny(x0))
            floors.append(time.perf_counter() - t0)
        return statistics.median(floors)

    floor0 = _measure_floor(12)
    log(f"tunnel dispatch+sync floor: {floor0*1e3:.1f} ms")

    def _perturb_first_float_leaf(args, s):
        leaves, treedef = jax.tree.flatten(args)
        for i, leaf in enumerate(leaves):
            if (hasattr(leaf, "dtype")
                    and jnp.issubdtype(leaf.dtype, jnp.floating)
                    and getattr(leaf, "size", 0)):
                eps = (s * 1e-30).astype(leaf.dtype)
                if leaf.ndim:
                    leaves[i] = leaf.at[(0,) * leaf.ndim].add(eps)
                else:
                    leaves[i] = leaf + eps
                break
        return jax.tree.unflatten(treedef, leaves)

    def _consume(out):
        total = jnp.zeros((), jnp.float32)
        for leaf in jax.tree.leaves(out):
            if hasattr(leaf, "dtype") and getattr(leaf, "size", 0):
                first = leaf[(0,) * leaf.ndim] if leaf.ndim else leaf
                total = total + first.astype(jnp.float32)
        return total

    def _build(op, args, K):
        @jax.jit
        def run(args):
            def body(_, carry):
                a, s = carry
                a = _perturb_first_float_leaf(a, s)
                out = jax.lax.optimization_barrier(op(*a))
                return (a, _consume(out))
            _, s = jax.lax.fori_loop(
                0, K, body, (args, jnp.zeros((), jnp.float32)))
            return s
        return run

    def chain_time(op, args, reps, target_s=0.08):
        """Median per-iteration seconds of ``op(*args)``, or None when the
        op is too fast to resolve above the sync-floor noise even at the
        maximum chain length (an unmeasurable cell must report nothing,
        not a rounded 0 that poisons downstream ratios)."""
        args = tuple(args)
        floor = _measure_floor()         # re-measured per site: it drifts
        probe = _build(op, args, 8)
        float(probe(args))                       # compile + warm
        t0 = time.perf_counter()
        float(probe(args))
        est = max((time.perf_counter() - t0 - floor) / 8, 1e-6)
        K = int(min(4096, max(8, target_s / est)))
        run = probe if K == 8 else _build(op, args, K)
        if K != 8:
            float(run(args))                     # compile + warm
        escalations = 0
        while True:                      # escalate K if margin too thin
            times = []
            for _ in range(max(3, reps)):
                t0 = time.perf_counter()
                float(run(args))
                times.append(time.perf_counter() - t0)
            # margin and K leave this loop as a matched pair: every
            # rebuild is followed by a re-measure before the division
            margin = statistics.median(times) - floor
            if (margin > max(0.01, 0.15 * floor) or K >= 4096
                    or escalations >= 2):
                break
            escalations += 1
            K = min(K * 8, 4096)
            run = _build(op, args, K)
            float(run(args))
        if margin <= 0:
            log(f"chain_time: op unmeasurable above sync-floor noise "
                f"even at K={K}; reporting no number")
            return None
        return margin / K

    return chain_time


def bench_frame_pipeline(median_time, n_rows: int):
    """(frame_pipeline) The fused expression-pipeline compiler
    (ops/compiler.py) vs the per-op eager path on a 20-op
    with_column/filter chain: the ISSUE-3 acceptance metric. One chain
    execution dispatches ONE compiled XLA program when fused vs 20
    interpreter-dispatched computations when eager; compile counters
    prove the plan-keyed cache reuses (0 recompiles once warm)."""
    import jax
    import numpy as np

    from sparkdq4ml_tpu.config import config
    from sparkdq4ml_tpu.frame.frame import Frame
    from sparkdq4ml_tpu.ops import compiler
    from sparkdq4ml_tpu.ops import expressions as E
    from sparkdq4ml_tpu.utils.profiling import counters

    base = Frame({"v": np.arange(n_rows, dtype=np.float64) / n_rows})

    def chain(f):
        for i in range(10):
            f = f.with_column(f"c{i}", E.col("v") * float(i + 1) + 0.5)
            f = f.filter(E.col(f"c{i}") > float(-1 - i))
        return f

    def run():
        out = chain(base)
        # flush + honest sync on EVERY produced column and the mask
        # (syncing just the mask would let async column slices escape the
        # clock); a device wait, never a host read
        jax.block_until_ready(list(out._data.values()) + [out._mask])
        return out

    compiler.clear_cache()
    counters.clear("pipeline")
    run()                                   # cold: trace + compile
    compiles_cold = counters.get("pipeline.compile")
    t_fused = median_time(run, REPS)
    compiles_steady = counters.get("pipeline.compile") - compiles_cold
    flushes = counters.get("pipeline.flush")
    hits = counters.get("pipeline.hit")
    prev_pipeline = config.pipeline
    config.pipeline = False
    try:
        run()                               # warm eager's own jit caches
        t_eager = median_time(run, REPS)
    finally:
        config.pipeline = prev_pipeline
    n_ops = 20
    return {
        "config": "frame_pipeline",
        "rows": n_rows,
        "chain_ops": n_ops,
        "fused_ms": round(t_fused * 1e3, 3),
        "eager_ms": round(t_eager * 1e3, 3),
        "fused_ops_per_s": round(n_ops / t_fused, 1),
        "eager_ops_per_s": round(n_ops / t_eager, 1),
        "speedup": round(t_eager / t_fused, 2),
        "compiles_cold": compiles_cold,
        "compiles_steady": compiles_steady,   # 0 ⇒ plan cache reuse
        "cache_hits": hits,
        "flushes": flushes,
    }


def bench_grouped_ops(median_time):
    """(grouped_ops) Device-resident grouped execution (ops/segments.py)
    vs the legacy host numpy path: groupBy().agg() across a rows × groups
    grid, plus sort and distinct — the ISSUE-4 acceptance surface. The
    device path is ONE jitted sort + segment-reduce program whose only
    host sync is the group count; the host path loops Python over groups.
    Compile counters prove the plan-keyed cache replays warm
    (compiles_steady=0 across repeated queries)."""
    import jax
    import numpy as np

    from sparkdq4ml_tpu.config import config
    from sparkdq4ml_tpu.frame import aggregates as A
    from sparkdq4ml_tpu.frame.frame import Frame
    from sparkdq4ml_tpu.ops import segments
    from sparkdq4ml_tpu.utils.profiling import counters

    if SMOKE:
        rows_sweep, groups_sweep = [100_000], [8, 1024]
    else:
        rows_sweep = [100_000, 1_000_000, 10_000_000]
        groups_sweep = [8, 1024, 100_000]
    # grouped ops run 10-10000x longer per call than the sub-ms fit
    # configs, so the global REPS=30 would push this section past the
    # bench lock window: 3 device reps / 1 host rep give a stable median
    # (the host path is a Python loop over groups; one rep keeps the
    # 1e7x100k cell from dominating wall-clock), and the sort/distinct
    # sweeps stop at 1e6 rows (logged, not silently dropped) — the 1e7
    # distinct host walk alone is ~a minute per rep.
    dev_reps = REPS if SMOKE else 3
    host_reps = REPS if SMOKE else 1
    out = []
    prev = config.grouped_exec
    for n_rows in rows_sweep:
        for n_groups in groups_sweep:
            if n_groups * 4 > n_rows:
                continue
            rng = np.random.default_rng(42)
            frame = Frame({
                "k": rng.integers(0, n_groups, n_rows).astype(np.float64),
                "v": rng.normal(size=n_rows),
            }).cache()
            aggs = [A.count(), A.sum("v"), A.avg("v"), A.min("v"),
                    A.max("v")]
            # honest GB/s denominators: agg and sort stream both float64
            # columns (k + v = 16 B/row); distinct runs on select("k")
            # and touches only the 8-byte key column
            op_bytes = {"agg": n_rows * 16, "sort": n_rows * 16,
                        "distinct": n_rows * 8}

            def run_agg():
                res = frame.group_by("k").agg(*aggs)
                jax.block_until_ready(
                    [c for c in res._data.values()
                     if getattr(c, "dtype", None) != object])

            def run_sort():
                res = frame.sort("v")
                jax.block_until_ready(list(res._data.values()))

            def run_distinct():
                res = frame.select("k").distinct()
                jax.block_until_ready(list(res._data.values()))

            ops = [("agg", run_agg)]
            if n_rows <= 1_000_000:
                ops += [("sort", run_sort), ("distinct", run_distinct)]
            elif n_groups == groups_sweep[0]:
                log(json.dumps({"config": "grouped_ops", "rows": n_rows,
                                "note": "sort/distinct capped at 1e6 rows"
                                        " (host walk ~minutes beyond)"}))
            row = {"config": "grouped_ops", "rows": n_rows,
                   "groups": n_groups}
            try:
                config.grouped_exec = True
                segments.clear_cache()
                counters.clear("grouped")
                for name, fn in ops:
                    before = counters.get("grouped.compile")
                    fn()                         # cold: trace + compile
                    cold = counters.get("grouped.compile") - before
                    t_dev = median_time(fn, dev_reps)
                    steady = counters.get("grouped.compile") - before - cold
                    config.grouped_exec = False
                    try:
                        fn()                     # warm host-path caches
                        t_host = median_time(fn, host_reps)
                    finally:
                        config.grouped_exec = True
                    row[f"{name}_device_ms"] = round(t_dev * 1e3, 3)
                    row[f"{name}_host_ms"] = round(t_host * 1e3, 3)
                    row[f"{name}_speedup"] = round(t_host / t_dev, 2)
                    row[f"{name}_device_gbps"] = round(
                        op_bytes[name] / t_dev / 1e9, 3)
                    row[f"{name}_compiles_cold"] = cold
                    row[f"{name}_compiles_steady"] = steady
            finally:
                config.grouped_exec = prev
            out.append(row)
            log(json.dumps(row))
    return out


def bench_ingest(median_time, session):
    """(ingest) Streaming native CSV ingest (native/csvparse.cpp +
    frame/native_csv.py) — the ISSUE-7 acceptance surface. Four arms per
    row count, all END-TO-END through ``read_csv`` (bytes on disk →
    device-ready Frame columns):

      scalar          one-shot parse, SIMD off, 1 thread — the floor
      simd            one-shot, runtime-dispatched SIMD tier, 1 thread
      simd_threads    one-shot, SIMD + chunk-parallel parse threads
      stream          the full pipeline: bounded chunks, SIMD + threads,
                      prefetch queue overlapping parse with host→device
                      transfer

    Streaming output is asserted bit-identical to the scalar one-shot arm
    (dtype + value parity per column) before any time is reported, and
    the golden DQ pipeline (dataset-abstract, count 24, RMSE 2.8099) is
    driven through the streaming reader with a chunk size small enough to
    actually stream. ``parse_frac`` reports parse wall ÷ (parse + fused
    DQ rules) — the "parse no longer dominates" row. CPU-backend caveat
    (ROADMAP standing constraint): SIMD wins are chip-dependent — on
    hosts where AVX is emulated/throttled the honest verdict can be ~1×,
    so parity + counter structure is the CPU assertion and the GB/s rows
    are the TPU-capture measurement."""
    import tempfile

    import jax
    import numpy as np

    from sparkdq4ml_tpu.config import config
    from sparkdq4ml_tpu.frame import native_csv
    from sparkdq4ml_tpu.frame.csv import read_csv
    from sparkdq4ml_tpu.ops.rules import dq_rules_fused
    from sparkdq4ml_tpu.utils.profiling import counters

    if not native_csv.streaming_available():
        log(json.dumps({"config": "ingest",
                        "note": "libdqcsv.so missing or pre-streaming ABI; "
                                "section skipped"}))
        return []

    rows_sweep = [100_000] if SMOKE else [100_000, 1_000_000, 10_000_000]
    reps = REPS if SMOKE else 3
    saved = (config.ingest_streaming, config.ingest_threads,
             config.ingest_chunk_bytes, config.ingest_prefetch,
             config.ingest_simd)
    out = []
    try:
        for n_rows in rows_sweep:
            fd, path = tempfile.mkstemp(prefix=f"ingest_bench_{n_rows}_",
                                        suffix=".csv")
            rng = np.random.default_rng(13)
            g = rng.integers(1, 40, n_rows)
            p = np.round(rng.uniform(1.0, 120.0, n_rows), 2)
            with os.fdopen(fd, "w") as f:
                f.write("\n".join(f"{a},{b}" for a, b in zip(g, p)))
                f.write("\n")
            nbytes = os.path.getsize(path)

            def set_arm(streaming, chunk, threads, simd, prefetch=2):
                config.ingest_streaming = streaming
                config.ingest_chunk_bytes = chunk
                config.ingest_threads = threads
                config.ingest_simd = simd
                config.ingest_prefetch = prefetch

            def parse():
                f = read_csv(path, engine="native")
                jax.block_until_ready([
                    c for c in f._data.values()
                    if getattr(c, "dtype", None) != object])
                return f

            whole = nbytes + 1  # one-shot: chunk bound beyond the file
            # stream arm: ~4+ chunks at every sweep size (a chunk bound
            # past the file would silently degrade to one-shot)
            stream_chunk = max(min(8 << 20, nbytes // 4), 1 << 16)
            arms = [
                ("scalar", (True, whole, 1, "off")),
                ("simd", (True, whole, 1, "auto")),
                ("simd_threads", (True, whole, 0, "auto")),
                ("stream", (True, stream_chunk, 0, "auto")),
            ]
            # bit parity BEFORE timing: stream (many chunks) == scalar
            set_arm(True, whole, 1, "off")
            ref = parse()
            set_arm(True, max(nbytes // 8, 1 << 16), 0, "auto")
            streamed = parse()
            for c in ref.columns:
                a, b = np.asarray(ref._data[c]), np.asarray(streamed._data[c])
                if a.dtype != b.dtype or not np.array_equal(
                        a, b, equal_nan=True):
                    log(f"ERROR: ingest bench: stream vs one-shot parity "
                        f"broke on column {c} at {n_rows} rows")
                    return out
            row = {"config": "ingest", "rows": n_rows,
                   "bytes": nbytes, "parity": "bit-identical",
                   "simd_verdict": native_csv.simd_level("auto")}
            t_by_arm = {}
            for name, (streaming, chunk, threads, simd) in arms:
                set_arm(streaming, chunk, threads, simd)
                if name == "stream":
                    # warmup doubles as the exact per-read chunk count
                    # (counters would otherwise accumulate across reps)
                    counters.clear("ingest")
                    parse()
                    row["stream_chunks"] = counters.get("ingest.chunks")
                else:
                    parse()  # page-cache + buffer-pool warmup
                t = median_time(parse, reps)
                t_by_arm[name] = t
                row[f"{name}_ms"] = round(t * 1e3, 2)
                row[f"{name}_gbps"] = round(nbytes / t / 1e9, 3)
            row["pipeline_vs_scalar"] = round(
                t_by_arm["scalar"] / min(t_by_arm["stream"],
                                         t_by_arm["simd_threads"]), 2)
            # parse share of the ingest→DQ wall: the fused rules pass on
            # the columns the stream just delivered
            set_arm(True, stream_chunk, 0, "auto")
            frame = parse()
            price = frame._data["_c1"]
            guest = frame._data["_c0"]

            def rules():
                jax.block_until_ready(dq_rules_fused(price, guest))

            rules()  # compile outside the clock
            t_rules = median_time(rules, reps)
            t_parse = t_by_arm["stream"]
            row["dq_rules_ms"] = round(t_rules * 1e3, 3)
            row["parse_frac"] = round(t_parse / (t_parse + t_rules), 4)
            out.append(row)
            log(json.dumps(row))
            try:
                os.remove(path)
            except OSError:
                pass

        # golden numbers THROUGH the streaming reader: the headline DQ +
        # Lasso pipeline on dataset-abstract with the chunk size forced
        # below the file size, so the 320-byte file genuinely streams
        config.ingest_streaming = True
        config.ingest_chunk_bytes = 64
        config.ingest_simd = "auto"
        config.ingest_threads = 0
        config.ingest_prefetch = 2
        counters.clear("ingest")
        import sparkdq4ml_tpu as dq
        from sparkdq4ml_tpu.models import LinearRegression, VectorAssembler

        dq.register_builtin_rules()
        df = (session.read.format("csv").option("inferSchema", "true")
              .load(os.path.join(REPO, "data", "dataset-abstract.csv")))
        df = (df.with_column_renamed("_c0", "guest")
                .with_column_renamed("_c1", "price"))
        df = df.with_column("price_no_min",
                            dq.call_udf("minimumPriceRule", dq.col("price")))
        df.create_or_replace_temp_view("price")
        df = session.sql("SELECT cast(guest as int) guest, price_no_min AS "
                         "price FROM price WHERE price_no_min > 0")
        df = df.with_column(
            "price_correct_correl",
            dq.call_udf("priceCorrelationRule", dq.col("price"),
                        dq.col("guest")))
        df.create_or_replace_temp_view("price")
        df = session.sql("SELECT guest, price_correct_correl AS price "
                         "FROM price WHERE price_correct_correl > 0")
        count = df.count()
        df = df.with_column("label", df.col("price"))
        df = VectorAssembler(["guest"], "features").transform(df)
        model = LinearRegression(max_iter=40, reg_param=1.0,
                                 elastic_net_param=1.0).fit(df)
        rmse = float(model.summary.root_mean_squared_error)
        golden = {"config": "ingest_golden", "dq_count": count,
                  "rmse": round(rmse, 4),
                  "streamed_chunks": counters.get("ingest.chunks"),
                  "golden_ok": bool(count == 24
                                    and abs(rmse - 2.809940) < 0.01)}
        if not golden["golden_ok"]:
            log("ERROR: ingest bench: golden numbers through the streaming "
                f"reader were count={count} rmse={rmse:.4f}, expected "
                "24 / 2.8099")
        out.append(golden)
        log(json.dumps(golden))
    finally:
        (config.ingest_streaming, config.ingest_threads,
         config.ingest_chunk_bytes, config.ingest_prefetch,
         config.ingest_simd) = saved
    return out


def bench_serving(session, data_path: str):
    """(serving) Closed-loop multi-tenant serving bench — the ISSUE-6
    acceptance metric. N concurrent clients (one logical tenant each)
    drive the headline DQ+Lasso query through the QueryServer in a
    closed loop (submit → wait → submit), giving sustained QPS and
    p50/p99 end-to-end latency, with the shared plan/jit cache ON vs
    OFF (per-tenant cache namespaces — what serving would cost if every
    tenant compiled its own plans). ``cross_tenant_new_compiles`` pins
    the reuse claim: with sharing on, the SECOND tenant's first query
    replays the first tenant's compiled programs with zero new pipeline/
    grouped compiles (cache_report diff). Every served query must return
    the golden numbers (count=24, RMSE 2.8099 ± 1%) or the bench exits
    1 — concurrency must never change results.

    The ``coalesced`` arm (ISSUE-18) repeats the shared-cache closed
    loop with cross-request plan coalescing ON: identical-plan flushes
    from concurrent clients rendezvous inside the hold window and run
    as ONE stacked (vmapped) device dispatch. ``cross_request_dispatches``
    is the batched-dispatch count (must sit well below ``queries`` —
    otherwise nothing coalesced) and ``batch_size_hist`` is the padded
    member-bucket histogram from the batched-plan cache."""
    import threading

    import sparkdq4ml_tpu as dq
    from sparkdq4ml_tpu.models import LinearRegression, VectorAssembler
    from sparkdq4ml_tpu.ops import compiler, segments
    from sparkdq4ml_tpu.serve import QueryServer, TenantQuota
    from sparkdq4ml_tpu.utils.profiling import counters

    clients = 8 if SMOKE else 32
    per_client = 2 if SMOKE else 6
    workers = 8
    golden_rmse = 2.809940          # SURVEY.md §2.3, dataset-abstract

    def job(ctx):
        df = (ctx.read.format("csv").option("inferSchema", "true")
              .option("header", "false").load(data_path))
        df = df.with_column_renamed("_c0", "guest") \
               .with_column_renamed("_c1", "price")
        df = df.with_column("price_no_min",
                            dq.call_udf("minimumPriceRule", dq.col("price")))
        ctx.register_view("price", df)
        df = ctx.sql("SELECT cast(guest as int) guest, price_no_min AS "
                     "price FROM price WHERE price_no_min > 0")
        df = df.with_column(
            "price_correct_correl",
            dq.call_udf("priceCorrelationRule", dq.col("price"),
                        dq.col("guest")))
        ctx.register_view("price", df)
        df = ctx.sql("SELECT guest, price_correct_correl AS price "
                     "FROM price WHERE price_correct_correl > 0")
        df = df.with_column("label", df.col("price"))
        df = VectorAssembler(["guest"], "features").transform(df)
        model = LinearRegression(max_iter=40, reg_param=1.0,
                                 elastic_net_param=1.0).fit(df)
        return {"count": df.count(),
                "rmse": float(model.summary.root_mean_squared_error)}

    def plan_compiles(report):
        # pipeline + grouped "misses" ARE the plan-compile counters; the
        # solver/fit factories are tenant-independent in both modes and
        # deliberately excluded from the reuse pin
        return sum(int(report.get(k, {}).get("misses", 0))
                   for k in ("pipeline", "grouped"))

    def run_arm(shared: bool, coalesce: bool = False):
        compiler.clear_cache()
        segments.clear_cache()
        server = QueryServer(
            session, workers=workers, max_queue=4 * clients,
            default_quota=TenantQuota(max_in_flight=2,
                                      max_queued=per_client + 2),
            shared_plan_cache=shared, coalesce=coalesce,
            coalesce_max_delay_ms=5.0, coalesce_max_batch=8,
            coalesce_min_queue_depth=2).start()
        # Cold warm-up on tenant-00, then the cross-tenant pin: does
        # tenant-01's FIRST query need any new compiled plan?
        r0 = server.submit(job, tenant="tenant-00").result()
        rep0 = plan_compiles(server.cache_report())
        r1 = server.submit(job, tenant="tenant-01").result()
        cross_new = plan_compiles(server.cache_report()) - rep0
        if coalesce:
            # untimed concurrent burst: rendezvous real batches so the
            # vmapped (plan, member-bucket) programs compile BEFORE the
            # timed loop — the arm measures steady-state coalesced QPS,
            # same warm-plan footing the uncoalesced arms get from r0/r1
            for _ in range(2):
                warm_threads = [
                    threading.Thread(target=lambda i=i: server.submit(
                        job, tenant=f"tenant-{i:02d}").result())
                    for i in range(clients)]
                for t in warm_threads:
                    t.start()
                for t in warm_threads:
                    t.join()

        co0 = counters.get("serve.coalesce.dispatches")
        co0_members = counters.get("serve.coalesce.batched")
        results: list = []
        res_lock = threading.Lock()

        def client(i: int):
            tenant = f"tenant-{i:02d}"
            out = [server.submit(job, tenant=tenant).result()
                   for _ in range(per_client)]
            with res_lock:
                results.extend(out)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        # batched-plan cache state BEFORE stop/clear: one row per
        # (plan, member bucket), hits+compiles = dispatches through it
        hist: dict = {}
        for e in compiler.coalesce_cache_stats()["entries"]:
            k = f"x{e['batch']}"
            hist[k] = (hist.get(k, 0) + int(e["hits"])
                       + int(e["compiles"]))
        server.stop()
        ok = [r for r in results if r.ok]
        golden_ok = all(
            r.ok                           # short-circuits: a failed
            and r.value["count"] == 24     # warm-up has value=None
            and abs(r.value["rmse"] - golden_rmse) / golden_rmse < 0.01
            for r in ok + [r0, r1])
        lats = sorted(r.e2e_ms for r in ok)

        def pct(p):
            return (round(lats[min(len(lats) - 1,
                                   int(p * (len(lats) - 1)))], 2)
                    if lats else None)

        arm = {
            "queries": len(results), "completed": len(ok),
            "qps": round(len(ok) / wall, 2), "wall_s": round(wall, 3),
            "p50_ms": pct(0.50), "p99_ms": pct(0.99),
            "cross_tenant_new_compiles": cross_new,
            "golden_ok": bool(golden_ok and r0.ok and r1.ok
                              and len(ok) == len(results)),
        }
        if coalesce:
            arm["cross_request_dispatches"] = (
                counters.get("serve.coalesce.dispatches") - co0)
            arm["coalesced_members"] = (
                counters.get("serve.coalesce.batched") - co0_members)
            arm["batch_size_hist"] = hist
        return arm

    def run_socket_arm(tracing: bool = False):
        # Same closed-loop workload through REAL sockets (serve/net.py):
        # half the clients speak the length-prefixed frame protocol,
        # half HTTP/1.1 chunked streaming, all via the resilient client.
        # Latencies are CLIENT-side wall time per logical call, so the
        # delta vs the in-process arm IS the wire + framing overhead.
        # ``tracing=True`` runs the identical workload with distributed
        # tracing ON (context propagation, span trees, tail sampling) —
        # the enabled-vs-disabled QPS pair is the tracing-overhead arm.
        from sparkdq4ml_tpu.serve import NetServer, ResilientClient
        from sparkdq4ml_tpu.utils import observability as _obs

        compiler.clear_cache()
        segments.clear_cache()
        was_tracing = _obs.TRACER.enabled
        if tracing:
            _obs.enable()
        else:
            _obs.disable()
        server = QueryServer(
            session, workers=workers, max_queue=4 * clients,
            default_quota=TenantQuota(max_in_flight=2,
                                      max_queued=per_client + 2),
            shared_plan_cache=True).start()
        net = NetServer(server, host="127.0.0.1", port=0).start()
        net.register_job("headline", job)
        warm = ResilientClient("127.0.0.1", net.port, transport="frame")
        r0 = warm.call_job("headline", tenant="tenant-00",
                           deadline_s=300.0)
        warm.close()

        results: list = []
        lats: list = []
        res_lock = threading.Lock()

        def wire_client(i: int):
            tenant = f"tenant-{i:02d}"
            wire = ResilientClient(
                "127.0.0.1", net.port,
                transport="frame" if i % 2 else "http", tenant=tenant)
            out, took = [], []
            try:
                for _ in range(per_client):
                    t_call = time.perf_counter()
                    out.append(wire.call_job("headline", tenant=tenant,
                                             deadline_s=300.0))
                    took.append((time.perf_counter() - t_call) * 1e3)
            finally:
                wire.close()
            with res_lock:
                results.extend(out)
                lats.extend(took)

        threads = [threading.Thread(target=wire_client, args=(i,))
                   for i in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        net.stop()
        server.stop()
        if was_tracing:
            _obs.enable()
        else:
            _obs.disable()
        ok = [r for r in results if r.ok]
        golden_ok = all(
            r.ok
            and r.value["count"] == 24
            and abs(r.value["rmse"] - golden_rmse) / golden_rmse < 0.01
            for r in ok + [r0])
        lat_sorted = sorted(lats)

        def pct(p):
            return (round(lat_sorted[min(len(lat_sorted) - 1,
                                         int(p * (len(lat_sorted) - 1)))],
                          2) if lat_sorted else None)

        return {
            "queries": len(results), "completed": len(ok),
            "qps": round(len(ok) / wall, 2), "wall_s": round(wall, 3),
            "p50_ms": pct(0.50), "p99_ms": pct(0.99),
            "golden_ok": bool(golden_ok and r0.ok
                              and len(ok) == len(results)),
        }

    shared = run_arm(True)
    isolated = run_arm(False)
    coalesced = run_arm(True, coalesce=True)
    socket_arm = run_socket_arm()
    # (tracing overhead) the same socket workload with distributed
    # tracing ON, then OFF again: tracing_enabled_qps is what the span
    # pipeline costs live; the disabled repeat vs the baseline socket
    # arm pins the one-flag-read contract — with tracing off the wire
    # path is byte-identical, so the ratio must sit at ~1.0 (gated by
    # eye + the test-suite no-op pin, not the regress gate: run-to-run
    # QPS noise swamps a one-branch delta)
    traced_arm = run_socket_arm(tracing=True)
    untraced_arm = run_socket_arm(tracing=False)
    # drop the tenant-namespaced plans the isolated arm salted in
    compiler.clear_cache()
    segments.clear_cache()
    arms = {"shared": shared, "isolated": isolated,
            "coalesced": coalesced, "socket": socket_arm,
            "traced": traced_arm, "untraced": untraced_arm}
    failed = [name for name, arm in arms.items()
              if not arm["golden_ok"]]
    if failed:
        log("ERROR: serving bench: a served query missed the golden "
            "numbers (count 24 / RMSE 2.8099) or failed outright in "
            f"arm(s): {', '.join(failed)}")
        sys.exit(1)
    row = {
        "config": "serving", "clients": clients,
        "queries_per_client": per_client, "workers": workers,
        "shared_cache": shared, "isolated_cache": isolated,
        "coalesced": coalesced,
        "socket": socket_arm,
        "shared_vs_isolated_qps": round(
            shared["qps"] / isolated["qps"], 2)
        if isolated["qps"] else None,
        "coalesced_vs_uncoalesced_qps": round(
            coalesced["qps"] / shared["qps"], 2)
        if shared["qps"] else None,
        "socket_vs_inproc_qps": round(
            socket_arm["qps"] / shared["qps"], 2)
        if shared["qps"] else None,
        "tracing_enabled_qps": traced_arm["qps"],
        "tracing_disabled_qps": untraced_arm["qps"],
        "tracing_disabled_overhead": round(
            socket_arm["qps"] / untraced_arm["qps"], 3)
        if untraced_arm["qps"] else None,
    }
    log(json.dumps(row))
    return row


_SHARD_WORKER = r'''
import json, os, sys, time
n, d, golden = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={max(d, 1)}"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu.frame.frame import Frame
from sparkdq4ml_tpu.utils.profiling import counters
import sparkdq4ml_tpu.ops.expressions as E
from sparkdq4ml_tpu.parallel import shard as shard_mod

sess = (dq.TpuSession.builder().app_name("bench-shard").master("local[*]")
        .config("spark.shard.enabled", "true" if d > 1 else "false")
        .config("spark.shard.minRows", "8" if golden else "1024")
        .get_or_create())

if golden:
    # headline DQ+Lasso golden workload, sharding per arm: parity is a
    # RESULT property, not a layout property
    dq.register_builtin_rules()
    df = (sess.read.format("csv").option("inferSchema", "true")
          .load(sys.argv[4]))
    df = df.with_column_renamed("_c0", "guest") \
           .with_column_renamed("_c1", "price")
    df = df.with_column("price_no_min",
                        dq.call_udf("minimumPriceRule", dq.col("price")))
    df.create_or_replace_temp_view("price")
    df = sess.sql("SELECT cast(guest as int) guest, price_no_min AS price "
                  "FROM price WHERE price_no_min > 0")
    df = df.with_column("price_correct_correl",
                        dq.call_udf("priceCorrelationRule",
                                    dq.col("price"), dq.col("guest")))
    df.create_or_replace_temp_view("price")
    df = sess.sql("SELECT guest, price_correct_correl AS price "
                  "FROM price WHERE price_correct_correl > 0")
    df = df.with_column("label", df.col("price"))
    from sparkdq4ml_tpu.models import LinearRegression, VectorAssembler
    df = VectorAssembler(["guest"], "features").transform(df)
    model = LinearRegression(max_iter=40, reg_param=1.0,
                             elastic_net_param=1.0).fit(df)
    print(json.dumps({
        "devices": d, "count": df.count(),
        "rmse": float(model.summary.root_mean_squared_error),
        "sharded": df._shard is not None}))
    sys.exit(0)

rng = np.random.default_rng(7)
f = Frame({"v": rng.normal(size=n),
           "k": rng.integers(0, 1024, n).astype(np.float64),
           "w": rng.normal(size=n)})
if d > 1:
    f = shard_mod.maybe_shard_frame(f)

def chain(fr):
    for i in range(10):
        fr = fr.with_column(f"c{i}", E.col("v") * float(i + 1) + 0.5)
        fr = fr.filter(E.col(f"c{i}") > float(-1 - i))
    return fr

def flush():
    out = chain(f)
    jax.block_until_ready(list(out._data.values()) + [out._mask])
    return out

def med(fn, reps=3):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]

out = flush()                                  # warm: trace + compile
compiles0 = counters.get("pipeline.compile")
pipe_ms = med(flush) * 1e3
steady = counters.get("pipeline.compile") - compiles0
m = np.asarray(out._mask)
ck_pipe = float(np.asarray(jnp.asarray(out._data["c9"]))[m].sum())

def grp():
    return f.group_by("k").agg({"v": "sum", "w": "avg"}).to_pydict()

gp = grp()                                     # warm
ck_group = [float(np.sum(gp["sum(v)"])), float(np.sum(gp["avg(w)"])),
            int(len(gp["k"]))]
group_ms = med(grp) * 1e3

rsz = max(n // 10, 16)
r = Frame({"k": rng.integers(0, 1024, rsz).astype(np.float64),
           "z": rng.normal(size=rsz)})
if d > 1:
    r = shard_mod.maybe_shard_frame(r)

def jn():
    return int(f.join(r, "k", "inner").num_slots)

jrows = jn()                                   # warm
join_ms = med(jn) * 1e3
print(json.dumps({
    "rows": n, "devices": d, "pipeline_ms": round(pipe_ms, 3),
    "groupby_ms": round(group_ms, 3), "join_ms": round(join_ms, 3),
    "compiles_steady": steady, "ck_pipe": ck_pipe, "ck_group": ck_group,
    "join_rows": jrows, "sharded": f._shard is not None}))
'''


def bench_sharded(log):
    """(sharded) Row-sharded frame execution (parallel/shard.py +
    the shard_map pipeline/grouped lowerings) across forced host device
    counts: the 20-op fused chain, GROUP BY (sum/avg), and an inner join
    at each row count × 1/2/4/8 devices, each arm an isolated subprocess
    (device count is a process-level XLA flag). Parity-asserted — the
    d>1 arms must reproduce the 1-device checksums (pipeline and join
    exact; the grouped merge collective at 1e-5 relative, the
    engine-default float32's reduction-order ULP envelope) — and
    golden-pinned via the headline DQ+Lasso workload with sharding on.
    CPU-sandbox honesty: forced host devices share the same cores, so
    these rows prove structure and scaling SHAPE (plus steady-state
    zero-recompile), not wall-clock wins — speedup columns are captured
    for TPU runs where the shards are real chips."""
    import subprocess
    import sys

    try:
        rows_list = [int(x) for x in os.environ.get(
            "BENCH_SHARD_ROWS", "1000000,10000000").split(",") if x]
    except ValueError:
        rows_list = [1_000_000, 10_000_000]
    devs = [1, 2, 4, 8]
    section = {"pipeline": [], "groupby": [], "join": [],
               "parity_ok": True, "parity_failures": []}

    def run_arm(n, d, golden=False, data=""):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _SHARD_WORKER, str(n), str(d),
                 "1" if golden else "0", data],
                cwd=REPO, env=env, capture_output=True, text=True,
                timeout=1800)
        except subprocess.SubprocessError as e:
            log(f"sharded arm n={n} d={d} failed: {e}")
            return None
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                return json.loads(line)
            except ValueError:
                continue
        log(f"sharded arm n={n} d={d} produced no JSON "
            f"(rc={proc.returncode}): {proc.stderr[-400:]}")
        return None

    for n in rows_list:
        base = None
        for d in devs:
            row = run_arm(n, d)
            if row is None:
                continue
            if d == 1:
                base = row
            else:
                ok = base is not None and (
                    row["ck_pipe"] == base["ck_pipe"]
                    and row["join_rows"] == base["join_rows"]
                    and row["ck_group"][2] == base["ck_group"][2]
                    # grouped float aggregates merge cross-shard partials
                    # — reduction order differs, so the engine-default
                    # float32 checksums compare at ULP-order tolerance
                    # (pipeline/join checksums stay EXACT-equality)
                    and all(abs(a - b) <= 1e-5 * max(abs(a), abs(b), 1.0)
                            for a, b in zip(row["ck_group"][:2],
                                            base["ck_group"][:2])))
                if not ok:
                    section["parity_ok"] = False
                    section["parity_failures"].append(
                        {"rows": n, "devices": d})
            for kind in ("pipeline", "groupby", "join"):
                entry = {
                    "config": f"{kind}_r{n}_d{d}",
                    "rows": n, "devices": d,
                    f"{kind}_ms": row[f"{kind}_ms"],
                }
                if base is not None and d > 1:
                    entry["speedup_vs_1dev"] = round(
                        base[f"{kind}_ms"] / row[f"{kind}_ms"], 3) \
                        if row[f"{kind}_ms"] else None
                if kind == "pipeline":
                    entry["compiles_steady"] = row["compiles_steady"]
                section[kind].append(entry)
            log(json.dumps({"config": "sharded", "rows": n, "devices": d,
                            **{k: row[k] for k in ("pipeline_ms",
                                                   "groupby_ms",
                                                   "join_ms")}}))
    gold = run_arm(0, 8, golden=True,
                   data=os.path.join(REPO, "data", "dataset-abstract.csv"))
    if gold is not None:
        section["golden"] = gold
        section["golden_ok"] = (
            gold.get("count") == 24
            and abs(gold.get("rmse", 0.0) - 2.809940) / 2.809940 < 0.01)
        if not section["golden_ok"]:
            log(f"sharded golden MISMATCH: {gold}")
    return section


def bench_optimizer(session, log):
    """(optimizer) Cost-based plan optimizer (sql/optimizer.py): the
    pushdown / join-order / boundary arms, each timed with the optimizer
    OFF (the literal parse shape) vs ON, parity-asserted (exact column
    equality for the order-preserving level-1 rewrites; sorted-row
    equality for the level-2 join reorder, where SQL imposes no order),
    and golden-pinned via the headline DQ+Lasso workload run under BOTH
    settings (count 24 / RMSE 2.8099 each).

    CPU-sandbox honesty: the pushdown/join-order wins here come from the
    host-side join planning (fewer rows into the hash plan, the small
    side sorted), which is chip-independent; the boundary arm's win is
    avoided XLA recompiles, also host-side. TPU captures inherit the
    same structure."""
    import time as _time

    import jax
    import numpy as np

    import sparkdq4ml_tpu as dq
    from sparkdq4ml_tpu.config import config
    from sparkdq4ml_tpu.frame.frame import Frame
    from sparkdq4ml_tpu.ops import compiler as _compiler
    from sparkdq4ml_tpu.utils.profiling import counters

    n = 100_000 if SMOKE else 1_000_000
    reps = 3 if SMOKE else 7
    rng = np.random.default_rng(11)
    section = {"parity_ok": True, "parity_failures": [], "rows": n}
    saved = (config.optimizer_enabled, config.optimizer_level)

    def med(fn):
        ts = []
        for _ in range(reps):
            t0 = _time.perf_counter()
            fn()
            ts.append(_time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2]

    big = Frame({"k": rng.integers(0, 4096, n).astype(np.float64),
                 "v": rng.normal(size=n),
                 **{f"x{i}": rng.normal(size=n) for i in range(6)}})
    mid = Frame({"k": np.arange(4096).astype(np.float64),
                 "u": rng.normal(size=4096)})
    small = Frame({"k": np.arange(64).astype(np.float64),
                   "w": rng.normal(size=64)})
    big.create_or_replace_temp_view("opt_big")
    mid.create_or_replace_temp_view("opt_mid")
    small.create_or_replace_temp_view("opt_small")

    def run(sql):
        out = session.sql(sql)
        jax.block_until_ready(out._mask)
        return out

    def sql_arm(name, sql, level=1, order_insensitive=False):
        config.optimizer_level = level
        config.optimizer_enabled = False
        ref = run(sql).to_pydict()          # warm plans off-arm
        t_off = med(lambda: run(sql)) * 1e3
        config.optimizer_enabled = True
        got = run(sql).to_pydict()          # warm plans + history on-arm
        t_on = med(lambda: run(sql)) * 1e3
        ok = sorted(ref) == sorted(got)
        if ok:
            for c in ref:
                a = np.asarray(ref[c], dtype=np.float64)
                b = np.asarray(got[c], dtype=np.float64)
                if order_insensitive:
                    a, b = np.sort(a), np.sort(b)
                ok = ok and a.shape == b.shape and bool(np.array_equal(a, b))
        if not ok:
            section["parity_ok"] = False
            section["parity_failures"].append(name)
        entry = {"config": f"optimizer_{name}",
                 "off_ms": round(t_off, 3), "on_ms": round(t_on, 3),
                 "speedup": round(t_off / t_on, 3) if t_on else None,
                 "rows_out": len(next(iter(ref.values()))) if ref else 0}
        section[name] = entry
        log(json.dumps(entry))
        return entry

    try:
        # (pushdown) selective WHERE past a join: the join's host-side
        # plan sees only surviving rows, and pruning drops the x0..x5
        # payload columns from the per-column join gathers
        sql_arm("pushdown",
                "SELECT k, v, u FROM opt_big JOIN opt_mid USING (k) "
                "WHERE v < -1.35")
        # (join_order) build-side selection: the 64-row side is the
        # LEFT relation, so the literal plan sorts the 1e6-row side;
        # the hint builds from the small side, bit-identical emission
        sql_arm("build_side",
                "SELECT k, w, v FROM opt_small JOIN opt_big USING (k)")
        # (join_order, level 2) reordering proper: the literal order
        # joins the 4096-row table first and carries every big row
        # through both plans; smallest-estimate-first joins the 64-row
        # table first and shrinks the intermediate 64x
        sql_arm("join_order",
                "SELECT v, u, w FROM opt_big JOIN opt_mid USING (k) "
                "JOIN opt_small USING (k) WHERE v < 0",
                level=2, order_insensitive=True)

        # (boundary) fused-stage boundary placement, level 2: V fresh
        # 12-step chains sharing a warm 6-step prefix. OFF compiles V
        # mega-programs; ON splits at the warm boundary (prefix replays,
        # only the 6-step tail compiles). Cold-compile wall-clock, one
        # pass per arm over a fresh plan cache.
        nv = 2 if SMOKE else 4
        fbase = Frame({"v": rng.normal(size=4096),
                       **{f"y{i}": rng.normal(size=4096)
                          for i in range(nv)}})

        def prefix(f):
            for i in range(6):
                f = f.with_column(f"p{i}", dq.col("v") * float(i + 1) + 0.5)
            return f

        def variant(f, j):
            f = prefix(f)
            for i in range(6):
                f = f.with_column(
                    f"t{i}", dq.col(f"y{j}") * dq.col(f"p{i}")
                    + dq.col(f"y{j}"))
            return f

        def flush(f):
            jax.block_until_ready(f._mask)
            return f

        def boundary_pass(enabled):
            _compiler.clear_cache()
            config.optimizer_enabled = True
            config.optimizer_level = 2 if enabled else 1
            flush(prefix(fbase))        # warm the prefix plan + history
            t0 = _time.perf_counter()
            outs = [flush(variant(fbase, j)) for j in range(nv)]
            dt = (_time.perf_counter() - t0) * 1e3
            return dt, outs[0]._data["t5"]

        t_b_off, ref_col = boundary_pass(False)
        splits0 = counters.get("optimizer.split")
        t_b_on, got_col = boundary_pass(True)
        splits = counters.get("optimizer.split") - splits0
        if not np.array_equal(np.asarray(ref_col), np.asarray(got_col)):
            section["parity_ok"] = False
            section["parity_failures"].append("boundary")
        entry = {"config": "optimizer_boundary", "variants": nv,
                 "off_ms": round(t_b_off, 3), "on_ms": round(t_b_on, 3),
                 "speedup": round(t_b_off / t_b_on, 3) if t_b_on else None,
                 "splits": splits}
        section["boundary"] = entry
        log(json.dumps(entry))

        # golden pin: the headline DQ+Lasso numbers under BOTH settings
        def golden_arm(enabled):
            config.optimizer_enabled = enabled
            config.optimizer_level = 2 if enabled else 1
            dq.register_builtin_rules()
            df = (session.read.format("csv")
                  .option("inferSchema", "true")
                  .load(os.path.join(REPO, "data",
                                     "dataset-abstract.csv")))
            df = (df.with_column_renamed("_c0", "guest")
                    .with_column_renamed("_c1", "price"))
            df = df.with_column(
                "price_no_min",
                dq.call_udf("minimumPriceRule", dq.col("price")))
            df.create_or_replace_temp_view("price")
            df = session.sql(
                "SELECT cast(guest as int) guest, price_no_min AS price "
                "FROM price WHERE price_no_min > 0")
            df = df.with_column(
                "price_correct_correl",
                dq.call_udf("priceCorrelationRule", dq.col("price"),
                            dq.col("guest")))
            df.create_or_replace_temp_view("price")
            df = session.sql(
                "SELECT guest, price_correct_correl AS price "
                "FROM price WHERE price_correct_correl > 0")
            count = df.count()
            from sparkdq4ml_tpu.models import (LinearRegression,
                                               VectorAssembler)

            df = df.with_column("label", df.col("price"))
            df = VectorAssembler(["guest"], "features").transform(df)
            model = LinearRegression(max_iter=40, reg_param=1.0,
                                     elastic_net_param=1.0).fit(df)
            return count, float(model.summary.root_mean_squared_error)

        c_off, r_off = golden_arm(False)
        c_on, r_on = golden_arm(True)
        golden = {"config": "optimizer_golden",
                  "count_off": c_off, "count_on": c_on,
                  "rmse_off": round(r_off, 4), "rmse_on": round(r_on, 4),
                  "golden_ok": bool(
                      c_off == 24 and c_on == 24 and r_off == r_on
                      and abs(r_on - 2.809940) < 0.01)}
        section["golden"] = golden
        if not golden["golden_ok"]:
            log(f"ERROR: optimizer bench golden MISMATCH: {golden}")
        log(json.dumps(golden))
    finally:
        config.optimizer_enabled, config.optimizer_level = saved
        for v in ("opt_big", "opt_mid", "opt_small"):
            try:
                session.sql(f"DROP VIEW IF EXISTS {v}")
            except Exception:
                pass
    return section


def bench_costprof(session, log):
    """(costprof) Device-cost observatory (utils/costprof.py +
    analysis/program/costs.py): AOT extraction latency per plan class
    (one lower+compile per cached program, amortized by the per-key
    cache + statstore persistence), report-render cost once warm, and
    the overhead-when-disabled pin — with spark.costprof.enabled=false
    the hot path pays one flag read, so the disabled-vs-never-loaded
    flush delta must be ~0 (reported as a ratio, gated by eye + the
    test-suite pin, not the regress gate: sub-ms deltas are noise).

    Chip-independence: extraction cost is host-side XLA compile time;
    the extracted flop/byte figures are the compiler's static
    accounting. Only the ACHIEVED gflops/gbps joins need real silicon."""
    import time as _time

    import jax
    import numpy as np

    import sparkdq4ml_tpu as dq
    from sparkdq4ml_tpu.config import config
    from sparkdq4ml_tpu.frame.frame import Frame
    from sparkdq4ml_tpu.utils import costprof
    from sparkdq4ml_tpu.utils import observability as _obs

    n = 100_000 if SMOKE else 1_000_000
    rng = np.random.default_rng(23)
    section = {"rows": n}
    saved = config.costprof_enabled

    def flush(f):
        jax.block_until_ready(f._mask)
        return f

    def chain(f):
        for i in range(8):
            f = f.with_column(f"c{i}", dq.col("v") * float(i + 1) + 0.25)
        return f.filter(dq.col("c7") > 0)

    frame = Frame({"v": rng.normal(size=n),
                   "k": rng.integers(0, 64, n).astype(np.float64)})
    try:
        # populate the caches the extractor will sweep: a fused
        # pipeline plan + a grouped plan
        from sparkdq4ml_tpu.frame import aggregates as A

        flush(chain(frame))
        frame.group_by("k").agg(A.sum("v"))

        # (overhead-when-disabled) steady-state flush wall with the
        # observatory off vs on — the hot path carries no costprof
        # hook, so this pins the one-flag-read contract at ~1.0
        def steady_flush():
            t0 = _time.perf_counter()
            flush(chain(frame))
            return (_time.perf_counter() - t0) * 1e3

        steady_flush()                      # warm
        config.costprof_enabled = False
        off = sorted(steady_flush() for _ in range(5))[2]
        config.costprof_enabled = True
        on = sorted(steady_flush() for _ in range(5))[2]
        section["disabled_flush_ms"] = round(off, 3)
        section["enabled_flush_ms"] = round(on, 3)
        section["disabled_overhead"] = round(on / off, 3) if off else None

        # (extraction latency per plan class) fresh profile cache; one
        # timed extract_all sweep, split per producer cache
        costprof.clear()
        handles, _errors = _obs.CACHES.programs()
        by_cache: dict = {}
        for h in handles:
            t0 = _time.perf_counter()
            prof = costprof.profile_for(h.program_key)
            dt = (_time.perf_counter() - t0) * 1e3
            row = by_cache.setdefault(
                h.cache, {"programs": 0, "profiled": 0,
                          "extract_ms": 0.0})
            row["programs"] += 1
            if prof is not None:
                row["profiled"] += 1
                row["extract_ms"] += dt
        for cache, row in sorted(by_cache.items()):
            row["extract_ms"] = round(row["extract_ms"], 3)
            entry = {"config": f"costprof_extract_{cache}", **row}
            log(json.dumps(entry))
        section["extract"] = by_cache

        # (report render) warm-cache fleet report cost
        t0 = _time.perf_counter()
        doc = costprof.report()
        section["report_ms"] = round((_time.perf_counter() - t0) * 1e3, 3)
        section["profiles"] = doc["size"]
        section["pending"] = doc["pending"]
        log(json.dumps({"config": "costprof_report",
                        "report_ms": section["report_ms"],
                        "profiles": section["profiles"],
                        "disabled_overhead": section["disabled_overhead"]}))
    finally:
        config.costprof_enabled = saved
    return section


def bench_dqprof(session, log):
    """(dqprof) Data-quality observatory (utils/dqprof.py): steady-state
    flush throughput with profiling ON (deferred sketch dispatch, zero
    host syncs) vs OFF, the overhead-when-disabled pin — with
    spark.dq.profile.enabled=false the hot path pays one flag read, so
    the disabled-vs-never-loaded flush delta must be ~1.0 (reported as
    a ratio, gated by eye + the test-suite pin, not the regress gate:
    sub-ms deltas are noise) — plus the cold drain + report-render
    cost once sketches have accumulated.

    Chip-independence: sketch reductions are tiny device programs; the
    profiled-vs-unprofiled ratio is the structural figure, the absolute
    walls are sandbox-dependent."""
    import time as _time

    import jax
    import numpy as np

    import sparkdq4ml_tpu as dq
    from sparkdq4ml_tpu.config import config
    from sparkdq4ml_tpu.frame.frame import Frame
    from sparkdq4ml_tpu.utils import dqprof

    n = 100_000 if SMOKE else 1_000_000
    rng = np.random.default_rng(29)
    section = {"rows": n}
    saved = config.dq_profile_enabled

    def flush(f):
        jax.block_until_ready(f._mask)
        return f

    def chain(f):
        for i in range(8):
            f = f.with_column(f"c{i}", dq.col("v") * float(i + 1) + 0.25)
        return f.filter(dq.col("c7") > 0)

    frame = Frame({"v": rng.normal(size=n)})

    def steady_flush():
        t0 = _time.perf_counter()
        flush(chain(frame))
        return (_time.perf_counter() - t0) * 1e3

    try:
        # warm both plan variants (hook on/off traces the same fused
        # program — the sketch programs are separate dispatches)
        config.dq_profile_enabled = True
        steady_flush()
        config.dq_profile_enabled = False
        steady_flush()

        # (overhead-when-disabled) the one-flag-read contract at ~1.0:
        # two interleaved disabled batches must agree (the per-flush
        # conf read neither accumulates nor drifts — the structural
        # zero-work pin is the raise-monkeypatch in tests/test_dqprof),
        # then profiled-vs-unprofiled prices the deferred sketch
        # dispatches themselves
        off_a = sorted(steady_flush() for _ in range(5))[2]
        off_b = sorted(steady_flush() for _ in range(5))[2]
        config.dq_profile_enabled = True
        dqprof.clear()
        on = sorted(steady_flush() for _ in range(5))[2]
        off = min(off_a, off_b)
        section["disabled_flush_ms"] = round(off, 3)
        section["profiled_flush_ms"] = round(on, 3)
        section["disabled_overhead"] = (round(off_b / off_a, 3)
                                        if off_a else None)
        section["profiled_overhead"] = round(on / off, 3) if off else None

        # (cold drain + report render) pull the accumulated deferred
        # sketches in the module's one batched counted sync, then the
        # warm report
        t0 = _time.perf_counter()
        doc = dqprof.report()
        section["report_ms"] = round((_time.perf_counter() - t0) * 1e3, 3)
        section["columns"] = doc["size"]
        section["pending"] = doc["pending"]
        log(json.dumps({"config": "dqprof_report",
                        "report_ms": section["report_ms"],
                        "columns": section["columns"],
                        "profiled_overhead": section["profiled_overhead"],
                        "disabled_flush_ms": section["disabled_flush_ms"],
                        "profiled_flush_ms": section["profiled_flush_ms"]}))
    finally:
        config.dq_profile_enabled = saved
    return section


def bench_aqe(session, log):
    """(aqe) Adaptive query execution (sql/adaptive.py): the two drift
    workloads, each run with AQE OFF (static plan to the end) vs ON,
    bit-parity asserted, replans counted from the ``aqe.replans``
    counters, and the headline ``adaptive_vs_static`` speedup reported
    per arm.

    * ``skewed_join`` — a hash-partitioned join plan whose probe side
      piles ~half its rows onto ONE key-hash partition; adaptive
      execution splits the skewed partition into balanced probe chunks
      (``spark.aqe.skewFactor``), merging back bit-identically.
    * ``misestimated_filter`` — a WHERE whose recorded selectivity says
      ~0.5% of rows survive into a GROUP BY; adaptive execution compacts
      the survivors into the observed power-of-two bucket
      (``spark.aqe.driftFactor``) so the grouped stage runs with far
      fewer padded slots.

    CPU-sandbox honesty: the structural claims (split happened, fewer
    padded slots, bit-parity) hold on any chip and are asserted here;
    the wall-clock speedup is real on device backends where padded
    slots cost device time, while on CPU the numbers are reported but
    gated only structurally."""
    import time as _time

    import jax
    import numpy as np

    from sparkdq4ml_tpu.config import config
    from sparkdq4ml_tpu.frame.frame import Frame, _vector_join_plan
    from sparkdq4ml_tpu.ops.compiler import bucket_size
    from sparkdq4ml_tpu.parallel.shard import partitioned_join_plan
    from sparkdq4ml_tpu.utils import statstore as _statstore
    from sparkdq4ml_tpu.utils.profiling import counters

    n = 50_000 if SMOKE else 400_000
    reps = 3 if SMOKE else 7
    rng = np.random.default_rng(23)
    section = {"parity_ok": True, "parity_failures": [], "rows": n}
    saved = (config.aqe_enabled, config.aqe_drift_factor,
             config.aqe_skew_factor)

    def med(fn):
        ts = []
        for _ in range(reps):
            t0 = _time.perf_counter()
            fn()
            ts.append(_time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2]

    try:
        # (skewed_join) synthetic 4-way exchange, probe side ~60% on one
        # key (continuous-float keys — integer-valued doubles would all
        # hash into one partition and degenerate the exchange): static
        # plans the whole skewed partition in one searchsorted pass over
        # its build side; adaptive splits it into balanced chunks
        parts = 4
        config.aqe_skew_factor = 2.0
        rk = rng.random(1024) * 100.0
        lk = np.where(rng.random(n) < 0.6, rk[7],
                      rk[rng.integers(0, 1024, n)])
        li = np.arange(n, dtype=np.int64)
        ri = np.arange(rk.size, dtype=np.int64)

        def plan_join():
            return partitioned_join_plan(
                _vector_join_plan, [lk], [rk], li, ri, "inner", parts)

        config.aqe_enabled = False
        ref = plan_join()
        t_off = med(plan_join) * 1e3
        config.aqe_enabled = True
        r0 = counters.get("aqe.replans.skew-split")
        got = plan_join()
        splits = counters.get("aqe.replans.skew-split") - r0
        t_on = med(plan_join) * 1e3
        ok = (ref is not None and got is not None
              and np.array_equal(ref[0], got[0])
              and np.array_equal(ref[1], got[1]))
        if not ok or splits < 1:
            section["parity_ok"] = False
            section["parity_failures"].append("skewed_join")
        entry = {"config": "aqe_skewed_join",
                 "off_ms": round(t_off, 3), "on_ms": round(t_on, 3),
                 "adaptive_vs_static_speedup": (round(t_off / t_on, 3)
                                                if t_on else None),
                 "replans": int(splits),
                 "pairs": 0 if ref is None else int(ref[0].size)}
        section["skewed_join"] = entry
        log(json.dumps(entry))

        # (misestimated_filter) ~0.5% selectivity into a GROUP BY: the
        # first (history-seeding) run records the true selectivity; with
        # AQE on, the second run's re-bucket hook compacts the survivors
        # before the grouped stage
        Frame({"k": rng.integers(0, 64, n).astype(np.float64),
               "v": rng.normal(size=n)}).create_or_replace_temp_view(
            "aqe_mis")
        sql = ("SELECT k, sum(v) AS s FROM aqe_mis "
               "WHERE v > 2.575 GROUP BY k")

        def run():
            out = session.sql(sql)
            jax.block_until_ready(out._mask)
            return out

        config.aqe_enabled = False
        ref = run().to_pydict()             # seeds selectivity history
        _statstore.STORE.drain_pending()
        t_off = med(run) * 1e3
        config.aqe_enabled = True
        r0 = counters.get("aqe.replans.re-bucket")
        got = run().to_pydict()
        rebuckets = counters.get("aqe.replans.re-bucket") - r0
        t_on = med(run) * 1e3
        ok = sorted(ref) == sorted(got)
        if ok:
            for c in ref:
                a = np.sort(np.asarray(ref[c], dtype=np.float64))
                b = np.sort(np.asarray(got[c], dtype=np.float64))
                ok = ok and a.shape == b.shape \
                    and bool(np.array_equal(a, b))
        if not ok or rebuckets < 1:
            section["parity_ok"] = False
            section["parity_failures"].append("misestimated_filter")
        entry = {"config": "aqe_misestimated_filter",
                 "off_ms": round(t_off, 3), "on_ms": round(t_on, 3),
                 "adaptive_vs_static_speedup": (round(t_off / t_on, 3)
                                                if t_on else None),
                 "replans": int(rebuckets),
                 "slots_static": bucket_size(n),
                 "rows_out": len(next(iter(ref.values()))) if ref else 0}
        section["misestimated_filter"] = entry
        log(json.dumps(entry))
        section["replans"] = int(splits + rebuckets)
        if not section["parity_ok"]:
            log("ERROR: aqe bench parity/structural FAILURES: "
                f"{section['parity_failures']}")
    finally:
        (config.aqe_enabled, config.aqe_drift_factor,
         config.aqe_skew_factor) = saved
        try:
            session.sql("DROP VIEW IF EXISTS aqe_mis")
        except Exception:
            pass
    return section


def _acquire_bench_lock(wait_s: float = 1200.0):
    """Serialize bench runs across processes via an exclusive flock.

    Two concurrent benches on this 1-core host (e.g. the capture daemon's
    and the driver's round-end run) time each other's contention instead
    of the chip. The lock makes the race deterministic: the second run
    waits for the first to finish, up to ``wait_s``, then proceeds anyway
    (a stale lock must not kill the driver capture). Returns the held fd
    (kept open for process lifetime) or None.
    """
    import fcntl

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench.lock")
    try:
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
    except OSError:
        return None
    t0 = time.monotonic()
    announced = False
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return fd
        except OSError:
            if time.monotonic() - t0 > wait_s:
                log(f"bench lock still held after {wait_s:.0f} s; "
                    "proceeding anyway (timings may be contended)")
                return fd
            if not announced:
                log("another bench run holds the lock; waiting for it "
                    f"to finish (up to {wait_s:.0f} s)...")
                announced = True
            time.sleep(5.0)


def main():
    # The driver contract is ONE JSON line; a wedged tunnel must yield an
    # honest backend=cpu result, not an infinite hang. A TRANSIENT wedge
    # must not concede the whole capture either (it did in round 3): probe
    # in a bounded retry loop — up to BENCH_PROBE_DEADLINE seconds
    # (default 20 min), one probe per ~60 s — before accepting CPU.
    from sparkdq4ml_tpu.utils.debug import backend_initializes_retry

    try:
        lock_wait = float(os.environ.get("BENCH_LOCK_WAIT", "1200"))
    except ValueError:
        log("BENCH_LOCK_WAIT is not a number; using 1200 s")
        lock_wait = 1200.0
    _acquire_bench_lock(lock_wait)

    try:
        deadline = float(os.environ.get("BENCH_PROBE_DEADLINE", "1200"))
    except ValueError:
        log("BENCH_PROBE_DEADLINE is not a number; using 1200 s")
        deadline = 1200.0
    if (os.environ.get("BENCH_SKIP_PROBE") != "1"
            and not backend_initializes_retry(deadline_s=deadline,
                                              interval_s=60.0, log=log)):
        log("accelerator backend failed to initialize for "
            f"{deadline:.0f} s (wedged tunnel?); "
            "falling back to CPU — results will carry backend=cpu")
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif os.environ.get("BENCH_SKIP_PROBE") != "1":
        # A healthy probe is necessary but not sufficient (the wedge is
        # intermittent): bound THIS process's real init too, so a wedge
        # arriving in the probe->init gap re-execs the bench pinned to
        # CPU instead of eating the whole capture window.
        from sparkdq4ml_tpu.utils.debug import bounded_backend_init

        bounded_backend_init(150)

    import jax
    import jax.numpy as jnp
    import numpy as np

    import sparkdq4ml_tpu as dq
    from sparkdq4ml_tpu.config import config
    from sparkdq4ml_tpu.models import VectorAssembler
    from sparkdq4ml_tpu.models.classification import fused_logistic_fit_packed
    from sparkdq4ml_tpu.ops import pallas_kernels
    from sparkdq4ml_tpu.parallel.distributed import (fused_linear_fit_packed,
                                                     pack_design, place_packed,
                                                     unpack_fit_result)

    path = os.path.join(REPO, "data", "dataset-full.csv")
    session = dq.TpuSession.builder().app_name("bench").master("local[*]").get_or_create()
    log(f"devices: {jax.devices()}")
    backend = jax.default_backend()
    device_kind = jax.devices()[0].device_kind
    roof = roofline_for(device_kind)
    is_tpu = backend == "tpu" or device_kind.lower().startswith("tpu")

    # ---- build the DQ-cleaned frame (no host reads of device arrays) ----
    dq.register_builtin_rules()
    df = (session.read.format("csv").option("inferSchema", "true")
          .option("header", "false").load(path))
    df = df.with_column_renamed("_c0", "guest").with_column_renamed("_c1", "price")
    df = df.with_column("price_no_min", dq.call_udf("minimumPriceRule", dq.col("price")))
    df.create_or_replace_temp_view("price")
    df = session.sql("SELECT cast(guest as int) guest, price_no_min AS price "
                     "FROM price WHERE price_no_min > 0")
    df = df.with_column("price_correct_correl",
                        dq.call_udf("priceCorrelationRule", dq.col("price"), dq.col("guest")))
    df.create_or_replace_temp_view("price")
    df = session.sql("SELECT guest, price_correct_correl AS price "
                     "FROM price WHERE price_correct_correl > 0")
    df = df.with_column("label", df.col("price"))
    df = VectorAssembler(["guest"], "features").transform(df)

    X = jnp.asarray(df._column_values("features"))
    y = jnp.asarray(df._column_values("label"))
    mask = df.mask
    mesh = None if session.mesh.devices.size <= 1 else session.mesh
    Zd = place_packed(pack_design(X, y, mask), mesh)

    # =====================================================================
    # PHASE 1 — every device timing loop, before ANY device→host read
    # =====================================================================

    median_time = make_median_time(jax)
    if is_tpu:
        # the tunnel's block_until_ready does not wait (see make_chain_timer)
        chain_time = make_chain_timer(jax, jnp, log)

        def timed(op, args, reps=5):
            return chain_time(op, tuple(args), reps)
    else:
        def timed(op, args, reps=REPS):
            return median_time(lambda: op(*args), reps)

    # (a) headline: Lasso fit, one packed dispatch
    fit_a = fused_linear_fit_packed(mesh, "fista", 40, 1e-6, True, True)
    hyper_a = jnp.asarray([1.0, 1.0], Zd.dtype)
    result_a = jax.block_until_ready(fit_a(Zd, hyper_a))
    t_a = timed(fit_a, (Zd, hyper_a))

    # (c) elastic-net general path (FISTA, mixed penalty, 100 iters)
    fit_c = fused_linear_fit_packed(mesh, "fista", 100, 1e-6, True, True)
    hyper_c = jnp.asarray([0.3, 0.5], Zd.dtype)
    t_c = timed(fit_c, (Zd, hyper_c))

    # (d) logistic on DQ rows: per-iteration psum loop. hyper has no L1
    # part, so the production router (LogisticRegression.fit) picks the
    # damped-Newton solver — bench the same program users get.
    yb = (y > jnp.median(y)).astype(Zd.dtype)   # device-side label build
    Zb = place_packed(pack_design(X, yb, mask), mesh)
    fit_d = fused_logistic_fit_packed(mesh, 100, 1e-6, True, True,
                                      solver="newton")
    hyper_d = jnp.asarray([0.01, 0.0], Zd.dtype)
    result_d = jax.block_until_ready(fit_d(Zb, hyper_d))  # iters read later
    t_d = timed(fit_d, (Zb, hyper_d))

    # (d_scale) logistic at 1e6×16: the regime config (d) cannot show on
    # 1024 rows — here the fused on-device loop (zero host barriers, MXU
    # matmuls) is measured against sklearn lbfgs on the same shape.
    n_ds, d_ds = (100_000, 16) if SMOKE else (1_000_000, 16)
    Xds = jax.random.normal(jax.random.PRNGKey(7), (n_ds, d_ds), jnp.float32)
    w_true = jax.random.normal(jax.random.PRNGKey(8), (d_ds,), jnp.float32)
    noise = 0.5 * jax.random.normal(jax.random.PRNGKey(9), (n_ds,),
                                    jnp.float32)
    yds = (Xds @ w_true + noise > 0).astype(jnp.float32)
    Zds = jax.block_until_ready(place_packed(
        pack_design(Xds, yds, jnp.ones((n_ds,), jnp.float32)), mesh))
    del Xds, yds, noise
    fit_ds = fused_logistic_fit_packed(mesh, 100, 1e-6, True, True,
                                       solver="newton")
    result_ds = jax.block_until_ready(fit_ds(Zds, hyper_d))  # iters read later
    t_ds = timed(fit_ds, (Zds, hyper_d), max(3, REPS // 6))

    # (dq) the fused rules+filter pass — the reference's UDF hot loop
    # (`App.java:68-95`) as ONE elementwise device pass
    from sparkdq4ml_tpu.ops.rules import dq_rules_fused

    n_dq = 100_000 if SMOKE else 1_000_000
    price_dq = jax.random.uniform(jax.random.PRNGKey(3), (n_dq,),
                                  jnp.float32, 1.0, 120.0)
    guest_dq = jax.random.randint(jax.random.PRNGKey(4), (n_dq,),
                                  1, 40).astype(jnp.float32)
    fused_rules_fn = jax.jit(dq_rules_fused)
    t_rules = timed(fused_rules_fn, (price_dq, guest_dq))

    # (e) CrossValidator grid: the fused device-complete CV program
    from sparkdq4ml_tpu.models import LinearRegression
    from sparkdq4ml_tpu.models.evaluation import RegressionEvaluator
    from sparkdq4ml_tpu.models.tuning import (ParamGridBuilder,
                                              cv_device_program)

    grid_reg, grid_en, folds = [0.1, 0.5, 1.0], [0.0, 0.5, 1.0], 3
    grid = (ParamGridBuilder().add_grid("reg_param", grid_reg)
            .add_grid("elastic_net_param", grid_en).build())
    cv_prog, cv_args, _, _ = cv_device_program(
        df, LinearRegression(max_iter=40, tol=1e-6), grid, "rmse", folds,
        7, mesh, RegressionEvaluator("rmse").is_larger_better())
    t_e = timed(cv_prog, tuple(cv_args))

    # (sweep) masked-Gramian pass: XLA vs compiled Pallas, data on device
    @jax.jit
    def xla_gram(Z):
        return Z.T @ Z

    # bf16-STORED variant: rows live in HBM at half the bytes and the MXU
    # is bf16-native; accumulation stays f32 (preferred_element_type)
    @jax.jit
    def xla_gram_bf16(Zh):
        return jax.lax.dot_general(
            Zh, Zh, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    sweep_rows = []        # timings (host floats, no device reads)
    pallas_diffs = []      # on-device |A_p - A_x| max scalars, read later
    pallas_mode = "on" if is_tpu else "interpret"
    for (n, d) in SWEEP_SHAPES:
        key = jax.random.PRNGKey(n + d)
        Z = jax.random.normal(key, (n, d + 2), jnp.float32)
        Z = jax.block_until_ready(Z)
        gb = n * (d + 2) * 4 / 1e9

        t_x = timed(xla_gram, (Z,), SWEEP_REPS)

        # bf16-stored Gramian is gated to TPU captures (VERDICT r4 item 6):
        # the variant exists for the MXU (bf16-native) + halved HBM bytes;
        # on CPU it measures only a conversion penalty (r4: 0.29–0.81×),
        # which read as a defect rather than a chip-only optimization.
        t_h = None
        if is_tpu:
            Zh = jax.block_until_ready(Z.astype(jnp.bfloat16))
            t_h = timed(xla_gram_bf16, (Zh,), SWEEP_REPS)
            gb_h = n * (d + 2) * 2 / 1e9
            del Zh

        t_p = None
        best_block = None
        pallas_err = None
        # Off-TPU the Pallas interpreter executes element-by-element — the
        # numerics cross-check at full sweep sizes would run for hours, so
        # it only runs compiled (TPU) or on the SMOKE shapes.
        if is_tpu or SMOKE:
            config.pallas = pallas_mode
            try:
                # The tunnel's remote-compile service flakes transiently
                # (HTTP 500 from a helper-subprocess crash killed the
                # d=512 cell of an otherwise healthy round-5 capture);
                # retry the first compile a couple of times before
                # declaring the cell dead.
                for cell_attempt in range(3):
                    try:
                        A_p = pallas_kernels.packed_gram_pallas(Z)
                        break
                    except Exception as e:  # noqa: BLE001
                        msg = str(e)
                        transient = ("HTTP 5" in msg
                                     or "remote_compile" in msg)
                        if cell_attempt == 2 or not transient:
                            raise
                        log(f"pallas cell ({n},{d}) transient compile "
                            f"failure (attempt {cell_attempt + 1}); "
                            "retrying in 10 s")
                        time.sleep(10.0)
                if is_tpu:
                    # Pre-pad rows to a multiple of every autotune block so
                    # the in-call pad branch (a full concatenate) never
                    # executes INSIDE the timing chain; zero rows add
                    # nothing to ZᵀZ and <4% to the traffic.
                    pal_pad = (-n) % 4096
                    Zp = jnp.concatenate(
                        [Z, jnp.zeros((pal_pad, d + 2), Z.dtype)]) \
                        if pal_pad else Z
                    Zp = jax.block_until_ready(Zp)
                    # Row-tile autotune: bigger tiles amortize grid/DMA
                    # overhead. Candidates whose input block would blow
                    # VMEM at this width are skipped up front (the full-D
                    # left operand double-buffers at block_rows × padded
                    # lanes), and a candidate that still fails on-chip
                    # only voids itself, not the cell.
                    lanes_pad = -((d + 2) // -128) * 128
                    for blk in (512, 1024, 2048, 4096):
                        if blk > n or blk * lanes_pad * 4 * 3 > 8 << 20:
                            continue

                        def pal_op(Zi, _blk=blk):
                            return pallas_kernels.packed_gram_pallas(
                                Zi, block_rows=_blk)

                        try:
                            t_b = timed(pal_op, (Zp,), SWEEP_REPS)
                        except Exception as e:  # noqa: BLE001
                            log(f"pallas block {blk} @ ({n},{d}) failed: "
                                f"{type(e).__name__}: {str(e)[:120]}")
                            continue
                        if t_b is not None and (t_p is None or t_b < t_p):
                            t_p, best_block = t_b, blk
                    del Zp
                A_x = xla_gram(Z)
                scale = jnp.maximum(jnp.max(jnp.abs(A_x)), 1.0)
                pallas_diffs.append(
                    ((n, d), jnp.max(jnp.abs(A_p - A_x)) / scale))
            except Exception as e:  # noqa: BLE001 - one bad cell must not
                # kill a whole TPU capture (an on-chip compile fault here
                # cost round 4 its only healthy-tunnel window); the cell
                # reports the error and the sweep continues.
                t_p, best_block = None, None
                pallas_err = f"{type(e).__name__}: {str(e)[:300]}"
                log(f"pallas cell ({n},{d}) failed: {pallas_err}")
            finally:
                config.pallas = "off"

        sweep_rows.append({
            "rows": n, "features": d,
            "xla_ms": round(t_x * 1e3, 3) if t_x else None,
            "xla_gbps": round(gb / t_x, 1) if t_x else None,
            "bf16_ms": round(t_h * 1e3, 3) if t_h else None,
            "bf16_gbps": round(gb_h / t_h, 1) if t_h else None,
            "bf16_rows_speedup": round(t_x / t_h, 2) if t_x and t_h else None,
            "pallas_ms": round(t_p * 1e3, 3) if t_p else None,
            "pallas_gbps": round(gb / t_p, 1) if t_p else None,
            "pallas_block": best_block,
            **({"pallas_error": pallas_err} if pallas_err else {}),
        })
        del Z

    # =====================================================================
    # PHASE 2 — host reads, CPU baselines, assertions
    # =====================================================================
    n_rows = df.count()
    log(f"DQ-clean rows: {n_rows} (expect 1024)")
    result = unpack_fit_result(result_a, 1)
    coef = float(result.coefficients[0])
    intercept = float(result.intercept)
    d_host = df.to_pydict()
    yv = d_host["label"].astype(np.float64)
    xv = d_host["guest"].astype(np.float64)
    rmse = float(np.sqrt(np.mean((yv - (coef * xv + intercept)) ** 2)))
    drift = abs(rmse - GOLDEN_RMSE_FULL) / GOLDEN_RMSE_FULL
    log(f"fit: coef={coef:.6f} intercept={intercept:.6f} rmse={rmse:.6f} "
        f"drift={drift*100:.4f}% (budget 1%)")
    if drift > 0.01:
        log("ERROR: RMSE drift exceeds the 1% acceptance budget")
        sys.exit(1)

    # pallas numerics: assert before reporting any pallas number
    for (shape, diff_dev) in pallas_diffs:
        diff = float(diff_dev)
        log(f"pallas-vs-xla rel diff @ {shape}: {diff:.2e}")
        if not diff < 5e-5:
            log(f"ERROR: pallas Gramian diverges from XLA at {shape}")
            sys.exit(1)

    # CPU baselines --------------------------------------------------------
    # sklearn is a strictly faster Spark-CPU proxy; without it, a pure-numpy
    # ISTA stands in for (a) and c/d report no baseline rather than dying
    # (the driver contract — one JSON line — must survive a missing dep).
    Xh = xv.reshape(-1, 1)
    sx, sy = Xh.std(ddof=1), yv.std(ddof=1)
    Xs = (Xh - Xh.mean()) / sx
    ys = (yv - yv.mean()) / sy
    yb_h = (yv > np.median(yv)).astype(np.float64)

    try:
        from sklearn.linear_model import (ElasticNet, Lasso,
                                          LogisticRegression as SkLogit)
        have_sklearn = True
    except ImportError:
        have_sklearn = False

    sk_iters_d = None
    sk_iters_ds = None
    t_ds_cpu = None
    if have_sklearn:
        base_a = "sklearn Lasso(cd) maxIter=40"
        t_a_cpu = median_time(
            lambda: Lasso(alpha=1.0 / sy, max_iter=40, tol=1e-6).fit(Xs, ys),
            REPS)
        t_c_cpu = median_time(
            lambda: ElasticNet(alpha=0.3 / sy, l1_ratio=0.5, max_iter=100,
                               tol=1e-6).fit(Xs, ys), REPS)
        t_d_cpu = median_time(
            lambda: SkLogit(C=100.0, max_iter=100, tol=1e-6).fit(Xs, yb_h),
            REPS)
        sk_iters_d = int(np.ravel(SkLogit(C=100.0, max_iter=100, tol=1e-6)
                                  .fit(Xs, yb_h).n_iter_)[0])

        # d_scale baseline: same shape/regime, independent draw (the
        # comparison is solver-vs-solver on the task family, not bitwise)
        rng_ds = np.random.default_rng(11)
        Xh_ds = rng_ds.standard_normal((n_ds, d_ds)).astype(np.float64)
        wh = rng_ds.standard_normal(d_ds)
        yh_ds = (Xh_ds @ wh + 0.5 * rng_ds.standard_normal(n_ds) > 0
                 ).astype(np.float64)
        est_ds = SkLogit(C=100.0, max_iter=100, tol=1e-6)
        t_ds_cpu = median_time(lambda: est_ds.fit(Xh_ds, yh_ds), 3)
        # n_iter_ read off the last timed fit — a dedicated fourth fit
        # would add a full t_ds_cpu to every capture for one integer
        sk_iters_ds = int(np.ravel(est_ds.n_iter_)[0])
        del Xh_ds
    else:
        base_a = "numpy ISTA maxIter=40"

        def ista():
            w = 0.0
            h = float(Xs[:, 0] @ Xs[:, 0]) / len(ys)
            c0 = float(Xs[:, 0] @ ys) / len(ys)
            lam = 1.0 / sy
            for _ in range(40):
                g = h * w - c0
                w = np.sign(w - g / h) * max(abs(w - g / h) - lam / h, 0.0)

        t_a_cpu = median_time(ista, REPS)
        t_c_cpu = t_d_cpu = None

    # CPU gram GB/s context for the sweep's smaller cells
    for row in sweep_rows:
        shape = (row["rows"], row["features"])
        if shape in CPU_SWEEP_SHAPES:
            rng = np.random.default_rng(0)
            Zc = rng.standard_normal((shape[0], shape[1] + 2),
                                     dtype=np.float32)
            t_cpu = median_time(lambda: Zc.T @ Zc, SWEEP_REPS)
            row["cpu_gbps"] = round(
                shape[0] * (shape[1] + 2) * 4 / 1e9 / t_cpu, 1)

    # (dq) numpy baseline for the fused rules pass — the vectorized-host
    # equivalent of the reference's per-row UDF chain
    rng_dq = np.random.default_rng(12)
    ph = rng_dq.uniform(1.0, 120.0, n_dq).astype(np.float32)
    gh = rng_dq.integers(1, 40, n_dq).astype(np.float32)

    def np_rules():
        pnm = np.where(ph < 20, -1.0, ph)
        pcc = np.where((gh < 14) & (ph > 90), -1.0, ph)
        return pnm, pcc, (pnm > 0) & (pcc > 0)

    t_rules_cpu = median_time(np_rules, REPS)
    # bytes touched: 2 f32 inputs read + 2 f32 outputs + 1 bool written
    rules_bytes = n_dq * (4 * 4 + 1)

    # (dq) CSV parse throughput: native C++ tokenizer vs pure-Python vs
    # pandas on a synthetic (guest,price) file at DQ-bench scale
    import tempfile

    n_csv = 100_000 if SMOKE else 1_000_000
    # unique per run: a fixed name would let concurrent benches race on
    # write/parse/remove
    csv_fd, csv_path = tempfile.mkstemp(prefix=f"dq_bench_{n_csv}_",
                                        suffix=".csv")
    rng_csv = np.random.default_rng(13)
    guests_csv = rng_csv.integers(1, 40, n_csv)
    prices_csv = np.round(rng_csv.uniform(1.0, 120.0, n_csv), 2)
    with os.fdopen(csv_fd, "w") as f:
        f.write("\n".join(f"{g},{p}" for g, p in
                          zip(guests_csv, prices_csv)))
        f.write("\n")
    csv_bytes = os.path.getsize(csv_path)

    from sparkdq4ml_tpu.frame import native_csv
    from sparkdq4ml_tpu.frame.csv import read_csv

    t_parse_native = None
    if native_csv.available():
        t_parse_native = median_time(
            lambda: read_csv(csv_path, engine="native"), 3)
    # the pure-python engine is O(seconds) at 1e6 rows, and a host parser
    # has no compile cache to warm: ONE direct timed run, no warmup rep
    t0 = time.perf_counter()
    read_csv(csv_path, engine="python")
    t_parse_py = time.perf_counter() - t0
    t_parse_pandas = None
    try:
        import pandas as pd

        t_parse_pandas = median_time(
            lambda: pd.read_csv(csv_path, header=None), 3)
    except ImportError:
        pass
    try:
        os.remove(csv_path)   # ~15 MB of /tmp litter otherwise
    except OSError:
        pass

    # (frame_pipeline) fused expression-pipeline compiler vs eager per-op
    # dispatch on a 20-op frame chain (CPU-meaningful: the dispatch
    # overhead being eliminated is host-side either way; on TPU the same
    # numbers ride the tunnel's async dispatch and carry its caveat)
    n_fp = 100_000 if SMOKE else 1_000_000
    frame_pipeline = bench_frame_pipeline(median_time, n_fp)

    # (grouped_ops) device-resident groupBy/sort/distinct vs the host
    # numpy path (ops/segments.py) across a rows × groups grid
    grouped_ops = bench_grouped_ops(median_time)

    # (ingest) streaming native CSV parse: scalar vs SIMD vs SIMD+threads
    # vs the full prefetch pipeline, bit-parity + golden-pinned
    ingest = bench_ingest(median_time, session)

    # (serving) closed-loop multi-tenant QPS/p99 on the headline DQ+Lasso
    # query (serve/), shared plan cache on vs off, golden-pinned
    serving = bench_serving(session,
                            os.path.join(REPO, "data",
                                         "dataset-abstract.csv"))

    if SMOKE and "BENCH_SHARD_ROWS" not in os.environ:
        os.environ["BENCH_SHARD_ROWS"] = "100000"
    sharded = bench_sharded(log)

    # (optimizer) cost-based plan rewrites: pushdown / join-order /
    # boundary arms, off-vs-on, parity-asserted, golden-pinned
    optimizer_sec = bench_optimizer(session, log)

    # (costprof) device-cost observatory: extraction latency per plan
    # class, report-render cost, overhead-when-disabled pinned ~0
    costprof_sec = bench_costprof(session, log)

    # (dqprof) data-quality observatory: profiled-vs-unprofiled flush
    # throughput, overhead-when-disabled pinned ~1.0, cold drain cost
    dqprof_sec = bench_dqprof(session, log)

    # (aqe) adaptive execution: skewed-join + misestimated-filter arms,
    # off-vs-on, bit-parity + structural assertions, replans counted
    aqe_sec = bench_aqe(session, log)

    # (e) baseline: sklearn GridSearchCV, same 3x3 grid / folds / family,
    # refit=True to match the in-program best-model refit
    t_e_cpu = None
    if have_sklearn:
        from sklearn.model_selection import GridSearchCV

        def cpu_grid():
            GridSearchCV(ElasticNet(max_iter=40, tol=1e-6),
                         {"alpha": [r / sy for r in grid_reg],
                          "l1_ratio": grid_en},
                         cv=folds, scoring="neg_root_mean_squared_error",
                         n_jobs=1, refit=True).fit(Xs, ys)

        t_e_cpu = median_time(cpu_grid, REPS)

    # =====================================================================
    # PHASE 3 — report
    # =====================================================================
    def cfg(name, t_dev, baseline_name, t_cpu, **extra):
        out = {"config": name,
               "device_ms": round(t_dev * 1e3, 4) if t_dev else None,
               "baseline": baseline_name if t_cpu else "unavailable",
               "baseline_ms": round(t_cpu * 1e3, 4) if t_cpu else None,
               "vs_baseline": round(t_cpu / t_dev, 2)
               if t_cpu and t_dev else None}
        out.update({k: v for k, v in extra.items() if v is not None})
        return out

    # Config (d) has never cleared 10× on 1024 rows and the reason is
    # structural, not a bug: report it instead of hiding it.
    iters_d = int(unpack_fit_result(np.asarray(result_d), 1).iterations)
    sk_clause = (f"vs sklearn lbfgs converging in {sk_iters_d} iterations"
                 if sk_iters_d is not None else
                 "(no sklearn baseline available)")
    analysis_d = (
        f"device runs {iters_d} damped-Newton iterations inside one fused "
        f"dispatch {sk_clause} on 1024 rows; at this size wall-clock is "
        f"bounded by per-dispatch overhead, not FLOPs — see "
        f"d_scale_logistic for the regime where the fused loop wins")

    # d_scale: close the argument with iteration-level numbers (VERDICT r4
    # item 3). CPU-vs-CPU the honest finding is parity: XLA-CPU's fused
    # damped-Newton and sklearn's lbfgs both converge in a handful of
    # iterations at 1e6×16 and both are memory-bound on the same host, so
    # neither side has a structural edge. The fused loop's claimed win —
    # zero per-iteration host barriers (vs treeAggregate, SURVEY §3.3) and
    # MXU matmuls — only materializes on the chip.
    iters_ds = int(unpack_fit_result(np.asarray(result_ds), d_ds).iterations)
    dev_ms_it = t_ds * 1e3 / max(iters_ds, 1) if t_ds else None
    if t_ds_cpu is not None and sk_iters_ds is not None:
        cpu_ms_it = t_ds_cpu * 1e3 / max(sk_iters_ds, 1)
        ds_cpu_clause = (f"sklearn lbfgs: {sk_iters_ds} iterations × "
                         f"{cpu_ms_it:.1f} ms/iter")
    else:
        ds_cpu_clause = "no sklearn baseline available"
    dev_it_clause = (f"{dev_ms_it:.1f} ms/iter" if dev_ms_it is not None
                     else "unmeasurable ms/iter (see timing_note)")
    if is_tpu:
        analysis_ds = (
            f"on-chip capture: fused damped-Newton runs {iters_ds} "
            f"iterations × {dev_it_clause} in one dispatch "
            f"(zero host barriers) vs {ds_cpu_clause} on the host CPU")
    else:
        analysis_ds = (
            f"CPU-vs-CPU this is parity, not a win: XLA-CPU fused Newton "
            f"({iters_ds} iterations × {dev_it_clause}, one "
            f"dispatch) vs {ds_cpu_clause}; both are memory-bound on the "
            f"same cores. The fused loop's claimed advantage — eliminating "
            f"the per-iteration host barrier (treeAggregate analogue, "
            f"SURVEY §3.3) and MXU-resident matmuls — requires the chip; "
            f"no on-chip number exists in this capture")

    configs = [
        cfg("a_linear_lasso_dataset_full", t_a, base_a, t_a_cpu),
        cfg("c_elasticnet_fista_path", t_c,
            "sklearn ElasticNet(cd) maxIter=100", t_c_cpu),
        cfg("d_logistic_dq_rows", t_d,
            "sklearn LogisticRegression(lbfgs) maxIter=100", t_d_cpu,
            analysis=analysis_d),
        cfg(f"d_scale_logistic_{n_ds}x{d_ds}", t_ds,
            f"sklearn LogisticRegression(lbfgs) {n_ds}x{d_ds}", t_ds_cpu,
            analysis=analysis_ds, device_iterations=iters_ds,
            device_ms_per_iter=round(dev_ms_it, 2)
            if dev_ms_it is not None else None,
            baseline_iterations=sk_iters_ds,
            baseline_ms_per_iter=round(t_ds_cpu * 1e3 / max(sk_iters_ds, 1),
                                       2)
            if t_ds_cpu is not None and sk_iters_ds else None),
        cfg("e_crossvalidator_grid", t_e,
            f"sklearn GridSearchCV(ElasticNet) {len(grid)}x{folds} refit",
            t_e_cpu),
        cfg(f"dq_rules_fused_{n_dq}", t_rules,
            f"numpy vectorized rules {n_dq}", t_rules_cpu,
            device_gbps=round(rules_bytes / t_rules / 1e9, 2)
            if t_rules else None,
            baseline_gbps=round(rules_bytes / t_rules_cpu / 1e9, 2),
            # The ~12 MB working set fits VMEM, so chained iterations
            # run on-chip-resident — device_gbps above the 819 GB/s HBM
            # roofline is expected and means VMEM-resident throughput,
            # not HBM streaming (see top-level timing_note).
            analysis=(
                "operands (~12 MB) stay VMEM-resident across chained "
                "iterations; device_gbps above the HBM roofline reports "
                "on-chip throughput, not HBM streaming — see timing_note")
            if is_tpu else None),
    ]
    parse_cfg = {
        "config": f"dq_parse_csv_{n_csv}",
        "file_mb": round(csv_bytes / 1e6, 1),
        "native_ms": round(t_parse_native * 1e3, 1) if t_parse_native
        else None,
        "native_gbps": round(csv_bytes / t_parse_native / 1e9, 3)
        if t_parse_native else None,
        "python_ms": round(t_parse_py * 1e3, 1),
        "python_gbps": round(csv_bytes / t_parse_py / 1e9, 3),
        "pandas_ms": round(t_parse_pandas * 1e3, 1) if t_parse_pandas
        else None,
        "pandas_gbps": round(csv_bytes / t_parse_pandas / 1e9, 3)
        if t_parse_pandas else None,
        "native_vs_python": round(t_parse_py / t_parse_native, 2)
        if t_parse_native else None,
        # The VERDICT-r4 cycle budget: where the single-core ns/byte goes.
        # Stage costs measured with a C-level stage harness on this host
        # class (1-core Xeon 2.1 GHz). The parse is bitmap-first: phase A
        # classifies every structural byte (AVX2 compare+movemask, ~24
        # GB/s) into a bitmap that also yields the record count; phase B
        # walks set bits, so each field's ADDRESS comes from the bitmap
        # instead of the previous field's parsed length — the ~20-cycle
        # per-field convert chains (Lemire SWAR digits, exact /10^frac)
        # are independent work the OoO core overlaps. Direct column-major
        # store; integral int32 flags are free for bare-digit fields (a
        # frac==0 word parse is integral by construction). No staging
        # vector, no transpose, no libm calls.
        "analysis": (
            f"{t_parse_native * 1e9 / csv_bytes:.2f} ns/byte end-to-end "
            "(python wrapper incl. one astype copy per column); C stage "
            "budget at ~4.4-byte fields: quote memchr ~0.07 ns/B, "
            "structural bitmap ~0.05, bitmap walk + field converts + "
            "column store ~2.2 — the per-field exact-divide (10^frac) "
            "and store/flag dispatch are the binding cost now that "
            "converts overlap; the next step-change needs batched "
            "multi-field SIMD conversion (AVX-512 class)")
        if t_parse_native else None,
    }
    configs.append(parse_cfg)

    # Roofline fractions (TPU only): achieved ÷ chip peak per sweep cell.
    # mfu uses the bf16 matmul peak as denominator for the f32 cells too,
    # making their mfu a conservative lower bound (stated in the README).
    if roof is not None:
        hbm_peak, tflops_peak = roof
        for row in sweep_rows:
            n_r, d_r = row["rows"], row["features"]
            flops = 2.0 * n_r * (d_r + 2) ** 2
            if row["xla_ms"]:               # None/0 = unmeasurable cell
                row["hbm_frac"] = round(row["xla_gbps"] / hbm_peak, 4)
                row["mfu"] = round(
                    flops / (row["xla_ms"] / 1e3) / (tflops_peak * 1e12), 4)
            if row["bf16_ms"]:
                row["bf16_hbm_frac"] = round(row["bf16_gbps"] / hbm_peak, 4)
                row["bf16_mfu"] = round(
                    flops / (row["bf16_ms"] / 1e3) / (tflops_peak * 1e12), 4)
            if row.get("pallas_gbps"):
                row["pallas_hbm_frac"] = round(
                    row["pallas_gbps"] / hbm_peak, 4)

    for c in configs:
        log(json.dumps(c))
    # frame_pipeline lives ONLY under its top-level key (the README
    # contract) — appending it to configs too would double-count it for
    # tooling that aggregates config rows; the stderr echo is for humans
    log(json.dumps(frame_pipeline))
    for row in sweep_rows:
        log(json.dumps(row))

    print(json.dumps({
        "metric": "linear_regression_fit_wallclock_dataset_full",
        "value": round(t_a * 1e3, 4) if t_a else None,
        "unit": "ms",
        "vs_baseline": round(t_a_cpu / t_a, 3) if t_a else None,
        "configs": configs,
        "frame_pipeline": frame_pipeline,
        "grouped_ops": grouped_ops,
        "ingest": ingest,
        "serving": serving,
        "sharded": sharded,
        "optimizer": optimizer_sec,
        "costprof": costprof_sec,
        "dqprof": dqprof_sec,
        "aqe": aqe_sec,
        "sweep": sweep_rows,
        "pallas_max_rel_diff": max((float(d) for _, d in pallas_diffs),
                                   default=None),
        "backend": backend,
        "device_kind": device_kind,
        "bf16_gated": None if is_tpu else (
            "bf16-stored Gramian gated to TPU captures: no MXU on this "
            "backend, the variant would measure only a conversion penalty"),
        "roofline": {"hbm_gbps": roof[0], "bf16_tflops": roof[1]}
        if roof else None,
        "timing_note": (
            "device ops timed as K data-dependent iterations inside one "
            "jitted fori_loop minus the measured dispatch+sync floor "
            "(the tunnel's block_until_ready does not wait — see "
            "make_chain_timer). Operands that fit on-chip memory "
            "(~<100 MB) stay resident across chained iterations, so "
            "small-cell gbps/hbm_frac can exceed the HBM roofline — "
            "those cells measure on-chip-resident throughput; cells "
            "larger than VMEM (e.g. 1e7 rows) are the HBM-bound "
            "numbers.") if is_tpu else None,
    }))


if __name__ == "__main__":
    main()
