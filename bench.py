"""Benchmark harness (BASELINE.md / BASELINE.json target).

Covers the five BASELINE.json configs plus a synthetic scale sweep:

(a/b) LinearRegression Lasso fit on dataset-full.csv (the headline metric:
      maxIter=40, regParam=1, elasticNetParam=1; single-chip mesh = config a,
      the same packed psum path sharded = config b, exercised in CI and the
      multichip dryrun),
(c)   elastic-net general path (FISTA, regParam=0.3, elasticNetParam=0.5),
(d)   LogisticRegression on the DQ-filtered rows (per-iteration-psum loop),
      plus a 1e6×16 scale variant (d_scale) where barrier elimination —
      not solver iteration counts — dominates,
(e)   CrossValidator grid (regParam × elasticNetParam, grid-parallel cell
      sharding) vs sklearn GridSearchCV(refit=True) — timed as the fused
      device-complete CV program (fold Gramians → every cell solved →
      winner selected → best model refit, one dispatch, no host reads;
      the same program CrossValidator.fit runs, which then adds exactly
      one host read to materialize the packed result),
(dq)  the DQ phase itself (`App.java:52-95`): CSV parse throughput
      (native C++ tokenizer vs pure-Python) on a ~1e6-row synthetic file,
      and the fused rules+filter pass (XLA, on device) vs vectorized numpy,
(sweep) the masked-Gramian data pass at n ∈ {1e5, 1e6, 1e7} × d ∈ {16, 128,
      512} (HBM-bounded subset), XLA vs compiled Pallas, with on-device
      numerics assertions — the MXU/HBM throughput story behind every fit.
      On TPU each cell also reports its roofline fractions: ``hbm_frac``
      (achieved GB/s ÷ chip HBM peak) and ``mfu`` (achieved FLOP/s ÷ chip
      bf16 matmul peak; f32 cells use the same denominator, so their mfu
      is a conservative lower bound).

Baselines are **measured CPU** stand-ins (sklearn / numpy, documented per
config): the reference publishes no numbers (SURVEY.md §6) and no JVM is
available, so sklearn-CPU — a C-optimized solver without Spark's RPC
barriers — is a strictly faster proxy than the Spark stack it stands in
for. ``vs_baseline`` = baseline_seconds / device_seconds.

Prints exactly ONE JSON line on stdout (driver contract); the per-config
results, sweep table, and pallas-vs-XLA table ride inside it. Per-config
lines are echoed to stderr for human reading.

Measurement hygiene: on the axon-tunneled TPU the FIRST device→host fetch
(``int()``/``float()``/``np.asarray`` on a device array) permanently
switches the process into a synchronous dispatch mode (~67 ms/call floor
afterwards; measured — ``block_until_ready`` alone does not trigger it).
ALL timing loops therefore run before ANY host read: device results and
on-device diff scalars are collected, and only after the last timing loop
does the host read anything. Data for the sweep is generated ON DEVICE
(jax.random) so multi-GB operands never cross the tunnel.
"""

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

GOLDEN_RMSE_FULL = 1.805140  # SURVEY.md §2.3, dataset-full Lasso
# BENCH_SMOKE=1: tiny sweep + few reps, for CI validation of the harness
# itself on CPU (real numbers come from the TPU run).
SMOKE = os.environ.get("BENCH_SMOKE") == "1"
REPS = 3 if SMOKE else 30
SWEEP_REPS = 2 if SMOKE else 5
# (rows, features) — sizes chosen to fit v5e HBM (16 GB) with headroom;
# the 1e7×128 / 1e7×512 cells would be 5–20 GB and are deliberately absent
# (documented cap, not silent truncation).
SWEEP_SHAPES = [(100_000, 16), (100_000, 128)] if SMOKE else \
    [(100_000, 16), (1_000_000, 16), (10_000_000, 16),
     (100_000, 128), (1_000_000, 128), (1_000_000, 512)]
CPU_SWEEP_SHAPES = {(100_000, 16), (1_000_000, 16), (100_000, 128)}

# Public per-chip peaks (vendor spec sheets), keyed by device_kind prefix:
# (HBM GB/s, bf16 dense matmul TFLOP/s). Drives the hbm_frac / mfu roofline
# fractions; unknown kinds (incl. "cpu") report no fractions.
ROOFLINE = {
    "TPU v4": (1228.0, 275.0),
    "TPU v5 lite": (819.0, 197.0),    # v5e
    "TPU v5e": (819.0, 197.0),
    "TPU v5p": (2765.0, 459.0),
    "TPU v6 lite": (1640.0, 918.0),   # v6e / Trillium
    "TPU v6e": (1640.0, 918.0),
}


def roofline_for(device_kind: str):
    for prefix, peaks in ROOFLINE.items():
        if device_kind.startswith(prefix):
            return peaks
    return None


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def make_median_time(jax):
    """Timing loop: each rep blocks on ITS OWN ``fn()`` result — blocking on
    a stale array measures only async dispatch enqueue (µs), not the
    computation. Opaque (non-pytree) results pass through block_until_ready
    untouched, which is correct for the synchronous CPU baselines."""
    def median_time(fn, reps):
        jax.block_until_ready(fn())   # warm: compile cached after
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            times.append(time.perf_counter() - t0)
        return statistics.median(times)
    return median_time


def main():
    # The driver contract is ONE JSON line; a wedged tunnel must yield an
    # honest backend=cpu result, not an infinite hang. A TRANSIENT wedge
    # must not concede the whole capture either (it did in round 3): probe
    # in a bounded retry loop — up to BENCH_PROBE_DEADLINE seconds
    # (default 20 min), one probe per ~60 s — before accepting CPU.
    from sparkdq4ml_tpu.utils.debug import backend_initializes_retry

    try:
        deadline = float(os.environ.get("BENCH_PROBE_DEADLINE", "1200"))
    except ValueError:
        log("BENCH_PROBE_DEADLINE is not a number; using 1200 s")
        deadline = 1200.0
    if (os.environ.get("BENCH_SKIP_PROBE") != "1"
            and not backend_initializes_retry(deadline_s=deadline,
                                              interval_s=60.0, log=log)):
        log("accelerator backend failed to initialize for "
            f"{deadline:.0f} s (wedged tunnel?); "
            "falling back to CPU — results will carry backend=cpu")
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif os.environ.get("BENCH_SKIP_PROBE") != "1":
        # A healthy probe is necessary but not sufficient (the wedge is
        # intermittent): bound THIS process's real init too, so a wedge
        # arriving in the probe->init gap re-execs the bench pinned to
        # CPU instead of eating the whole capture window.
        from sparkdq4ml_tpu.utils.debug import bounded_backend_init

        bounded_backend_init(150)

    import jax
    import jax.numpy as jnp
    import numpy as np

    import sparkdq4ml_tpu as dq
    from sparkdq4ml_tpu.config import config
    from sparkdq4ml_tpu.models import VectorAssembler
    from sparkdq4ml_tpu.models.classification import fused_logistic_fit_packed
    from sparkdq4ml_tpu.ops import pallas_kernels
    from sparkdq4ml_tpu.parallel.distributed import (fused_linear_fit_packed,
                                                     pack_design, place_packed,
                                                     unpack_fit_result)

    path = os.path.join(REPO, "data", "dataset-full.csv")
    session = dq.TpuSession.builder().app_name("bench").master("local[*]").get_or_create()
    log(f"devices: {jax.devices()}")
    backend = jax.default_backend()
    device_kind = jax.devices()[0].device_kind
    roof = roofline_for(device_kind)
    is_tpu = backend == "tpu" or device_kind.lower().startswith("tpu")

    # ---- build the DQ-cleaned frame (no host reads of device arrays) ----
    dq.register_builtin_rules()
    df = (session.read.format("csv").option("inferSchema", "true")
          .option("header", "false").load(path))
    df = df.with_column_renamed("_c0", "guest").with_column_renamed("_c1", "price")
    df = df.with_column("price_no_min", dq.call_udf("minimumPriceRule", dq.col("price")))
    df.create_or_replace_temp_view("price")
    df = session.sql("SELECT cast(guest as int) guest, price_no_min AS price "
                     "FROM price WHERE price_no_min > 0")
    df = df.with_column("price_correct_correl",
                        dq.call_udf("priceCorrelationRule", dq.col("price"), dq.col("guest")))
    df.create_or_replace_temp_view("price")
    df = session.sql("SELECT guest, price_correct_correl AS price "
                     "FROM price WHERE price_correct_correl > 0")
    df = df.with_column("label", df.col("price"))
    df = VectorAssembler(["guest"], "features").transform(df)

    X = jnp.asarray(df._column_values("features"))
    y = jnp.asarray(df._column_values("label"))
    mask = df.mask
    mesh = None if session.mesh.devices.size <= 1 else session.mesh
    Zd = place_packed(pack_design(X, y, mask), mesh)

    # =====================================================================
    # PHASE 1 — every device timing loop, before ANY device→host read
    # =====================================================================

    median_time = make_median_time(jax)

    # (a) headline: Lasso fit, one packed dispatch
    fit_a = fused_linear_fit_packed(mesh, "fista", 40, 1e-6, True, True)
    hyper_a = jnp.asarray([1.0, 1.0], Zd.dtype)
    result_a = jax.block_until_ready(fit_a(Zd, hyper_a))
    t_a = median_time(lambda: fit_a(Zd, hyper_a), REPS)

    # (c) elastic-net general path (FISTA, mixed penalty, 100 iters)
    fit_c = fused_linear_fit_packed(mesh, "fista", 100, 1e-6, True, True)
    hyper_c = jnp.asarray([0.3, 0.5], Zd.dtype)
    t_c = median_time(lambda: fit_c(Zd, hyper_c), REPS)

    # (d) logistic on DQ rows: per-iteration psum loop. hyper has no L1
    # part, so the production router (LogisticRegression.fit) picks the
    # damped-Newton solver — bench the same program users get.
    yb = (y > jnp.median(y)).astype(Zd.dtype)   # device-side label build
    Zb = place_packed(pack_design(X, yb, mask), mesh)
    fit_d = fused_logistic_fit_packed(mesh, 100, 1e-6, True, True,
                                      solver="newton")
    hyper_d = jnp.asarray([0.01, 0.0], Zd.dtype)
    result_d = jax.block_until_ready(fit_d(Zb, hyper_d))  # iters read later
    t_d = median_time(lambda: fit_d(Zb, hyper_d), REPS)

    # (d_scale) logistic at 1e6×16: the regime config (d) cannot show on
    # 1024 rows — here the fused on-device loop (zero host barriers, MXU
    # matmuls) is measured against sklearn lbfgs on the same shape.
    n_ds, d_ds = (100_000, 16) if SMOKE else (1_000_000, 16)
    Xds = jax.random.normal(jax.random.PRNGKey(7), (n_ds, d_ds), jnp.float32)
    w_true = jax.random.normal(jax.random.PRNGKey(8), (d_ds,), jnp.float32)
    noise = 0.5 * jax.random.normal(jax.random.PRNGKey(9), (n_ds,),
                                    jnp.float32)
    yds = (Xds @ w_true + noise > 0).astype(jnp.float32)
    Zds = jax.block_until_ready(place_packed(
        pack_design(Xds, yds, jnp.ones((n_ds,), jnp.float32)), mesh))
    del Xds, yds, noise
    fit_ds = fused_logistic_fit_packed(mesh, 100, 1e-6, True, True,
                                       solver="newton")
    result_ds = jax.block_until_ready(fit_ds(Zds, hyper_d))  # iters read later
    t_ds = median_time(lambda: fit_ds(Zds, hyper_d), max(3, REPS // 6))

    # (dq) the fused rules+filter pass — the reference's UDF hot loop
    # (`App.java:68-95`) as ONE elementwise device pass
    from sparkdq4ml_tpu.ops.rules import dq_rules_fused

    n_dq = 100_000 if SMOKE else 1_000_000
    price_dq = jax.random.uniform(jax.random.PRNGKey(3), (n_dq,),
                                  jnp.float32, 1.0, 120.0)
    guest_dq = jax.random.randint(jax.random.PRNGKey(4), (n_dq,),
                                  1, 40).astype(jnp.float32)
    fused_rules_fn = jax.jit(dq_rules_fused)
    t_rules = median_time(lambda: fused_rules_fn(price_dq, guest_dq), REPS)

    # (e) CrossValidator grid: the fused device-complete CV program
    from sparkdq4ml_tpu.models import LinearRegression
    from sparkdq4ml_tpu.models.evaluation import RegressionEvaluator
    from sparkdq4ml_tpu.models.tuning import (ParamGridBuilder,
                                              cv_device_program)

    grid_reg, grid_en, folds = [0.1, 0.5, 1.0], [0.0, 0.5, 1.0], 3
    grid = (ParamGridBuilder().add_grid("reg_param", grid_reg)
            .add_grid("elastic_net_param", grid_en).build())
    cv_prog, cv_args, _, _ = cv_device_program(
        df, LinearRegression(max_iter=40, tol=1e-6), grid, "rmse", folds,
        7, mesh, RegressionEvaluator("rmse").is_larger_better())
    t_e = median_time(lambda: cv_prog(*cv_args), REPS)

    # (sweep) masked-Gramian pass: XLA vs compiled Pallas, data on device
    @jax.jit
    def xla_gram(Z):
        return Z.T @ Z

    # bf16-STORED variant: rows live in HBM at half the bytes and the MXU
    # is bf16-native; accumulation stays f32 (preferred_element_type)
    @jax.jit
    def xla_gram_bf16(Zh):
        return jax.lax.dot_general(
            Zh, Zh, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    sweep_rows = []        # timings (host floats, no device reads)
    pallas_diffs = []      # on-device |A_p - A_x| max scalars, read later
    pallas_mode = "on" if is_tpu else "interpret"
    for (n, d) in SWEEP_SHAPES:
        key = jax.random.PRNGKey(n + d)
        Z = jax.random.normal(key, (n, d + 2), jnp.float32)
        Z = jax.block_until_ready(Z)
        gb = n * (d + 2) * 4 / 1e9

        t_x = median_time(lambda: xla_gram(Z), SWEEP_REPS)

        # bf16-stored Gramian is gated to TPU captures (VERDICT r4 item 6):
        # the variant exists for the MXU (bf16-native) + halved HBM bytes;
        # on CPU it measures only a conversion penalty (r4: 0.29–0.81×),
        # which read as a defect rather than a chip-only optimization.
        t_h = None
        if is_tpu:
            Zh = jax.block_until_ready(Z.astype(jnp.bfloat16))
            t_h = median_time(lambda: xla_gram_bf16(Zh), SWEEP_REPS)
            gb_h = n * (d + 2) * 2 / 1e9
            del Zh

        t_p = None
        best_block = None
        # Off-TPU the Pallas interpreter executes element-by-element — the
        # numerics cross-check at full sweep sizes would run for hours, so
        # it only runs compiled (TPU) or on the SMOKE shapes.
        if is_tpu or SMOKE:
            config.pallas = pallas_mode
            try:
                A_p = pallas_kernels.packed_gram_pallas(Z)
                if is_tpu:
                    # Row-tile autotune: bigger tiles amortize grid/DMA
                    # overhead; all candidates fit VMEM double-buffered.
                    for blk in (512, 1024, 2048, 4096):
                        if blk > n:
                            continue
                        t_b = median_time(
                            lambda: pallas_kernels.packed_gram_pallas(
                                Z, block_rows=blk), SWEEP_REPS)
                        if t_p is None or t_b < t_p:
                            t_p, best_block = t_b, blk
                A_x = xla_gram(Z)
                scale = jnp.maximum(jnp.max(jnp.abs(A_x)), 1.0)
                pallas_diffs.append(
                    ((n, d), jnp.max(jnp.abs(A_p - A_x)) / scale))
            finally:
                config.pallas = "off"

        sweep_rows.append({
            "rows": n, "features": d,
            "xla_ms": round(t_x * 1e3, 3),
            "xla_gbps": round(gb / t_x, 1),
            "bf16_ms": round(t_h * 1e3, 3) if t_h else None,
            "bf16_gbps": round(gb_h / t_h, 1) if t_h else None,
            "bf16_rows_speedup": round(t_x / t_h, 2) if t_h else None,
            "pallas_ms": round(t_p * 1e3, 3) if t_p else None,
            "pallas_gbps": round(gb / t_p, 1) if t_p else None,
            "pallas_block": best_block,
        })
        del Z

    # =====================================================================
    # PHASE 2 — host reads, CPU baselines, assertions
    # =====================================================================
    n_rows = df.count()
    log(f"DQ-clean rows: {n_rows} (expect 1024)")
    result = unpack_fit_result(result_a, 1)
    coef = float(result.coefficients[0])
    intercept = float(result.intercept)
    d_host = df.to_pydict()
    yv = d_host["label"].astype(np.float64)
    xv = d_host["guest"].astype(np.float64)
    rmse = float(np.sqrt(np.mean((yv - (coef * xv + intercept)) ** 2)))
    drift = abs(rmse - GOLDEN_RMSE_FULL) / GOLDEN_RMSE_FULL
    log(f"fit: coef={coef:.6f} intercept={intercept:.6f} rmse={rmse:.6f} "
        f"drift={drift*100:.4f}% (budget 1%)")
    if drift > 0.01:
        log("ERROR: RMSE drift exceeds the 1% acceptance budget")
        sys.exit(1)

    # pallas numerics: assert before reporting any pallas number
    for (shape, diff_dev) in pallas_diffs:
        diff = float(diff_dev)
        log(f"pallas-vs-xla rel diff @ {shape}: {diff:.2e}")
        if not diff < 5e-5:
            log(f"ERROR: pallas Gramian diverges from XLA at {shape}")
            sys.exit(1)

    # CPU baselines --------------------------------------------------------
    # sklearn is a strictly faster Spark-CPU proxy; without it, a pure-numpy
    # ISTA stands in for (a) and c/d report no baseline rather than dying
    # (the driver contract — one JSON line — must survive a missing dep).
    Xh = xv.reshape(-1, 1)
    sx, sy = Xh.std(ddof=1), yv.std(ddof=1)
    Xs = (Xh - Xh.mean()) / sx
    ys = (yv - yv.mean()) / sy
    yb_h = (yv > np.median(yv)).astype(np.float64)

    try:
        from sklearn.linear_model import (ElasticNet, Lasso,
                                          LogisticRegression as SkLogit)
        have_sklearn = True
    except ImportError:
        have_sklearn = False

    sk_iters_d = None
    sk_iters_ds = None
    t_ds_cpu = None
    if have_sklearn:
        base_a = "sklearn Lasso(cd) maxIter=40"
        t_a_cpu = median_time(
            lambda: Lasso(alpha=1.0 / sy, max_iter=40, tol=1e-6).fit(Xs, ys),
            REPS)
        t_c_cpu = median_time(
            lambda: ElasticNet(alpha=0.3 / sy, l1_ratio=0.5, max_iter=100,
                               tol=1e-6).fit(Xs, ys), REPS)
        t_d_cpu = median_time(
            lambda: SkLogit(C=100.0, max_iter=100, tol=1e-6).fit(Xs, yb_h),
            REPS)
        sk_iters_d = int(np.ravel(SkLogit(C=100.0, max_iter=100, tol=1e-6)
                                  .fit(Xs, yb_h).n_iter_)[0])

        # d_scale baseline: same shape/regime, independent draw (the
        # comparison is solver-vs-solver on the task family, not bitwise)
        rng_ds = np.random.default_rng(11)
        Xh_ds = rng_ds.standard_normal((n_ds, d_ds)).astype(np.float64)
        wh = rng_ds.standard_normal(d_ds)
        yh_ds = (Xh_ds @ wh + 0.5 * rng_ds.standard_normal(n_ds) > 0
                 ).astype(np.float64)
        est_ds = SkLogit(C=100.0, max_iter=100, tol=1e-6)
        t_ds_cpu = median_time(lambda: est_ds.fit(Xh_ds, yh_ds), 3)
        # n_iter_ read off the last timed fit — a dedicated fourth fit
        # would add a full t_ds_cpu to every capture for one integer
        sk_iters_ds = int(np.ravel(est_ds.n_iter_)[0])
        del Xh_ds
    else:
        base_a = "numpy ISTA maxIter=40"

        def ista():
            w = 0.0
            h = float(Xs[:, 0] @ Xs[:, 0]) / len(ys)
            c0 = float(Xs[:, 0] @ ys) / len(ys)
            lam = 1.0 / sy
            for _ in range(40):
                g = h * w - c0
                w = np.sign(w - g / h) * max(abs(w - g / h) - lam / h, 0.0)

        t_a_cpu = median_time(ista, REPS)
        t_c_cpu = t_d_cpu = None

    # CPU gram GB/s context for the sweep's smaller cells
    for row in sweep_rows:
        shape = (row["rows"], row["features"])
        if shape in CPU_SWEEP_SHAPES:
            rng = np.random.default_rng(0)
            Zc = rng.standard_normal((shape[0], shape[1] + 2),
                                     dtype=np.float32)
            t_cpu = median_time(lambda: Zc.T @ Zc, SWEEP_REPS)
            row["cpu_gbps"] = round(
                shape[0] * (shape[1] + 2) * 4 / 1e9 / t_cpu, 1)

    # (dq) numpy baseline for the fused rules pass — the vectorized-host
    # equivalent of the reference's per-row UDF chain
    rng_dq = np.random.default_rng(12)
    ph = rng_dq.uniform(1.0, 120.0, n_dq).astype(np.float32)
    gh = rng_dq.integers(1, 40, n_dq).astype(np.float32)

    def np_rules():
        pnm = np.where(ph < 20, -1.0, ph)
        pcc = np.where((gh < 14) & (ph > 90), -1.0, ph)
        return pnm, pcc, (pnm > 0) & (pcc > 0)

    t_rules_cpu = median_time(np_rules, REPS)
    # bytes touched: 2 f32 inputs read + 2 f32 outputs + 1 bool written
    rules_bytes = n_dq * (4 * 4 + 1)

    # (dq) CSV parse throughput: native C++ tokenizer vs pure-Python vs
    # pandas on a synthetic (guest,price) file at DQ-bench scale
    import tempfile

    n_csv = 100_000 if SMOKE else 1_000_000
    # unique per run: a fixed name would let concurrent benches race on
    # write/parse/remove
    csv_fd, csv_path = tempfile.mkstemp(prefix=f"dq_bench_{n_csv}_",
                                        suffix=".csv")
    rng_csv = np.random.default_rng(13)
    guests_csv = rng_csv.integers(1, 40, n_csv)
    prices_csv = np.round(rng_csv.uniform(1.0, 120.0, n_csv), 2)
    with os.fdopen(csv_fd, "w") as f:
        f.write("\n".join(f"{g},{p}" for g, p in
                          zip(guests_csv, prices_csv)))
        f.write("\n")
    csv_bytes = os.path.getsize(csv_path)

    from sparkdq4ml_tpu.frame import native_csv
    from sparkdq4ml_tpu.frame.csv import read_csv

    t_parse_native = None
    if native_csv.available():
        t_parse_native = median_time(
            lambda: read_csv(csv_path, engine="native"), 3)
    # the pure-python engine is O(seconds) at 1e6 rows, and a host parser
    # has no compile cache to warm: ONE direct timed run, no warmup rep
    t0 = time.perf_counter()
    read_csv(csv_path, engine="python")
    t_parse_py = time.perf_counter() - t0
    t_parse_pandas = None
    try:
        import pandas as pd

        t_parse_pandas = median_time(
            lambda: pd.read_csv(csv_path, header=None), 3)
    except ImportError:
        pass
    try:
        os.remove(csv_path)   # ~15 MB of /tmp litter otherwise
    except OSError:
        pass

    # (e) baseline: sklearn GridSearchCV, same 3x3 grid / folds / family,
    # refit=True to match the in-program best-model refit
    t_e_cpu = None
    if have_sklearn:
        from sklearn.model_selection import GridSearchCV

        def cpu_grid():
            GridSearchCV(ElasticNet(max_iter=40, tol=1e-6),
                         {"alpha": [r / sy for r in grid_reg],
                          "l1_ratio": grid_en},
                         cv=folds, scoring="neg_root_mean_squared_error",
                         n_jobs=1, refit=True).fit(Xs, ys)

        t_e_cpu = median_time(cpu_grid, REPS)

    # =====================================================================
    # PHASE 3 — report
    # =====================================================================
    def cfg(name, t_dev, baseline_name, t_cpu, **extra):
        out = {"config": name, "device_ms": round(t_dev * 1e3, 4),
               "baseline": baseline_name if t_cpu else "unavailable",
               "baseline_ms": round(t_cpu * 1e3, 4) if t_cpu else None,
               "vs_baseline": round(t_cpu / t_dev, 2) if t_cpu else None}
        out.update(extra)
        return out

    # Config (d) has never cleared 10× on 1024 rows and the reason is
    # structural, not a bug: report it instead of hiding it.
    iters_d = int(unpack_fit_result(np.asarray(result_d), 1).iterations)
    sk_clause = (f"vs sklearn lbfgs converging in {sk_iters_d} iterations"
                 if sk_iters_d is not None else
                 "(no sklearn baseline available)")
    analysis_d = (
        f"device runs {iters_d} damped-Newton iterations inside one fused "
        f"dispatch {sk_clause} on 1024 rows; at this size wall-clock is "
        f"bounded by per-dispatch overhead, not FLOPs — see "
        f"d_scale_logistic for the regime where the fused loop wins")

    # d_scale: close the argument with iteration-level numbers (VERDICT r4
    # item 3). CPU-vs-CPU the honest finding is parity: XLA-CPU's fused
    # damped-Newton and sklearn's lbfgs both converge in a handful of
    # iterations at 1e6×16 and both are memory-bound on the same host, so
    # neither side has a structural edge. The fused loop's claimed win —
    # zero per-iteration host barriers (vs treeAggregate, SURVEY §3.3) and
    # MXU matmuls — only materializes on the chip.
    iters_ds = int(unpack_fit_result(np.asarray(result_ds), d_ds).iterations)
    dev_ms_it = t_ds * 1e3 / max(iters_ds, 1)
    if t_ds_cpu is not None and sk_iters_ds is not None:
        cpu_ms_it = t_ds_cpu * 1e3 / max(sk_iters_ds, 1)
        ds_cpu_clause = (f"sklearn lbfgs: {sk_iters_ds} iterations × "
                         f"{cpu_ms_it:.1f} ms/iter")
    else:
        ds_cpu_clause = "no sklearn baseline available"
    if is_tpu:
        analysis_ds = (
            f"on-chip capture: fused damped-Newton runs {iters_ds} "
            f"iterations × {dev_ms_it:.1f} ms/iter in one dispatch "
            f"(zero host barriers) vs {ds_cpu_clause} on the host CPU")
    else:
        analysis_ds = (
            f"CPU-vs-CPU this is parity, not a win: XLA-CPU fused Newton "
            f"({iters_ds} iterations × {dev_ms_it:.1f} ms/iter, one "
            f"dispatch) vs {ds_cpu_clause}; both are memory-bound on the "
            f"same cores. The fused loop's claimed advantage — eliminating "
            f"the per-iteration host barrier (treeAggregate analogue, "
            f"SURVEY §3.3) and MXU-resident matmuls — requires the chip; "
            f"no on-chip number exists in this capture")

    configs = [
        cfg("a_linear_lasso_dataset_full", t_a, base_a, t_a_cpu),
        cfg("c_elasticnet_fista_path", t_c,
            "sklearn ElasticNet(cd) maxIter=100", t_c_cpu),
        cfg("d_logistic_dq_rows", t_d,
            "sklearn LogisticRegression(lbfgs) maxIter=100", t_d_cpu,
            analysis=analysis_d),
        cfg(f"d_scale_logistic_{n_ds}x{d_ds}", t_ds,
            f"sklearn LogisticRegression(lbfgs) {n_ds}x{d_ds}", t_ds_cpu,
            analysis=analysis_ds, device_iterations=iters_ds,
            device_ms_per_iter=round(dev_ms_it, 2),
            baseline_iterations=sk_iters_ds,
            baseline_ms_per_iter=round(t_ds_cpu * 1e3 / max(sk_iters_ds, 1),
                                       2)
            if t_ds_cpu is not None and sk_iters_ds else None),
        cfg("e_crossvalidator_grid", t_e,
            f"sklearn GridSearchCV(ElasticNet) {len(grid)}x{folds} refit",
            t_e_cpu),
        cfg(f"dq_rules_fused_{n_dq}", t_rules,
            f"numpy vectorized rules {n_dq}", t_rules_cpu,
            device_gbps=round(rules_bytes / t_rules / 1e9, 2),
            baseline_gbps=round(rules_bytes / t_rules_cpu / 1e9, 2)),
    ]
    parse_cfg = {
        "config": f"dq_parse_csv_{n_csv}",
        "file_mb": round(csv_bytes / 1e6, 1),
        "native_ms": round(t_parse_native * 1e3, 1) if t_parse_native
        else None,
        "native_gbps": round(csv_bytes / t_parse_native / 1e9, 3)
        if t_parse_native else None,
        "python_ms": round(t_parse_py * 1e3, 1),
        "python_gbps": round(csv_bytes / t_parse_py / 1e9, 3),
        "pandas_ms": round(t_parse_pandas * 1e3, 1) if t_parse_pandas
        else None,
        "pandas_gbps": round(csv_bytes / t_parse_pandas / 1e9, 3)
        if t_parse_pandas else None,
        "native_vs_python": round(t_parse_py / t_parse_native, 2)
        if t_parse_native else None,
        # The VERDICT-r4 cycle budget: where the single-core ns/byte goes.
        # Stage costs measured with a C-level stage harness on this host
        # class (1-core Xeon 2.1 GHz). The parse is bitmap-first: phase A
        # classifies every structural byte (AVX2 compare+movemask, ~24
        # GB/s) into a bitmap that also yields the record count; phase B
        # walks set bits, so each field's ADDRESS comes from the bitmap
        # instead of the previous field's parsed length — the ~20-cycle
        # per-field convert chains (Lemire SWAR digits, exact /10^frac)
        # are independent work the OoO core overlaps. Direct column-major
        # store; integral int32 flags are free for bare-digit fields (a
        # frac==0 word parse is integral by construction). No staging
        # vector, no transpose, no libm calls.
        "analysis": (
            f"{t_parse_native * 1e9 / csv_bytes:.2f} ns/byte end-to-end "
            "(python wrapper incl. one astype copy per column); C stage "
            "budget at ~4.4-byte fields: quote memchr ~0.07 ns/B, "
            "structural bitmap ~0.05, bitmap walk + field converts + "
            "column store ~2.2 — the per-field exact-divide (10^frac) "
            "and store/flag dispatch are the binding cost now that "
            "converts overlap; the next step-change needs batched "
            "multi-field SIMD conversion (AVX-512 class)")
        if t_parse_native else None,
    }
    configs.append(parse_cfg)

    # Roofline fractions (TPU only): achieved ÷ chip peak per sweep cell.
    # mfu uses the bf16 matmul peak as denominator for the f32 cells too,
    # making their mfu a conservative lower bound (stated in the README).
    if roof is not None:
        hbm_peak, tflops_peak = roof
        for row in sweep_rows:
            n_r, d_r = row["rows"], row["features"]
            flops = 2.0 * n_r * (d_r + 2) ** 2
            row["hbm_frac"] = round(row["xla_gbps"] / hbm_peak, 4)
            row["mfu"] = round(
                flops / (row["xla_ms"] / 1e3) / (tflops_peak * 1e12), 4)
            if row["bf16_ms"] is not None:
                row["bf16_hbm_frac"] = round(row["bf16_gbps"] / hbm_peak, 4)
                row["bf16_mfu"] = round(
                    flops / (row["bf16_ms"] / 1e3) / (tflops_peak * 1e12), 4)
            if row.get("pallas_gbps"):
                row["pallas_hbm_frac"] = round(
                    row["pallas_gbps"] / hbm_peak, 4)

    for c in configs:
        log(json.dumps(c))
    for row in sweep_rows:
        log(json.dumps(row))

    print(json.dumps({
        "metric": "linear_regression_fit_wallclock_dataset_full",
        "value": round(t_a * 1e3, 4),
        "unit": "ms",
        "vs_baseline": round(t_a_cpu / t_a, 3),
        "configs": configs,
        "sweep": sweep_rows,
        "pallas_max_rel_diff": max((float(d) for _, d in pallas_diffs),
                                   default=None),
        "backend": backend,
        "device_kind": device_kind,
        "bf16_gated": None if is_tpu else (
            "bf16-stored Gramian gated to TPU captures: no MXU on this "
            "backend, the variant would measure only a conversion penalty"),
        "roofline": {"hbm_gbps": roof[0], "bf16_tflops": roof[1]}
        if roof else None,
    }))


if __name__ == "__main__":
    main()
