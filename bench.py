"""Benchmark harness (BASELINE.md / BASELINE.json target).

Measures the LinearRegression fit wall-clock on ``dataset-full.csv`` (the
reference's Lasso config: maxIter=40, regParam=1, elasticNetParam=1) on the
available accelerator, against a **measured CPU baseline**: scikit-learn's
coordinate-descent Lasso on the same standardized problem, fit in-process.

The reference publishes no numbers (SURVEY.md §6); a Spark-CPU run is not
possible here (no JVM), so sklearn-CPU is the conservative proxy — it is a
C-optimized solver *without* Spark's per-iteration RPC barriers, JVM boxing,
or task-scheduling overhead, i.e. a strictly faster baseline than the Spark
stack it stands in for. ``vs_baseline`` = baseline_seconds / tpu_seconds
(speedup; target ≥10× per BASELINE.json).

Also verifies the ≤1% RMSE-drift acceptance criterion before reporting.

Prints exactly ONE JSON line on stdout; diagnostics go to stderr.

Measurement hygiene: on the axon-tunneled TPU in this environment, the FIRST
device→host data fetch (``int()``/``float()``/``np.asarray`` on a device
array) permanently switches the process into a synchronous dispatch mode
(~67 ms/call floor afterwards; measured — ``block_until_ready`` alone does
not trigger it). All timing therefore happens BEFORE any host read: warm-up
and the timing loop use only ``block_until_ready``; row counts, RMSE checks,
and result fetches run after the loop.
"""

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

GOLDEN_RMSE_FULL = 1.805140  # SURVEY.md §2.3, dataset-full Lasso
REPS = 30


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def main():
    import jax
    import numpy as np

    import sparkdq4ml_tpu as dq
    from sparkdq4ml_tpu.models import LinearRegression, VectorAssembler
    from sparkdq4ml_tpu.parallel.distributed import (fused_linear_fit_packed,
                                                     pack_design, place_packed,
                                                     unpack_fit_result)

    path = os.path.join(REPO, "data", "dataset-full.csv")
    session = dq.TpuSession.builder().app_name("bench").master("local[*]").get_or_create()
    log(f"devices: {jax.devices()}")

    # DQ pipeline (not benchmarked here; the fit is the BASELINE.json metric)
    dq.register_builtin_rules()
    df = (session.read.format("csv").option("inferSchema", "true")
          .option("header", "false").load(path))
    df = df.with_column_renamed("_c0", "guest").with_column_renamed("_c1", "price")
    df = df.with_column("price_no_min", dq.call_udf("minimumPriceRule", dq.col("price")))
    df.create_or_replace_temp_view("price")
    df = session.sql("SELECT cast(guest as int) guest, price_no_min AS price "
                     "FROM price WHERE price_no_min > 0")
    df = df.with_column("price_correct_correl",
                        dq.call_udf("priceCorrelationRule", dq.col("price"), dq.col("guest")))
    df.create_or_replace_temp_view("price")
    df = session.sql("SELECT guest, price_correct_correl AS price "
                     "FROM price WHERE price_correct_correl > 0")
    df = df.with_column("label", df.col("price"))
    df = VectorAssembler(["guest"], "features").transform(df)

    import jax.numpy as jnp

    # Device arrays throughout — no np.asarray before timing (host-read trap).
    X = jnp.asarray(df._column_values("features"))
    y = jnp.asarray(df._column_values("label"))
    mask = df.mask

    # --- accelerator fit: ONE jitted program (packed Gramian + FISTA loop),
    # the same fused packed path LinearRegression.fit dispatches: one input
    # buffer, one output buffer (per-buffer dispatch cost dominates this
    # problem size — see pack_design). NO device→host fetch may happen
    # before/inside the loop (see module docstring); block_until_ready syncs
    # without reading.
    mesh = None if session.mesh.devices.size <= 1 else session.mesh
    fit_fn = fused_linear_fit_packed(mesh, "fista", 40, 1e-6, True, True)
    Zd = place_packed(pack_design(X, y, mask), mesh)
    hyper = jnp.asarray([1.0, 1.0], Zd.dtype)

    def device_fit():
        return fit_fn(Zd, hyper)

    result = jax.block_until_ready(device_fit())   # compile (excluded; cached after)
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        result = jax.block_until_ready(device_fit())
        times.append(time.perf_counter() - t0)
    tpu_s = statistics.median(times)

    # ---- timing done; host reads are safe from here on --------------------
    n_rows = df.count()
    log(f"DQ-clean rows: {n_rows} (expect 1024)")
    result = unpack_fit_result(result, X.shape[1] if X.ndim > 1 else 1)
    coef = float(result.coefficients[0])
    intercept = float(result.intercept)
    d = df.to_pydict()
    yv = d["label"].astype(np.float64)
    xv = d["guest"].astype(np.float64)
    rmse = float(np.sqrt(np.mean((yv - (coef * xv + intercept)) ** 2)))
    drift = abs(rmse - GOLDEN_RMSE_FULL) / GOLDEN_RMSE_FULL
    log(f"fit: coef={coef:.6f} intercept={intercept:.6f} rmse={rmse:.6f} "
        f"drift={drift*100:.4f}% (budget 1%)")
    if drift > 0.01:
        log("ERROR: RMSE drift exceeds the 1% acceptance budget")
        sys.exit(1)

    # --- CPU baseline: sklearn coordinate-descent Lasso on the same problem
    Xh = np.asarray(d["guest"], np.float64).reshape(-1, 1)
    yh = yv
    sx, sy = Xh.std(ddof=1), yh.std(ddof=1)
    Xs = (Xh - Xh.mean()) / sx
    ys = (yh - yh.mean()) / sy
    try:
        from sklearn.linear_model import Lasso

        def cpu_fit():
            Lasso(alpha=1.0 / sy, max_iter=40, tol=1e-6).fit(Xs, ys)

        baseline_name = "sklearn-cpu Lasso(cd)"
    except ImportError:  # pure-numpy ISTA fallback
        def cpu_fit():
            w = 0.0
            h = float(Xs[:, 0] @ Xs[:, 0]) / len(ys)
            c = float(Xs[:, 0] @ ys) / len(ys)
            lam = 1.0 / sy
            for _ in range(40):
                g = h * w - c
                w = np.sign(w - g / h) * max(abs(w - g / h) - lam / h, 0.0)

        baseline_name = "numpy ISTA"

    cpu_fit()  # warm-up
    cpu_times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        cpu_fit()
        cpu_times.append(time.perf_counter() - t0)
    cpu_s = statistics.median(cpu_times)

    speedup = cpu_s / tpu_s
    log(f"device fit: {tpu_s*1e3:.3f} ms | baseline ({baseline_name}): "
        f"{cpu_s*1e3:.3f} ms | speedup {speedup:.2f}x")

    print(json.dumps({
        "metric": "linear_regression_fit_wallclock_dataset_full",
        "value": round(tpu_s * 1e3, 4),
        "unit": "ms",
        "vs_baseline": round(speedup, 3),
    }))


if __name__ == "__main__":
    main()
