"""Tour of the IO + reshape + pandas-interop surface: CSV (native
tokenizer), JSON, Parquet round-trips, unpivot/melt, applyInPandas /
mapInPandas, and spark.table. Every section asserts its result, so this
doubles as an integration smoke.

Run: python examples/io_tour.py
"""

import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu import functions as F


def main() -> None:
    spark = (dq.TpuSession.builder().app_name("io-tour")
             .master("local[*]").get_or_create())
    data_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "data")
    tmp = tempfile.mkdtemp(prefix="io_tour_")

    # -- CSV in (the reference's own source, native C tokenizer) ----------
    df = (spark.read.format("csv").option("inferSchema", "true")
          .load(os.path.join(data_dir, "dataset-full.csv"))
          .with_column_renamed("_c0", "guest")
          .with_column_renamed("_c1", "price"))
    n = df.count()
    assert n == 1040
    print(f"csv: {n} rows")

    # -- Parquet round-trip ----------------------------------------------
    pq_path = os.path.join(tmp, "inv.parquet")
    df.write.parquet(pq_path)
    back = spark.read.parquet(pq_path)
    assert back.count() == n
    np.testing.assert_allclose(
        np.sort(np.asarray(back.to_pydict()["price"], np.float64)),
        np.sort(np.asarray(df.to_pydict()["price"], np.float64)))
    print(f"parquet: round-trip {back.count()} rows, prices identical")

    # -- JSON round-trip --------------------------------------------------
    js_path = os.path.join(tmp, "inv.jsonl")
    df.limit(100).write.json(js_path)
    jback = spark.read.json(js_path)
    assert jback.count() == 100
    print("json: round-trip 100 rows")

    # -- unpivot / melt ---------------------------------------------------
    wide = df.limit(5).select("guest", "price") \
        .with_column("price2", dq.col("price") * 2)
    long = wide.unpivot("guest", ["price", "price2"], "metric", "amount")
    assert long.count() == 10
    d = long.to_pydict()
    assert list(d["metric"][:2]) == ["price", "price2"]   # row-major
    print("unpivot: 5 wide rows x 2 value cols ->", long.count(), "long rows")

    # -- applyInPandas: per-group demeaning -------------------------------
    def demean(g):
        g = g.copy()
        g["price"] = g["price"] - g["price"].mean()
        return g

    demeaned = (df.group_by("guest")
                .apply_in_pandas(demean, "guest DOUBLE, price DOUBLE"))
    assert demeaned.count() == n
    means = (demeaned.group_by("guest").agg(
        F.avg("price").alias("m")).to_pydict()["m"])
    assert max(abs(float(m)) for m in means) < 1e-3
    print(f"applyInPandas: {n} rows demeaned per guest size "
          f"(max residual mean {max(abs(float(m)) for m in means):.2e})")

    # -- mapInPandas ------------------------------------------------------
    def add_ratio(batches):
        for b in batches:
            b = b.copy()
            b["ratio"] = b["price"] / b["guest"]
            yield b

    with_ratio = df.map_in_pandas(
        add_ratio, "guest DOUBLE, price DOUBLE, ratio DOUBLE")
    assert with_ratio.columns == ["guest", "price", "ratio"]
    print("mapInPandas: ratio column added,", with_ratio.count(), "rows")

    # -- spark.table ------------------------------------------------------
    df.create_or_replace_temp_view("inv")
    assert spark.table("inv").count() == n
    spark.catalog.drop("inv")
    print("spark.table: view round-trip OK")

    shutil.rmtree(tmp, ignore_errors=True)
    spark.stop()
    print("io_tour OK")


if __name__ == "__main__":
    main()
