"""Port of the reference application
(`DataQuality4MachineLearningApp.java:28-155`) to the TPU-native framework —
same phases, same banners, same observable outputs: session init, UDF
registration, CSV load (bare-CR), two DQ rules + SQL cleanups, label column,
VectorAssembler, Lasso LinearRegression (maxIter=40, regParam=1,
elasticNetParam=1), transform/show, training summary, and the prediction for
40 guests.

Run:  python examples/dq4ml_pipeline.py [path/to/dataset.csv]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu.models import LinearRegression, Vectors, VectorAssembler
from sparkdq4ml_tpu.utils import PhaseTimer, configure_logging


def start(filename: str) -> None:
    timer = PhaseTimer()

    # Session init (`App.java:38-41`): device discovery + mesh construction
    # replaces the driver JVM / executor pool.
    spark = dq.TpuSession.builder().app_name("DQ4ML").master("local[*]").get_or_create()

    # DQ Section (`App.java:44-95`)
    # ----------
    spark.udf.register("minimumPriceRule", dq.minimum_price_rule, "double")
    spark.udf.register("priceCorrelationRule", dq.price_correlation_rule, "double")

    def load_phase():
        return (spark.read.format("csv")
                .option("inferSchema", "true").option("header", "false")
                .load(filename))

    with timer.phase("load"):
        df = load_phase()

    df = df.with_column_renamed("_c0", "guest")
    df = df.with_column_renamed("_c1", "price")

    print("----")
    print("Load & Format")
    df.show()
    print("----")

    def dq_phase(d, show=False):
        d = d.with_column("price_no_min",
                          dq.call_udf("minimumPriceRule", d.col("price")))
        if show:
            print("----")
            print("1st DQ rule")
            d.print_schema()
            d.show(50)
            print("----")

        d.create_or_replace_temp_view("price")
        d = spark.sql("SELECT cast(guest as int) guest, price_no_min AS price "
                      "FROM price WHERE price_no_min > 0")
        if show:
            print("----")
            print("1st DQ rule - clean-up")
            d.print_schema()
            d.show(50)
            print("----")

        d = d.with_column("price_correct_correl",
                          dq.call_udf("priceCorrelationRule",
                                      d.col("price"), d.col("guest")))
        d.create_or_replace_temp_view("price")
        return spark.sql("SELECT guest, price_correct_correl AS price "
                         "FROM price WHERE price_correct_correl > 0")

    df_loaded = df
    with timer.phase("dq_rules"):
        df = dq_phase(df_loaded, show=True)

    print("----")
    print("2nd DQ rule")
    df.show(50)
    print("----")

    # ML Section (`App.java:98-126`)
    # ----------
    df = df.with_column("label", df.col("price"))

    assembler = VectorAssembler().setInputCols(["guest"]).setOutputCol("features")
    df = assembler.transform(df)
    df.print_schema()
    df.show()

    lr = LinearRegression().setMaxIter(40).setRegParam(1).setElasticNetParam(1)

    with timer.phase("fit"):
        model = lr.fit(df)

    # Steady-state re-runs against the XLA compile cache (the cold numbers
    # above are compile-dominated; conflating the two misleads). "fit" here
    # is the full API call — it materializes the model, so it INCLUDES
    # device→host fetches; bench.py reports the device-only dispatch figure.
    timer.steady("load", load_phase, sync=lambda f: f.mask)
    timer.steady("dq_rules", lambda: dq_phase(df_loaded),
                 sync=lambda f: f.mask)
    timer.steady("fit", lambda: lr.fit(df))

    model.transform(df).show()

    # Summary (`App.java:132-146`)
    trainingSummary = model.summary
    print("numIterations: " + str(trainingSummary.totalIterations))
    print("objectiveHistory: [" +
          ",".join(str(v) for v in trainingSummary.objectiveHistory) + "]")
    trainingSummary.residuals.show()
    print("RMSE: " + str(trainingSummary.rootMeanSquaredError))
    print("r2: " + str(trainingSummary.r2))

    print("Intersection: " + str(model.intercept))
    print("Regression parameter: " + str(model.getRegParam()))
    print("Tol: " + str(model.getTol()))

    # Prediction (`App.java:148-154`)
    feature = 40.0
    features = Vectors.dense(40.0)
    p = model.predict(features)
    print(f"Prediction for {feature} guests is {p}")

    pairs = timer.report_pairs()
    print("phase wall-clock (s, cold = first run incl. XLA compile):",
          {k: {m: (round(v, 4) if v is not None else None)
               for m, v in p.items()} for k, p in pairs.items()})

    # Pipeline-compiler telemetry (README § "Pipeline compiler & jit
    # cache"): steady-state reruns should show `compile` frozen while
    # `flush`/`hit` climb — cache reuse across the repeated DQ queries.
    from sparkdq4ml_tpu.utils.profiling import counters
    print("pipeline counters:", counters.snapshot("pipeline"))


if __name__ == "__main__":
    configure_logging()
    default = os.path.join(os.path.dirname(__file__), "..", "data",
                           "dataset-abstract.csv")
    start(sys.argv[1] if len(sys.argv) > 1 else default)
