"""Tour of the SQL + frame engine on the reference's own data: temp views,
SELECT/CAST/WHERE (the reference's DQ cleanups, `App.java:76-90`), GROUP BY
+ HAVING, JOIN, window functions (fluent and SQL OVER), explode, selectExpr,
and the df.na accessor. Every section asserts its result, so this doubles as
an integration smoke.

Run: python examples/sql_tour.py [csv_path]   (defaults to data/dataset-full.csv)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu import functions as F
from sparkdq4ml_tpu.frame.window import Window
from sparkdq4ml_tpu.ops.expressions import Col


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "data", "dataset-full.csv")
    spark = (dq.TpuSession.builder().app_name("sql-tour")
             .master("local[*]").get_or_create())

    # -- load + the reference's own SQL cleanups --------------------------
    df = (spark.read.format("csv").option("inferSchema", "true")
          .load(path)
          .with_column_renamed("_c0", "guest").with_column_renamed("_c1", "price"))
    df.create_or_replace_temp_view("inventory")
    n_raw = df.count()

    clean = spark.sql(
        "SELECT CAST(guest AS INT) AS guest, CAST(price AS DOUBLE) AS price "
        "FROM inventory WHERE price > 0 AND guest > 0")
    print(f"rows: raw={n_raw} clean={clean.count()}")
    assert clean.count() <= n_raw
    clean.create_or_replace_temp_view("clean")

    # -- aggregation: GROUP BY + HAVING -----------------------------------
    busy = spark.sql(
        "SELECT guest, COUNT(*) AS n, AVG(price) AS avg_price FROM clean "
        "GROUP BY guest HAVING COUNT(*) > 10 ORDER BY guest")
    print("guests with >10 bookings:")
    busy.show(5)
    n_col = dict(busy.to_pydict())["n"]
    assert all(int(v) > 10 for v in n_col)

    # the same aggregate through the fluent API must agree
    fluent = (clean.group_by("guest")
              .agg(F.count().alias("n"), F.avg("price").alias("avg_price"))
              .filter(Col("n") > 10).sort("guest"))
    assert fluent.count() == busy.count()

    # -- join: price vs the per-guest average -----------------------------
    busy.create_or_replace_temp_view("busy")
    joined = spark.sql(
        "SELECT guest, price, avg_price FROM clean "
        "JOIN busy USING (guest)")
    assert joined.count() > 0
    over = joined.filter(Col("price") > Col("avg_price")).count()
    print(f"bookings above their guest-size average: {over}/{joined.count()}")

    # -- window functions: fluent + SQL OVER agree ------------------------
    w = Window.partition_by("guest").order_by("price")
    ranked = clean.with_column("rk", F.dense_rank().over(w)) \
                  .with_column("prev", F.lag("price", 1).over(w))
    sql_ranked = spark.sql(
        "SELECT guest, price, "
        "DENSE_RANK() OVER (PARTITION BY guest ORDER BY price) AS rk "
        "FROM clean")
    a = sorted(map(tuple, zip(*[np.asarray(v, np.float64) for v in
                                (ranked.to_pydict()["guest"],
                                 ranked.to_pydict()["rk"])])))
    b = sorted(map(tuple, zip(*[np.asarray(v, np.float64) for v in
                                (sql_ranked.to_pydict()["guest"],
                                 sql_ranked.to_pydict()["rk"])])))
    assert a == b
    print("window: fluent dense_rank == SQL OVER dense_rank "
          f"({len(a)} rows)")

    # -- selectExpr + na accessor -----------------------------------------
    feat = clean.select_expr("guest", "price",
                             "price / guest AS price_per_guest")
    assert feat.columns == ["guest", "price", "price_per_guest"]
    assert feat.na.drop().count() == feat.count()  # no nulls after DQ
    print("selectExpr price_per_guest head:",
          [round(float(r[2]), 2) for r in feat.take(3)])

    # -- explode a split array --------------------------------------------
    pair = clean.limit(3).select_expr(
        "guest", "concat_ws(',', guest, price) AS s")
    exploded = pair.select(
        "guest", F.explode(F.split(F.col("s"), ",")).alias("v"))
    assert exploded.count() == 2 * pair.count()
    print("explode: 3 rows x split-array(2) ->", exploded.count(), "rows")

    # -- CTEs + uncorrelated subqueries -----------------------------------
    premium = spark.sql(
        "WITH stats AS (SELECT avg(price) AS ap FROM clean) "
        "SELECT guest, price FROM clean "
        "WHERE price > (SELECT ap FROM stats) ORDER BY price DESC LIMIT 5")
    mean_price = float(np.mean(clean.to_pydict()["price"]))
    assert all(float(p) > mean_price
               for p in premium.to_pydict()["price"])
    print("CTE + scalar subquery: top-5 above-average prices:",
          [round(float(p), 1) for p in premium.to_pydict()["price"]])

    # LEFT SEMI agrees with IN (subquery) — the rewrite Spark itself does
    semi = spark.sql("SELECT price FROM clean LEFT SEMI JOIN busy "
                     "USING (guest)")
    in_sub = spark.sql("SELECT price FROM clean "
                       "WHERE guest IN (SELECT guest FROM busy)")
    assert semi.count() == in_sub.count()
    print(f"semi-join == IN(subquery): {semi.count()} rows both ways")

    # -- derived table + ORDER BY aggregate -------------------------------
    spread = spark.sql(
        "SELECT guest, max(price) - min(price) AS spread "
        "FROM (SELECT guest, price FROM clean WHERE guest > 1) g "
        "GROUP BY guest ORDER BY max(price) - min(price) DESC LIMIT 3")
    s_vals = [float(v) for v in spread.to_pydict()["spread"]]
    assert s_vals == sorted(s_vals, reverse=True)
    print("derived table + ORDER BY agg: top spreads:", s_vals)

    # -- window value functions -------------------------------------------
    fv = spark.sql(
        "SELECT guest, price, first_value(price) OVER "
        "(PARTITION BY guest ORDER BY price) AS cheapest FROM clean")
    d = fv.to_pydict()
    by_guest: dict = {}
    for g, p in zip(d["guest"].tolist(), d["price"].tolist()):
        by_guest[g] = min(by_guest.get(g, p), p)
    assert all(float(c) == by_guest[g]
               for g, c in zip(d["guest"].tolist(), d["cheapest"].tolist()))
    print("first_value OVER: per-guest cheapest verified on",
          len(by_guest), "guests")

    # -- SQL DDL ----------------------------------------------------------
    spark.sql("CREATE OR REPLACE TEMP VIEW premium AS "
              "SELECT guest, price FROM clean WHERE price > 90")
    assert spark.catalog.table_exists("premium")
    n_premium = spark.sql("SELECT count(*) AS n FROM premium") \
        .to_pydict()["n"][0]
    spark.sql("DROP VIEW premium")
    assert not spark.catalog.table_exists("premium")
    print(f"DDL: CREATE TEMP VIEW ({n_premium} rows) + DROP round-trip")

    spark.stop()
    print("sql_tour OK")


if __name__ == "__main__":
    main()
