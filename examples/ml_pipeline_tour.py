"""Tour of the wider ML surface on the reference's own data: DQ pipeline →
train/test split → Pipeline(assembler → Lasso) → persistence round-trip →
cross-validated grid search → logistic classifier on a derived label.

Run: python examples/ml_pipeline_tour.py [csv_path]
(defaults to data/dataset-full.csv; golden numbers in SURVEY.md §2.3)
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu.models import (BinaryClassificationEvaluator,
                                   CrossValidator, LinearRegression,
                                   LogisticRegression, ParamGridBuilder,
                                   Pipeline, PipelineModel,
                                   RegressionEvaluator, VectorAssembler)


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "data", "dataset-full.csv")

    session = (dq.TpuSession.builder().app_name("ml-tour")
               .master("local[*]").get_or_create())
    dq.register_builtin_rules()

    # --- DQ phase (the reference's cleanup chain, SURVEY.md §3.2) ----------
    df = (session.read.format("csv").option("inferSchema", "true")
          .option("header", "false").load(path))
    df = (df.with_column_renamed("_c0", "guest")
            .with_column_renamed("_c1", "price"))
    df = df.with_column("price_no_min",
                        dq.call_udf("minimumPriceRule", dq.col("price")))
    df.create_or_replace_temp_view("price")
    df = session.sql("SELECT cast(guest as int) guest, price_no_min AS price "
                     "FROM price WHERE price_no_min > 0")
    df = df.with_column(
        "price_correct_correl",
        dq.call_udf("priceCorrelationRule", dq.col("price"), dq.col("guest")))
    df.create_or_replace_temp_view("price")
    df = session.sql("SELECT guest, price_correct_correl AS price "
                     "FROM price WHERE price_correct_correl > 0")
    df = df.with_column("label", df.col("price"))
    assert df.count() == 1024          # golden DQ count (SURVEY §2.3)
    print(f"DQ-clean rows: {df.count()}")

    # --- train/test split + Pipeline fit -----------------------------------
    train, test = df.random_split([0.8, 0.2], seed=7)
    pipe = Pipeline([
        VectorAssembler(["guest"], "features"),
        LinearRegression(max_iter=40, reg_param=1.0, elastic_net_param=1.0),
    ])
    model = pipe.fit(train)
    rmse = RegressionEvaluator(metric_name="rmse").evaluate(
        model.transform(test))
    assert rmse < 4.0                  # ~1.77 measured; wide margin
    print(f"held-out RMSE (train {train.count()} / test {test.count()}): "
          f"{rmse:.4f}")

    # --- persistence round-trip --------------------------------------------
    with tempfile.TemporaryDirectory() as tmp:
        ckpt = os.path.join(tmp, "pipeline_model")
        model.save(ckpt)
        restored = PipelineModel.load(ckpt)
        r2 = RegressionEvaluator(metric_name="r2").evaluate(
            restored.transform(test))
        assert r2 > 0.99               # persistence must not drift
        print(f"restored model r2 on test: {r2:.4f}")

    # --- cross-validated grid over (regParam x elasticNetParam) ------------
    grid = (ParamGridBuilder()
            .add_grid("reg_param", [0.01, 0.1, 1.0])
            .add_grid("elastic_net_param", [0.0, 0.5, 1.0]).build())
    fdf = VectorAssembler(["guest"], "features").transform(df)
    cv = CrossValidator(LinearRegression(max_iter=40), grid,
                        RegressionEvaluator(metric_name="rmse"), num_folds=3)
    cv_model = cv.fit(fdf)
    best = cv_model.best_index
    assert cv_model.avg_metrics[best] < 3.0
    print(f"CV best params: {grid[best]}  avg RMSE {cv_model.avg_metrics[best]:.4f}")

    # --- logistic classifier: is this a "large party" booking? -------------
    ldf = fdf.with_column("label", (fdf.col("guest") > 25).cast("double"))
    lmodel = LogisticRegression(max_iter=50, reg_param=0.01).fit(ldf)
    auc = BinaryClassificationEvaluator().evaluate(lmodel.transform(ldf))
    assert auc > 0.99                  # separable threshold labels
    print(f"large-party classifier AUC: {auc:.4f} "
          f"(iterations: {lmodel.summary.total_iterations})")

    # --- the wider model zoo on the same catering data ----------------------
    import numpy as np

    from sparkdq4ml_tpu.models import (ClusteringEvaluator, GBTRegressor,
                                       GeneralizedLinearRegression, KMeans,
                                       RandomForestClassifier)

    glm = GeneralizedLinearRegression(family="gamma", link="log").fit(fdf)
    print(f"gamma-GLM price fit: deviance {glm.summary.deviance:.1f}, "
          f"AIC {glm.summary.aic:.1f}")

    gbt = GBTRegressor(max_iter=20, max_depth=3, step_size=0.2).fit(fdf)
    gbt_rmse = RegressionEvaluator(metric_name="rmse").evaluate(
        gbt.transform(fdf))
    assert gbt_rmse < 4.0
    print(f"GBT price fit RMSE: {gbt_rmse:.4f}")

    rf = RandomForestClassifier(num_trees=10, max_depth=4).fit(ldf)
    rf_out = rf.transform(ldf).to_pydict()
    rf_acc = float(np.mean(rf_out["prediction"] == rf_out["label"]))
    assert rf_acc > 0.95
    print(f"random-forest large-party accuracy: {rf_acc:.3f}")

    km = KMeans(k=3, seed=7, features_col="features").fit(fdf)
    sil = ClusteringEvaluator(features_col="features").evaluate(
        km.transform(fdf))
    assert sil > 0.5
    print(f"k=3 guest clustering silhouette: {sil:.3f} "
          f"(sizes {sorted(km.summary.cluster_sizes)})")

    # --- round-3 families: SVC, FM, survival, patterns, embeddings ----------
    from sparkdq4ml_tpu import Frame
    from sparkdq4ml_tpu.models import (AFTSurvivalRegression,
                                       BucketedRandomProjectionLSH,
                                       FMClassifier, FPGrowth,
                                       IsotonicRegression, LinearSVC,
                                       Word2Vec)

    svc = LinearSVC(max_iter=100, reg_param=0.01).fit(ldf)
    svc_out = svc.transform(ldf).to_pydict()
    print(f"linear-SVC large-party accuracy: "
          f"{float(np.mean(svc_out['prediction'] == svc_out['label'])):.3f}")

    rng = np.random.default_rng(0)
    Xf = rng.normal(size=(400, 2))
    yf = (Xf[:, 0] * Xf[:, 1] > 0).astype(np.float64)   # XOR quadrants
    fm_df = VectorAssembler(["a", "b"], "features").transform(
        Frame({"a": Xf[:, 0], "b": Xf[:, 1], "label": yf}))
    fm = FMClassifier(factor_size=4, max_iter=400, step_size=0.05,
                      seed=1).fit(fm_df)
    fm_acc = float(np.mean(np.asarray(
        fm.transform(fm_df).to_pydict()["prediction"]) == yf))
    print(f"factorization-machine XOR accuracy: {fm_acc:.3f} "
          f"(a linear model gets ~0.5)")

    iso = IsotonicRegression().fit(Frame({
        "features": np.asarray(fdf.to_pydict()["guest"], np.float64),
        "label": np.asarray(fdf.to_pydict()["price"], np.float64)}))
    print(f"isotonic price(30 guests): {iso.predict(30.0):.2f}")

    t = np.exp(1.0 + 0.3 * Xf[:, 0]
               + 0.4 * np.log(rng.exponential(size=400)))
    aft_df = VectorAssembler(["a"], "features").transform(Frame({
        "a": Xf[:, 0], "label": t,
        "censor": (rng.random(400) > 0.2).astype(np.float64)}))
    aft = AFTSurvivalRegression(max_iter=300).fit(aft_df)
    print(f"AFT survival: coef {float(aft.coefficients[0]):+.3f}, "
          f"scale {aft.scale:.3f}")

    baskets = Frame({"items": dq.list_column(
        [["wine", "cheese"], ["wine", "cheese", "bread"],
         ["beer", "chips"], ["wine", "cheese", "grapes"],
         ["beer", "chips", "salsa"]])})
    fp = FPGrowth(min_support=0.4, min_confidence=0.7).fit(baskets)
    top_rule = fp.association_rules.to_pydict()
    if len(top_rule["confidence"]):
        print(f"FPGrowth: {len(fp.itemsets)} frequent itemsets, e.g. rule "
              f"{top_rule['antecedent'][0]} -> {top_rule['consequent'][0]}")

    docs = Frame({"toks": dq.list_column(
        [list(rng.choice(["wine", "cheese", "grapes"], 6))
         if rng.random() < 0.5 else
         list(rng.choice(["beer", "chips", "salsa"], 6))
         for _ in range(200)])})
    w2v = Word2Vec(vector_size=8, min_count=1, max_iter=8, window_size=3,
                   batch_size=256, seed=1, input_col="toks",
                   output_col="vec").fit(docs)
    syn = w2v.find_synonyms("wine", 1).to_pydict()["word"][0]
    print(f"word2vec nearest neighbor of 'wine': {syn}")

    lsh = BucketedRandomProjectionLSH(bucket_length=2.0, num_hash_tables=4,
                                      seed=3).fit(fm_df)
    nn = lsh.approx_nearest_neighbors(fm_df, Xf[0], 3)
    print(f"LSH 3-NN distances: "
          f"{np.round(np.sort(np.asarray(nn.to_pydict()['distCol'])), 3)}")

    from sparkdq4ml_tpu.models import LDA, PowerIterationClustering, PrefixSpan

    topics = Frame({"features": np.stack(
        [np.bincount(rng.integers(0, 6, 40), minlength=12).astype(np.float64)
         if rng.random() < 0.5 else
         np.bincount(rng.integers(6, 12, 40), minlength=12).astype(np.float64)
         for _ in range(60)])})
    lda = LDA(k=2, max_iter=25, optimizer="em", seed=1).fit(topics)
    tops = lda.describe_topics(3).to_pydict()["termIndices"]
    print(f"LDA top terms per topic: {[list(map(int, t)) for t in tops]} "
          f"(perplexity {lda.log_perplexity(topics):.2f})")

    ring = Frame({
        "src": np.asarray([0, 1, 2, 3, 4, 5, 0, 3], np.int64),
        "dst": np.asarray([1, 2, 0, 4, 5, 3, 2, 5], np.int64),
        "weight": np.asarray([1, 1, 1, 1, 1, 1, 1, 1], np.float64)})
    pic = PowerIterationClustering(k=2, max_iter=20).assign_clusters(ring)
    print(f"PIC clusters over two triangles: "
          f"{pic.to_pydict()['cluster'].tolist()}")

    visits = Frame({"sequence": dq.list_column(
        [[["home"], ["search"], ["cart"]],
         [["home"], ["search"], ["cart"], ["buy"]],
         [["home"], ["cart"]],
         [["search"], ["cart"]]])})
    ps = PrefixSpan(min_support=0.5).find_frequent_sequential_patterns(visits)
    d = ps.to_pydict()
    longest = max(d["sequence"], key=lambda s: sum(len(i) for i in s))
    print(f"PrefixSpan: {len(d['freq'])} frequent sequences, "
          f"longest {longest}")


if __name__ == "__main__":
    main()
