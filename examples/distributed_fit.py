"""Distributed (multi-chip) fits on a virtual mesh — the `master("local[*]")`
analogue: run the SAME sharded `shard_map`+`psum` code paths the framework
uses on a real TPU pod, on N fake CPU devices in one process.

    python examples/distributed_fit.py          # 8 virtual devices

Every fit below row-shards its data over the mesh's `data` axis and
reduces sufficient statistics with `jax.lax.psum` over ICI — the
`treeAggregate` replacement (SURVEY.md §3.3). The script asserts
sharded ≡ single-device for each family.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu import functions as F
from sparkdq4ml_tpu.models import (KMeans, LDA, LinearRegression,
                                   LogisticRegression, RandomForestRegressor,
                                   VectorAssembler)
from sparkdq4ml_tpu.parallel.mesh import make_mesh


def main():
    mesh = make_mesh(8)
    print(f"mesh: {mesh.devices.size} devices over axis "
          f"{tuple(mesh.axis_names)}")

    rng = np.random.default_rng(0)
    n, d = 4096, 4
    X = rng.normal(size=(n, d))
    w_true = np.asarray([3.0, -2.0, 0.5, 1.0])
    y = X @ w_true + 0.7 + 0.05 * rng.normal(size=n)

    frame = VectorAssembler([f"x{j}" for j in range(d)], "features") \
        .transform(Frame({**{f"x{j}": X[:, j] for j in range(d)},
                          "label": y}))

    for name, est, attr in [
        ("LinearRegression", LinearRegression(max_iter=100), "coefficients"),
        ("LogisticRegression", LogisticRegression(max_iter=50),
         "coefficients"),
        ("KMeans", KMeans(k=3, seed=1), None),
        ("RandomForestRegressor",
         RandomForestRegressor(num_trees=5, max_depth=4, seed=2), None),
    ]:
        if name == "LogisticRegression":
            fit_frame = frame.with_column(
                "label",
                F.when(dq.col("label") > float(np.median(y)), 1.0)
                .otherwise(0.0))
        else:
            fit_frame = frame
        single = est.fit(fit_frame)
        sharded = est.fit(fit_frame, mesh=mesh)
        if attr:
            a = np.asarray(getattr(single, attr))
            b = np.asarray(getattr(sharded, attr))
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
            print(f"{name}: sharded == single "
                  f"(coef[0] = {float(b.ravel()[0]):+.4f})")
        else:
            pa = np.asarray(single.transform(fit_frame)
                            ._column_values("prediction"))
            pb = np.asarray(sharded.transform(fit_frame)
                            ._column_values("prediction"))
            if name == "KMeans":
                # integer cluster ids: demand near-total agreement (a
                # borderline point may flip under f32 psum ordering)
                agree = float(np.mean(pa == pb))
                assert agree > 0.99, f"{name} agreement {agree:.3f}"
                print(f"{name}: sharded == single "
                      f"({agree:.1%} of assignments)")
            else:
                # continuous leaf means: f32 psum ordering perturbs split
                # stats in the last ulp — compare numerically
                np.testing.assert_allclose(pa, pb, rtol=5e-3, atol=5e-3)
                print(f"{name}: sharded == single (predictions agree)")

    docs = Frame({"features": rng.poisson(
        1.0, size=(512, 24)).astype(np.float64)})
    lda = LDA(k=3, max_iter=10, optimizer="em", seed=1)
    # float32 here (production default; tests assert 1e-8 in f64) — psum
    # reduction order differs from the single-device sum
    np.testing.assert_allclose(lda.fit(docs).topics,
                               lda.fit(docs, mesh=mesh).topics,
                               rtol=5e-4, atol=5e-4)
    print("LDA (variational EM): sharded == single")

    print("all sharded fits match their single-device fits")


if __name__ == "__main__":
    main()
