from .mesh import (DATA_AXIS, data_sharding, make_mesh, parse_master,
                   replicated_sharding)
