"""Device mesh construction — the cluster-runtime init analogue.

Spark's ``master("local[*]")`` (`DataQuality4MachineLearningApp.java:40`)
spins up one in-process executor with task parallelism = host cores. The TPU
equivalent (SURVEY.md §3.1) is device discovery + a 1-D ``jax.sharding.Mesh``
over the chips; the data axis is named ``"data"`` because row-sharded data
parallelism is the reference stack's only parallelism strategy (SURVEY.md §5
"Parallelism strategies" — the model is two scalars; TP/PP/SP have nothing to
act on and are deliberately not invented).

Multi-host: ``jax.devices()`` already enumerates the global device set under
``jax.distributed``; the same 1-D mesh then spans hosts, and the psum in the
fit path rides ICI within a slice and DCN across slices — no framework code
changes (that is the point of SPMD).
"""

from __future__ import annotations

import contextlib
import functools
import re
import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"

# ---------------------------------------------------------------------------
# Collective-dispatch serialization
# ---------------------------------------------------------------------------

#: Process-wide guard for executing multi-device collective programs.
#: XLA:CPU's intra-process collectives rendezvous participant threads per
#: (device set, op); when two executions of psum-bearing programs overlap
#: — exactly what a concurrent serving workload produces — the
#: participant threads of the two runs interleave and BOTH rendezvous
#: wait forever (observed live under 32 concurrent packed Lasso fits:
#: "This thread has been waiting for 5000ms ... waiting for all
#: participants"). Serializing dispatch-to-completion of multi-device
#: programs is the correctness fix; single-device programs (the common
#: serving hot path) never take the lock. RLock: a guarded program may be
#: invoked from inside another guarded region on the same thread (e.g. a
#: fallback rung re-dispatching).
_COLLECTIVE_LOCK = threading.RLock()


def _multi_device(mesh) -> bool:
    return mesh is not None and getattr(mesh, "devices", None) is not None \
        and mesh.devices.size > 1


@contextlib.contextmanager
def collective_guard(mesh=None):
    """Hold the process-wide collective lock while a multi-device program
    runs (no-op for ``None``/single-device meshes). Callers must keep the
    device work INSIDE the guard — jax dispatch is async, so block on the
    result before leaving the block (``serialize_collectives`` does both
    for jitted callables)."""
    if not _multi_device(mesh):
        yield
        return
    with _COLLECTIVE_LOCK:
        yield


def serialize_collectives(fn, mesh):
    """Wrap a jitted multi-device program so every call holds the
    collective lock for dispatch AND completion (``block_until_ready``
    inside the lock — releasing with the collective still in flight
    would re-create the interleave). Identity when the mesh is ``None``
    or single-device, so the wrapper costs nothing on the common path;
    under ``jax.jit`` tracing the block is a no-op on tracers and the
    lock is only held for the trace."""
    if not _multi_device(mesh):
        return fn

    @functools.wraps(fn)
    def locked(*args, **kwargs):
        with _COLLECTIVE_LOCK:
            return jax.block_until_ready(fn(*args, **kwargs))
    return locked


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-compat ``shard_map`` — every sharded program in the
    framework routes through here. Newer jax exports it as
    ``jax.shard_map`` (with the replication check spelled ``check_vma``);
    0.4.x ships only ``jax.experimental.shard_map`` with the older
    ``check_rep`` spelling. Without this shim the whole sharded execution
    layer (Gramian psum, clustering/tree/ALS statistics) crashes with
    ``AttributeError`` on a 0.4.x runtime — a version skew is an
    environment fault and gets the same graceful treatment as a device
    fault.

    Wherever the kwarg is spelled ``check_rep`` (the pre-``check_vma``
    checker), it is forced **off**: that checker has no replication rule
    for ``while``/``scan`` — the primitives every solver loop here is
    built on — and aborts compilation with ``NotImplementedError``. The
    check is a static lint, not a semantics change; the modern
    ``check_vma`` checker (which does infer through loops) still honors
    the caller's flag."""
    from ..utils.profiling import counters

    # One sharded-program BUILD (trace-time, not per-dispatch): the
    # collective-shape signal the observability layer surfaces as
    # ``parallel.shard_map_builds``.
    counters.increment("parallel.shard_map_builds")
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check_vma)
        except TypeError:   # public export, pre-check_vma kwarg naming
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def parse_master(master: Optional[str]) -> Optional[int]:
    """Spark master string → device count (None = all available).

    ``local[*]``/``local``/``tpu``/None → all devices; ``local[N]`` → N.
    """
    if master is None:
        return None
    m = master.strip().lower()
    if m in ("local", "local[*]", "tpu", "tpu[*]", "*", "pod", "pod[*]"):
        return None
    match = re.fullmatch(r"(?:local|tpu|pod)\[(\d+)\]", m)
    if match:
        return int(match.group(1))
    raise ValueError(f"unsupported master string {master!r}")


def make_mesh(num_devices: Optional[int] = None,
              devices: Optional[Sequence] = None,
              axis_name: str = DATA_AXIS) -> Mesh:
    """Build a 1-D data-parallel mesh over the first ``num_devices`` devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} present")
        devices = devices[:num_devices]
    from ..utils.observability import METRICS

    METRICS.set_gauge("mesh.devices", len(devices))
    return Mesh(np.asarray(devices), (axis_name,))


def normalize_mesh(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """Treat a trivial (≤1-device) mesh as no mesh — the shared guard every
    ``fit(frame, mesh=...)`` entry point applies before building a sharded
    program."""
    return None if mesh is None or mesh.devices.size <= 1 else mesh


def data_sharding(mesh: Mesh, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Rows sharded over the data axis (leading-dim sharding)."""
    return NamedSharding(mesh, PartitionSpec(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
