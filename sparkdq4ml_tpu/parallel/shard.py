"""Row-sharded frame layout — the ``ShardedStore`` behind ``spark.shard.*``.

ROADMAP item 1: every Frame op ran single-device, so the 1e9-row regime
was capped by one device's HBM and FLOPs. This module is the layout half
of the sharded-frames refactor (the lowering halves live in
``ops/compiler.py`` — the ``shard_map``-wrapped pipeline flush — and
``ops/segments.py`` — local segment-reduce + cross-shard merge
collective, per "Large Scale Distributed Linear Algebra With TPUs",
arxiv 2112.09017):

* A sharded frame's ``_data``/``_mask`` are **global jax arrays laid out
  row-sharded** over the 1-D ``parallel/mesh`` data axis with a
  ``NamedSharding``. The row axis pads up to ``devices × bucket`` where
  ``bucket`` reuses the pipeline compiler's power-of-two bucket
  discipline (:func:`ops.compiler.bucket_size` over the per-shard row
  count), and the padded tail rides a ``False`` validity mask — the same
  masked-slot invariant every consumer in the engine already honors, so
  a sharded frame is semantically indistinguishable from its
  single-device twin (bit-identical results are a *construction*
  property, not a test hope).
* :class:`ShardedStore` is the layout descriptor a frame carries
  (``Frame._shard``): device count, per-shard padded bucket, true row
  count, per-shard valid-row counts. Plan keys extend with its
  :meth:`~ShardedStore.tag` so sharded and single-device programs
  coexist in the same bounded-LRU jit caches.
* Placement is **contiguous range partitioning** (shard ``i`` holds row
  slots ``[i·bucket, (i+1)·bucket)``): global row order — and with it
  every order-sensitive semantics (first occurrence, sort stability,
  join output order) — is preserved exactly.

The session context (``configure``/``reset``) is installed by
``session._init_pipeline`` from ``spark.shard.{enabled,minRows,devices}``
and torn down on ``stop()`` — session-scoped like every other conf
family. With sharding disabled (the default), every hook here is one
flag/None check.

CPU-sandbox honesty (ROADMAP standing constraint): on the forced-host-
device CPU backend these paths assert *structure* — one fused program
per flush, one cross-shard merge collective, unchanged host-sync counts
— not speedups; the wall-clock wins need real chips.
"""

from __future__ import annotations

import logging
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..config import config
from ..utils import observability as _obs
from ..utils.profiling import counters
from .mesh import DATA_AXIS

logger = logging.getLogger("sparkdq4ml_tpu.parallel.shard")

__all__ = [
    "ShardedStore", "configure", "reset", "active_mesh", "store_for",
    "maybe_shard_frame", "shard_frame", "gather_arrays",
    "partitioned_join_plan", "hash_partition", "record_exchange",
    "record_skew",
]


def record_exchange(kind: str, nbytes: int) -> None:
    """Exchange-volume accounting (device-cost observatory,
    ``utils/costprof.py``): one counter bump per cross-shard data
    movement, sized STATICALLY from the participating array shapes —
    never a sync. Kinds mirror the EXPLAIN ``Exchange`` markers:
    ``psum`` (the grouped merge collective), ``all_to_all`` (the
    hash-partition distinct exchange), ``gather`` (a ladder/sort
    degrade to single-device placement). One flag read when the
    observatory is disabled."""
    if not config.costprof_enabled:
        return
    nb = max(int(nbytes), 0)
    counters.increment("shard.exchange_bytes", nb)
    counters.increment(f"shard.exchange_bytes.{kind}", nb)


def record_skew(store: "ShardedStore") -> None:
    """Row-balance gauge (device-cost observatory): worst/mean valid
    rows per shard of the most recent placement, from the layout's own
    HOST-KNOWN counts (contiguous range partitioning — no sync). 1.0 =
    perfectly balanced; ``devices`` = all rows on one shard. One flag
    read when the observatory is disabled."""
    if not config.costprof_enabled:
        return
    counts = store.shard_counts()
    mean = store.rows / max(store.devices, 1)
    if mean <= 0:
        return
    _obs.METRICS.set_gauge("shard.skew",
                           round(max(counts) / mean, 4))


class ShardedStore:
    """Layout descriptor of one row-sharded frame: ``devices`` shards of
    ``bucket`` padded row slots each, holding ``rows`` true rows placed
    contiguously (shard ``i``'s valid count is
    ``clip(rows - i*bucket, 0, bucket)``)."""

    __slots__ = ("mesh", "rows", "bucket")

    def __init__(self, mesh: Mesh, rows: int, bucket: int):
        self.mesh = mesh
        self.rows = int(rows)
        self.bucket = int(bucket)

    @property
    def devices(self) -> int:
        return int(self.mesh.devices.size)

    @property
    def slots(self) -> int:
        """Global padded row slots (= the sharded frame's ``num_slots``)."""
        return self.devices * self.bucket

    def sharding(self) -> NamedSharding:
        """Rows over the data axis (leading-dim sharding)."""
        return NamedSharding(self.mesh, PartitionSpec(DATA_AXIS))

    def shard_counts(self) -> list[int]:
        """Per-shard valid row counts (EXPLAIN's per-shard rows column)."""
        return [max(0, min(self.rows - i * self.bucket, self.bucket))
                for i in range(self.devices)]

    def tag(self) -> str:
        """Plan-key layout tag: sharded and single-device plans must
        never share a cache entry (their programs differ), while two
        sharded frames on the same device count do (bucket size shows up
        in the argument shapes, which jit already keys on)."""
        return f"shard[{self.devices}]"

    def __repr__(self) -> str:
        return (f"ShardedStore(devices={self.devices}, "
                f"bucket={self.bucket}, rows={self.rows})")


# ---------------------------------------------------------------------------
# Session-scoped context (spark.shard.*)
# ---------------------------------------------------------------------------

#: The configured shard mesh (None = sharding unavailable). Installed by
#: session._init_pipeline via :func:`configure`; ``config.shard_enabled``
#: gates every read so a disabled session costs one flag check.
_MESH: Optional[Mesh] = None


def configure(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """Install the shard mesh for this process (session-scoped; the
    session's ``stop()`` restores via :func:`reset`). ``spark.shard.
    devices`` caps the device count; a trivial (≤1-device) result
    disables sharding — there is nothing to shard across."""
    global _MESH
    if mesh is None:
        _MESH = None
        return None
    devices = list(mesh.devices.flat)
    limit = int(config.shard_devices)
    if limit > 0:
        devices = devices[:limit]
    if len(devices) <= 1:
        _MESH = None
        return None
    if len(devices) == mesh.devices.size:
        _MESH = mesh
    else:
        from .mesh import make_mesh

        _MESH = make_mesh(devices=devices)
    return _MESH


def reset() -> None:
    configure(None)


def active_mesh() -> Optional[Mesh]:
    """The shard mesh when sharding is enabled AND multi-device."""
    if not config.shard_enabled:
        return None
    return _MESH


def store_for(n: int) -> Optional[ShardedStore]:
    """The layout a frame of ``n`` true rows would shard into, or None
    when sharding is inactive or ``n`` is below ``spark.shard.minRows``
    (the host-fallback threshold: tiny frames are not worth the
    placement traffic)."""
    mesh = active_mesh()
    if mesh is None or n <= 0 or n < int(config.shard_min_rows):
        return None
    from ..ops.compiler import bucket_size

    bucket = bucket_size(max(1, math.ceil(n / mesh.devices.size)))
    return ShardedStore(mesh, n, bucket)


# ---------------------------------------------------------------------------
# Placement / gather
# ---------------------------------------------------------------------------

def _is_host_col(arr) -> bool:
    return isinstance(arr, np.ndarray) and arr.dtype == object


def _pad_host(arr: np.ndarray, slots: int) -> np.ndarray:
    out = np.empty(slots, dtype=object)
    out[: len(arr)] = arr
    out[len(arr):] = None
    return out


def place_column(arr, store: ShardedStore):
    """Pad one column to the store's slot count and lay it out
    row-sharded (host/object columns pad with ``None`` and stay host).
    Accepts columns already at slot length (re-placement)."""
    if _is_host_col(arr):
        if len(arr) == store.slots:
            return arr
        return _pad_host(arr, store.slots)
    a = jnp.asarray(arr)
    n = a.shape[0]
    if n != store.slots:
        fill = jnp.zeros((store.slots - n,) + a.shape[1:], a.dtype)
        a = jnp.concatenate([a, fill], axis=0)
    return jax.device_put(a, store.sharding())


def shard_frame(frame):
    """Return a row-sharded twin of ``frame`` (same values, same valid
    rows; physical slots pad to ``devices × bucket`` with a ``False``
    mask tail). The input frame is untouched. Raises when sharding is
    inactive — callers wanting the soft form use
    :func:`maybe_shard_frame`."""
    store = store_for(frame.num_slots)
    if store is None:
        raise RuntimeError(
            "sharding is inactive (spark.shard.enabled off, a "
            "single-device mesh, or the frame is below "
            "spark.shard.minRows)")
    return _place(frame, store)


def maybe_shard_frame(frame):
    """Shard ``frame`` when the context says to, else return it
    unchanged — the ingest/read hand-off hook (one None check when
    sharding is off)."""
    if getattr(frame, "_shard", None) is not None:
        return frame
    store = store_for(frame.num_slots)
    if store is None:
        return frame
    return _place(frame, store)


def _place(frame, store: ShardedStore):
    from ..frame.frame import Frame

    data = frame._data            # flush-on-read: pending pipeline settles
    mask = frame._mask
    placed = {name: place_column(arr, store) for name, arr in data.items()}
    pmask = jnp.asarray(mask, jnp.bool_)
    if pmask.shape[0] != store.slots:
        pmask = jnp.concatenate([
            pmask, jnp.zeros((store.slots - pmask.shape[0],), jnp.bool_)])
    pmask = jax.device_put(pmask, store.sharding())
    out = Frame.__new__(Frame)
    out._data_store = placed
    out._mask_store = pmask
    out._pending = ()
    out._n = store.slots
    out._shard = store
    counters.increment("shard.place")
    record_skew(store)
    return out


def gather_arrays(store: ShardedStore, *arrays):
    """Re-place arrays on the mesh's first device — the one-level
    degradation of every sharded ladder (device fault on one shard →
    single-device execution). A device→device transfer, never a counted
    host sync."""
    dev = store.mesh.devices.flat[0]
    out = tuple(jax.device_put(jnp.asarray(a), dev) for a in arrays)
    record_exchange("gather",
                    sum(a.size * a.dtype.itemsize for a in out))
    return out


def gather_store(frame):
    """Degrade a sharded frame's columns to single-device placement
    (host/object columns pass through). Returns ``(data, mask)`` — the
    caller installs them and drops ``_shard``."""
    store = frame._shard
    dev = store.mesh.devices.flat[0]
    data = {name: (arr if _is_host_col(arr)
                   else jax.device_put(jnp.asarray(arr), dev))
            for name, arr in frame._data_store.items()}
    mask = jax.device_put(jnp.asarray(frame._mask_store, jnp.bool_), dev)
    counters.increment("shard.gather")
    record_exchange(
        "gather",
        sum(a.size * a.dtype.itemsize for a in data.values()
            if not _is_host_col(a)) + mask.size * mask.dtype.itemsize)
    return data, mask


# ---------------------------------------------------------------------------
# Hash-partitioned join planning (the shuffle lowering's host realization)
# ---------------------------------------------------------------------------

def hash_partition(cols: list[np.ndarray], parts: int) -> np.ndarray:
    """Per-row partition id over float64-converted key columns — the
    host mirror of the device exchange's key hash. Null-safe: NaN (the
    engine's SQL NULL) hashes to one partition, ``-0.0`` folds onto
    ``0.0`` (they compare equal and must land together)."""
    n = len(cols[0]) if cols else 0
    h = np.zeros(n, np.uint64)
    for c in cols:
        c = np.asarray(c, np.float64)
        nulls = np.isnan(c)
        z = np.where(c == 0.0, 0.0, c)          # -0.0 == 0.0 → same bits
        z = np.where(nulls, 0.0, z)
        bits = z.view(np.uint64)
        h = h * np.uint64(0x100000001B3) ^ bits
        h = h * np.uint64(0x100000001B3) ^ nulls.astype(np.uint64)
    return (h % np.uint64(max(parts, 1))).astype(np.int64)


def partitioned_join_plan(plan_fn, lcols, rcols, li, ri, how: str,
                          parts: int):
    """Hash-partition shuffle lowering of the vectorized join plan: rows
    of each side partition by key hash, ``plan_fn`` (the single-device
    ``_vector_join_plan``) runs per partition, and the per-partition
    pair lists merge back into EXACTLY the unpartitioned plan's order —
    sound because equal keys land in one partition, so every left row's
    complete match set is partition-local, and a stable sort on the left
    row index restores the global emission order (unmatched right rows
    re-sort by right index, the canonical append order).

    Returns ``(lpairs, rpairs)`` or ``None`` when any partition's plan
    bails (the caller falls back to the unpartitioned plan).

    Adaptive skew split (``sql/adaptive.py``): a probe-side partition
    whose row count crosses ``spark.aqe.skewFactor`` x the mean — the
    live per-exchange analogue of the ``shard.skew`` placement gauge —
    splits into balanced probe chunks, each planned against the
    partition's FULL build side. Bit-identical: every left row's
    complete match set is chunk-local (the build side never splits) and
    the stable left-index sort below already restores the global
    emission order regardless of which sub-plan emitted a pair. Gated
    to join types whose unmatched-right detection is not cross-chunk
    (a right row unmatched in one chunk may match in another); one
    conf read when AQE is off."""
    t_l = hash_partition(lcols, parts)
    t_r = hash_partition(rcols, parts)
    aqe_on = config.aqe_enabled
    mean_rows = li.size / max(parts, 1)
    lp_all, rp_all = [], []
    extra_r = []                     # unmatched right rows (right/outer)
    for p in range(parts):
        ls = np.nonzero(t_l == p)[0]
        rs = np.nonzero(t_r == p)[0]
        if ls.size == 0 and rs.size == 0:
            continue
        if rs.size == 0:
            # fully-determined plans, mirroring Frame.join's empty-right
            # guard: inner/right/semi match nothing, left/outer/anti
            # keep every left row null-filled
            if how in ("inner", "right", "left_semi"):
                continue
            lp_all.append(li[ls].astype(np.int64))
            rp_all.append(np.full(ls.size, -1, np.int64))
            continue
        if (aqe_on and mean_rows > 0
                and how in ("inner", "left", "left_semi", "left_anti")
                and ls.size >= mean_rows
                * max(float(config.aqe_skew_factor), 1.0)
                and ls.size >= 2):
            from ..sql import adaptive as _aqe

            if _aqe.guard("skew-split"):
                target = max(int(math.ceil(mean_rows)), 1)
                chunks = range(0, ls.size, target)
                for c0 in chunks:
                    lc = ls[c0: c0 + target]
                    sub = plan_fn([c[lc] for c in lcols],
                                  [c[rs] for c in rcols],
                                  li[lc], ri[rs], how)
                    if sub is None:
                        # a chunk plan bailed: the caller falls back to
                        # the UNPARTITIONED plan, so no split happened —
                        # record nothing
                        return None
                    lp_c, rp_c = sub
                    lp_all.append(lp_c)
                    rp_all.append(rp_c)
                _aqe.record(
                    "skew-split",
                    f"Exchange partition {p}: {ls.size} probe rows >= "
                    f"{float(config.aqe_skew_factor):g}x mean "
                    f"{mean_rows:.0f}; split into {len(chunks)} chunks",
                    est_before=int(round(mean_rows)),
                    est_after=int(ls.size))
                continue
        sub = plan_fn([c[ls] for c in lcols], [c[rs] for c in rcols],
                      li[ls], ri[rs], how)
        if sub is None:
            return None
        lp, rp = sub
        if how in ("right", "outer"):
            appended = lp < 0
            extra_r.append(rp[appended])
            lp, rp = lp[~appended], rp[~appended]
        lp_all.append(lp)
        rp_all.append(rp)
    lp = np.concatenate(lp_all) if lp_all else np.empty(0, np.int64)
    rp = np.concatenate(rp_all) if rp_all else np.empty(0, np.int64)
    order = np.argsort(lp, kind="stable")
    lp, rp = lp[order], rp[order]
    if how in ("right", "outer"):
        ex = (np.sort(np.concatenate(extra_r)) if extra_r
              else np.empty(0, np.int64))
        lp = np.concatenate([lp, np.full(ex.size, -1, np.int64)])
        rp = np.concatenate([rp, ex])
    counters.increment("shard.join_partitioned")
    return lp, rp
