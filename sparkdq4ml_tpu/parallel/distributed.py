"""Distributed statistics: row sharding + ICI collectives.

This is the ``treeAggregate``-over-netty replacement (SURVEY.md §3.3, §5
"Distributed communication backend"): rows are sharded over the mesh's
``data`` axis; each device computes its local augmented Gramian with one
masked matmul; ``jax.lax.psum`` reduces over ICI. Coefficient "broadcast" is
implicit in SPMD replication — the solver then runs identically on every
device on the replicated statistics, so there is no driver↔executor boundary
at all (zero host syncs per iteration vs. Spark's two).

Padding: row counts rarely divide the mesh size; rows are padded with
``mask=False`` slots, which the mask-weighted statistics ignore by
construction — the same mechanism that makes DQ filtering static-shaped
(SURVEY.md §7 "Masked-filter semantics").
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.solvers import augmented_gram
from .mesh import DATA_AXIS


def pad_rows(X: np.ndarray, y: np.ndarray, mask: np.ndarray, multiple: int):
    """Pad the row dimension to a multiple of the shard count (mask=False)."""
    n = X.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return X, y, mask
    Xp = np.concatenate([X, np.zeros((rem, X.shape[1]), X.dtype)])
    yp = np.concatenate([y, np.zeros((rem,), y.dtype)])
    mp = np.concatenate([mask, np.zeros((rem,), bool)])
    return Xp, yp, mp


@jax.jit
def _gram_single(X, y, mask):
    return augmented_gram(X, y, mask)


@functools.lru_cache(maxsize=None)
def _gram_sharded_fn(mesh: Mesh):
    """Build (once per mesh) the jitted sharded Gramian: local matmul + psum."""

    def local(X, y, mask):
        return jax.lax.psum(augmented_gram(X, y, mask), DATA_AXIS)

    sharded = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P())
    return jax.jit(sharded)


@functools.lru_cache(maxsize=None)
def fused_linear_fit_fn(mesh: Optional[Mesh], solver: str, max_iter: int,
                        tol: float, fit_intercept: bool, standardization: bool):
    """ONE jitted program for the whole fit: sharded masked Gramian (+psum)
    feeding the solver loop — a single dispatch, zero host round-trips.

    This is the fit hot path ``LinearRegression.fit`` uses; Spark's
    equivalent is 1 + 2·maxIter RPC barriers (SURVEY.md §3.3).
    """
    from ..models.owlqn import owlqn_solve
    from ..models.solvers import fista_solve, normal_solve

    if solver == "normal":
        def solve_A(A, reg, alpha):
            return normal_solve(A, reg, alpha, fit_intercept=fit_intercept,
                                standardization=standardization)
    elif solver == "owlqn":
        def solve_A(A, reg, alpha):
            return owlqn_solve(A, reg, alpha, max_iter=max_iter, tol=tol,
                               fit_intercept=fit_intercept,
                               standardization=standardization)
    else:
        def solve_A(A, reg, alpha):
            return fista_solve(A, reg, alpha, max_iter=max_iter, tol=tol,
                               fit_intercept=fit_intercept,
                               standardization=standardization)

    if mesh is None or mesh.devices.size <= 1:
        def fit(X, y, mask, reg, alpha):
            return solve_A(augmented_gram(X, y, mask), reg, alpha)
    else:
        sharded_gram = jax.shard_map(
            lambda Xs, ys, ms: jax.lax.psum(augmented_gram(Xs, ys, ms), DATA_AXIS),
            mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P())

        def fit(X, y, mask, reg, alpha):
            return solve_A(sharded_gram(X, y, mask), reg, alpha)

    return jax.jit(fit)


def place_sharded(X, y, mask, mesh: Optional[Mesh]):
    """Pad rows to the shard count and device_put with row sharding.
    Single-device/no-mesh inputs pass through as device arrays."""
    if mesh is None or mesh.devices.size <= 1:
        return (jnp.asarray(X), jnp.asarray(y), jnp.asarray(mask, jnp.bool_))
    Xh, yh, mh = pad_rows(np.asarray(X), np.asarray(y), np.asarray(mask, bool),
                          mesh.devices.size)
    shard = NamedSharding(mesh, P(DATA_AXIS))
    return (jax.device_put(Xh, shard), jax.device_put(yh, shard),
            jax.device_put(mh, shard))


def compute_gram(X, y, mask, mesh: Optional[Mesh] = None):
    """Augmented Gramian ``A``, sharded over ``mesh`` when it has >1 device.

    Accepts host or device arrays; on the sharded path, inputs are placed with
    a row-sharded ``NamedSharding`` so each device holds only its shard (HBM
    never sees the replicated matrix).
    """
    if mesh is None or mesh.devices.size <= 1:
        return _gram_single(jnp.asarray(X), jnp.asarray(y),
                            jnp.asarray(mask, jnp.bool_))
    nshards = mesh.devices.size
    Xh = np.asarray(X)
    yh = np.asarray(y)
    mh = np.asarray(mask, bool)
    Xh, yh, mh = pad_rows(Xh, yh, mh, nshards)
    shard = NamedSharding(mesh, P(DATA_AXIS))
    Xd = jax.device_put(Xh, shard)
    yd = jax.device_put(yh, shard)
    md = jax.device_put(mh, shard)
    return _gram_sharded_fn(mesh)(Xd, yd, md)
