"""Distributed statistics: row sharding + ICI collectives.

This is the ``treeAggregate``-over-netty replacement (SURVEY.md §3.3, §5
"Distributed communication backend"): rows are sharded over the mesh's
``data`` axis; each device computes its local augmented Gramian with one
masked matmul; ``jax.lax.psum`` reduces over ICI. Coefficient "broadcast" is
implicit in SPMD replication — the solver then runs identically on every
device on the replicated statistics, so there is no driver↔executor boundary
at all (zero host syncs per iteration vs. Spark's two).

Padding: row counts rarely divide the mesh size; rows are padded with
``mask=False`` slots, which the mask-weighted statistics ignore by
construction — the same mechanism that makes DQ filtering static-shaped
(SURVEY.md §7 "Masked-filter semantics").
"""

from __future__ import annotations

import functools
import logging
import threading
from collections import namedtuple
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.solvers import augmented_gram
from ..ops.segments import abstract_specs
from .mesh import DATA_AXIS, serialize_collectives, shard_map

logger = logging.getLogger("sparkdq4ml_tpu.distributed")


# ---------------------------------------------------------------------------
# Enumerable jit-factory memo (the lru_cache replacement)
# ---------------------------------------------------------------------------

_CacheInfo = namedtuple("CacheInfo", ("hits", "misses", "maxsize",
                                      "currsize"))


class _RecordedProgram:
    """One memoized factory product: the guarded dispatch entry plus the
    raw trace body and the abstract example calling convention recorded
    on first execution. ``functools.lru_cache`` could report stats but
    never LIST its entries — which left the packed sharded fits with no
    re-trace surface for the program auditor (``observability.
    ProgramHandle``); this wrapper is that surface."""

    __slots__ = ("dispatch", "trace_body", "jit_fn", "mesh", "example")

    def __init__(self, dispatch, trace_body, jit_fn, mesh):
        self.dispatch = dispatch
        self.trace_body = trace_body
        self.jit_fn = jit_fn
        self.mesh = mesh
        self.example = None

    def __call__(self, *args):
        # One None-check per dispatch on the steady path — this wrapper
        # sits on the dispatch-lean packed-fit hot loop, so recording
        # happens exactly once (shape/dtype metadata, no device read).
        if self.example is None:
            self.example = abstract_specs(args)
        return self.dispatch(*args)


class _EnumerableFactory:
    """Memoizing decorator for the jit factories with the
    ``cache_info()``/``cache_clear()`` surface of ``functools.lru_cache``
    (the observability trace-probe and the pallas tests use both) PLUS
    entry enumeration — ``entries()`` yields ``(key, product)`` pairs so
    the program auditor can re-trace every cached fit program without a
    private import. Builds serialize on one lock (factory builds are
    rare trace-time events; a double-build would strand replay stats)."""

    def __init__(self, builder):
        self._builder = builder
        self._entries: dict = {}
        self._hits = 0
        self._misses = 0
        self._lock = threading.Lock()
        functools.update_wrapper(self, builder)

    def __call__(self, *key):
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._hits += 1
                return hit
            self._misses += 1
            product = self._builder(*key)
            self._entries[key] = product
            return product

    def entries(self) -> list:
        with self._lock:
            return list(self._entries.items())

    def cache_info(self) -> _CacheInfo:
        with self._lock:
            return _CacheInfo(self._hits, self._misses, None,
                              len(self._entries))

    def cache_clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._hits = 0
            self._misses = 0


def pad_rows(X: np.ndarray, y: np.ndarray, mask: np.ndarray, multiple: int):
    """Pad the row dimension to a multiple of the shard count (mask=False)."""
    n = X.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return X, y, mask
    Xp = np.concatenate([X, np.zeros((rem, X.shape[1]), X.dtype)])
    yp = np.concatenate([y, np.zeros((rem,), y.dtype)])
    mp = np.concatenate([mask, np.zeros((rem,), bool)])
    return Xp, yp, mp


@jax.jit
def _gram_single(X, y, mask):
    return augmented_gram(X, y, mask)


@_EnumerableFactory
def _gram_sharded_fn(mesh: Mesh):
    """Build (once per mesh) the jitted sharded Gramian: local matmul + psum."""

    def local(X, y, mask):
        return jax.lax.psum(augmented_gram(X, y, mask), DATA_AXIS)

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P())
    jitted = jax.jit(sharded)
    return _RecordedProgram(serialize_collectives(jitted, mesh), sharded,
                            jitted, mesh)


def _resolve_solve_A(solver: str, max_iter: int, tol: float,
                     fit_intercept: bool, standardization: bool):
    """Solver-loop factory on the augmented Gramian ``A`` (shared by the
    packed and unpacked fused fit paths)."""
    from ..models.owlqn import owlqn_solve
    from ..models.solvers import fista_solve, normal_solve

    if solver == "normal":
        def solve_A(A, reg, alpha):
            return normal_solve(A, reg, alpha, fit_intercept=fit_intercept,
                                standardization=standardization)
    elif solver == "owlqn":
        def solve_A(A, reg, alpha):
            return owlqn_solve(A, reg, alpha, max_iter=max_iter, tol=tol,
                               fit_intercept=fit_intercept,
                               standardization=standardization)
    else:
        def solve_A(A, reg, alpha):
            return fista_solve(A, reg, alpha, max_iter=max_iter, tol=tol,
                               fit_intercept=fit_intercept,
                               standardization=standardization)
    return solve_A


def pack_design(X, y, mask) -> np.ndarray:
    """Pack ``Z = [X, y, 1]·mask`` into ONE array — the single transfer unit
    of the packed fit path.

    Why packing matters here: every device argument of a dispatch costs a
    fixed per-buffer overhead (~10 µs each through the axon tunnel — measured;
    5 args ≈ 74 µs, 1 arg ≈ 33 µs floor). The masked augmented Gramian only
    ever consumes ``Z`` (``A = ZᵀZ``, solvers.augmented_gram), so pre-masking
    on the host collapses (X, y, mask) into one buffer with zero information
    loss: the mask column *is* the masked ones-column, and all-zero padding
    rows contribute nothing to ``ZᵀZ`` — no mask bookkeeping needed.

    Device arrays are packed ON DEVICE (jnp ops, async): ``np.asarray`` on a
    device array is a device→host read, and the first such read permanently
    drops the process into ~67 ms-per-dispatch synchronous mode on the
    tunneled TPU (bench.py module docstring) — packing must never be the
    first reader.
    """
    xp = jnp if any(isinstance(a, jax.Array) for a in (X, y, mask)) else np
    X = xp.asarray(X)
    if X.ndim == 1:
        X = X[:, None]
    y = xp.asarray(y, X.dtype)
    w = xp.asarray(mask, X.dtype)
    Z = xp.concatenate([X, y[:, None], xp.ones_like(y)[:, None]], axis=1)
    return Z * w[:, None]



def pack_design_weighted(X, y, mask, w):
    """Packed design for WEIGHTED fits: ``Z = [X·m, y·m, w·m]`` — the mask
    zeroes invalid rows (boolean, exactly like :func:`pack_design`) while
    the last column carries the real instance weights, so one buffer still
    ships everything the weighted logistic/softmax cores consume
    (``classification._unpack_zw``)."""
    xp = jnp if any(isinstance(a, jax.Array) for a in (X, y, mask, w)) else np
    X = xp.asarray(X)
    if X.ndim == 1:
        X = X[:, None]
    y = xp.asarray(y, X.dtype)
    m = xp.asarray(mask, X.dtype)
    wv = xp.asarray(w, X.dtype)
    Z = xp.concatenate([X, y[:, None], wv[:, None]], axis=1)
    return Z * m[:, None]


def place_packed(Z, mesh: Optional[Mesh]):
    """Pad packed rows to the shard count and device_put row-sharded.
    Zero padding rows are mask=0 rows by construction (see pack_design)."""
    if mesh is None or mesh.devices.size <= 1:
        return jnp.asarray(Z)
    xp = jnp if isinstance(Z, jax.Array) else np  # never read device→host
    Z = xp.asarray(Z)
    rem = (-Z.shape[0]) % mesh.devices.size
    if rem:
        Z = xp.concatenate([Z, xp.zeros((rem, Z.shape[1]), Z.dtype)])
    return jax.device_put(Z, NamedSharding(mesh, P(DATA_AXIS)))


@_EnumerableFactory
def fused_linear_fit_packed(mesh: Optional[Mesh], solver: str, max_iter: int,
                            tol: float, fit_intercept: bool,
                            standardization: bool):
    """Packed-I/O variant of :func:`fused_linear_fit_fn` — the dispatch-lean
    hot path ``LinearRegression.fit`` and ``bench.py`` use.

    Signature: ``fit(Z, hyper) -> flat`` where ``Z = pack_design(X, y, mask)``
    (row-sharded over the mesh), ``hyper = [regParam, elasticNetParam]`` as a
    device array, and ``flat`` is one buffer:
    ``[coef(d) | intercept | iterations | converged | objective_history]``
    (decode with :func:`unpack_fit_result`). One input buffer + one output
    buffer ≈ the minimum possible dispatch cost; the compute is identical to
    the unpacked path (local ``ZᵀZ`` on the MXU, ``psum`` over ICI, solver
    loop on replicated statistics).
    """
    solve_A = _resolve_solve_A(solver, max_iter, tol, fit_intercept,
                               standardization)

    def local_gram(Z):
        # Honors config.pallas like the unpacked augmented_gram; inside
        # shard_map the dispatch gate sees the varying mesh axes and falls
        # back to the XLA matmul.
        from ..ops import pallas_kernels

        if pallas_kernels.dispatch_to_pallas(Z):
            return pallas_kernels.packed_gram_pallas(Z)
        return Z.T @ Z

    if mesh is None or mesh.devices.size <= 1:
        gram = local_gram
    else:
        gram = shard_map(
            lambda Zs: jax.lax.psum(local_gram(Zs), DATA_AXIS),
            mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P())

    def fit(Z, hyper):
        r = solve_A(gram(Z), hyper[0], hyper[1])
        dt = r.coefficients.dtype
        scalars = jnp.stack([r.intercept.astype(dt),
                             r.iterations.astype(dt),
                             r.converged.astype(dt)])
        return jnp.concatenate(
            [r.coefficients, scalars, r.objective_history.astype(dt)])

    # Multi-device programs serialize dispatch-to-completion on the
    # process-wide collective guard (mesh.serialize_collectives): two
    # overlapping psum executions interleave their participant threads on
    # XLA:CPU and deadlock — the exact workload a concurrent QueryServer
    # produces. Identity wrapper (zero cost) off-mesh.
    jitted = jax.jit(fit)
    return _RecordedProgram(serialize_collectives(jitted, mesh), fit,
                            jitted, mesh)


def _factory_program_key(name: str, key: tuple) -> str:
    """Stable program key for one factory entry: factory name + the memo
    key with the mesh summarized structurally (axis names + sizes, not
    device object reprs)."""
    parts = []
    for k in key:
        if isinstance(k, Mesh):
            axes = ",".join(f"{a}:{n}" for a, n in
                            zip(k.axis_names, k.devices.shape))
            parts.append(f"mesh({axes})")
        else:
            parts.append(repr(k))
    return f"{name}({', '.join(parts)})"


def fit_factory_cache_stats() -> dict:
    """Registry callback (observability.CACHES): memo introspection of
    the packed/sharded jit factories — the fit-path entries of
    ``session.cache_report()``. ``hits`` are factory replays (no new
    trace+compile); ``misses`` are cold builds."""
    out: dict = {"kind": "memoized jit factories (fused linear fit)"}
    for name, factory in (("fused_linear_fit_packed",
                           fused_linear_fit_packed),
                          ("gram_sharded", _gram_sharded_fn)):
        try:
            info = factory.cache_info()
            out[name] = {"size": info.currsize, "hits": info.hits,
                         "misses": info.misses,
                         "entries": [
                             {"program_key": _factory_program_key(name, k)}
                             for k, _ in factory.entries()]}
        except Exception as e:
            out[name] = {"error": str(e)}
    return out


def fit_program_handles() -> list:
    """Registry callback (CACHES.register_programs): one traceable
    handle per cached packed/sharded fit program that has executed.
    ``guarded=True`` by construction — every product of these factories
    routes dispatch through ``mesh.serialize_collectives`` — so the
    collective-topology detector can cross-check the jaxpr's collectives
    against the mesh AND the guard wrapping in one place."""
    from ..utils import observability as _obs

    out = []
    for name, factory in (("fused_linear_fit_packed",
                           fused_linear_fit_packed),
                          ("gram_sharded", _gram_sharded_fn)):
        for key, rec in factory.entries():
            if rec.example is None:
                continue
            # Scale only the ROW-indexed inputs (the widest leading dim
            # = the shared row count): hyperparameter vectors and other
            # small fixed-shape args keep their calling convention.
            # Two factors (x2/x4) give the retrace detector a pair of
            # FRESH traces — jax may serve the recorded shape from a
            # trace cache predating a config flip (pallas mode).
            leaves = [s for s in jax.tree_util.tree_leaves(rec.example)
                      if hasattr(s, "shape") and s.shape]
            rows = max((s.shape[0] for s in leaves), default=0)

            def scaled(factor):
                return jax.tree_util.tree_map(
                    lambda s: jax.ShapeDtypeStruct(
                        (s.shape[0] * factor,) + tuple(s.shape[1:]),
                        s.dtype)
                    if hasattr(s, "shape") and s.shape
                    and s.shape[0] == rows else s, rec.example)
            # NO expected/observed trace accounting here: the jit entry
            # legitimately retraces on input SHARDING layout (row-sharded
            # vs replicated placements of the same shapes — exactly what
            # the resilience fallback rungs produce), which the
            # shape-signature recorder cannot observe. The retrace
            # detector's variant re-trace still covers shape stability.
            meta: dict = {}
            out.append(_obs.ProgramHandle(
                "fit.factories", _factory_program_key(name, key),
                rec.trace_body, args=rec.example,
                variants={"bucket": [(scaled(2), {}), (scaled(4), {})]},
                mesh=rec.mesh, guarded=True, meta=meta))
    return out


def _register_cache_stats() -> None:
    from ..utils import observability as _obs

    _obs.CACHES.register("fit.factories", fit_factory_cache_stats)
    _obs.CACHES.register_programs("fit.factories", fit_program_handles)


_register_cache_stats()


def unpack_fit_result(flat, d: int):
    """Decode the packed fit output (host side) into a ``FitResult``."""
    from ..models.solvers import FitResult

    flat = np.asarray(flat)
    return FitResult(
        coefficients=flat[:d],
        intercept=flat[d],
        iterations=np.int32(flat[d + 1]),
        objective_history=flat[d + 3:],
        converged=bool(flat[d + 2]))


def _pre_sharded(a, mesh) -> bool:
    """True when ``a`` is a jax array ALREADY row-sharded over exactly
    ``mesh``'s device list — the sharded-frames fast path (ROADMAP item
    1 end-to-end leg): fit packing then consumes the frame's shard
    partials directly instead of gathering to host and re-sharding."""
    sh = getattr(a, "sharding", None)
    if not isinstance(sh, NamedSharding):
        return False
    spec = tuple(sh.spec)
    if not spec or spec[0] != DATA_AXIS \
            or any(s is not None for s in spec[1:]):
        return False
    try:
        return [d.id for d in sh.mesh.devices.flat] \
            == [d.id for d in mesh.devices.flat]
    except Exception:
        return False


def pad_and_shard_rows(mesh: Optional[Mesh], *arrays):
    """Zero-pad every array's leading axis to the shard count and
    device_put them row-sharded; with no (or a trivial) mesh, pass through
    as plain device arrays. The generic variadic variant of
    ``place_sharded``, shared by the GLM/clustering fits — zero padding
    rows carry zero weight by construction in every masked statistic.

    Arrays that arrive ALREADY row-sharded over this mesh at a divisible
    row count (a sharded frame's columns) pass through untouched — no
    host gather, no re-placement."""
    if mesh is None or mesh.devices.size <= 1:
        return tuple(jnp.asarray(a) for a in arrays)
    if arrays[0].shape[0] % mesh.devices.size == 0 and \
            all(_pre_sharded(a, mesh) for a in arrays):
        from ..utils.profiling import counters

        counters.increment("shard.fit_passthrough")
        return tuple(arrays)
    rem = (-arrays[0].shape[0]) % mesh.devices.size
    shard = NamedSharding(mesh, P(DATA_AXIS))
    out = []
    for a in arrays:
        a = np.asarray(a)
        if rem:
            a = np.concatenate(
                [a, np.zeros((rem,) + a.shape[1:], a.dtype)])
        out.append(jax.device_put(a, shard))
    return tuple(out)


def place_sharded(X, y, mask, mesh: Optional[Mesh]):
    """Pad rows to the shard count and device_put with row sharding.
    Single-device/no-mesh inputs pass through as device arrays; inputs
    already row-sharded over this mesh (a sharded frame's columns) pass
    through without the host round trip."""
    if mesh is None or mesh.devices.size <= 1:
        return (jnp.asarray(X), jnp.asarray(y), jnp.asarray(mask, jnp.bool_))
    if X.shape[0] % mesh.devices.size == 0 and \
            all(_pre_sharded(a, mesh) for a in (X, y, mask)):
        from ..utils.profiling import counters

        counters.increment("shard.fit_passthrough")
        return X, y, mask
    Xh, yh, mh = pad_rows(np.asarray(X), np.asarray(y), np.asarray(mask, bool),
                          mesh.devices.size)
    shard = NamedSharding(mesh, P(DATA_AXIS))
    return (jax.device_put(Xh, shard), jax.device_put(yh, shard),
            jax.device_put(mh, shard))


def _gram_single_cpu(Xh, yh, mh):
    """Single-device Gramian pinned to the host CPU backend — the last
    rung of the sharded-Gramian fallback ladder: when the mesh path is
    failing (lost device, wedged tunnel), the statistics still compute,
    just slower. Falls back to the default device when this process has
    no CPU backend (should not happen; jax always registers one)."""
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        return _gram_single(jnp.asarray(Xh), jnp.asarray(yh),
                            jnp.asarray(mh, jnp.bool_))
    with jax.default_device(cpu):
        return _gram_single(jax.device_put(Xh, cpu), jax.device_put(yh, cpu),
                            jax.device_put(np.asarray(mh, bool), cpu))


def compute_gram(X, y, mask, mesh: Optional[Mesh] = None):
    """Augmented Gramian ``A``, sharded over ``mesh`` when it has >1 device.

    Accepts host or device arrays; on the sharded path, inputs are placed with
    a row-sharded ``NamedSharding`` so each device holds only its shard (HBM
    never sees the replicated matrix).

    The sharded path runs under the resilience policy
    (``utils.recovery.resilient_call``): a device error — real
    ``XlaRuntimeError`` or one injected at the ``gram_sharded`` fault
    site — retries with backoff, trips the ``gram_sharded`` circuit
    breaker, and ultimately falls back to the single-device CPU Gramian
    with a logged warning instead of aborting the fit. Identical
    statistics either way (the psum and the single matmul compute the
    same ``A``); only throughput degrades.
    """
    if mesh is None or mesh.devices.size <= 1:
        return _gram_single(jnp.asarray(X), jnp.asarray(y),
                            jnp.asarray(mask, jnp.bool_))
    from ..utils import faults as _faults
    from ..utils import observability as _obs
    from ..utils import recovery as _recovery
    from ..utils.profiling import counters

    nshards = mesh.devices.size
    # Sharded-frame fast path: inputs already row-sharded over THIS mesh
    # consume the frame's shard partials directly — no host gather, no
    # re-placement (padded slots are mask=False rows, zero weight in A).
    pre = (getattr(X, "shape", (1,))[0] % nshards == 0
           and all(_pre_sharded(a, mesh) for a in (X, y, mask)))
    if pre:
        counters.increment("shard.fit_passthrough")
        Xp, yp, mp = X, y, mask
    else:
        Xp, yp, mp = pad_rows(np.asarray(X), np.asarray(y),
                              np.asarray(mask, bool), nshards)
    shard = NamedSharding(mesh, P(DATA_AXIS))

    def sharded():
        _faults.inject("gram_sharded")
        counters.increment("parallel.psum_dispatches")
        # Per-shard Gramian timing: with tracing ON the span blocks on the
        # result so the duration covers the actual collective, not just
        # the async enqueue — an enabled-mode-only sync, per the
        # observability cost contract (disabled mode adds no host work).
        with _obs.span("parallel.gram_shard", cat="parallel",
                       shards=nshards, rows=int(Xp.shape[0]),
                       rows_per_shard=int(Xp.shape[0]) // nshards,
                       device=mesh.devices.flat[0].platform) as s:
            Xd = Xp if pre else jax.device_put(Xp, shard)
            yd = yp if pre else jax.device_put(yp, shard)
            md = mp if pre else jax.device_put(mp, shard)
            A = _gram_sharded_fn(mesh)(Xd, yd, md)
            if s is not _obs._NOOP:
                jax.block_until_ready(A)
            return A

    def single_cpu():
        logger.warning(
            "sharded Gramian failed on %d devices; falling back to the "
            "single-device CPU path", nshards)
        # fault-path host pull: the ladder's last rung computes on host
        # CPU whatever the mesh state is
        return _gram_single_cpu(np.asarray(Xp), np.asarray(yp),
                                np.asarray(mp, bool))

    mark = _obs.recovery_mark()
    # np.shape reads metadata only — never a device pull
    n_rows, n_feats = (int(s) for s in np.shape(X)[:2])
    with _obs.span("parallel.gram", cat="parallel", shards=nshards,
                   rows=n_rows, features=n_feats) as s:
        A = _recovery.resilient_call(
            sharded, site="gram_sharded",
            policy=_recovery.active_policy("gram_sharded"),
            fallbacks=[("single_cpu", single_cpu)],
            breaker=_recovery.DEVICE_BREAKER)
        _obs.annotate_recovery(s, mark)
        return A
