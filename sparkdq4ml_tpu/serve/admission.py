"""Admission control for the query-serving layer.

Every decision about whether a submitted query RUNS is made here, before
any engine work happens — the Snap ML lesson (PAPERS.md, arxiv
1803.06333) that a hierarchical execution framework needs its resource
policy at the front door, and the "Memory Safe Computations with XLA"
lesson (arxiv 2206.14148) that device-memory bounds belong in the plan
admission decision, not in an OOM backtrace.

Four gates, applied in order (first refusal wins):

1. **Overload shedding** — a per-tenant :class:`~sparkdq4ml_tpu.utils.
   recovery.CircuitBreaker` (the PR-1 machinery): a tenant whose queries
   keep failing or blowing deadlines trips its breaker and new
   submissions are *shed* instantly (status ``"shed"``) until the
   cooldown admits a half-open trial. A misbehaving tenant cannot occupy
   queue slots the healthy tenants need.
2. **Global queue bound** — total queued jobs across tenants is capped
   (``max_queue``); beyond it submissions are rejected with
   ``"queue_full"`` instead of growing an unbounded backlog.
3. **Per-tenant queue quota** — each tenant may hold at most
   ``quota.max_queued`` waiting jobs (``"tenant_queue_full"``); one
   chatty tenant cannot monopolize the global queue.
4. **Memory gate** — a job that declares an estimated device footprint
   (``est_bytes``) is checked against ``memory_limit_bytes`` on top of
   the live-array census (:func:`utils.meminfo.would_fit`); an
   over-budget job is rejected with ``"memory"`` *before* it can OOM the
   device mid-flight. Advisory (the census is a lower bound on allocator
   pressure), and only applied when both the limit and the estimate are
   known — a job with no estimate is admitted. The coalescing layer
   (``serve/coalesce.py``) sizes its STACKED batches against the same
   budget via :meth:`AdmissionController.batch_limit` — members that
   each fit individually must not stack N× over the gate.

Per-tenant **in-flight** quotas (``quota.max_in_flight``) are enforced by
the server's scheduler, not here: an admitted job waits in its tenant's
queue until the tenant has a free execution slot.

Every refusal is a structured :class:`Rejection` (status + machine-
readable reason + human detail) and lands in the ``serve.reject.*`` /
``serve.shed`` counters — refusals are observable, never silent.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..utils import meminfo
from ..utils.profiling import counters
from ..utils.recovery import CircuitBreaker


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Per-tenant resource limits. ``max_in_flight`` bounds concurrent
    executions (scheduler-enforced); ``max_queued`` bounds the waiting
    backlog (admission-enforced)."""

    max_in_flight: int = 4
    max_queued: int = 16

    def __post_init__(self):
        if self.max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        if self.max_queued < 0:
            raise ValueError("max_queued must be >= 0")


@dataclasses.dataclass(frozen=True)
class Rejection:
    """One structured admission refusal."""

    status: str          # "rejected" | "shed"
    reason: str          # queue_full | tenant_queue_full | memory |
    #                      breaker_open | shutdown
    detail: str = ""


class AdmissionController:
    """The four-gate admission policy (module docstring). Stateless apart
    from the breaker it is handed; the server calls :meth:`admit` under
    its scheduler lock so the queue-depth figures it sees are exact."""

    def __init__(self, max_queue: int = 64,
                 memory_limit_bytes: Optional[int] = None,
                 breaker: Optional[CircuitBreaker] = None):
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self.max_queue = int(max_queue)
        self.memory_limit_bytes = (None if memory_limit_bytes is None
                                   else int(memory_limit_bytes))
        self.breaker = breaker

    @staticmethod
    def breaker_key(tenant: str) -> str:
        return f"serve/{tenant}"

    def admit(self, tenant: str, quota: TenantQuota, queued_total: int,
              tenant_queued: int,
              est_bytes: Optional[int] = None,
              live_bytes: Optional[int] = None) -> Optional[Rejection]:
        """None = admitted; otherwise the structured refusal. Counters:
        ``serve.shed``, ``serve.reject`` plus ``serve.reject.<reason>``.
        ``live_bytes`` lets the caller take the live-array census BEFORE
        its scheduler lock (the census walks every live jax array — an
        O(arrays) scan the server must not hold its condition lock
        through); the gate is advisory, so a slightly stale figure is
        fine. ``None`` = census taken here."""
        if self.breaker is not None and not self.breaker.allow(
                self.breaker_key(tenant)):
            counters.increment("serve.shed")
            return Rejection(
                "shed", "breaker_open",
                f"tenant {tenant!r} circuit breaker is open "
                "(recent failures/deadline overruns); retry after cooldown")
        if queued_total >= self.max_queue:
            return self._reject(
                "queue_full",
                f"server queue is full ({queued_total}/{self.max_queue})")
        if tenant_queued >= quota.max_queued:
            return self._reject(
                "tenant_queue_full",
                f"tenant {tenant!r} queue is full "
                f"({tenant_queued}/{quota.max_queued})")
        if (self.memory_limit_bytes is not None and est_bytes is not None
                and est_bytes > 0):
            fits, live = meminfo.would_fit(
                est_bytes, self.memory_limit_bytes, live=live_bytes)
            if not fits:
                return self._reject(
                    "memory",
                    f"estimated {int(est_bytes)} B + live {live} B exceeds "
                    f"the {self.memory_limit_bytes} B device-memory limit")
        return None

    def batch_limit(self, per_member_bytes: Optional[int], max_batch: int,
                    live_bytes: Optional[int] = None) -> int:
        """Largest coalesced-batch member count whose STACKED footprint
        (``members × per_member_bytes``) still passes the memory gate —
        the batched-dispatch complement of :meth:`admit`, which prices
        one request at a time. Without it, N admitted jobs that each fit
        individually could stack into one dispatch ``N×`` over the very
        budget their admissions were checked against. Floor 1: a solo
        dispatch is exactly the footprint the member's own admission
        already cleared. ``live_bytes`` reuses a census the caller took
        (``None`` = census here); no limit or no estimate = no clamp."""
        max_batch = max(1, int(max_batch))
        if (self.memory_limit_bytes is None or per_member_bytes is None
                or per_member_bytes <= 0):
            return max_batch
        live = meminfo.live_bytes() if live_bytes is None else int(live_bytes)
        headroom = self.memory_limit_bytes - live
        return max(1, min(max_batch,
                          int(headroom // int(per_member_bytes))))

    @staticmethod
    def _reject(reason: str, detail: str) -> Rejection:
        counters.increment("serve.reject")
        counters.increment(f"serve.reject.{reason}")
        return Rejection("rejected", reason, detail)
