"""Cross-request plan coalescing: adaptive micro-batching of
identical-plan queries into one stacked device dispatch.

ROADMAP item 2's raw-speed half. The shared plan cache (PR 13) already
proves cross-tenant structural plan identity — N concurrent requests
whose flushes hash to the same plan key are provably running the SAME
compiled program — yet each still pays its own device dispatch. This
module is the Snap ML hierarchy argument (PAPERS.md, arxiv 1803.06333:
amortize per-dispatch overhead by batching work at every level) applied
to the serving tier: a short, load-triggered hold window groups those
flushes, stacks their padded inputs along a new leading member axis,
executes ONE vmapped program (``ops/compiler.run_batched``), and
de-interleaves the results to each waiter.

**Grouping key** — ``(plan key, row bucket, literal type signature)``.
The plan key already embeds the dtype tag, the cache namespace, and the
shard tag, so different dtypes, isolated tenants, and sharded flushes
never coalesce by construction. Hoisted numeric literals are NOT in the
key: ``price < 3`` and ``price < 4`` share one plan and DO coalesce —
each literal slot stacks into a ``(batch,)`` argument the vmapped body
broadcasts per member. The literal TYPE signature rides the group key
so an int and a float in the same slot (different weak-type promotion)
dispatch separately rather than risk a dtype drift.

**Rendezvous** — the first flush to arrive for a key becomes the batch
LEADER: it waits up to ``maxDelayMs`` (cut short the moment the batch
fills) for followers, closes the batch, and executes. Followers deposit
their padded inputs and block on the batch's done event; the leader
always resolves it (success, degrade, or per-member error). A batch of
one executes the plain per-request program — no batched machinery, no
counters.

**Adaptivity** — the server arms a scope only when the queue depth at
pop time is at least ``minQueueDepth`` AND the job's deadline has
headroom for the window (a near-deadline job dispatches solo, never
waits). Below that the contextvar stays None and ``run_pipeline`` is
byte-for-byte the per-request path (one None check, test-pinned).

**Sizing** — the batch cap is ``min(maxBatch, admission.batch_limit)``:
the admission memory gate prices the STACKED batch (members ×
per-member estimate) against the same budget single requests pass, so
coalescing cannot OOM a gate the members individually cleared.

**Fault ladder** — site ``coalesce`` (``device_error`` / ``stall`` /
``oom``): any batched-dispatch failure, injected or real, degrades the
WHOLE batch to per-request replay of the same cached plan — golden
results on every rung — counted ``serve.coalesce.degraded`` with a
``recovery.fallback`` event; a member whose replay itself fails gets
that error delivered individually (its own Frame ladder takes over).

Observability: ``serve.coalesce.batched/dispatches/degraded`` counters,
``serve.coalesce.batch_size/window_ms`` histograms, and — with tracing
on — one shared ``serve.coalesce`` span per member tree carrying the
batch id and the full member trace-id list, so every ``/trace/<id>``
lookup shows which requests rode which batch.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from typing import Optional

from ..ops import compiler as _compiler
from ..utils import faults as _faults
from ..utils import observability as _obs
from ..utils.profiling import counters

__all__ = ["Coalescer"]

#: Follower safety bound (s): the leader resolves every batch in a
#: ``finally``, so this only fires if a leader thread is killed mid-
#: dispatch — same order as the wire layer's RESULT_BOUND_S.
_FOLLOWER_BOUND_S = 600.0

#: Deadline headroom multiple: a job enters a scope only when its
#: remaining budget exceeds this many hold windows, so waiting one full
#: window can never be what blows the deadline.
_HEADROOM_WINDOWS = 4.0

_BATCH_IDS = itertools.count(1)


class _Member:
    """One flush waiting in a batch: the padded calling convention plus
    the request's trace context (for the shared batch span)."""

    __slots__ = ("kept", "donated", "mask", "lits", "ctx")

    def __init__(self, kept, donated, mask, lits, ctx):
        self.kept = kept
        self.donated = donated
        self.mask = mask
        self.lits = lits
        self.ctx = ctx


class _Batch:
    """One rendezvous: members join while ``open``; the leader closes,
    executes, fills ``results`` (one ``("ok", value) | ("err", exc)``
    per member, member order) and sets ``done``."""

    __slots__ = ("members", "open", "limit", "full", "done", "results")

    def __init__(self, limit: int):
        self.members: list[_Member] = []
        self.open = True
        self.limit = int(limit)
        self.full = threading.Event()
        self.done = threading.Event()
        self.results: Optional[list] = None


class _Sink:
    """Per-job handle the compiler's coalesce scope holds: binds the
    job's trace context to the shared :class:`Coalescer`."""

    __slots__ = ("co", "ctx")

    def __init__(self, co: "Coalescer", ctx):
        self.co = co
        self.ctx = ctx

    def dispatch(self, plan, b, kept, donated, mask, lits):
        return self.co._dispatch(self.ctx, plan, b, kept, donated,
                                 mask, lits)


class Coalescer:
    """The serving tier's cross-request batcher (module docstring).

    One instance per :class:`~.server.QueryServer`, shared by every
    worker; stateless apart from the open-batch table. Thread-safe: the
    one lock guards only list/dict membership — stacking, device
    execution, metrics, and spans all happen outside it (the serve
    layer's lock-hygiene rule)."""

    def __init__(self, admission=None, max_delay_ms: float = 2.0,
                 max_batch: int = 8, min_queue_depth: int = 2):
        self.admission = admission
        self.max_delay_s = max(float(max_delay_ms), 0.0) / 1e3
        self.max_batch = max(int(max_batch), 1)
        self.min_queue_depth = max(int(min_queue_depth), 0)
        self._lock = threading.Lock()
        self._open: dict[tuple, _Batch] = {}

    # -- scope (the server's per-job arming decision) -----------------------
    def scope(self, job, queue_depth: int):
        """The context manager ``_execute`` wraps a job's work in: the
        compiler coalesce scope when this job qualifies, else the shared
        nullcontext (light load / no headroom / degenerate conf — the
        per-request path, untouched)."""
        if (queue_depth < self.min_queue_depth or self.max_batch <= 1
                or self.max_delay_s <= 0.0):
            return contextlib.nullcontext()
        if job.deadline_ts is not None and (
                job.deadline_ts - time.perf_counter()
                < _HEADROOM_WINDOWS * self.max_delay_s):
            # a job this close to its (wire) deadline must never sit in
            # a hold window: dispatch solo, exactly the uncoalesced path
            return contextlib.nullcontext()
        return _compiler.coalesce_scope(_Sink(self, job.trace))

    # -- member dispatch (called from inside run_pipeline) ------------------
    def _dispatch(self, ctx, plan, b, kept, donated, mask, lits):
        cap = self.max_batch
        if (self.admission is not None
                and self.admission.memory_limit_bytes is not None):
            # price the STACKED batch against the memory gate BEFORE the
            # rendezvous lock (the census walks every live array)
            per = _compiler.est_member_bytes(plan, kept, donated, b)
            cap = self.admission.batch_limit(per, cap)
        member = _Member(kept, donated, mask, lits, ctx)
        key = (plan.key, b, tuple(type(v).__name__ for v in lits))
        with self._lock:
            batch = self._open.get(key)
            if batch is not None and batch.open \
                    and len(batch.members) < batch.limit:
                batch.members.append(member)
                idx = len(batch.members) - 1
                if len(batch.members) >= batch.limit:
                    batch.open = False
                    del self._open[key]
                    batch.full.set()
                leader = False
            else:
                batch = _Batch(cap)
                batch.members.append(member)
                idx = 0
                leader = True
                if cap > 1:
                    self._open[key] = batch
        if not leader:
            batch.done.wait(_FOLLOWER_BOUND_S)
            return self._take(batch, idx)
        return self._lead(key, batch, plan, b)

    def _lead(self, key, batch, plan, b):
        t0 = time.perf_counter()
        if batch.limit > 1:
            batch.full.wait(self.max_delay_s)
        with self._lock:
            batch.open = False
            if self._open.get(key) is batch:
                del self._open[key]
        window_ms = (time.perf_counter() - t0) * 1e3
        try:
            if len(batch.members) == 1:
                m = batch.members[0]
                # no partner arrived: the plain per-request program —
                # bit-identical, uncounted, and any error is simply this
                # flush's own error
                batch.results = [None]
                return plan.fn(m.kept, m.donated, m.mask, m.lits)
            self._run_batch(batch, plan, b, window_ms)
            return self._take(batch, 0)
        finally:
            if batch.results is None:
                # leader died before filling results (a non-Exception
                # unwind): fail the followers rather than wedge them
                batch.results = [
                    ("err", RuntimeError("coalesced batch abandoned"))
                ] * len(batch.members)
            batch.done.set()

    def _run_batch(self, batch, plan, b, window_ms: float) -> None:
        members = batch.members
        n = len(members)
        t0 = time.perf_counter()
        try:
            # chaos hooks at the batched-dispatch boundary (one None
            # check without a plan): a due device_error raises the same
            # JaxRuntimeError class a real batched device fault would; a
            # due stall marks the batched program wedged; a due oom
            # shrinks the stacked-bytes budget under this batch
            _faults.inject("coalesce")
            if _faults.fired("coalesce", "stall"):
                raise _Stalled("injected coalesce stall")
            budget = _faults.shrunk_budget("coalesce")
            if budget is not None:
                per = _compiler.est_member_bytes(
                    plan, members[0].kept, members[0].donated, b)
                if n * per > budget:
                    raise _OverBudget(
                        f"stacked est {n * per} B > budget {budget} B")
            outs = _compiler.run_batched(
                plan, b, [(m.kept, m.donated, m.mask, m.lits)
                          for m in members])
        except Exception as e:   # noqa: BLE001 — every rung degrades
            self._degrade(batch, plan, e)
            return
        batch.results = [("ok", o) for o in outs]
        counters.increment("serve.coalesce.dispatches")
        counters.increment("serve.coalesce.batched", n)
        _obs.METRICS.observe("serve.coalesce.batch_size", float(n))
        _obs.METRICS.observe("serve.coalesce.window_ms", window_ms)
        self._emit_spans(members, plan, n, window_ms,
                         (time.perf_counter() - t0) * 1e3,
                         degraded=False)

    def _degrade(self, batch, plan, cause: BaseException) -> None:
        """The whole-batch fault rung: per-request replay of the SAME
        cached plan — golden results by construction (each member runs
        exactly the program it would have run uncoalesced); a member
        whose replay fails gets that error individually."""
        from ..utils.recovery import RECOVERY_LOG

        members = batch.members
        counters.increment("serve.coalesce.degraded")
        RECOVERY_LOG.record(
            "coalesce", "fallback", rung="per_request",
            cause=f"{type(cause).__name__}: {cause}",
            detail=f"batched dispatch of {len(members)} member(s) "
                   "degraded to per-request replay")
        results = []
        for m in members:
            try:
                results.append(
                    ("ok", plan.fn(m.kept, m.donated, m.mask, m.lits)))
            except Exception as e:   # noqa: BLE001 — per-member verdict
                results.append(("err", e))
        batch.results = results
        self._emit_spans(members, plan, len(members), 0.0, 0.0,
                         degraded=True)

    @staticmethod
    def _take(batch, idx: int):
        res = batch.results[idx] if batch.results is not None else None
        if res is None:
            raise RuntimeError("coalesced batch never resolved")
        kind, payload = res
        if kind == "err":
            raise payload
        return payload

    @staticmethod
    def _emit_spans(members, plan, n: int, window_ms: float,
                    exec_ms: float, *, degraded: bool) -> None:
        """One shared ``serve.coalesce`` span per member request tree —
        same batch id and member trace-id list on each, so any member's
        ``/trace/<id>`` shows the whole rendezvous."""
        if not _obs.TRACER.enabled:
            return
        bid = next(_BATCH_IDS)
        ids = ",".join(m.ctx.trace_id for m in members
                       if m.ctx is not None)
        for m in members:
            if m.ctx is None:
                continue
            _obs.emit_span(
                "serve.coalesce", cat="serve", dur_ms=exec_ms, ctx=m.ctx,
                batch_id=bid, batch=n, members=ids,
                window_ms=round(window_ms, 3),
                plan_key=plan.key[:160], degraded=degraded)

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            open_batches = len(self._open)
        return {
            "max_delay_ms": self.max_delay_s * 1e3,
            "max_batch": self.max_batch,
            "min_queue_depth": self.min_queue_depth,
            "open_batches": open_batches,
            "batched": counters.get("serve.coalesce.batched"),
            "dispatches": counters.get("serve.coalesce.dispatches"),
            "degraded": counters.get("serve.coalesce.degraded"),
        }


class _Stalled(RuntimeError):
    """Injected ``coalesce:stall`` — the batched program is treated as
    wedged and the batch degrades; deliberately NOT a JaxRuntimeError
    (nothing device-side failed, so nothing should retry device-side)."""


class _OverBudget(RuntimeError):
    """Stacked batch priced over the (fault-shrunk) byte budget — the
    memory rung of the coalesce ladder."""
