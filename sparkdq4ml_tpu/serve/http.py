"""Live HTTP observability endpoint — telemetry at the process boundary.

The Snap ML hierarchy (PAPERS.md, arxiv 1803.06333) frames why a serving
system must export its telemetry OUTSIDE the process: in-process
``session.metrics_text()`` is useless to the Prometheus scraper, the
load balancer's health probe, or the operator tailing a wedged box. This
module is the stdlib-only (``http.server``) answer — one daemon thread,
four read-only routes over state other subsystems already maintain:

========== ==============================================================
route      payload
========== ==============================================================
/metrics   the Prometheus text snapshot (``observability.
           prometheus_text()`` — counters, gauges, cumulative-bucket
           histograms, HELP/TYPE headers), engine + server in one scrape
/healthz   JSON health verdict: worker liveness, queue depth vs bound,
           circuit-breaker state — HTTP 200 when serving, 503 when
           shedding-degraded (load-balancer semantics)
/plans     the plan-statistics observatory (``utils.statstore``) report:
           per-plan-key selectivity, wall/compile digests, byte bounds
/trace     recent finished spans as JSON (bounded tail of the span
           buffer) — the "what just happened" view. ``?trace_id=``
           filters to one wire trace, ``?limit=N`` bounds the tail
/trace/    every completed span TREE for one wire trace id (the id a
<id>       client holds from its ``ClientResult.trace_id``) from the
           tail sampler — retained store first, recent ring fallback;
           404 when the id aged out of both
/incidents flight-recorder index: bounded listing of captured incident
           bundles (id, trigger, time, trace id); ``/incidents/<id>``
           returns one full bundle (404 on miss)
/profile   the device-cost observatory (``utils.costprof``) report:
           per-plan AOT cost profile (flops/bytes/collective traffic)
           joined with statstore wall history into achieved GFLOP/s /
           GB/s, roofline verdicts, top-N by device-time share, plus
           the newest managed profiler-capture path. ``?top=N`` bounds
           the entry list; extraction is budgeted per scrape (pending
           entries fill in on later scrapes) so a scrape latency stays
           bounded by a constant, not the cache population
/profile/  arms one managed jax-profiler capture for ``?seconds=N``
trace      (``utils.profiling.start_capture`` — bounded retention,
           timestamp+context naming); 409 while a capture is running
========== ==============================================================

Security: binds ``127.0.0.1`` by default (``spark.serve.metricsHost`` to
widen — the routes are read-only but unauthenticated; fronting with a
real proxy is the operator's job). OFF by default: no
``spark.serve.metricsPort`` → no socket, no thread, no cost (the
pay-for-use rule every subsystem here follows).

Every route handler reads lock-protected snapshots only — a scrape can
never stall a worker, and the 100 ms scraper the chaos soak runs
alongside 32 clients is the regression gate for that claim.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

logger = logging.getLogger("sparkdq4ml_tpu.serve.http")

#: /trace returns at most this many of the newest finished spans.
TRACE_TAIL = 256


def _json_default(v):
    return str(v)


class TelemetryServer:
    """The observability HTTP front end. Standalone-usable (``server``
    may be None — /healthz then reports the engine view only) but
    normally owned by a :class:`~.server.QueryServer` (started from
    ``spark.serve.metricsPort``, stopped with the server)."""

    def __init__(self, server=None, host: str = "127.0.0.1",
                 port: int = 0):
        self.query_server = server
        self.host = host
        self._requested_port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._httpd is not None

    @property
    def port(self) -> Optional[int]:
        """The BOUND port (resolves a requested port of 0)."""
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            return self
        telemetry = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # quiet by default
                logger.debug("telemetry %s", fmt % args)

            def do_GET(self):                     # noqa: N802 (stdlib API)
                telemetry._handle(self)

        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="sparkdq4ml-telemetry")
        self._thread.start()
        logger.info("telemetry endpoint on http://%s:%d "
                    "(/metrics /healthz /plans /trace /incidents)",
                    self.host, self.port)
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "TelemetryServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- routes -------------------------------------------------------------
    def _handle(self, req) -> None:
        try:
            path = req.path.split("?", 1)[0].rstrip("/") or "/"
            if path == "/metrics":
                body, ctype, code = self._metrics()
            elif path == "/healthz":
                body, ctype, code = self._healthz()
            elif path == "/plans":
                body, ctype, code = self._plans()
            elif path == "/trace":
                body, ctype, code = self._trace(req.path)
            elif path == "/profile":
                body, ctype, code = self._profile(req.path)
            elif path == "/profile/trace":
                body, ctype, code = self._profile_trace(req.path)
            elif path.startswith("/trace/"):
                body, ctype, code = self._trace_tree(
                    path[len("/trace/"):])
            elif path == "/dq":
                body, ctype, code = self._dq(req.path)
            elif path == "/incidents":
                body, ctype, code = self._incidents()
            elif path.startswith("/incidents/"):
                body, ctype, code = self._incident(
                    path[len("/incidents/"):])
            else:
                body, ctype, code = (
                    json.dumps({"error": "unknown route", "routes": [
                        "/metrics", "/healthz", "/plans", "/trace",
                        "/trace/<trace_id>", "/incidents",
                        "/incidents/<id>", "/profile",
                        "/profile/trace", "/dq"]}),
                    "application/json", 404)
        except Exception as e:   # a route bug must answer, not hang
            logger.debug("telemetry route failed", exc_info=True)
            body = json.dumps({"error": f"{type(e).__name__}: {e}"})
            ctype, code = "application/json", 500
        payload = body.encode()
        try:
            req.send_response(code)
            req.send_header("Content-Type", ctype)
            req.send_header("Content-Length", str(len(payload)))
            req.end_headers()
            req.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            pass                 # scraper went away mid-answer

    def _metrics(self):
        from ..utils import observability as _obs

        return (_obs.prometheus_text(),
                "text/plain; version=0.0.4; charset=utf-8", 200)

    def _healthz(self):
        doc: dict = {"status": "ok"}
        srv = self.query_server
        if srv is not None:
            stats = srv.stats()
            open_breakers = sorted(
                key for key, st in (stats.get("breaker") or {}).items()
                if st.get("open"))
            queue_depth = stats.get("queue_depth", 0)
            saturated = queue_depth >= srv.admission.max_queue
            doc.update({
                "serving": stats["running"],
                "workers": stats["workers"],
                "queue_depth": queue_depth,
                "max_queue": srv.admission.max_queue,
                "tenants": len(stats.get("tenants") or ()),
                "open_breakers": open_breakers,
            })
            if stats.get("draining"):
                # drain window (begin_drain()/stop() in progress): the
                # balancer must stop routing here NOW, even though
                # in-flight work is still finishing — 503 from the
                # first moment of the drain, not only once stopped
                doc["status"] = "draining"
            elif not stats["running"]:
                doc["status"] = "stopped"
            elif open_breakers or saturated:
                # degraded = load is being shed (breaker) or the queue
                # is at its admission bound — the 503 a balancer should
                # route around, while /metrics keeps answering 200
                doc["status"] = "degraded"
                doc["degraded_because"] = (
                    ["breaker_open"] if open_breakers else []) + (
                    ["queue_full"] if saturated else [])
        else:
            doc["serving"] = False
        code = 200 if doc["status"] == "ok" else 503
        return json.dumps(doc), "application/json", code

    def _plans(self):
        from ..config import config as _cfg
        from ..utils import statstore as _stats

        if not _cfg.stats_enabled:
            return (json.dumps({"enabled": False, "entries": []}),
                    "application/json", 200)
        doc = _stats.STORE.report()
        doc["enabled"] = True
        return (json.dumps(doc, default=_json_default),
                "application/json", 200)

    @staticmethod
    def _query_params(raw_path: str) -> dict:
        from urllib.parse import parse_qs, urlsplit

        qs = parse_qs(urlsplit(raw_path).query)
        return {k: v[-1] for k, v in qs.items() if v}

    def _dq(self, raw_path: str):
        """Data-quality observatory view (``utils/dqprof.py``): column
        profiles + drift scores + per-rule violation tallies. The drain
        this triggers is the module's own counted cold-path sync."""
        from ..config import config as _cfg
        from ..utils import dqprof as _dqprof

        if not _cfg.dq_profile_enabled:
            return (json.dumps({"enabled": False, "columns": [],
                                "rules": []}),
                    "application/json", 200)
        params = self._query_params(raw_path)
        try:
            top = int(params.get("top", 64))
        except ValueError:
            top = 64
        return (json.dumps(_dqprof.report(top=top),
                           default=_json_default),
                "application/json", 200)

    def _profile(self, raw_path: str):
        from ..config import config as _cfg
        from ..utils import costprof as _costprof
        from ..utils import observability as _obs
        from ..utils.profiling import counters as _counters

        if not _cfg.costprof_enabled:
            return (json.dumps({"enabled": False, "entries": []}),
                    "application/json", 200)
        params = self._query_params(raw_path)
        try:
            top = int(params.get("top", 32))
        except ValueError:
            top = 32
        doc = _costprof.report(top=top)
        doc["skew"] = _obs.METRICS.get_gauge("shard.skew") or None
        doc["exchange_bytes"] = _counters.snapshot("shard.exchange_bytes")
        return (json.dumps(doc, default=_json_default),
                "application/json", 200)

    def _profile_trace(self, raw_path: str):
        from ..utils import profiling as _profiling

        params = self._query_params(raw_path)
        try:
            seconds = float(params.get("seconds", 1.0))
        except ValueError:
            seconds = 1.0
        label = params.get("label", "http")
        try:
            path = _profiling.start_capture(seconds, label=label)
        except RuntimeError as e:
            # one capture at a time (the jax profiler is process-global)
            return (json.dumps({"armed": False, "error": str(e)}),
                    "application/json", 409)
        return (json.dumps({"armed": True, "path": path,
                            "seconds": min(seconds,
                                           _profiling.MAX_CAPTURE_S)}),
                "application/json", 200)

    def _trace(self, raw_path: str):
        from ..utils import observability as _obs

        params = self._query_params(raw_path)
        try:
            limit = min(int(params.get("limit", TRACE_TAIL)),
                        TRACE_TAIL)
        except ValueError:
            limit = TRACE_TAIL
        wanted = params.get("trace_id")
        spans = _obs.TRACER.spans()
        if wanted:
            # the filter matches the WIRE trace id (what a client holds)
            # as well as the internal one, so either join key works
            spans = [s for s in spans
                     if str(s.trace_id) == wanted
                     or s.attrs.get("wire_trace_id") == wanted]
        rows = [{
            "name": s.name, "cat": s.cat, "trace_id": s.trace_id,
            "span_id": s.sid, "parent_id": s.parent_id, "tid": s.tid,
            "ts_us": s.ts_us, "dur_us": s.dur_us,
            "attrs": {k: v for k, v in s.attrs.items()},
        } for s in spans[-max(0, limit):]]
        return (json.dumps({"spans": rows, "dropped": _obs.TRACER.dropped,
                            "enabled": _obs.TRACER.enabled},
                           default=_json_default),
                "application/json", 200)

    def _trace_tree(self, trace_id: str):
        from ..utils import observability as _obs

        trees = _obs.TAIL.lookup(trace_id)
        if not trees:
            return (json.dumps({"error": "unknown trace_id",
                                "trace_id": trace_id}),
                    "application/json", 404)
        return (json.dumps({"trace_id": trace_id, "trees": trees},
                           default=_json_default),
                "application/json", 200)

    def _incidents(self):
        from ..utils import incidents as _incidents

        return (json.dumps({"incidents": _incidents.RECORDER.list(),
                            "recorder": _incidents.RECORDER.report()},
                           default=_json_default),
                "application/json", 200)

    def _incident(self, incident_id: str):
        from ..utils import incidents as _incidents

        bundle = _incidents.RECORDER.get(incident_id)
        if bundle is None:
            return (json.dumps({"error": "unknown incident",
                                "id": incident_id}),
                    "application/json", 404)
        return (json.dumps(bundle, default=_json_default),
                "application/json", 200)
