"""QueryServer — the concurrent query-serving front end.

The reference app is a single-caller batch script; the ROADMAP north star
is serving heavy traffic from many users. This module is the layer in
between: a :class:`QueryServer` multiplexes N concurrent *logical
tenants* over the one process-wide engine (one device, one jit-cache
population), following Snap ML's hierarchical execution framing
(PAPERS.md, arxiv 1803.06333) — many workloads, one shared accelerator
state.

Architecture::

    clients ── submit(sql | fn, tenant=..) ──► AdmissionController
                                                   │ admitted
                                             per-tenant FIFO queues
                                                   │ round-robin, gated on
                                                   │ quota.max_in_flight
                                             worker thread-pool
                                                   │ plan_namespace(tenant)
                                                   │   (isolated mode only)
                                             engine (frame / SQL / fits)

* **Sessions / tenants** — each tenant gets a :class:`TenantContext`
  with its OWN temp-view :class:`~sparkdq4ml_tpu.sql.catalog.Catalog`
  (two tenants can both ``CREATE VIEW price`` without colliding), over
  the SHARED engine and its process-wide plan/jit caches.
* **Shared plan cache** — the structural plan keys from PRs 3/4 contain
  no tenant identity, so tenant B's first query replays tenant A's
  compiled programs with zero new compiles (test-pinned via
  ``cache_report`` diffs). ``shared_plan_cache=False`` partitions the
  pipeline + grouped caches per tenant via
  :func:`ops.compiler.plan_namespace` — the control arm of the serving
  bench. (Solver/fit jit factories key on model params only and stay
  shared in both modes; they carry no per-tenant state.)
* **Admission control** — see :mod:`serve.admission`: breaker shedding,
  global + per-tenant queue bounds, device-memory gate.
* **Deadlines** — ``deadline_s`` bounds a query end-to-end. A job still
  queued past its deadline never executes; a result that lands after the
  deadline is discarded; and ``QueryFuture.result()`` returns a
  structured ``deadline_exceeded`` :class:`QueryResult` at most a grace
  period after the deadline even when the execution is wedged — a
  deadline is never a hang. The in-flight XLA dispatch itself cannot be
  cancelled (same contract as ``utils.recovery.DeadlineExceeded``); the
  worker discards its late result and records ``serve.late_result``.
* **SLO observability** — ``serve.queue_depth`` / ``serve.in_flight`` /
  ``serve.tenants`` gauges, ``serve.queue_ms`` / ``serve.exec_ms`` /
  ``serve.e2e_ms`` latency histograms (plus per-tenant
  ``serve.e2e_ms.<tenant>`` series, capped at
  :data:`MAX_TENANT_SERIES`), and admit/reject/shed/deadline/complete/
  error counters — all through the PR-2 Prometheus surface
  (``session.metrics()`` / ``prometheus_text()`` cover engine + server
  in one scrape). ``submit(collect_stats=True)`` runs the query under
  the PR-5 ``observability.query_stats`` collector and attaches it to
  the result.

Cost contract: a process that never starts a server pays nothing — no
threads, no counters, no gauges (the disabled-mode rule every subsystem
here follows). Threading model: see ``session.py`` § "Threading model".
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import threading
import time
from typing import Any, Optional

from ..config import CONF_FALSE
from ..config import config as _cfg
from ..utils import faults as _faults
from ..utils import incidents as _incidents
from ..utils import observability as _obs
from ..utils.profiling import counters
from ..utils.recovery import CircuitBreaker
from .admission import AdmissionController, TenantQuota

#: Per-tenant latency-histogram cap: beyond this many distinct tenants the
#: aggregate ``serve.e2e_ms`` histogram still records every query but no
#: new per-tenant series is created (unbounded label cardinality is how
#: scrapes die in production).
MAX_TENANT_SERIES = 64

#: How long past a job's deadline ``QueryFuture.result()`` keeps waiting
#: for the worker's own (more informative) resolution before synthesizing
#: the structured deadline result itself.
RESULT_GRACE_S = 0.25

#: Admitted-tenant sweep threshold: when a NEW tenant's first admitted
#: job would grow the tenant table past this, idle stateless tenants
#: (empty queue, nothing in flight, no registered views, default quota)
#: are reaped first. Without it, one admitted trivial query per unique
#: tenant name grows the round-robin scan and process memory forever —
#: the admitted-flood sibling of the refused-flood hardening in submit().
TENANT_REAP_THRESHOLD = 1024


class ServeError(RuntimeError):
    """Base class for serving-layer errors raised by ``value()``."""


class QueryRefused(ServeError):
    """The query never ran: admission rejected or shed it."""


class QueryDeadlineExceeded(ServeError):
    """The query's end-to-end deadline passed before a result landed."""


class QueryExecutionError(ServeError):
    """The query ran and raised; the original error string is attached."""


@dataclasses.dataclass
class QueryResult:
    """Structured outcome of one submitted query — ALWAYS returned (never
    raised) by ``QueryFuture.result()``; use :meth:`value_or_raise` for
    exception-style consumption."""

    status: str                      # ok | rejected | shed |
    #                                  deadline_exceeded | error
    tenant: str
    value: Any = None
    reason: str = ""                 # machine-readable refusal reason
    detail: str = ""                 # human-readable refusal detail
    error: str = ""                  # exception repr for status="error"
    where: str = ""                  # deadline site: queue | exec | wait
    tag: Optional[str] = None
    queue_ms: Optional[float] = None
    exec_ms: Optional[float] = None
    e2e_ms: Optional[float] = None
    stats: Optional[object] = None   # QueryStatsCollector (collect_stats)

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    def value_or_raise(self):
        if self.status == "ok":
            return self.value
        if self.status in ("rejected", "shed"):
            raise QueryRefused(
                f"query for tenant {self.tenant!r} {self.status} "
                f"({self.reason}): {self.detail}")
        if self.status == "deadline_exceeded":
            raise QueryDeadlineExceeded(
                f"query for tenant {self.tenant!r} exceeded its deadline "
                f"({self.where})")
        raise QueryExecutionError(
            f"query for tenant {self.tenant!r} failed: {self.error}")


class _Job:
    """One admitted unit of work. Resolution is idempotent — the first
    resolver (worker, or a deadline-synthesizing waiter) wins; later
    attempts are reported back so the loser can record ``late_result``."""

    __slots__ = ("work", "tenant", "tag", "deadline_s", "deadline_ts",
                 "t_submit", "est_bytes", "collect_stats", "attempts",
                 "trace", "_event", "_lock", "result")

    def __init__(self, work, tenant, tag, deadline_s, est_bytes,
                 collect_stats, trace=None):
        self.work = work
        self.tenant = tenant
        self.tag = tag
        # wire trace context (observability.TraceContext once adopted by
        # _execute; None with tracing off — the disabled-mode no-op)
        self.trace = trace
        self.deadline_s = deadline_s
        self.t_submit = time.perf_counter()
        self.deadline_ts = (None if deadline_s is None
                            else self.t_submit + float(deadline_s))
        self.est_bytes = est_bytes
        self.collect_stats = collect_stats
        self.attempts = 0      # executions so far (the requeue ladder)
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.result: Optional[QueryResult] = None

    def resolve(self, result: QueryResult) -> bool:
        with self._lock:
            if self.result is not None:
                return False
            self.result = result
        self._event.set()
        return True


class QueryFuture:
    """Handle to one submitted query."""

    def __init__(self, job: _Job, server: "QueryServer"):
        self._job = job
        self._server = server

    def done(self) -> bool:
        return self._job._event.is_set()

    def result(self, timeout: Optional[float] = None) -> QueryResult:
        """Block until the query resolves and return its
        :class:`QueryResult`. Deadline queries NEVER hang: at most
        ``deadline + grace`` after submission this returns a structured
        ``deadline_exceeded`` result even if the execution is wedged
        (the worker's late result is then discarded). Without a
        deadline, ``timeout`` bounds the wait (``TimeoutError`` on
        expiry, matching ``concurrent.futures`` semantics)."""
        job = self._job
        while True:
            wait = timeout
            if job.deadline_ts is not None:
                bound = max(0.0, job.deadline_ts - time.perf_counter()) \
                    + RESULT_GRACE_S
                wait = bound if timeout is None else min(timeout, bound)
            if job._event.wait(wait):
                return job.result
            if (job.deadline_ts is not None
                    and time.perf_counter() >= job.deadline_ts):
                self._server._resolve_deadline(job, where="wait")
                return job.result
            if timeout is not None:
                raise TimeoutError(
                    f"query for tenant {job.tenant!r} not done within "
                    f"{timeout:.3g} s")
            # no deadline, no timeout: keep waiting

    def value(self, timeout: Optional[float] = None):
        """``result().value_or_raise()`` — exception-style consumption."""
        return self.result(timeout).value_or_raise()


class TenantContext:
    """What a tenant's job sees: tenant-scoped SQL/temp views over the
    shared engine. The catalog is PER TENANT (two tenants can both
    register a ``price`` view); UDF registry, jit caches, and the device
    are shared process state."""

    def __init__(self, server: "QueryServer", tenant: str):
        from ..sql.catalog import Catalog

        self._server = server
        self.tenant = tenant
        self.catalog = Catalog()

    def sql(self, query: str):
        """Run SQL against THIS tenant's temp views."""
        from ..sql.parser import execute as _sql_execute

        return _sql_execute(query, self.catalog)

    def register_view(self, name: str, frame) -> None:
        """Tenant-scoped ``createOrReplaceTempView`` (the Frame method of
        the same name registers in the process-default catalog and is
        NOT tenant-isolated — server jobs should register here)."""
        self.catalog.register(name, frame)

    create_or_replace_temp_view = register_view

    def table(self, name: str):
        return self.catalog.lookup(name)

    @property
    def session(self):
        s = self._server.session
        if s is None:
            raise RuntimeError("this QueryServer was built without a "
                               "TpuSession; ctx.session is unavailable")
        return s

    @property
    def read(self):
        from ..frame.csv import DataFrameReader

        return DataFrameReader(self.session)


class _TenantState:
    __slots__ = ("name", "quota", "queue", "in_flight", "context",
                 "exposed")

    def __init__(self, server, name: str, quota: TenantQuota):
        self.name = name
        self.quota = quota
        self.queue: collections.deque[_Job] = collections.deque()
        self.in_flight = 0
        self.context = TenantContext(server, name)
        # True once server.context(tenant) handed this context out: a
        # client may be holding it to register views later, so the reap
        # sweep must not orphan it (jobs see the context only transiently
        # during _execute and are not "exposed" in this sense).
        self.exposed = False


class QueryServer:
    """Multi-tenant query server over one engine (module docstring).

    Usable directly or as a context manager::

        with QueryServer(session, workers=8) as srv:
            fut = srv.submit("SELECT count(*) c FROM t", tenant="a")
            print(fut.result().value.to_pydict())

    or built from session conf via ``session.serve()`` (``spark.serve.*``
    keys — see :meth:`from_conf`).
    """

    def __init__(self, session=None, *, workers: int = 4,
                 max_queue: int = 64,
                 default_quota: Optional[TenantQuota] = None,
                 memory_limit_bytes: Optional[int] = None,
                 shared_plan_cache: bool = True,
                 default_deadline_s: Optional[float] = None,
                 breaker_threshold: int = 5,
                 breaker_cooldown: float = 5.0,
                 breaker: Optional[CircuitBreaker] = None,
                 metrics_port: Optional[int] = None,
                 metrics_host: str = "127.0.0.1",
                 slo_p99_ms: Optional[float] = None,
                 coalesce: Optional[bool] = None,
                 coalesce_max_delay_ms: Optional[float] = None,
                 coalesce_max_batch: Optional[int] = None,
                 coalesce_min_queue_depth: Optional[int] = None):
        if workers < 1:
            raise ValueError("workers must be >= 1")
        self.session = session
        self.workers = int(workers)
        self.default_quota = default_quota or TenantQuota()
        self.shared_plan_cache = bool(shared_plan_cache)
        self.default_deadline_s = default_deadline_s
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            failure_threshold=int(breaker_threshold),
            cooldown=float(breaker_cooldown))
        self.admission = AdmissionController(
            max_queue=max_queue, memory_limit_bytes=memory_limit_bytes,
            breaker=self.breaker)
        # Live HTTP telemetry (serve/http.py): OFF unless a port is
        # given (spark.serve.metricsPort) — no socket, no thread, no
        # cost. 127.0.0.1 by default; port 0 = ephemeral (tests/soak).
        self.metrics_port = (None if metrics_port is None
                             else int(metrics_port))
        self.metrics_host = str(metrics_host)
        self.telemetry = None          # TelemetryServer once started
        # Per-tenant SLO burn-rate tracking (spark.serve.sloP99Ms): the
        # p99 target in ms; None = zero-cost off. Budget = 1% of
        # requests may exceed the target (a p99 promise); burn rate =
        # observed over-target fraction / 1%, published as the
        # serve.slo_burn[.<tenant>] gauges so a scrape shows budget
        # exhaustion BEFORE the breaker trips.
        self.slo_p99_ms = None if slo_p99_ms is None else float(slo_p99_ms)
        self._slo: dict[str, list] = {}    # tenant -> [total, over]
        self._slo_all = [0, 0]
        self._cond = threading.Condition()
        self._tenants: dict[str, _TenantState] = {}
        self._rr: list[str] = []       # round-robin tenant order
        self._rr_idx = 0
        self._queued_total = 0
        self._accepting = False
        self._draining = False         # stop()/begin_drain() in progress
        self._threads: list[threading.Thread] = []
        self.net = None                # NetServer once started (net.py)
        # Cross-request coalescing (serve/coalesce.py): explicit kwargs
        # win; None defers to the spark.serve.coalesce.* conf at start()
        # (the same deferred one-flag read as the net front end).
        self._coalesce_conf = (coalesce, coalesce_max_delay_ms,
                               coalesce_max_batch,
                               coalesce_min_queue_depth)
        self.coalescer = None          # Coalescer once started
        # tenants granted a per-tenant latency series (MAX_TENANT_SERIES
        # cap); own lock — _finish runs while stop() may hold self._cond
        self._series_lock = threading.Lock()
        self._series: set[str] = set()

    # -- conf ---------------------------------------------------------------
    @classmethod
    def from_conf(cls, session=None, conf=None, **overrides) -> "QueryServer":
        """Build from ``spark.serve.*`` conf keys (defaults in
        parentheses): ``workers`` (4), ``maxQueue`` (64), ``maxInFlight``
        (4) / ``maxQueuedPerTenant`` (16) for the default tenant quota,
        ``memoryLimitBytes`` (unset), ``defaultDeadline`` seconds
        (unset), ``sharedPlanCache`` (true), ``breakerThreshold`` (5) /
        ``breakerCooldown`` (5.0 s) for the shedding breaker. Keyword
        ``overrides`` win over conf."""
        conf = dict(conf if conf is not None
                    else (session.conf if session is not None else {}))

        def num(key, default, cast):
            v = conf.get(f"spark.serve.{key}")
            return default if v is None else cast(v)

        kw: dict = {
            "workers": num("workers", 4, int),
            "max_queue": num("maxQueue", 64, int),
            "default_quota": TenantQuota(
                max_in_flight=num("maxInFlight", 4, int),
                max_queued=num("maxQueuedPerTenant", 16, int)),
            "memory_limit_bytes": num("memoryLimitBytes", None, int),
            "default_deadline_s": num("defaultDeadline", None, float),
            "shared_plan_cache": str(
                conf.get("spark.serve.sharedPlanCache", "true")
            ).lower() not in CONF_FALSE,
            "breaker_threshold": num("breakerThreshold", 5, int),
            "breaker_cooldown": num("breakerCooldown", 5.0, float),
            "metrics_port": num("metricsPort", None, int),
            "metrics_host": str(conf.get("spark.serve.metricsHost",
                                         "127.0.0.1")),
            "slo_p99_ms": num("sloP99Ms", None, float),
            "coalesce": (
                None if "spark.serve.coalesce.enabled" not in conf
                else str(conf["spark.serve.coalesce.enabled"]).lower()
                not in CONF_FALSE),
            "coalesce_max_delay_ms": num("coalesce.maxDelayMs", None,
                                         float),
            "coalesce_max_batch": num("coalesce.maxBatch", None, int),
            "coalesce_min_queue_depth": num("coalesce.minQueueDepth",
                                            None, int),
        }
        kw.update(overrides)
        return cls(session, **kw)

    # -- lifecycle ----------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._accepting

    @property
    def draining(self) -> bool:
        """True from drain start (``begin_drain``/``stop``) until a
        stop completes — the window where /healthz answers 503 while
        in-flight work still finishes."""
        return self._draining

    def start(self) -> "QueryServer":
        """Spin up the worker pool (idempotent)."""
        with self._cond:
            if self._accepting:
                return self
            self._accepting = True
            self._draining = False
            # Stragglers a timed-out stop() left wedged in a device call
            # rejoin the pool the moment accepting flips back on (their
            # loop re-enters _next_job) — spawn only the difference, or
            # the pool runs oversized with threads no future stop() ever
            # joins and the workers gauge lies.
            self._threads = [t for t in self._threads if t.is_alive()]
            new = [
                threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"sparkdq4ml-serve-{i}")
                for i in range(len(self._threads), self.workers)]
            self._threads.extend(new)
            for t in new:
                t.start()
            _obs.METRICS.set_gauge("serve.workers", len(self._threads))
        if self.metrics_port is not None and self.telemetry is None:
            from .http import TelemetryServer

            self.telemetry = TelemetryServer(
                self, host=self.metrics_host,
                port=self.metrics_port).start()
        # Network front end (serve/net.py): exactly ONE flag read when
        # disabled — no import, no socket, no event loop, no thread
        # (the same zero-cost-off contract as telemetry above).
        if _cfg.serve_net_enabled and self.net is None:
            from .net import NetServer

            self.net = NetServer(self).start()
        # Cross-request coalescer (serve/coalesce.py): the same
        # zero-cost-off contract — disabled mode reads exactly one flag,
        # builds nothing, and every dispatch stays per-request.
        co_on = self._coalesce_conf[0]
        if co_on is None:
            co_on = _cfg.serve_coalesce_enabled
        if co_on and self.coalescer is None:
            from .coalesce import Coalescer

            _, delay, batch, depth = self._coalesce_conf
            self.coalescer = Coalescer(
                admission=self.admission,
                max_delay_ms=(_cfg.serve_coalesce_max_delay_ms
                              if delay is None else float(delay)),
                max_batch=(_cfg.serve_coalesce_max_batch
                           if batch is None else int(batch)),
                min_queue_depth=(_cfg.serve_coalesce_min_queue_depth
                                 if depth is None else int(depth)))
        return self

    def begin_drain(self) -> None:
        """Enter the drain window WITHOUT stopping: new submissions are
        refused (structured shutdown rejection), /healthz flips to 503
        so balancers stop routing here, but workers keep finishing
        queued + in-flight jobs and the sockets stay up to deliver
        their results. ``stop()`` completes the shutdown."""
        with self._cond:
            self._draining = True
            self._accepting = False
            self._cond.notify_all()

    def stop(self, drain: bool = True,
             timeout: Optional[float] = 30.0) -> None:
        """Stop accepting work and shut the pool down. ``drain=True``
        (default) lets queued + in-flight jobs finish; ``drain=False``
        resolves every queued job with a structured ``shutdown``
        rejection (in-flight jobs still finish — XLA dispatches are not
        cancellable). ``timeout`` bounds the join per worker; a wedged
        device call past it leaves that daemon worker behind rather than
        hanging the caller."""
        with self._cond:
            if not self._accepting and not self._threads:
                return
            self._accepting = False
            self._draining = True
        # The network front end drains FIRST, while the worker pool is
        # still alive: its in-flight connections hold futures whose jobs
        # the workers must still execute — stopping the pool first would
        # strand every connected client on a dead queue.
        net, self.net = self.net, None
        if net is not None:
            net.stop(drain=drain, timeout=timeout)
        with self._cond:
            if not drain:
                for state in self._tenants.values():
                    while state.queue:
                        job = state.queue.popleft()
                        self._queued_total -= 1
                        # refusals are observable, never silent (the
                        # admission contract) — shutdown rejections count
                        # like any other reject reason
                        counters.increment("serve.reject")
                        counters.increment("serve.reject.shutdown")
                        self._finish(job, QueryResult(
                            status="rejected", tenant=job.tenant,
                            reason="shutdown", tag=job.tag,
                            detail="server stopping (drain=False)"),
                            executed=False)
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout)
        self._threads = [t for t in self._threads if t.is_alive()]
        # a stopped server has no worker pool — scrapes must not keep
        # reporting the pre-stop count (stragglers past the join timeout
        # are the honest residue)
        _obs.METRICS.set_gauge("serve.workers", len(self._threads))
        self._update_gauges()
        # telemetry goes down LAST: the final gauge values above are
        # scrape-able until the socket closes
        telemetry, self.telemetry = self.telemetry, None
        if telemetry is not None:
            telemetry.stop()
        self._draining = False         # drain window over: fully stopped

    def __enter__(self) -> "QueryServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- tenant surface -----------------------------------------------------
    def context(self, tenant: str = "default") -> TenantContext:
        """The tenant's :class:`TenantContext` (created on first use) —
        register views here before submitting SQL-string jobs."""
        with self._cond:
            state = self._state(tenant)
            state.exposed = True
            return state.context

    def set_quota(self, tenant: str, quota: TenantQuota) -> None:
        with self._cond:
            self._state(tenant).quota = quota

    def _state(self, tenant: str) -> _TenantState:
        # callers hold self._cond
        state = self._tenants.get(tenant)
        if state is None:
            if len(self._tenants) >= TENANT_REAP_THRESHOLD:
                self._reap_idle_tenants_locked()
            state = _TenantState(self, tenant, self.default_quota)
            self._tenants[tenant] = state
            self._rr.append(tenant)
            _obs.METRICS.set_gauge("serve.tenants", len(self._tenants))
        return state

    def _reap_idle_tenants_locked(self) -> None:
        """Drop tenants with no live work and no durable state (no
        registered views, default quota, context never handed out via
        :meth:`context`): their state is pure bookkeeping and is rebuilt
        for free if the name ever returns. Tenants holding temp views, an
        operator-set quota, or an exposed context are NEVER reaped —
        that's real state a client may come back for.

        The breaker entry is part of the tenant's bookkeeping and is
        reaped with it: ``CircuitBreaker._state`` grows one key per
        tenant that ever failed, so leaving it behind would re-open the
        unbounded-memory hole this sweep closes (a returning name starts
        with a clean failure count, same as its rebuilt state)."""
        dead = [name for name, s in self._tenants.items()
                if not s.queue and s.in_flight == 0
                and not s.exposed
                and s.quota is self.default_quota
                and not s.context.catalog.list_views()]
        if not dead:
            return
        for name in dead:
            del self._tenants[name]
            self.breaker.reset(self.admission.breaker_key(name))
        self._rr = [n for n in self._rr if n in self._tenants]
        self._rr_idx = 0
        counters.increment("serve.tenants_reaped", len(dead))
        _obs.METRICS.set_gauge("serve.tenants", len(self._tenants))

    # -- submission ---------------------------------------------------------
    def submit(self, work, tenant: str = "default", *,
               deadline_s: Optional[float] = None,
               est_bytes: Optional[int] = None,
               collect_stats: bool = False,
               tag: Optional[str] = None,
               trace=None) -> QueryFuture:
        """Submit one query for ``tenant``.

        ``work`` is either a SQL string (run against the tenant's
        catalog) or a callable taking the :class:`TenantContext`.
        Admission happens synchronously — a refused query resolves
        immediately with its structured rejection. ``est_bytes``
        declares the job's estimated device footprint for the memory
        gate; ``deadline_s`` (default ``default_deadline_s``) bounds the
        query end-to-end; ``collect_stats`` attaches a per-query
        ``QueryStatsCollector`` to the result; ``trace`` carries the
        wire trace context (a ``TraceContext`` or raw ``traceparent``
        string) the executing span tree adopts as its root."""
        if isinstance(work, str):
            sql_text = work
            work = lambda ctx: ctx.sql(sql_text)   # noqa: E731
        elif not callable(work):
            raise TypeError(f"work must be a SQL string or a callable "
                            f"taking a TenantContext, got {type(work)}")
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        job = _Job(work, tenant, tag, deadline_s, est_bytes, collect_stats,
                   trace=trace)
        # Take the memory-gate census BEFORE the scheduler lock: it walks
        # every live jax array, and holding self._cond through that scan
        # would stall every worker and submitter. Advisory gate — the
        # slightly stale figure is within its documented precision.
        live = None
        if (self.admission.memory_limit_bytes is not None
                and est_bytes is not None and est_bytes > 0):
            from ..utils import meminfo

            live = meminfo.live_bytes()
        # serve_admit chaos hooks (one None check without a plan), run
        # BEFORE the scheduler lock — a firing hook logs and annotates,
        # and log I/O under self._cond would serialize every submitter
        # and worker (the same lock-hygiene rule that keeps the
        # live-array census above outside it). A due breaker_trip forces
        # the tenant's breaker open — THIS submission sheds through the
        # normal gate and recovery follows the normal half-open path; a
        # due oom injects an allocator-census-OOM memory rejection
        # (works without a configured memory limit, so the gate's
        # refusal path is soak-testable everywhere).
        injected = None
        if _faults.active() is not None:
            if _faults.fired("serve_admit", "breaker_trip"):
                self.breaker.trip(self.admission.breaker_key(tenant))
                # a breaker transition is a flight-recorder trigger
                # whether the trip was organic or injected
                if _obs.TRACER.enabled:
                    _incidents.RECORDER.record(
                        "breaker_trip",
                        detail=f"injected trip, tenant {tenant!r}",
                        extra={"breaker": self.breaker.snapshot()})
            if _faults.fired("serve_admit", "oom"):
                injected = AdmissionController._reject(
                    "memory", "injected allocator-census OOM "
                    "(serve_admit chaos)")
        with self._cond:
            if not self._accepting:
                raise RuntimeError("QueryServer is not running "
                                   "(start() it, or session.serve())")
            # Admission runs against the EXISTING tenant state (or the
            # default quota for a first-time name): tenant state is only
            # allocated for ADMITTED work, so a flood of refused
            # submissions under unique tenant names cannot grow
            # _tenants/_rr (and the scheduler scan) without bound.
            existing = self._tenants.get(tenant)
            verdict = injected if injected is not None \
                else self.admission.admit(
                    tenant,
                    existing.quota if existing is not None
                    else self.default_quota,
                    self._queued_total,
                    len(existing.queue) if existing is not None else 0,
                    est_bytes=est_bytes, live_bytes=live)
            if verdict is not None:
                if _obs.TRACER.enabled and trace is not None:
                    # a refused wire request still gets a (one-span)
                    # tree: its echoed trace_id must resolve via
                    # /trace/<id> like any admitted request's — opened
                    # BEFORE resolve() so the wire layer's completion
                    # hook cannot race an unregistered context
                    ctx = _obs.TraceContext.adopt(trace)
                    job.trace = ctx
                    with _obs.request_span("serve.query", ctx,
                                           tenant=tenant,
                                           rejected=verdict.status):
                        pass
                    _obs.TAIL.finish_request(
                        ctx, status=verdict.status,
                        reason=verdict.reason, e2e_ms=None,
                        breaker_opened=False, slo_ms=self.slo_p99_ms)
                job.resolve(QueryResult(
                    status=verdict.status, tenant=tenant, tag=tag,
                    reason=verdict.reason, detail=verdict.detail))
                return QueryFuture(job, self)
            state = self._state(tenant)
            counters.increment("serve.admit")
            state.queue.append(job)
            self._queued_total += 1
            self._update_gauges_locked()
            self._cond.notify()
        return QueryFuture(job, self)

    # -- scheduler ----------------------------------------------------------
    def _next_job(self):
        """Round-robin over tenants with queued work AND a free in-flight
        slot; None when the server is stopping and nothing is left."""
        with self._cond:
            while True:
                n = len(self._rr)
                for off in range(n):
                    name = self._rr[(self._rr_idx + off) % n]
                    state = self._tenants[name]
                    if (state.queue
                            and state.in_flight < state.quota.max_in_flight):
                        self._rr_idx = (self._rr_idx + off + 1) % n
                        job = state.queue.popleft()
                        self._queued_total -= 1
                        state.in_flight += 1
                        self._update_gauges_locked()
                        return job, state
                if not self._accepting and self._queued_total == 0:
                    return None, None
                self._cond.wait()

    def _worker_loop(self) -> None:
        while True:
            job, state = self._next_job()
            if job is None:
                return
            try:
                self._execute(job, state)
            finally:
                with self._cond:
                    state.in_flight -= 1
                    self._update_gauges_locked()
                    self._cond.notify()

    # -- execution ----------------------------------------------------------
    def _execute(self, job: _Job, state: _TenantState) -> None:
        t_start = time.perf_counter()
        queue_ms = (t_start - job.t_submit) * 1e3
        # ONE flag read adopts (or locally mints) the request's wire
        # trace context; disabled mode allocates nothing and the span
        # below is the shared no-op.
        trace = (_obs.TraceContext.adopt(job.trace)
                 if _obs.TRACER.enabled else None)
        job.trace = trace
        if job.deadline_ts is not None and t_start >= job.deadline_ts:
            # queue-expired jobs still register a (minimal) request tree
            # so the client-held trace id resolves server-side
            with _obs.request_span("serve.query", trace,
                                   tenant=job.tenant, tag=job.tag,
                                   expired="queue"):
                pass
            self._finish(job, QueryResult(
                status="deadline_exceeded", tenant=job.tenant, tag=job.tag,
                where="queue", queue_ms=queue_ms,
                e2e_ms=queue_ms), executed=False, queue_ms=queue_ms,
                e2e_ms=queue_ms)
            return
        ns_cm = (contextlib.nullcontext() if self.shared_plan_cache
                 else _plan_namespace(job.tenant))
        # Adaptive coalescing arm (ONE None check when the coalescer is
        # off): the queue depth REMAINING at pop time is the load
        # signal — below minQueueDepth, or without deadline headroom for
        # a hold window, the scope is the shared nullcontext and every
        # dispatch below is byte-for-byte the per-request path.
        co = self.coalescer
        co_cm = (contextlib.nullcontext() if co is None
                 else co.scope(job, self._queued_total))
        stats = None
        status, value, error = "ok", None, ""
        job.attempts += 1
        try:
            with ns_cm, co_cm, _shard_guard(), _obs.request_span(
                    "serve.query", trace,
                    tenant=job.tenant, tag=job.tag,
                    attempt=job.attempts):
                if trace is not None:
                    # admission and queueing happened before this span
                    # opened (caller thread / queue wait) — record them
                    # as back-dated children of the request root
                    _obs.emit_span("serve.admit", cat="serve",
                                   ctx=trace, tenant=job.tenant)
                    _obs.emit_span("serve.queue", cat="serve",
                                   dur_ms=queue_ms, ctx=trace)
                # serve_exec chaos hook (one None check without a plan):
                # a due device_error raises the same XlaRuntimeError
                # class a real worker device fault would
                _faults.inject("serve_exec")
                if job.collect_stats:
                    with _obs.query_stats() as stats:
                        value = _materialize(job.work(state.context))
                else:
                    value = _materialize(job.work(state.context))
        except Exception as e:    # noqa: BLE001 - a tenant's bad query
            if self._maybe_requeue(job, state, e):
                return             # re-enters the tenant queue; no finish
            status, error = "error", f"{type(e).__name__}: {e}"
        t_end = time.perf_counter()
        exec_ms = (t_end - t_start) * 1e3
        e2e_ms = (t_end - job.t_submit) * 1e3
        if (job.deadline_ts is not None and t_end >= job.deadline_ts
                and status == "ok"):
            # honest semantics: a deadline is a promise about END-TO-END
            # latency; a value that arrives late is discarded, not handed
            # back as if the SLO held
            status, value = "deadline_exceeded", None
        result = QueryResult(
            status=status, tenant=job.tenant, tag=job.tag, value=value,
            error=error, where="exec" if status == "deadline_exceeded"
            else "", queue_ms=queue_ms, exec_ms=exec_ms, e2e_ms=e2e_ms,
            stats=stats)
        self._finish(job, result, executed=True, queue_ms=queue_ms,
                     exec_ms=exec_ms, e2e_ms=e2e_ms)

    def _maybe_requeue(self, job: _Job, state: _TenantState,
                       err: BaseException) -> bool:
        """Deadline-aware requeue — the serve rung of the degradation
        ladder (ISSUE 11). A worker exception of the RETRYABLE class
        (``XlaRuntimeError`` / recovery ``DeadlineExceeded`` — never a
        tenant's bad SQL, which is deterministic and must fail fast)
        re-enters the tenant's queue while the per-tenant
        :class:`~..utils.recovery.RetryPolicy` grants attempts AND the
        job's deadline has headroom for the policy backoff, which is
        slept in this worker before the requeue (see below). Every
        requeued attempt counts against the tenant's breaker, so a
        persistently faulting tenant still trips to shed. Returns True
        when the job was requeued (the caller must not resolve it)."""
        import jax

        from ..utils import recovery as _rec

        if not isinstance(err, (jax.errors.JaxRuntimeError,
                                _rec.DeadlineExceeded)):
            return False
        cause = f"{type(err).__name__}: {err}"
        policy = self._retry_policy(job.tenant)
        if job.attempts >= policy.max_attempts:
            _rec.RECOVERY_LOG.record(
                "serve_exec", "exhausted", attempt=job.attempts,
                rung="requeue", cause=cause)
            if _obs.TRACER.enabled:
                # fault-ladder engagement exhausted its rung — capture
                # the evidence while the recovery log still has it
                _incidents.RECORDER.record(
                    "fault_ladder",
                    trace=job.trace if isinstance(
                        job.trace, _obs.TraceContext) else None,
                    detail=f"serve_exec requeue exhausted after "
                           f"{job.attempts} attempts: {cause}")
            return False
        wait = policy.backoff(job.attempts, "serve_exec")
        if job.deadline_ts is not None \
                and time.perf_counter() + wait >= job.deadline_ts:
            _rec.RECOVERY_LOG.record(
                "serve_exec", "deadline", attempt=job.attempts,
                rung="requeue", cause=cause,
                detail="no deadline headroom; failing instead of requeue")
            return False
        if wait > 0.0:
            # The backoff is served HERE, in the failing worker, before
            # the job re-enters the queue: with an idle worker slot an
            # appendleft'ed job would otherwise re-execute within
            # microseconds and exhaust every attempt while a transient
            # fault is still present. The job is not yet queued, so no
            # other worker can grab it early; the cost is one worker
            # slot for the (policy-bounded, deterministic-jitter) wait —
            # the same in-place sleep resilient_call makes.
            policy.sleep(wait)
        with self._cond:
            if not self._accepting:
                return False       # stopping: resolve as the error it is
            state.queue.appendleft(job)
            self._queued_total += 1
            self._update_gauges_locked()
            self._cond.notify()
        # Count the failed attempt against the tenant's breaker ONLY for
        # attempts that actually requeue: a non-requeued failure resolves
        # as an error result and _finish records it there — counting in
        # both places charged the final attempt twice and tripped the
        # breaker ~2x faster than its configured threshold.
        self.breaker.record_failure(self.admission.breaker_key(job.tenant))
        counters.increment("serve.requeue")
        _rec.RECOVERY_LOG.record(
            "serve_exec", "retry", attempt=job.attempts, rung="requeue",
            cause=cause, backoff_s=wait)
        return True

    def _retry_policy(self, tenant: str):
        """Per-tenant retry policy for the requeue ladder: global
        ``spark.recovery.*`` keys, overlaid by ``spark.recovery.
        serve_exec.*``, overlaid by ``spark.recovery.serve_exec.
        <tenant>.*`` — one misbehaving tenant can be tuned (or starved of
        retries) without touching the others."""
        from ..utils.recovery import RetryPolicy

        conf = self.session.conf if self.session is not None else {}
        kw = RetryPolicy._conf_kwargs(conf, "spark.recovery.")
        kw.update(RetryPolicy._conf_kwargs(
            conf, "spark.recovery.serve_exec."))
        kw.update(RetryPolicy._conf_kwargs(
            conf, f"spark.recovery.serve_exec.{tenant}."))
        return RetryPolicy(**kw)

    def _finish(self, job: _Job, result: QueryResult, *, executed: bool,
                queue_ms: Optional[float] = None,
                exec_ms: Optional[float] = None,
                e2e_ms: Optional[float] = None) -> None:
        won = job.resolve(result)
        breaker_opened = False
        if won:
            key = self.admission.breaker_key(job.tenant)
            if result.status == "ok":
                counters.increment("serve.complete")
                self.breaker.record_success(key)
            elif result.status == "error":
                counters.increment("serve.error")
                breaker_opened = self.breaker.record_failure(key)
            elif result.status == "deadline_exceeded":
                counters.increment("serve.deadline_exceeded")
                breaker_opened = self.breaker.record_failure(key)
            # rejected/shed counters were recorded at admission (or at
            # the drain=False shutdown site)
        elif executed:
            # a real execution value landed after someone else (the
            # deadline waiter) resolved the job — discarded, counted.
            # Lost races that never ran work (a queued-past-deadline job
            # the worker pops after the waiter gave up) are NOT late
            # results: nothing was computed, nothing was discarded.
            counters.increment("serve.late_result")
        if queue_ms is not None:
            _obs.METRICS.observe("serve.queue_ms", queue_ms)
        if exec_ms is not None:
            _obs.METRICS.observe("serve.exec_ms", exec_ms)
        # e2e is the CLIENT-experienced latency: exactly one observation
        # per job, made by the resolution the client actually received.
        # A deadline overrun resolved from the queue pop or the waiter
        # must land in the histogram — under queue saturation those are
        # the worst latencies, and skipping them (while recording the
        # exec-path ones) made a scrape-derived p99 read healthy in the
        # exact regime deadlines exist for. A losing worker's later
        # value is resource accounting (queue/exec above), not latency.
        if not won:
            e2e_ms = None
        if e2e_ms is not None:
            _obs.METRICS.observe("serve.e2e_ms", e2e_ms)
            with self._series_lock:
                granted = (job.tenant in self._series
                           or len(self._series) < MAX_TENANT_SERIES)
                if granted:
                    self._series.add(job.tenant)
            if granted:
                _obs.METRICS.observe(f"serve.e2e_ms.{job.tenant}", e2e_ms)
            if self.slo_p99_ms is not None:
                self._record_slo(job.tenant, e2e_ms, granted)
        if _obs.TRACER.enabled \
                and isinstance(job.trace, _obs.TraceContext):
            if won:
                # hand the completion verdict to the tail sampler; the
                # tree finalizes here unless the wire layer deferred
                # (stream spans still to come — it completes after the
                # page write-out)
                _obs.TAIL.finish_request(
                    job.trace, status=result.status,
                    reason=result.reason, e2e_ms=e2e_ms,
                    breaker_opened=breaker_opened,
                    slo_ms=self.slo_p99_ms)
                if breaker_opened:
                    _incidents.RECORDER.record(
                        "breaker_trip", trace=job.trace,
                        detail=f"tenant {job.tenant!r}, "
                               f"status {result.status}",
                        extra={"breaker": self.breaker.snapshot()})
            else:
                # lost race: the winning resolution carried the client-
                # visible verdict, but it may have landed BEFORE this
                # execution opened the tree — record this resolution as
                # the verdict only if none is stored yet, then finalize
                # so a late execution cannot leak a pending tree
                # (idempotent once the bucket is gone)
                _obs.TAIL.finish_request(
                    job.trace, status=result.status,
                    reason=result.reason, e2e_ms=None,
                    breaker_opened=False, slo_ms=self.slo_p99_ms)
                _obs.TAIL.complete(job.trace)

    def _record_slo(self, tenant: str, e2e_ms: float,
                    granted: bool) -> None:
        """Incremental SLO burn-rate update, fed by the SAME
        client-experienced latencies the ``serve.e2e_ms`` histograms
        record (exactly one observation per job). SLO semantics: p99 ≤
        ``slo_p99_ms``, i.e. an error budget of 1% of requests over
        target; burn rate = over-target fraction / 1% — 1.0 burns the
        budget exactly, >1 exhausts it early, and a scrape of
        ``serve.slo_burn.<tenant>`` shows that long before the tenant's
        failure-driven breaker trips. ``granted`` reuses the per-tenant
        series cap so gauge cardinality is bounded with the histograms."""
        over = e2e_ms > self.slo_p99_ms
        with self._series_lock:
            self._slo_all[0] += 1
            self._slo_all[1] += over
            burn_all = (self._slo_all[1] / self._slo_all[0]) / 0.01
            cell = None
            if granted:
                cell = self._slo.setdefault(tenant, [0, 0])
                cell[0] += 1
                cell[1] += over
                burn = (cell[1] / cell[0]) / 0.01
        _obs.METRICS.set_gauge("serve.slo_burn", round(burn_all, 4))
        if cell is not None:
            _obs.METRICS.set_gauge(f"serve.slo_burn.{tenant}",
                                   round(burn, 4))
        # flight-recorder trigger: sustained burn over the configured
        # threshold (min 100 samples so a cold start can't fire it);
        # the recorder's per-trigger cooldown bounds repeat captures
        if _obs.TRACER.enabled and self._slo_all[0] >= 100 \
                and burn_all >= _incidents.RECORDER.slo_burn_threshold:
            _incidents.RECORDER.record(
                "slo_burn",
                detail=f"burn {burn_all:.2f} over "
                       f"{self._slo_all[0]} samples",
                extra={"slo_p99_ms": self.slo_p99_ms})

    def _resolve_deadline(self, job: _Job, where: str) -> None:
        """Waiter-side deadline resolution (``QueryFuture.result``):
        synthesize the structured result; idempotent vs the worker."""
        now = time.perf_counter()
        e2e_ms = (now - job.t_submit) * 1e3
        self._finish(job, QueryResult(
            status="deadline_exceeded", tenant=job.tenant, tag=job.tag,
            where=where, e2e_ms=e2e_ms),
            executed=False, e2e_ms=e2e_ms)

    # -- introspection ------------------------------------------------------
    def _update_gauges_locked(self) -> None:
        _obs.METRICS.set_gauge("serve.queue_depth", self._queued_total)
        _obs.METRICS.set_gauge(
            "serve.in_flight",
            sum(s.in_flight for s in self._tenants.values()))

    def _update_gauges(self) -> None:
        with self._cond:
            self._update_gauges_locked()

    def stats(self) -> dict:
        """One structured snapshot: queue/in-flight state per tenant, the
        shedding breaker, and every ``serve.*`` counter."""
        with self._cond:
            tenants = {
                name: {"queued": len(s.queue), "in_flight": s.in_flight,
                       "max_in_flight": s.quota.max_in_flight,
                       "max_queued": s.quota.max_queued}
                for name, s in self._tenants.items()}
            queued_total = self._queued_total
        return {
            "running": self.running,
            "draining": self.draining,
            "workers": self.workers,
            "queue_depth": queued_total,
            "shared_plan_cache": self.shared_plan_cache,
            "tenants": tenants,
            "breaker": self.breaker.snapshot(),
            "counters": counters.snapshot("serve."),
            "coalesce": (None if self.coalescer is None
                         else self.coalescer.stats()),
        }

    def cache_report(self) -> dict:
        """The unified jit-cache introspection view (PR 5) — the shared
        plan/jit cache this server multiplexes tenants over."""
        return _obs.cache_report()


def _plan_namespace(tenant: str):
    from ..ops.compiler import plan_namespace

    return plan_namespace(tenant)


def _shard_guard():
    """Serialize served-query EXECUTION while row-sharding is active
    (``spark.shard.enabled`` on a multi-device mesh): a sharded query's
    eager host-boundary reductions (``count``'s mask sum, ``limit``'s
    cumsum) dispatch multi-device programs outside any jit factory's
    ``serialize_collectives`` wrapper, and overlapping multi-device
    executions are the XLA:CPU rendezvous-deadlock class PR 6 closed.
    With sharding active every query already spans the whole mesh, so
    whole-query serialization is the correct dispatch semantics (the
    mesh is the unit of concurrency), not a throughput concession. The
    plan caches stay namespace-partitioned exactly as before — the
    shard layout tag composes with the tenant namespace prefix inside
    the plan key. One flag/None check when sharding is off."""
    from ..parallel.mesh import collective_guard
    from ..parallel.shard import active_mesh

    return collective_guard(active_mesh())


def _materialize(value):
    """Flush any lazy Frame state in a job's return value INSIDE the
    serve scope. A callable job may return a Frame with pending fused-
    pipeline steps; left lazy, the client's first read would flush on the
    client thread — OUTSIDE the tenant's ``plan_namespace`` (silently
    un-partitioning the isolated-cache mode), the ``serve.query`` span,
    and the exec/deadline accounting. Walks one container level (dict /
    list / tuple), matching the shapes jobs actually return."""
    if hasattr(value, "_flush") and getattr(value, "_pending", None):
        value._flush()
    elif isinstance(value, dict):
        for v in value.values():
            if hasattr(v, "_flush") and getattr(v, "_pending", None):
                v._flush()
    elif isinstance(value, (list, tuple)):
        for v in value:
            if hasattr(v, "_flush") and getattr(v, "_pending", None):
                v._flush()
    return value
