"""Network serving front end — the socket protocol over the QueryServer.

ROADMAP item 2 asks for a real network protocol in front of the
thread-pool serving layer (PR 6); this module is its robustness half:
an asyncio front end layered over the existing
:class:`~.server.QueryServer` admission/tenant/breaker machinery, built
to the same fault-site + degradation-ladder discipline as every other
subsystem (PR 10).

Two framings over one listening socket, sniffed per connection from the
first four bytes:

* **HTTP/1.1** (``POST /query``) — the interoperable framing. The
  request body is a JSON document (``sql`` or a registered ``job``
  name, ``tenant``, ``deadline_ms``, ``idem``, ``tag``,
  ``est_bytes`` — the declared device footprint the admission memory
  gate and the coalescer's batch sizing price); the ``X-DQ-Tenant`` /
  ``X-DQ-Deadline-Ms`` / ``X-DQ-Idempotency-Key`` / ``X-DQ-Tag`` /
  ``X-DQ-Est-Bytes`` headers override. Responses stream as
  ``Transfer-Encoding: chunked`` ndjson — one JSON line per result
  page, then one terminal line with the structured status — so a large
  SELECT never materializes per client. ``GET /healthz`` answers the
  drain state (503 while draining/stopped — balancer semantics).
* **Length-prefixed frames** (magic ``DQW1``) — the low-overhead
  framing: 4-byte magic once, then per message a 4-byte big-endian
  length + JSON payload. Requests use the same document; responses are
  a sequence of page frames then one ``{"end": true, "status": ...}``
  frame. Connections are keep-alive: the client sends the next request
  after the previous end frame.

**Wire deadline propagation** is RELATIVE, never absolute: the client
sends its remaining budget in milliseconds (``X-DQ-Deadline-Ms`` /
``deadline_ms``) and the server re-anchors it on its own monotonic
clock at receipt — two hosts whose wall clocks disagree by minutes
still agree on the budget (clock-skew tolerance by construction). The
budget becomes the job's server-side ``deadline_s``: a queued-past-
deadline job never executes, and the waiter-synthesized
``deadline_exceeded`` result reaches the client as a structured frame,
never a hang or reset.

**Fault sites** (``utils.faults.FAULT_SITES``): ``net_accept``
(``conn_reset``), ``net_read`` (``conn_reset``/``stall``/
``slow_client``), ``net_write`` (``conn_reset``/``partial_write``/
``stall``). Ladders: a reset aborts the connection with a
``net.conn_reset`` count + recovery event (the resilient client
retries, idempotency-key dedup keeping the query exactly-once); a
stall/slow client is the read/write-timeout ladder — the connection is
cut after ``connTimeoutMs`` with a structured ``conn_timeout`` error
where the protocol still permits one (``net.conn_timeout`` + recovery
event); a partial write truncates the response mid-stream
(``net.partial_write``), which the client detects as a torn frame and
retries. A peer that vanishes while its query is still pending is
abandoned through the server's own accounting
(:meth:`~.server.QueryServer._finish` with a structured
``client_gone`` error), so the worker's late value is discarded via
the existing ``serve.late_result`` path — counted, never silent.

Slow-loris protection: the whole request read shares ONE
``connTimeoutMs`` bound (a byte-trickling peer cannot extend it),
reader buffers are bounded by ``maxFrameBytes``, and the writer's
high-water mark forces backpressure so a slow-draining client hits the
write timeout instead of growing the server's buffers.

Security: binds ``127.0.0.1`` by default (``spark.serve.net.host`` to
widen) — the endpoint is unauthenticated, same posture as the
telemetry server; fronting with a real proxy is the operator's job.
OFF by default: with ``spark.serve.net.enabled=false`` the
``QueryServer`` reads exactly one flag and starts nothing — no socket,
no event loop, no thread.
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

from ..config import config as _cfg
from ..utils import faults as _faults
from ..utils import incidents as _incidents
from ..utils import observability as _obs
from ..utils.profiling import counters
from ..utils.recovery import RECOVERY_LOG
from .server import QueryFuture, QueryResult

logger = logging.getLogger("sparkdq4ml_tpu.serve.net")

#: Frame-protocol magic: the client's first four bytes. Anything else is
#: parsed as HTTP (requests start with the method token).
MAGIC = b"DQW1"

#: Bound on idempotency-key dedup entries (LRU): a retried query re-
#: attaches to its original job instead of re-executing; past the bound
#: the oldest key evicts and a very late retry re-executes (documented
#: best-effort window, bounded memory).
IDEM_CACHE = 512

#: Hard bound on waiting for one query's result on behalf of a
#: connection: queries without a wire deadline cannot wedge a waiter
#: thread (and its connection) forever — past it the client gets a
#: structured error, same zero-hangs contract as ``QueryFuture``.
RESULT_BOUND_S = 600.0

#: Writer high-water mark: past this many unflushed bytes the page loop
#: blocks in ``drain()`` (backpressure), so a slow-draining client runs
#: into the write timeout instead of ballooning server-side buffers.
WRITE_HIGH_WATER = 1 << 16

#: An injected ``stall``/``slow_client`` sleeps this long for real (a
#: token, deterministic pause) and then takes the SAME timeout ladder a
#: full ``connTimeoutMs`` expiry would — the ladder is exercised without
#: the soak paying the full wall-clock timeout per injection.
STALL_EMULATION_S = 0.05

_HTTP_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
                 408: "Request Timeout", 413: "Payload Too Large",
                 429: "Too Many Requests", 500: "Internal Server Error",
                 503: "Service Unavailable", 504: "Gateway Timeout"}

#: Structured status → HTTP response code (pre-stream errors; once the
#: chunked stream started the terminal ndjson line carries the status).
_STATUS_HTTP = {"ok": 200, "rejected": 429, "shed": 503,
                "deadline_exceeded": 504, "error": 500}


class _Abort(Exception):
    """Tear the connection down now (reset semantics) — raised by the
    fault ladders and the disconnect paths; the handler's finally block
    owns the cleanup."""


def _json_default(v):
    tolist = getattr(v, "tolist", None)
    if callable(tolist):
        return tolist()
    return str(v)


class _Conn:
    """One accepted connection: the stream pair plus a pushback buffer
    (the protocol sniff and the disconnect watch both read ahead)."""

    __slots__ = ("reader", "writer", "buf", "peer", "streaming", "proto")

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self.buf = b""
        try:
            self.peer = writer.get_extra_info("peername")
        except Exception:
            self.peer = None
        self.streaming = False     # a chunked/page stream has started
        self.proto = None          # "frame" | "http" once sniffed

    async def read_exactly(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = await self.reader.read(n - len(self.buf))
            if not chunk:
                raise asyncio.IncompleteReadError(self.buf, n)
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    async def read_line(self, limit: int) -> bytes:
        while b"\n" not in self.buf:
            if len(self.buf) > limit:
                raise _FrameOverflow(f"header line over {limit} bytes")
            chunk = await self.reader.read(2048)
            if not chunk:
                raise asyncio.IncompleteReadError(self.buf, limit)
            self.buf += chunk
        line, _, self.buf = self.buf.partition(b"\n")
        return line + b"\n"

    def pushback(self, data: bytes) -> None:
        self.buf = data + self.buf


class _FrameOverflow(Exception):
    """A request exceeded ``maxFrameBytes`` — refused with a structured
    413, bounding per-connection buffers."""


class NetServer:
    """The asyncio socket front end over one :class:`QueryServer`.

    Runs its own event loop on a dedicated thread (the engine is
    threaded, not async); connection handlers bridge to the blocking
    ``QueryFuture`` API through a bounded waiter thread pool. Normally
    started by ``QueryServer.start()`` when ``spark.serve.net.enabled``
    is set, but directly constructible for tests and the chaos soak
    (every constructor default reads the session-scoped config)."""

    def __init__(self, server, *, host: Optional[str] = None,
                 port: Optional[int] = None,
                 backlog: Optional[int] = None,
                 conn_timeout_s: Optional[float] = None,
                 max_frame_bytes: Optional[int] = None,
                 page_rows: Optional[int] = None,
                 waiters: int = 64):
        self.server = server
        self.host = _cfg.serve_net_host if host is None else str(host)
        self._requested_port = (_cfg.serve_net_port if port is None
                                else int(port))
        self.backlog = (_cfg.serve_net_backlog if backlog is None
                        else int(backlog))
        self.conn_timeout_s = (
            _cfg.serve_net_conn_timeout_ms / 1e3
            if conn_timeout_s is None else float(conn_timeout_s))
        self.max_frame_bytes = (
            _cfg.serve_net_max_frame_bytes
            if max_frame_bytes is None else int(max_frame_bytes))
        self.page_rows = (_cfg.serve_net_stream_page_rows
                          if page_rows is None else int(page_rows))
        self._waiters = int(waiters)
        self._jobs: dict[str, Callable] = {}
        self._idem: collections.OrderedDict[str, QueryFuture] = \
            collections.OrderedDict()
        self._idem_lock = threading.Lock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._listener = None
        self._conns: set = set()
        self._draining = False
        self._port: Optional[int] = None
        self._started = threading.Event()

    # -- lifecycle ----------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._loop is not None and not self._draining

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def port(self) -> Optional[int]:
        """The BOUND port (resolves a requested port of 0)."""
        return self._port

    def register_job(self, name: str, work: Callable) -> None:
        """Expose ``work`` (a callable taking a ``TenantContext``) as a
        named server-side job wire clients can invoke by name — the
        stored-procedure shape for work that is not a SQL string (the
        soak's headline DQ+Lasso flow)."""
        self._jobs[name] = work

    def start(self) -> "NetServer":
        if self._loop is not None:
            return self
        self._draining = False
        self._pool = ThreadPoolExecutor(
            max_workers=self._waiters,
            thread_name_prefix="sparkdq4ml-net-wait")
        self._thread = threading.Thread(
            target=self._run_loop, daemon=True, name="sparkdq4ml-net")
        self._thread.start()
        if not self._started.wait(timeout=10.0) or self._port is None:
            raise RuntimeError("NetServer failed to bind "
                               f"{self.host}:{self._requested_port}")
        logger.info("network serving on %s:%d (HTTP/1.1 + DQW1 frames)",
                    self.host, self._port)
        return self

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def _bind():
            self._listener = await asyncio.start_server(
                self._accept, host=self.host, port=self._requested_port,
                backlog=self.backlog, limit=self.max_frame_bytes)
            self._port = self._listener.sockets[0].getsockname()[1]
            self._started.set()

        try:
            loop.run_until_complete(_bind())
        except Exception:
            logger.exception("NetServer bind failed")
            self._loop = None
            self._started.set()
            loop.close()
            return
        try:
            loop.run_forever()
        finally:
            # drain callbacks scheduled during shutdown, then close
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.close()

    def stop(self, drain: bool = True,
             timeout: Optional[float] = 30.0) -> None:
        """Graceful drain: flip to draining (healthz → 503), close the
        listener (stop accepting), let in-flight requests finish —
        their queries still run on the QueryServer workers, which the
        caller must not stop first — then close the loop. ``drain=
        False`` (or the timeout) aborts the stragglers instead."""
        loop, self._loop = self._loop, None
        if loop is None:
            return
        self._draining = True

        async def _close_listener():
            if self._listener is not None:
                self._listener.close()
                await self._listener.wait_closed()
                self._listener = None

        try:
            asyncio.run_coroutine_threadsafe(
                _close_listener(), loop).result(timeout=10.0)
            deadline = (None if timeout is None
                        else time.monotonic() + float(timeout))
            while drain and self._conns:
                if deadline is not None and time.monotonic() > deadline:
                    break
                time.sleep(0.02)

            async def _abort_rest():
                for task in list(self._conns):
                    task.cancel()

            asyncio.run_coroutine_threadsafe(
                _abort_rest(), loop).result(timeout=10.0)
        except Exception:
            logger.debug("NetServer drain cleanup failed", exc_info=True)
        loop.call_soon_threadsafe(loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        self._port = None
        self._started.clear()
        _obs.METRICS.set_gauge("net.active", 0)

    def __enter__(self) -> "NetServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- fault hooks ---------------------------------------------------------
    def _read_fault(self) -> None:
        """net_read chaos switchpoint, once per request read. A due
        ``conn_reset`` aborts like a peer RST; ``stall``/``slow_client``
        take the read-timeout ladder (the injection stands in for the
        peer trickling/stalling past ``connTimeoutMs``)."""
        if _faults.active() is None:
            return
        if _faults.fired("net_read", "conn_reset"):
            self._ladder_reset("net_read")
        for kind in ("stall", "slow_client"):
            if _faults.fired("net_read", kind):
                RECOVERY_LOG.record("net_read", "timeout", rung="cut",
                                    cause=f"injected {kind}")
                counters.increment("net.conn_timeout")
                raise _InjectedStall()

    def _write_fault(self, payload: bytes, writer) -> Optional[bytes]:
        """net_write chaos switchpoint, once per payload write. Returns
        a TRUNCATED payload for a due ``partial_write`` (the caller
        writes it then aborts); raises for reset/stall."""
        if _faults.active() is None:
            return None
        if _faults.fired("net_write", "conn_reset"):
            self._ladder_reset("net_write")
        if _faults.fired("net_write", "partial_write"):
            RECOVERY_LOG.record("net_write", "partial_write", rung="cut",
                                cause="injected partial_write")
            counters.increment("net.partial_write")
            return payload[:max(1, len(payload) // 2)]
        if _faults.fired("net_write", "stall"):
            RECOVERY_LOG.record("net_write", "timeout", rung="cut",
                                cause="injected stall")
            counters.increment("net.conn_timeout")
            raise _InjectedStall()
        return None

    @staticmethod
    def _ladder_reset(site: str) -> None:
        RECOVERY_LOG.record(site, "conn_reset", rung="abort",
                            cause="injected conn_reset")
        counters.increment("net.conn_reset")
        raise _Abort()

    # -- connection handling -------------------------------------------------
    async def _accept(self, reader, writer) -> None:
        task = asyncio.current_task()
        self._conns.add(task)
        counters.increment("net.accept")
        _obs.METRICS.set_gauge("net.active", len(self._conns))
        conn = _Conn(reader, writer)
        try:
            writer.transport.set_write_buffer_limits(
                high=WRITE_HIGH_WATER)
        except Exception:
            pass
        try:
            if _faults.active() is not None \
                    and _faults.fired("net_accept", "conn_reset"):
                self._ladder_reset("net_accept")
            head = await asyncio.wait_for(conn.read_exactly(4),
                                          self.conn_timeout_s)
            counters.increment("net.bytes_in", 4)
            if head == MAGIC:
                conn.proto = "frame"
                await self._frame_loop(conn)
            else:
                conn.proto = "http"
                conn.pushback(head)
                await self._http_request(conn)
        except (_Abort, asyncio.IncompleteReadError, ConnectionError):
            self._abort(conn)
        except asyncio.TimeoutError:
            # a REAL slow peer ran past connTimeoutMs (slow loris, dead
            # drain): the timeout ladder, counted here
            RECOVERY_LOG.record("net_read", "timeout", rung="cut",
                                cause="connTimeoutMs expired")
            counters.increment("net.conn_timeout")
            await self._timeout_cut(conn)
        except _InjectedStall:
            # injected stall/slow_client: counted at its switchpoint,
            # same ladder tail as the real expiry above
            await self._timeout_cut(conn)
        except asyncio.CancelledError:
            self._abort(conn)
            raise
        except Exception:
            logger.debug("connection handler failed", exc_info=True)
            self._abort(conn)
        finally:
            self._conns.discard(task)
            _obs.METRICS.set_gauge("net.active", len(self._conns))
            try:
                conn.writer.close()
            except Exception:
                pass

    @staticmethod
    def _abort(conn: _Conn) -> None:
        try:
            conn.writer.transport.abort()
        except Exception:
            pass

    async def _timeout_cut(self, conn: _Conn) -> None:
        """The read/write-timeout ladder tail: one structured
        ``conn_timeout`` error if the response stream has not started,
        then the connection closes. Real ``wait_for`` expiries count
        here; the injected rungs counted at their switchpoint."""
        if not conn.streaming:
            doc = {"status": "error", "reason": "conn_timeout",
                   "error": "connection read/write timed out "
                            f"({self.conn_timeout_s:.3g}s)"}
            try:
                if conn.proto == "frame":
                    doc["end"] = True
                    payload = json.dumps(doc).encode()
                    conn.writer.write(
                        struct.pack(">I", len(payload)) + payload)
                    await asyncio.wait_for(conn.writer.drain(), 2.0)
                else:
                    await asyncio.wait_for(
                        self._send_http_doc(conn, 408, doc, raw=True),
                        timeout=2.0)
                counters.increment("net.error_frames")
            except Exception:
                pass
        if _obs.TRACER.enabled:
            # the timeout ladder cut a connection — flight-recorder
            # trigger (per-trigger cooldown bounds repeat captures)
            _incidents.RECORDER.record(
                "fault_ladder",
                detail=f"net conn_timeout cut "
                       f"({self.conn_timeout_s:.3g}s, "
                       f"proto {conn.proto})")
        self._abort(conn)

    # -- frame protocol ------------------------------------------------------
    async def _frame_loop(self, conn: _Conn) -> None:
        while True:
            try:
                head = await asyncio.wait_for(conn.read_exactly(4),
                                              self.conn_timeout_s * 4)
            except asyncio.IncompleteReadError:
                return                      # clean keep-alive close
            self._read_fault()
            (length,) = struct.unpack(">I", head)
            if length > self.max_frame_bytes:
                counters.increment("net.frame_overflow")
                await self._send_frame(conn, {
                    "end": True, "status": "error",
                    "reason": "frame_overflow",
                    "error": f"frame of {length} bytes over "
                             f"maxFrameBytes={self.max_frame_bytes}"})
                counters.increment("net.error_frames")
                return
            body = await asyncio.wait_for(conn.read_exactly(length),
                                          self.conn_timeout_s)
            counters.increment("net.bytes_in", 4 + length)
            counters.increment("net.requests")
            try:
                req = json.loads(body.decode())
            except (ValueError, UnicodeDecodeError) as e:
                await self._send_end(conn, QueryResult(
                    status="error", tenant="", reason="bad_request",
                    error=f"unparseable frame: {e}"), pages=0)
                return
            result, fut = await self._submit_and_wait(conn, req)
            ctx = self._trace_ctx(fut)
            t_stream = time.perf_counter()
            pages = 0
            try:
                if result.status == "ok":
                    dts = self._stream_deadline(fut)
                    for page in self._pages(result.value):
                        if dts is not None \
                                and time.perf_counter() > dts:
                            result = self._page_deadline(result, pages)
                            break
                        page["page"] = pages
                        await self._send_frame(conn, page)
                        pages += 1
                        counters.increment("net.pages")
                await self._send_end(
                    conn, result, pages=pages,
                    trace_id=ctx.trace_id if ctx is not None else None)
            finally:
                self._finish_trace(
                    ctx, pages=pages, proto="frame",
                    stream_ms=(time.perf_counter() - t_stream) * 1e3)

    async def _send_frame(self, conn: _Conn, doc: dict) -> None:
        payload = json.dumps(doc, default=_json_default).encode()
        data = struct.pack(">I", len(payload)) + payload
        await self._write(conn, data)

    async def _send_end(self, conn: _Conn, result: QueryResult,
                        pages: int,
                        trace_id: Optional[str] = None) -> None:
        doc = self._end_doc(result)
        doc["end"] = True
        doc["pages"] = pages
        if trace_id is not None:
            # echo the wire trace id so every client-held result is
            # joinable with the server-side tree; with tracing disabled
            # the frame stays byte-identical (no trace_id key at all)
            doc["trace_id"] = trace_id
        if result.status != "ok":
            counters.increment("net.error_frames")
        await self._send_frame(conn, doc)

    # -- HTTP protocol -------------------------------------------------------
    async def _http_request(self, conn: _Conn) -> None:
        # ONE timeout bound spans the whole head+body read: a trickling
        # peer (slow loris) cannot stretch it byte by byte
        try:
            method, path, headers, body = await asyncio.wait_for(
                self._read_http(conn), self.conn_timeout_s)
        except _FrameOverflow as e:
            counters.increment("net.frame_overflow")
            await self._send_http_doc(conn, 413, {
                "status": "error", "reason": "frame_overflow",
                "error": str(e)})
            return
        counters.increment("net.requests")
        if method == "GET" and path == "/healthz":
            draining = self._draining or getattr(
                self.server, "draining", False)
            ok = not draining and self.server.running
            await self._send_http_doc(
                conn, 200 if ok else 503,
                {"status": "ok" if ok else
                 ("draining" if draining else "stopped")})
            return
        if method != "POST" or path != "/query":
            await self._send_http_doc(conn, 404, {
                "status": "error", "reason": "unknown_route",
                "routes": ["POST /query", "GET /healthz"]})
            return
        req = {}
        if body:
            try:
                req = json.loads(body.decode())
            except (ValueError, UnicodeDecodeError) as e:
                await self._send_http_doc(conn, 400, {
                    "status": "error", "reason": "bad_request",
                    "error": f"unparseable body: {e}"})
                return
        for header, field in (("x-dq-tenant", "tenant"),
                              ("x-dq-deadline-ms", "deadline_ms"),
                              ("x-dq-idempotency-key", "idem"),
                              ("x-dq-tag", "tag"),
                              ("x-dq-est-bytes", "est_bytes"),
                              ("traceparent", "traceparent")):
            if header in headers:
                req[field] = headers[header]
        result, fut = await self._submit_and_wait(conn, req)
        ctx = self._trace_ctx(fut)
        trace_id = ctx.trace_id if ctx is not None else None
        if result.status != "ok":
            counters.increment("net.error_frames")
            doc = self._end_doc(result)
            if trace_id is not None:
                doc["trace_id"] = trace_id
            try:
                await self._send_http_doc(
                    conn, _STATUS_HTTP.get(result.status, 500), doc)
            finally:
                self._finish_trace(ctx, pages=0, stream_ms=0.0,
                                   proto="http")
            return
        t_stream = time.perf_counter()
        pages = 0
        try:
            pages = await self._stream_http(conn, result,
                                            trace_id=trace_id, fut=fut)
        finally:
            self._finish_trace(
                ctx, pages=pages, proto="http",
                stream_ms=(time.perf_counter() - t_stream) * 1e3)

    async def _read_http(self, conn: _Conn):
        request_line = (await conn.read_line(self.max_frame_bytes)) \
            .decode("latin-1").strip()
        self._read_fault()
        parts = request_line.split()
        if len(parts) < 2:
            raise _FrameOverflow(f"bad request line {request_line!r}")
        method, path = parts[0].upper(), parts[1].split("?", 1)[0]
        headers: dict[str, str] = {}
        total = len(request_line)
        while True:
            line = (await conn.read_line(self.max_frame_bytes)) \
                .decode("latin-1")
            total += len(line)
            if total > self.max_frame_bytes:
                raise _FrameOverflow(
                    f"HTTP head over maxFrameBytes="
                    f"{self.max_frame_bytes}")
            line = line.strip()
            if not line:
                break
            name, _, value = line.partition(":")
            headers[name.strip().lower()] = value.strip()
        length = int(headers.get("content-length", "0") or "0")
        if length > self.max_frame_bytes:
            raise _FrameOverflow(
                f"body of {length} bytes over maxFrameBytes="
                f"{self.max_frame_bytes}")
        body = await conn.read_exactly(length) if length else b""
        counters.increment("net.bytes_in", total + length)
        return method, path, headers, body

    async def _send_http_doc(self, conn: _Conn, code: int, doc: dict,
                             raw: bool = False) -> None:
        payload = json.dumps(doc, default=_json_default).encode()
        head = (f"HTTP/1.1 {code} {_HTTP_REASONS.get(code, 'OK')}\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n").encode("latin-1")
        if raw:
            # timeout-ladder tail: best-effort, no nested fault hooks
            conn.writer.write(head + payload)
            await conn.writer.drain()
            return
        await self._write(conn, head + payload)

    async def _stream_http(self, conn: _Conn, result: QueryResult,
                           trace_id: Optional[str] = None,
                           fut=None) -> int:
        head = ("HTTP/1.1 200 OK\r\n"
                "Content-Type: application/x-ndjson\r\n"
                "Transfer-Encoding: chunked\r\n"
                "Connection: close\r\n\r\n").encode("latin-1")
        await self._write(conn, head)
        conn.streaming = True
        pages = 0
        dts = self._stream_deadline(fut)
        for page in self._pages(result.value):
            if dts is not None and time.perf_counter() > dts:
                result = self._page_deadline(result, pages)
                break
            page["page"] = pages
            await self._write_chunk(conn, page)
            pages += 1
            counters.increment("net.pages")
        end = self._end_doc(result)
        end["end"] = True        # same self-describing marker as frames
        end["pages"] = pages
        if trace_id is not None:
            end["trace_id"] = trace_id
        await self._write_chunk(conn, end)
        await self._write(conn, b"0\r\n\r\n")
        return pages

    async def _write_chunk(self, conn: _Conn, doc: dict) -> None:
        line = json.dumps(doc, default=_json_default).encode() + b"\n"
        await self._write(
            conn, f"{len(line):x}\r\n".encode("latin-1") + line + b"\r\n")

    async def _write(self, conn: _Conn, data: bytes) -> None:
        truncated = self._write_fault(data, conn.writer)
        if truncated is not None:
            conn.writer.write(truncated)
            try:
                await asyncio.wait_for(conn.writer.drain(), 2.0)
            except Exception:
                pass
            raise _Abort()
        conn.writer.write(data)
        await asyncio.wait_for(conn.writer.drain(), self.conn_timeout_s)
        counters.increment("net.bytes_out", len(data))

    # -- submission bridge ---------------------------------------------------
    async def _submit_and_wait(self, conn: _Conn, req: dict):
        """Admit the wire request into the QueryServer (idempotency-key
        dedup first) and await its result without blocking the event
        loop; a peer that disconnects mid-wait abandons the job through
        the server's accounting. Always returns a structured
        ``QueryResult`` — never raises for tenant-visible failures."""
        try:
            fut = self._resolve_future(req)
        except _BadRequest as e:
            return QueryResult(status="error",
                               tenant=str(req.get("tenant", "")),
                               reason=e.reason, error=str(e)), None
        except RuntimeError as e:
            # submit() while the server drains/stops — the shutdown gate
            return QueryResult(status="rejected",
                               tenant=str(req.get("tenant", "")),
                               reason="shutdown", detail=str(e)), None
        loop = asyncio.get_running_loop()
        bound = RESULT_BOUND_S
        job = fut._job
        if job.deadline_ts is not None:
            bound = max(0.1, job.deadline_ts - time.perf_counter()) + 2.0
        res_task = loop.run_in_executor(self._pool, self._wait_result,
                                        fut, bound)
        watch = None
        if not conn.buf:
            watch = asyncio.ensure_future(conn.reader.read(1))
        try:
            if watch is None:
                return await res_task, fut
            done, _ = await asyncio.wait(
                {res_task, watch}, return_when=asyncio.FIRST_COMPLETED)
            if res_task in done:
                return res_task.result(), fut
            data = watch.result()
            if data:
                # pipelined bytes from a keep-alive client: not a
                # disconnect — push back and keep waiting
                conn.pushback(data)
                return await res_task, fut
            # peer vanished mid-wait: abandon through the server's own
            # accounting — serve.error now, the worker's late value is
            # discarded via the existing serve.late_result path
            counters.increment("net.client_gone")
            self._abandon(fut)
            await res_task
            # the abandon verdict is in; nobody will stream, so the
            # deferred tree finalizes here (no-op if never opened)
            self._finish_trace(self._trace_ctx(fut), pages=0,
                               stream_ms=0.0, proto=conn.proto or "")
            raise _Abort()
        finally:
            if watch is not None and not watch.done():
                watch.cancel()

    def _wait_result(self, fut: QueryFuture, bound: float) -> QueryResult:
        try:
            return fut.result(timeout=bound)
        except TimeoutError:
            job = fut._job
            return QueryResult(
                status="error", tenant=job.tenant, tag=job.tag,
                reason="result_bound",
                error=f"no result within the {bound:.0f}s wire bound")

    # -- tracing bridge ------------------------------------------------------
    @staticmethod
    def _trace_ctx(fut) -> Optional["_obs.TraceContext"]:
        """The request's adopted trace context (None for pre-admission
        refusals, which never reached ``submit``)."""
        if fut is None:
            return None
        trace = getattr(getattr(fut, "_job", None), "trace", None)
        return trace if isinstance(trace, _obs.TraceContext) else None

    @staticmethod
    def _finish_trace(ctx, *, pages: int, stream_ms: float,
                      proto: str) -> None:
        """Wire-side finalization of a deferred request tree: a
        back-dated ``serve.stream`` span for the page write-out, then
        the tail sampler's keep-policy completion. Idempotent."""
        if ctx is None or not _obs.TRACER.enabled:
            return
        if ctx.root_sid is not None and pages:
            _obs.emit_span("serve.stream", cat="serve",
                           dur_ms=stream_ms, ctx=ctx, pages=pages,
                           proto=proto)
        _obs.TAIL.complete(ctx)

    def _abandon(self, fut: QueryFuture) -> None:
        job = fut._job
        e2e_ms = (time.perf_counter() - job.t_submit) * 1e3
        self.server._finish(job, QueryResult(
            status="error", tenant=job.tenant, tag=job.tag,
            reason="client_gone", error="peer disconnected mid-request",
            e2e_ms=e2e_ms), executed=False, e2e_ms=e2e_ms)

    def _resolve_future(self, req: dict) -> QueryFuture:
        tenant = str(req.get("tenant") or "default")
        idem = req.get("idem")
        if idem:
            with self._idem_lock:
                fut = self._idem.get(idem)
                if fut is not None:
                    self._idem.move_to_end(idem)
                    counters.increment("net.idem_hit")
                    return fut
        work = req.get("sql")
        if work is None:
            name = req.get("job")
            work = self._jobs.get(name) if name else None
            if work is None:
                raise _BadRequest(
                    "bad_request", f"no 'sql' and no registered job "
                    f"{name!r}")
        deadline_s = None
        if req.get("deadline_ms") is not None:
            try:
                deadline_s = max(1e-3, float(req["deadline_ms"]) / 1e3)
            except (TypeError, ValueError):
                raise _BadRequest(
                    "bad_request",
                    f"bad deadline_ms {req['deadline_ms']!r}")
        est_bytes = None
        if req.get("est_bytes") is not None:
            try:
                est_bytes = max(0, int(req["est_bytes"]))
            except (TypeError, ValueError):
                raise _BadRequest(
                    "bad_request",
                    f"bad est_bytes {req['est_bytes']!r}")
        # ONE flag read: with tracing on, the wire traceparent (frame doc
        # field / HTTP header) becomes the request's context — malformed
        # or absent degrades to a locally-minted root, NEVER an error.
        # defer=True: this wire layer finalizes the tree after streaming.
        trace = (_obs.TraceContext.adopt(req.get("traceparent"),
                                         defer=True)
                 if _obs.TRACER.enabled else None)
        fut = self.server.submit(
            work, tenant=tenant, deadline_s=deadline_s,
            tag=str(req["tag"]) if req.get("tag") is not None else None,
            est_bytes=est_bytes, trace=trace)
        if idem:
            with self._idem_lock:
                self._idem[idem] = fut
                while len(self._idem) > IDEM_CACHE:
                    self._idem.popitem(last=False)
        return fut

    # -- result paging -------------------------------------------------------
    def _pages(self, value):
        """Result pages: a Frame streams ``page_rows`` rows at a time as
        column slices; anything else is one ``value`` page. The column
        pull is one host materialization per query (the same boundary a
        direct ``to_pydict`` consumer pays); paging bounds the PER-
        CLIENT serialized bytes in flight."""
        if hasattr(value, "to_pydict"):
            cols = value.to_pydict()
            n = max((len(v) for v in cols.values()), default=0)
            step = max(1, self.page_rows)
            for lo in range(0, n, step):
                yield {"rows": {k: v[lo:lo + step]
                                for k, v in cols.items()}}
            if n == 0:
                yield {"rows": {k: [] for k in cols}}
            return
        yield {"value": value}

    @staticmethod
    def _stream_deadline(fut) -> Optional[float]:
        """The job's wire deadline carried INTO streaming: ``deadline_s``
        bounds queueing and execution, but a large SELECT's result could
        page out past it indefinitely — each page send re-checks this
        ``perf_counter`` bound, so the deadline covers the stream end to
        end. None (no wire deadline, or a dedup/reject path without a
        job) streams unbounded as before."""
        job = getattr(fut, "_job", None)
        return getattr(job, "deadline_ts", None)

    @staticmethod
    def _page_deadline(result: QueryResult, pages: int) -> QueryResult:
        """Truncate a result stream at the wire deadline: the pages
        already sent stand, the rest are dropped, and the terminal frame
        carries a structured ``deadline_exceeded`` (site ``stream``) —
        the client sees a clean refusal, never a wedged socket."""
        counters.increment("net.page_deadline")
        return QueryResult(
            status="deadline_exceeded", tenant=result.tenant,
            reason="deadline", where="stream", tag=result.tag,
            queue_ms=result.queue_ms, exec_ms=result.exec_ms,
            e2e_ms=result.e2e_ms,
            detail=f"wire deadline expired mid-stream after {pages} "
                   "page(s); remaining pages dropped")

    @staticmethod
    def _end_doc(result: QueryResult) -> dict:
        doc = {"status": result.status, "tenant": result.tenant}
        for field in ("reason", "detail", "error", "where", "tag",
                      "queue_ms", "exec_ms", "e2e_ms"):
            v = getattr(result, field, None)
            if v not in (None, ""):
                doc[field] = v
        if result.status == "ok" and not hasattr(result.value,
                                                 "to_pydict") \
                and not isinstance(result.value, (dict, list)):
            doc["value"] = result.value
        return doc


class _BadRequest(Exception):
    def __init__(self, reason: str, msg: str):
        super().__init__(msg)
        self.reason = reason


class _InjectedStall(Exception):
    """An injected ``stall``/``slow_client`` standing in for a peer
    exceeding ``connTimeoutMs`` — handled by the same ladder as a real
    ``asyncio.TimeoutError``."""
