"""Concurrent query-serving layer: multi-tenant sessions over one engine,
a shared plan/jit cache, admission control, and SLO observability.

Entry points: :class:`QueryServer` (or ``session.serve()``),
:class:`TenantQuota`, and the structured :class:`QueryResult`; the
network front end is :class:`NetServer` + :class:`ResilientClient`
(``spark.serve.net.*`` — see README § "Network serving"). See
``serve/server.py`` for the architecture and README § "Serving".
"""

from .admission import AdmissionController, Rejection, TenantQuota
from .client import ClientResult, ResilientClient, WireError
from .coalesce import Coalescer
from .http import TelemetryServer
from .net import NetServer
from .server import (MAX_TENANT_SERIES, QueryDeadlineExceeded,
                     QueryExecutionError, QueryFuture, QueryRefused,
                     QueryResult, QueryServer, ServeError, TenantContext)

__all__ = [
    "AdmissionController", "Rejection", "TenantQuota",
    "QueryServer", "QueryFuture", "QueryResult", "TenantContext",
    "ServeError", "QueryRefused", "QueryDeadlineExceeded",
    "QueryExecutionError", "MAX_TENANT_SERIES", "TelemetryServer",
    "NetServer", "ResilientClient", "ClientResult", "WireError",
    "Coalescer",
]
