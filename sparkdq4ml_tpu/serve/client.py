"""Resilient wire client for the network serving front end.

The other half of :mod:`~.net`: a synchronous, dependency-free client
that speaks both framings (``DQW1`` length-prefixed frames, or HTTP/1.1
with chunked ndjson streaming) and wraps every request in the engine's
own :class:`~..utils.recovery.RetryPolicy` — exponential backoff with
deterministic jitter, a per-attempt socket timeout, and a total budget
past which the caller gets a structured ``deadline_exceeded`` rather
than a longer wait.

**Exactly-once across retries** is the idempotency-key contract: every
logical query carries one ``idem`` key (``uuid4``, constant across all
retries AND hedges of that query); the server dedups on it, so an
attempt that died after the server admitted the query — torn frame,
reset mid-stream — re-attaches to the ORIGINAL job on retry instead of
executing it a second time. Only a wire failure is retried; a
structured server answer (rejection, shed, execution error, deadline)
is final, with one exception — ``conn_timeout``, the server cutting a
connection it judged too slow, which is a transport verdict and retries
like any other wire fault.

**The client never raises and never hangs** for request-shaped
failures: every path returns a :class:`ClientResult` (wire faults
exhaust into ``status="error", reason="net_exhausted"``), mirroring the
``QueryResult``-never-raises contract server-side. Every retry/hedge
lands in :data:`~..utils.recovery.RECOVERY_LOG` under site
``net_client`` plus the ``net.client_retry`` / ``net.client_hedge``
counters, so client-side resilience is as observable as the server's.

**Hedging** (``spark.serve.client.hedging``, off by default): after one
backoff interval without a response the client races a second
connection carrying the SAME idempotency key; the first finished
attempt wins and the dedup makes the loser harmless. Tail-latency
insurance for read-mostly traffic — leave it off when queries are
expensive, every hedge occupies a server waiter slot.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import struct
import threading
import time
import uuid
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Any, Optional

from ..config import config as _cfg
from ..utils import observability as _obs
from ..utils.profiling import counters
from ..utils.recovery import RECOVERY_LOG, RetryPolicy
from .net import MAGIC

#: Statuses a server answer can carry; anything else on the wire is a
#: protocol violation and treated as a wire fault (retried).
_KNOWN_STATUSES = ("ok", "rejected", "shed", "deadline_exceeded", "error")


class WireError(Exception):
    """A transport-level failure of one attempt (reset, timeout, torn
    frame, unparseable payload) — retried by the policy loop, never
    surfaced to the caller directly."""


@dataclasses.dataclass
class ClientResult:
    """Structured outcome of one logical query — ALWAYS returned, never
    raised, whatever happened on the wire."""

    status: str                  # ok | rejected | shed |
    #                              deadline_exceeded | error
    tenant: str = ""
    value: Any = None            # merged pages (column dict) or scalar
    pages: int = 0               # result pages streamed
    reason: str = ""
    detail: str = ""
    error: str = ""
    where: str = ""              # "client" when synthesized client-side
    tag: Optional[str] = None
    attempts: int = 1            # wire attempts spent (incl. hedges)
    e2e_ms: Optional[float] = None   # server-side figure when present
    #: Wire trace id of the logical query (constant across retries and
    #: hedges) — joins this result to the server-side span tree via
    #: ``/trace/<trace_id>``. None when tracing was off client-side AND
    #: the server echoed none.
    trace_id: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.status == "ok"


class ResilientClient:
    """One logical client over the :class:`~.net.NetServer` socket.

    ``transport="frame"`` keeps ONE connection alive across queries
    (reconnecting transparently after a wire fault); ``transport=
    "http"`` opens one connection per request (the framing is
    ``Connection: close``). Thread-safe per instance via a request
    lock — for N concurrent client threads use N instances (the soak's
    shape), not one shared one."""

    def __init__(self, host: str, port: int, *,
                 transport: str = "frame",
                 tenant: str = "default",
                 policy: Optional[RetryPolicy] = None,
                 hedging: Optional[bool] = None,
                 connect_timeout: float = 5.0):
        if transport not in ("frame", "http"):
            raise ValueError(f"transport must be 'frame' or 'http', "
                             f"got {transport!r}")
        self.host = host
        self.port = int(port)
        self.transport = transport
        self.tenant = tenant
        self.policy = policy if policy is not None else RetryPolicy(
            max_attempts=max(1, int(_cfg.serve_client_retries)),
            backoff_base=float(_cfg.serve_client_backoff_ms) / 1e3)
        self.hedging = (bool(_cfg.serve_client_hedging)
                        if hedging is None else bool(hedging))
        self.connect_timeout = float(connect_timeout)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._hedge_pool: Optional[ThreadPoolExecutor] = None

    # -- public API ----------------------------------------------------------
    def query(self, sql: str, *, tenant: Optional[str] = None,
              deadline_s: Optional[float] = None,
              tag: Optional[str] = None,
              est_bytes: Optional[int] = None) -> ClientResult:
        """Run one SQL query; blocks until a structured result.
        ``est_bytes`` declares the device footprint for the server's
        admission memory gate and coalesced-batch sizing."""
        return self._run({"sql": sql}, tenant=tenant,
                         deadline_s=deadline_s, tag=tag,
                         est_bytes=est_bytes)

    def call_job(self, name: str, *, tenant: Optional[str] = None,
                 deadline_s: Optional[float] = None,
                 tag: Optional[str] = None,
                 est_bytes: Optional[int] = None) -> ClientResult:
        """Invoke a server-side job registered via
        :meth:`~.net.NetServer.register_job`."""
        return self._run({"job": name}, tenant=tenant,
                         deadline_s=deadline_s, tag=tag,
                         est_bytes=est_bytes)

    def healthz(self) -> dict:
        """One HTTP health probe (works against either transport's
        port — healthz is HTTP-only). Raises :class:`WireError` on a
        dead endpoint; returns the decoded doc plus ``http_code``."""
        try:
            code, _, body = self._http_roundtrip(
                b"GET /healthz HTTP/1.1\r\nHost: dq\r\n"
                b"Connection: close\r\n\r\n",
                timeout=self.connect_timeout)
            doc = json.loads(body.decode() or "{}")
            doc["http_code"] = code
            return doc
        except (OSError, ValueError) as e:
            raise WireError(f"healthz probe failed: {e}") from e

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
        if self._hedge_pool is not None:
            self._hedge_pool.shutdown(wait=False)
            self._hedge_pool = None

    def __enter__(self) -> "ResilientClient":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- retry engine --------------------------------------------------------
    def _run(self, doc: dict, *, tenant: Optional[str],
             deadline_s: Optional[float],
             tag: Optional[str],
             est_bytes: Optional[int] = None) -> ClientResult:
        doc = dict(doc)
        doc["tenant"] = tenant if tenant is not None else self.tenant
        if tag is not None:
            doc["tag"] = tag
        if est_bytes is not None:
            doc["est_bytes"] = int(est_bytes)
        if deadline_s is not None:
            # RELATIVE budget on the wire — clock-skew tolerant by
            # construction (the server re-anchors on its own clock)
            doc["deadline_ms"] = max(1.0, float(deadline_s) * 1e3)
        doc["idem"] = uuid.uuid4().hex   # constant across retries+hedges
        # One flag read: with tracing off no context is minted and the
        # wire doc stays byte-identical to the untraced protocol.
        trace = _obs.TraceContext.mint() if _obs.TRACER.enabled else None
        trace_id = trace.trace_id if trace is not None else None
        policy = self.policy
        started = time.monotonic()
        budget = policy.total_deadline
        if deadline_s is not None:
            # the wire deadline bounds the whole logical query too:
            # past it the server answers deadline_exceeded anyway
            slack = float(deadline_s) + 2.0 * policy.max_attempts
            budget = slack if budget is None else min(budget, slack)
        last_err = "no attempt ran"
        attempts = 0
        for attempt in range(1, policy.max_attempts + 1):
            remaining = (None if budget is None
                         else budget - (time.monotonic() - started))
            if remaining is not None and remaining <= 0:
                return ClientResult(
                    status="deadline_exceeded", tenant=doc["tenant"],
                    where="client", tag=tag, attempts=attempts,
                    trace_id=trace_id,
                    detail=f"client budget of {budget:.3g}s exhausted "
                           f"after {attempts} attempt(s)")
            attempts += 1
            attempt_doc = doc
            if trace is not None:
                # same trace id every attempt, a FRESH child span id per
                # attempt — the server tells retries and hedges apart
                attempt_doc = dict(doc)
                attempt_doc["traceparent"] = trace.child_traceparent()
            try:
                result = self._hedged_attempt(attempt_doc, attempt,
                                              remaining)
            except WireError as e:
                last_err = str(e)
                backoff = policy.backoff(attempt, "net_client")
                action = ("retry" if attempt < policy.max_attempts
                          else "exhausted")
                RECOVERY_LOG.record("net_client", action,
                                    attempt=attempt, cause=last_err,
                                    backoff_s=backoff)
                if action == "retry":
                    counters.increment("net.client_retry")
                    policy.sleep(backoff)
                continue
            if result.reason == "conn_timeout" \
                    and attempt < policy.max_attempts:
                # the server's slow-connection verdict: a transport
                # outcome, retried like a reset
                last_err = "server cut the connection (conn_timeout)"
                backoff = policy.backoff(attempt, "net_client")
                RECOVERY_LOG.record("net_client", "retry",
                                    attempt=attempt, cause=last_err,
                                    backoff_s=backoff)
                counters.increment("net.client_retry")
                policy.sleep(backoff)
                continue
            if attempt > 1:
                RECOVERY_LOG.record("net_client", "recovered",
                                    attempt=attempt)
            result.attempts = attempts
            if result.trace_id is None:
                result.trace_id = trace_id
            return result
        return ClientResult(
            status="error", tenant=doc["tenant"], reason="net_exhausted",
            where="client", tag=tag, attempts=attempts,
            trace_id=trace_id,
            error=f"wire failed {attempts} attempt(s); last: {last_err}")

    def _hedged_attempt(self, doc: dict, attempt: int,
                        remaining: Optional[float]) -> ClientResult:
        timeout = self._attempt_timeout(doc, remaining)
        if not self.hedging:
            return self._attempt(doc, timeout)
        if self._hedge_pool is None:
            self._hedge_pool = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="sparkdq4ml-hedge")
        primary = self._hedge_pool.submit(self._attempt, doc, timeout,
                                          fresh=False)
        done, _ = wait([primary],
                       timeout=self.policy.backoff(max(1, attempt),
                                                   "net_client") or 0.05,
                       return_when=FIRST_COMPLETED)
        if done:
            return primary.result()
        counters.increment("net.client_hedge")
        RECOVERY_LOG.record("net_client", "hedge", attempt=attempt,
                            detail="racing a second connection "
                                   "(same idempotency key)")
        hedge = self._hedge_pool.submit(self._attempt,
                                        self._hedge_doc(doc), timeout,
                                        fresh=True)
        done, _ = wait([primary, hedge], timeout=timeout + 5.0,
                       return_when=FIRST_COMPLETED)
        for fut in (tuple(done) or (primary,)):
            try:
                return fut.result()
            except WireError:
                continue
        # whichever finished raised; block on the other within budget
        rest = [f for f in (primary, hedge) if not f.done()]
        if rest:
            done2, _ = wait(rest, timeout=timeout + 5.0)
            for fut in done2:
                try:
                    return fut.result()
                except WireError:
                    continue
        raise WireError("both hedged attempts failed")

    @staticmethod
    def _hedge_doc(doc: dict) -> dict:
        """The hedge carries the same trace id but its own child span id
        (it IS a distinct wire attempt); without a traceparent the doc
        passes through untouched."""
        tp = doc.get("traceparent")
        if not tp:
            return doc
        hedged = dict(doc)
        hedged["traceparent"] = f"00-{tp[3:35]}-{os.urandom(8).hex()}-01"
        return hedged

    def _attempt_timeout(self, doc: dict,
                         remaining: Optional[float]) -> float:
        timeout = self.policy.attempt_deadline
        if timeout is None:
            timeout = 30.0
            if doc.get("deadline_ms") is not None:
                timeout = doc["deadline_ms"] / 1e3 + 5.0
        if remaining is not None:
            timeout = max(0.1, min(timeout, remaining))
        return timeout

    # -- single attempt ------------------------------------------------------
    def _attempt(self, doc: dict, timeout: float,
                 fresh: bool = False) -> ClientResult:
        try:
            if self.transport == "frame":
                end, pages, n = self._frame_roundtrip(doc, timeout, fresh)
            else:
                end, pages, n = self._http_query(doc, timeout)
        except (OSError, ValueError, struct.error, WireError) as e:
            raise WireError(f"{type(e).__name__}: {e}") from e
        status = str(end.get("status", ""))
        if status not in _KNOWN_STATUSES:
            raise WireError(f"protocol violation: unknown status "
                            f"{status!r} in end frame")
        return ClientResult(
            status=status, tenant=str(end.get("tenant", "")),
            value=self._merge(pages, end), pages=n,
            reason=str(end.get("reason", "")),
            detail=str(end.get("detail", "")),
            error=str(end.get("error", "")),
            where=str(end.get("where", "")),
            tag=end.get("tag"), e2e_ms=end.get("e2e_ms"),
            trace_id=end.get("trace_id"))

    @staticmethod
    def _merge(pages: list, end: dict):
        """Merged result value: row pages concatenate column-wise in
        page order; a scalar rides in its single ``value`` page (or the
        end doc)."""
        if not pages:
            return end.get("value")
        if "value" in pages[0] and "rows" not in pages[0]:
            return pages[0]["value"]
        cols: dict[str, list] = {}
        for page in pages:
            for k, v in page.get("rows", {}).items():
                cols.setdefault(k, []).extend(v)
        return cols

    # -- frame transport -----------------------------------------------------
    def _frame_roundtrip(self, doc: dict, timeout: float, fresh: bool):
        with self._lock if not fresh else _NoopLock():
            sock = None
            try:
                if fresh:
                    sock = self._connect()
                else:
                    if self._sock is None:
                        self._sock = self._connect()
                    sock = self._sock
                sock.settimeout(timeout)
                payload = json.dumps(doc).encode()
                sock.sendall(struct.pack(">I", len(payload)) + payload)
                pages: list = []
                while True:
                    frame = self._read_frame(sock)
                    if frame.get("end"):
                        return frame, pages, len(pages)
                    pages.append(frame)
            except (WireError, OSError, ValueError, struct.error) as e:
                # the persistent connection is poisoned mid-exchange
                # (truncated frame, reset, torn JSON): drop it so the
                # retry reconnects clean instead of reusing a dead peer
                if not fresh and sock is self._sock:
                    self._sock = None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                if isinstance(e, WireError):
                    raise
                raise WireError(f"{type(e).__name__}: {e}") from e
            finally:
                if fresh and sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout)
        sock.sendall(MAGIC)
        return sock

    @staticmethod
    def _read_frame(sock: socket.socket) -> dict:
        head = _read_exactly(sock, 4)
        (length,) = struct.unpack(">I", head)
        body = _read_exactly(sock, length)
        frame = json.loads(body.decode())
        if not isinstance(frame, dict):
            raise WireError(f"non-object frame: {frame!r}")
        return frame

    # -- HTTP transport ------------------------------------------------------
    def _http_query(self, doc: dict, timeout: float):
        doc = dict(doc)
        # HTTP carries the context in the standard header, not the body
        traceparent = doc.pop("traceparent", None)
        body = json.dumps(doc).encode()
        head = (f"POST /query HTTP/1.1\r\nHost: dq\r\n"
                "Content-Type: application/json\r\n"
                + (f"traceparent: {traceparent}\r\n"
                   if traceparent else "")
                + f"Content-Length: {len(body)}\r\n"
                "Connection: close\r\n\r\n").encode("latin-1")
        code, headers, payload = self._http_roundtrip(head + body,
                                                      timeout=timeout)
        if "chunked" in headers.get("transfer-encoding", ""):
            payload = _dechunk(payload)
        lines = [ln for ln in payload.split(b"\n") if ln.strip()]
        if not lines:
            raise WireError(f"empty HTTP {code} response")
        docs = [json.loads(ln.decode()) for ln in lines]
        end = docs[-1]
        if not isinstance(end, dict) or "status" not in end:
            raise WireError(f"no status in HTTP {code} terminal line")
        return end, docs[:-1], len(docs) - 1

    def _http_roundtrip(self, request: bytes, timeout: float):
        """One raw HTTP/1.1 exchange (Connection: close — read to EOF).
        Hand-rolled over a plain socket rather than http.client so torn
        responses surface as the wire faults they are."""
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout)
        try:
            sock.settimeout(timeout)
            sock.sendall(request)
            raw = b""
            while b"\r\n\r\n" not in raw:
                chunk = sock.recv(65536)
                if not chunk:
                    raise WireError("connection closed in HTTP head")
                raw += chunk
            head, _, body = raw.partition(b"\r\n\r\n")
            lines = head.decode("latin-1").split("\r\n")
            code = int(lines[0].split()[1])
            headers: dict[str, str] = {}
            for line in lines[1:]:
                name, _, value = line.partition(":")
                headers[name.strip().lower()] = value.strip()
            length = headers.get("content-length")
            while True:
                if length is not None and len(body) >= int(length):
                    break
                chunk = sock.recv(65536)
                if not chunk:
                    if length is not None and len(body) < int(length):
                        raise WireError(
                            f"truncated HTTP body ({len(body)}"
                            f"/{length} bytes)")
                    break
                body += chunk
            return code, headers, body
        finally:
            try:
                sock.close()
            except OSError:
                pass


class _NoopLock:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def _read_exactly(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise WireError(f"connection closed mid-frame "
                            f"({len(buf)}/{n} bytes)")
        buf += chunk
    return buf


def _dechunk(payload: bytes) -> bytes:
    """Decode a chunked transfer body; a missing terminal 0-chunk is a
    torn stream (the partial_write fault made visible) → WireError."""
    out, rest = b"", payload
    while True:
        line, sep, rest = rest.partition(b"\r\n")
        if not sep:
            raise WireError("torn chunked stream (no size line)")
        try:
            size = int(line.strip() or b"0", 16)
        except ValueError as e:
            raise WireError(f"bad chunk size {line!r}") from e
        if size == 0:
            return out
        if len(rest) < size + 2:
            raise WireError(f"torn chunk ({len(rest)}/{size} bytes)")
        out += rest[:size]
        rest = rest[size + 2:]


def from_conf(host: str, port: int, **overrides) -> ResilientClient:
    """Client wired from the active session's ``spark.serve.client.*``
    conf (retries, backoffMs, hedging) — the conf-first construction
    path mirroring ``QueryServer.from_conf``."""
    return ResilientClient(host, port, **overrides)
