"""Global configuration for the framework.

The reference hard-codes every constant (thresholds, paths, LR params — see
SURVEY.md §5 "Config / flag system"); its only knobs are MLlib's ``setX``
builder pattern, which the estimators here reproduce. This module holds the
few framework-level defaults that Spark keeps in ``SparkConf``.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

#: Conf boolean spellings — THE shared vocabulary for every conf parser
#: (session ``spark.*`` keys, ``spark.serve.*`` keys, env gates). One
#: tuple each, so a new spelling cannot silently diverge between parsers.
CONF_FALSE = ("false", "off", "0", "no")
CONF_TRUE = ("true", "on", "1", "yes")

#: THE ``spark.*`` conf-key registry — every key the engine reads must be
#: declared here (enforced statically by dqlint's ``conf-key`` rule,
#: ``sparkdq4ml_tpu/analysis/rules/conf_keys.py``). The tag records who
#: owns the key's lifecycle:
#:
#: * ``"session"`` — applied by ``session._init_pipeline`` with
#:   save/restore, so one session's setting never leaks process-wide
#:   (the rule verifies the key literal actually appears there);
#: * ``"init"`` — read once during session construction/infrastructure
#:   bring-up (backend probe, compilation cache, observability install,
#:   fault plan, multi-host bootstrap); restored by ``stop()`` where it
#:   mutates process state.
CONF_KEYS = {
    "spark.pipeline.enabled": "session",
    "spark.pipeline.minBucket": "session",
    "spark.pipeline.cacheSize": "session",
    "spark.groupedExec.enabled": "session",
    "spark.explain.memory": "session",
    "spark.explain.caches": "session",
    "spark.serve.enabled": "session",
    "spark.serve.net.enabled": "session",
    "spark.serve.net.port": "session",
    "spark.serve.net.host": "session",
    "spark.serve.net.backlog": "session",
    "spark.serve.net.connTimeoutMs": "session",
    "spark.serve.net.maxFrameBytes": "session",
    "spark.serve.net.streamPageRows": "session",
    "spark.serve.client.retries": "session",
    "spark.serve.client.backoffMs": "session",
    "spark.serve.client.hedging": "session",
    "spark.serve.coalesce.enabled": "session",
    "spark.serve.coalesce.maxDelayMs": "session",
    "spark.serve.coalesce.maxBatch": "session",
    "spark.serve.coalesce.minQueueDepth": "session",
    "spark.audit.enabled": "session",
    "spark.audit.memoryFraction": "session",
    "spark.audit.deviceBudget": "session",
    "spark.audit.constBytes": "session",
    "spark.ingest.streaming": "session",
    "spark.ingest.threads": "session",
    "spark.ingest.chunkBytes": "session",
    "spark.ingest.prefetch": "session",
    "spark.ingest.simd": "session",
    "spark.chaos.seed": "session",
    "spark.chaos.seeds": "session",
    "spark.chaos.soakSeconds": "session",
    "spark.optimizer.enabled": "session",
    "spark.optimizer.level": "session",
    "spark.aqe.enabled": "session",
    "spark.aqe.driftFactor": "session",
    "spark.aqe.broadcastThreshold": "session",
    "spark.aqe.skewFactor": "session",
    "spark.stats.enabled": "session",
    "spark.stats.path": "session",
    "spark.stats.maxEntries": "session",
    "spark.stats.flushOnStop": "session",
    "spark.shard.enabled": "session",
    "spark.shard.minRows": "session",
    "spark.shard.devices": "session",
    "spark.costprof.enabled": "session",
    "spark.costprof.ridge": "session",
    "spark.profiling.maxCaptures": "session",
    "spark.trace.ringSize": "session",
    "spark.trace.retainedSize": "session",
    "spark.trace.exemplars": "session",
    "spark.incident.enabled": "session",
    "spark.incident.dir": "session",
    "spark.incident.maxBundles": "session",
    "spark.incident.cooldownS": "session",
    "spark.incident.sloBurnThreshold": "session",
    "spark.dq.profile.enabled": "session",
    "spark.dq.histogramBins": "session",
    "spark.dq.driftThreshold": "session",
    "spark.dq.baselineMode": "session",
    "spark.observability.enabled": "init",
    "spark.observability.maxSpans": "init",
    "spark.observability.logSpans": "init",
    "spark.faults": "init",
    "spark.faults.seed": "init",
    "spark.recovery.validate": "init",
    "spark.backend.probe": "init",
    "spark.backend.probeTimeout": "init",
    "spark.compilation.cache": "init",
    "spark.compilation.cacheDir": "init",
    "spark.distributed.coordinator": "init",
    "spark.distributed.numProcesses": "init",
    "spark.distributed.processId": "init",
    "spark.serve.sharedPlanCache": "init",
}

#: Dynamic key families (formatted per site/tenant at runtime): any key
#: starting with one of these prefixes is declared by the family.
CONF_KEY_PREFIXES = (
    "spark.recovery.",   # per-site retry policy (RetryPolicy.from_conf)
    "spark.serve.",      # QueryServer.from_conf tuning family
)


@dataclasses.dataclass
class _Config:
    # Default floating dtype for frame columns and solvers. float32 rides the
    # TPU MXU/VPU natively; tests may select float64 (with jax_enable_x64) for
    # tight golden-number parity on CPU.
    default_float_dtype: jnp.dtype = jnp.float32
    # Default integer dtype (Spark CSV inference yields IntegerType → int32).
    default_int_dtype: jnp.dtype = jnp.int32
    # Rows shown by Frame.show() when no argument is given (Spark default: 20).
    default_show_rows: int = 20
    # Fused expression-pipeline compiler (ops/compiler.py): consecutive
    # compilable Frame.with_column/filter ops coalesce into ONE jitted XLA
    # program per structural plan key (spark.pipeline.enabled conf; False
    # restores the exact per-op eager path).
    pipeline: bool = True
    # Row-slot bucket floor for the pipeline's shape-bucketed padding
    # (rows pad up to the next power of two, never below this).
    pipeline_min_bucket: int = 8
    # Above this row count programs compile at EXACT length instead of a
    # padded bucket: the per-flush pad + unpad copies are O(n) and at
    # this scale cost more than an occasional retrace, while below it
    # bucketing lets frames of different lengths (e.g. two CSV loads)
    # share one compiled program.
    pipeline_exact_threshold: int = 1 << 17
    # Bounded LRU size of the plan-keyed jit cache.
    pipeline_cache_size: int = 256
    # Device-resident grouped execution (ops/segments.py): numeric
    # groupBy/sort/distinct lower to one jitted program (device sort +
    # segment reductions) instead of the host numpy boundary
    # (spark.groupedExec.enabled conf; False restores the legacy path).
    grouped_exec: bool = True
    # EXPLAIN ANALYZE (sql/parser.py): sample device memory at span
    # boundaries during the analyzed query (spark.explain.memory conf) —
    # a live-array census per span; off leaves peak_mem unattributed.
    explain_memory: bool = True
    # Append the jit-cache introspection section (one line per compiled
    # program the query touched) to EXPLAIN ANALYZE output
    # (spark.explain.caches conf).
    explain_caches: bool = True
    # Query-serving layer (serve/): gates session.serve(). False
    # (spark.serve.enabled=false) makes session.serve() refuse to start a
    # server; the layer is otherwise pay-for-use — a process that never
    # starts a QueryServer runs zero serve code (no threads, no metrics).
    serve_enabled: bool = True
    # Network serving front end (serve/net.py): the asyncio socket
    # protocol over the QueryServer — HTTP/1.1 with chunked streaming
    # pages plus the length-prefixed frame protocol. OFF by default
    # (spark.serve.net.enabled): QueryServer.start() reads exactly this
    # one flag when disabled — no socket, no event loop, no thread.
    serve_net_enabled: bool = False
    # Bind point (spark.serve.net.{host,port}): 127.0.0.1 by default —
    # the same unauthenticated-endpoint security posture as the
    # telemetry server; port 0 = ephemeral (tests/soak).
    serve_net_host: str = "127.0.0.1"
    serve_net_port: int = 0
    # Listen backlog (spark.serve.net.backlog).
    serve_net_backlog: int = 64
    # Per-connection read/write timeout in ms
    # (spark.serve.net.connTimeoutMs) — the slow-loris guard: a peer
    # that stalls a request read or a response drain past this is cut
    # with a net.conn_timeout recovery event, never held open.
    serve_net_conn_timeout_ms: int = 10_000
    # Bound on one wire request (frame payload / HTTP head+body) in
    # bytes (spark.serve.net.maxFrameBytes): past it the request is
    # refused with a structured error, bounding per-connection buffers.
    serve_net_max_frame_bytes: int = 4 << 20
    # Rows per streamed result page (spark.serve.net.streamPageRows):
    # a large SELECT leaves the server one page at a time instead of
    # materializing the whole response per client.
    serve_net_stream_page_rows: int = 4096
    # Resilient-client defaults (serve/client.py, RetryPolicy-backed):
    # attempts per call (spark.serve.client.retries), first backoff in
    # ms (spark.serve.client.backoffMs), and opt-in hedging — a second
    # connection racing the first after one backoff interval
    # (spark.serve.client.hedging; idempotency keys keep the hedge
    # exactly-once server-side).
    serve_client_retries: int = 3
    serve_client_backoff_ms: float = 50.0
    serve_client_hedging: bool = False
    # Cross-request plan coalescing (serve/coalesce.py): OFF by default
    # (spark.serve.coalesce.enabled) — QueryServer.start() reads exactly
    # this one flag when disabled, and the per-request dispatch path is
    # byte-for-byte PR-17 behavior (one None check in run_pipeline).
    serve_coalesce_enabled: bool = False
    # Hold window in ms (spark.serve.coalesce.maxDelayMs): how long a
    # batch leader waits for same-plan followers before dispatching; cut
    # short the moment the batch fills.
    serve_coalesce_max_delay_ms: float = 2.0
    # Member cap per batched dispatch (spark.serve.coalesce.maxBatch),
    # clamped further by the admission memory gate pricing the STACKED
    # batch bytes.
    serve_coalesce_max_batch: int = 8
    # Load trigger (spark.serve.coalesce.minQueueDepth): a worker arms
    # the coalescing scope only when the queue depth at pop time is at
    # least this — light load keeps the pure per-request path.
    serve_coalesce_min_queue_depth: int = 2
    # dqaudit — the jaxpr-level program-audit tier (analysis/program/):
    # gates the EXPLAIN `est peak` static-memory column and
    # session.audit_report() (spark.audit.enabled). The auditor is
    # strictly offline/on-demand either way — disabling only removes
    # the EXPLAIN annotation and makes audit_report() refuse.
    audit_enabled: bool = True
    # Static per-program peak-bytes bound must fit this fraction of the
    # device byte budget (spark.audit.memoryFraction).
    audit_memory_fraction: float = 0.9
    # Explicit device byte budget for the static-memory detector
    # (spark.audit.deviceBudget); 0 = use the allocator bytes_limit
    # where the backend exposes one (XLA:CPU exposes none, so the
    # memory gate is advisory-only there unless set).
    audit_device_budget: int = 0
    # Captured-constant size above which the hidden-sync detector flags
    # host-constant capture inside a jitted body
    # (spark.audit.constBytes).
    audit_const_bytes: int = 4096
    # Streaming CSV ingest (frame/native_csv.py): files larger than one
    # chunk parse through the native dq_stream API in bounded chunks cut
    # on structural record boundaries, with a prefetch thread overlapping
    # parse of chunk N+1 with host->device transfer of chunk N
    # (spark.ingest.streaming conf; False restores the exact legacy
    # one-shot native path).
    ingest_streaming: bool = True
    # Parse threads per chunk: 0 = auto (DQCSV_THREADS env, then a
    # size-based heuristic in the native layer), else an explicit cap
    # (spark.ingest.threads).
    ingest_threads: int = 0
    # Chunk size in bytes for the streaming parse — the static per-chunk
    # memory bound; also the streaming threshold: smaller files take one
    # one-shot native call (spark.ingest.chunkBytes).
    ingest_chunk_bytes: int = 8 << 20
    # Bounded prefetch queue depth: how many parsed-but-untransferred
    # chunks the producer thread may run ahead (spark.ingest.prefetch).
    ingest_prefetch: int = 2
    # SIMD tier for the native parse: "auto" (runtime CPU-feature
    # dispatch, overridable by DQCSV_SIMD env), "off" (scalar),
    # "avx2", "avx512" — explicit tiers clamp to what the CPU supports
    # (spark.ingest.simd).
    ingest_simd: str = "auto"
    # Chaos-soak defaults (scripts/chaos_soak.py): base seed of the
    # seeded random fault schedules (spark.chaos.seed), how many seeds
    # the soak sweeps (spark.chaos.seeds), and a minimum per-seed soak
    # duration in seconds — 0 runs each seed's workload exactly once
    # (spark.chaos.soakSeconds). Session-scoped like every other knob;
    # the harness CLI flags override.
    chaos_seed: int = 0
    chaos_seeds: int = 5
    chaos_soak_s: float = 0.0
    # Cost-based plan optimizer (sql/optimizer.py + lowering hooks in
    # ops/compiler.py and ops/segments.py): statstore-driven rewrites
    # over the parsed Query — predicate/projection pushdown, build-side
    # selection, grouped dense-skip, history-informed memory chunking —
    # applied before execution (spark.optimizer.enabled; false runs
    # every query at its literal parse shape, one flag read per query).
    optimizer_enabled: bool = True
    # Rewrite aggressiveness (spark.optimizer.level): 1 = rewrites that
    # preserve physical emission order bit-for-bit (the default); 2 adds
    # join reordering and fused-stage boundary splitting — row MULTISETS
    # stay exact, but physical row order may legally change where SQL
    # imposes none.
    optimizer_level: int = 1
    # Adaptive query execution (sql/adaptive.py + stage-boundary hooks):
    # mid-query re-planning from the rows/bytes THIS execution just
    # observed — build-side flips and broadcast shuffle-skips at the join
    # boundary, downstream re-bucketing after a misestimated filter,
    # skewed-exchange partition splits, and the grouped engine's
    # estimate-informed lowering choice. Every transform is bit-identical
    # by construction (the masked-slot invariant + the partitioned plan's
    # stable order merge); spark.aqe.enabled=false reduces every hook to
    # one flag read and runs the static plan end to end.
    aqe_enabled: bool = True
    # Drift ratio (observed vs estimate, either direction) that triggers
    # a re-plan decision (spark.aqe.driftFactor). Below it the static
    # plan stands — estimates are advisory, re-planning has real cost.
    aqe_drift_factor: float = 4.0
    # Observed build-side byte bound under which a drift-triggered join
    # skips the hash-partition shuffle entirely and runs the single
    # (broadcast-style) plan (spark.aqe.broadcastThreshold).
    aqe_broadcast_threshold: int = 8 << 20
    # Live partition-balance ratio (largest/mean probe rows within one
    # exchange) past which a skewed partition splits into balanced
    # chunks (spark.aqe.skewFactor) — the PR-13 decomposable merge
    # re-sorts the chunk plans back into the exact global order.
    aqe_skew_factor: float = 4.0
    # Plan-statistics observatory (utils/statstore.py): per-plan-key
    # running stats — observed selectivity, wall/compile-ms digests,
    # host syncs, est/measured peak bytes — feeding EXPLAIN's history-
    # informed `est rows` column and (ROADMAP item 4) the cost-based
    # optimizer. spark.stats.enabled=false reduces every producer hook
    # to one flag read (test-pinned no-op).
    stats_enabled: bool = True
    # Snapshot path for cross-session persistence (spark.stats.path);
    # empty = in-memory only. Loaded (merge) at session init, written
    # (merge-don't-clobber, atomic) by stop() when stats_flush_on_stop.
    stats_path: str = ""
    # Bounded per-key entry table (spark.stats.maxEntries): past it the
    # least-recently-updated entry evicts (stats.evict counter).
    stats_max_entries: int = 512
    # Persist on session stop() (spark.stats.flushOnStop).
    stats_flush_on_stop: bool = True
    # Row-sharded frames (parallel/shard.py): Frame._data/_mask lay out
    # row-partitioned across the device mesh, the fused pipeline flush
    # lowers as ONE shard_map program per plan, and grouped execution
    # merges per-shard segment reductions with one cross-shard
    # collective. Off by default (spark.shard.enabled): sharding is a
    # scale feature, activated per session where a multi-device mesh
    # exists; a trivial mesh leaves it inert either way.
    shard_enabled: bool = False
    # Row-count floor below which frames stay single-device
    # (spark.shard.minRows) — placement traffic and the merge collective
    # only pay for themselves at scale; joins/distinct likewise
    # host-fallback below this bound.
    shard_min_rows: int = 1 << 16
    # Cap on the shard device count (spark.shard.devices); 0 = the whole
    # session mesh.
    shard_devices: int = 0
    # Device-cost observatory (utils/costprof.py + analysis/program/
    # costs.py): AOT cost-analysis extraction over every cached program,
    # roofline verdicts in EXPLAIN ANALYZE, shard-skew/exchange-volume
    # accounting, and the /profile telemetry route. Extraction runs
    # lazily on cold surfaces only (report/EXPLAIN/save/scrape);
    # spark.costprof.enabled=false reduces every hook to one flag read
    # and restores byte-identical PR-14 EXPLAIN output.
    costprof_enabled: bool = True
    # Roofline ridge point in FLOPs per byte accessed
    # (spark.costprof.ridge): an operator whose arithmetic intensity is
    # at or above this is verdicted compute-bound, below it
    # memory-bound. The default 8 is a generic accelerator-class ridge;
    # calibrate per chip from a TPU capture (the CPU-sandbox verdicts
    # are structural, not absolute — see README).
    costprof_ridge: float = 8.0
    # Bounded retention of managed jax-profiler captures
    # (spark.profiling.maxCaptures): utils/profiling.start_capture
    # prunes the oldest capture directories past this count.
    profiling_max_captures: int = 4
    # Tail-based request-tree retention (utils/observability.TailSampler):
    # bounded ring of recently completed serving request trees
    # (spark.trace.ringSize) and bounded retained store of keep-policy
    # promoted trees keyed by wire trace id (spark.trace.retainedSize).
    # Only populated while observability is enabled — disabled mode
    # registers nothing.
    trace_ring_size: int = 256
    trace_retained_size: int = 64
    # Emit OpenMetrics exemplars on histogram buckets (the last kept
    # trace id per serve.e2e_ms bucket) in the Prometheus exporter
    # (spark.trace.exemplars) — off by default: exemplar suffixes are an
    # OpenMetrics extension some plain-Prometheus scrapers reject.
    trace_exemplars: bool = False
    # Incident flight recorder (utils/incidents.py): on a trigger
    # (breaker trip, fault-ladder engagement, SLO burn crossing
    # spark.incident.sloBurnThreshold) snapshot a correlated incident
    # bundle — request span tree, metrics deltas, RECOVERY_LOG slice,
    # plan/stats rows. Active only while observability is enabled AND
    # (spark.incident.enabled or spark.incident.dir is set); bundles
    # persist atomically to spark.incident.dir (empty = in-memory only),
    # retention-capped at spark.incident.maxBundles, rate-limited per
    # trigger kind by spark.incident.cooldownS.
    incident_enabled: bool = False
    incident_dir: str = ""
    incident_max_bundles: int = 32
    incident_cooldown_s: float = 5.0
    incident_slo_burn_threshold: float = 8.0
    # Data-quality observatory (utils/dqprof.py): per-column profile
    # sketches + per-rule violation accounting dispatched as deferred
    # device reductions from the flush hook, drained only on cold paths
    # (report / the /dq route / EXPLAIN ANALYZE) — the hot path adds
    # zero counted host syncs. spark.dq.profile.enabled=false reduces
    # every hook to one conf read and pins EXPLAIN byte-identical.
    dq_profile_enabled: bool = True
    # Fixed-bucket histogram resolution over the log-compressed domain
    # (spark.dq.histogramBins) — identical bins values merge
    # bucket-for-bucket across flushes, shards, and sessions.
    dq_histogram_bins: int = 32
    # PSI drift score past this captures an incident bundle and tags
    # the span for tail-keep (spark.dq.driftThreshold).
    dq_drift_threshold: float = 0.25
    # Drift reference policy (spark.dq.baselineMode): "first" adopts a
    # persisted statstore snapshot when present else pins the first
    # drained profile; "persisted" only ever adopts; "off" disables
    # drift scoring.
    dq_baseline_mode: str = "first"
    # Pallas fast-path selection for the hot ops (ops/pallas_kernels.py):
    # the single-device Gramian in solvers.augmented_gram and the fused DQ
    # chain entry point ops/rules.py:dq_rules_fused. "off" = plain XLA
    # (default; XLA fuses these well), "on" = compiled Pallas kernels,
    # "auto" = Pallas when the backend is TPU, "interpret" = Pallas
    # interpreter (CPU tests/CI of the kernel code). shard_map/vmap traces
    # always use XLA (see pallas_kernels.dispatch_to_pallas).
    pallas: str = "off"


config = _Config()


def float_dtype() -> jnp.dtype:
    return config.default_float_dtype


def int_dtype() -> jnp.dtype:
    return config.default_int_dtype
