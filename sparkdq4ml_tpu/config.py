"""Global configuration for the framework.

The reference hard-codes every constant (thresholds, paths, LR params — see
SURVEY.md §5 "Config / flag system"); its only knobs are MLlib's ``setX``
builder pattern, which the estimators here reproduce. This module holds the
few framework-level defaults that Spark keeps in ``SparkConf``.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass
class _Config:
    # Default floating dtype for frame columns and solvers. float32 rides the
    # TPU MXU/VPU natively; tests may select float64 (with jax_enable_x64) for
    # tight golden-number parity on CPU.
    default_float_dtype: jnp.dtype = jnp.float32
    # Default integer dtype (Spark CSV inference yields IntegerType → int32).
    default_int_dtype: jnp.dtype = jnp.int32
    # Rows shown by Frame.show() when no argument is given (Spark default: 20).
    default_show_rows: int = 20
    # Pallas fast-path selection for the hot ops (ops/pallas_kernels.py):
    # the single-device Gramian in solvers.augmented_gram and the fused DQ
    # chain entry point ops/rules.py:dq_rules_fused. "off" = plain XLA
    # (default; XLA fuses these well), "on" = compiled Pallas kernels,
    # "auto" = Pallas when the backend is TPU, "interpret" = Pallas
    # interpreter (CPU tests/CI of the kernel code). shard_map/vmap traces
    # always use XLA (see pallas_kernels.dispatch_to_pallas).
    pallas: str = "off"


config = _Config()


def float_dtype() -> jnp.dtype:
    return config.default_float_dtype


def int_dtype() -> jnp.dtype:
    return config.default_int_dtype
