"""dqlint — static invariant analyzers for the engine's standing contracts.

Every PR since the seed has re-enforced the same invariants by hand:
counted host syncs, ``collective_guard`` on every mesh-bearing jit
factory, session-scoped ``spark.*`` conf save/restore, the disabled-mode
observability no-op contract, and consistent lock orderings across the
threaded layers. This package promotes them from reviewer memory to
tier-1 tooling ("Memory Safe Computations with XLA", arxiv 2206.14148:
engine invariants belong in statically checked, first-class constraints).

Architecture (``core.py``):

* one AST parse per file (``SourceFile``), shared by every rule;
* a rule registry (``rules/``) — each rule is a class with a ``visit``
  (per-file) and optional ``finalize`` (whole-tree) pass;
* ``# dqlint: ok(<rule>): reason`` line pragmas and
  ``# dqlint: ok-file(<rule>): reason`` module pragmas for reasoned
  exemptions;
* a JSON baseline for grandfathered findings (fingerprint = stripped
  source line, so unrelated line drift never invalidates it);
* structured findings (rule, path, line, message) with text and JSON
  renderings.

Entry points: ``scripts/check_static.py`` (the tier-1 gate, all rules),
plus the legacy ``scripts/check_logger_ns.py`` / ``check_segments_np.py``
CLIs which now delegate to the framework's ports of those lints.
"""

from .core import (Baseline, Finding, SourceFile, load_tree, run_rules)
from .rules import ALL_RULES, get_rules

__all__ = ["Baseline", "Finding", "SourceFile", "load_tree", "run_rules",
           "ALL_RULES", "get_rules"]
