"""Rule ``host-sync`` — device→host transfers only inside counted
wrappers.

The engine's standing constraint (ROADMAP): every device path pins its
``frame.host_sync`` count, so an uncounted transfer is invisible to
EXPLAIN ANALYZE, to the span layer's per-op sync deltas, and to the
pinning tests — it silently re-introduces the host round-trips the
engine was built to remove. Until now each sync site was pinned by a
hand-written test; this rule closes the class.

Flagged site kinds (in the device-touching layers ``frame/``, ``ops/``,
``models/``, ``sql/``, ``parallel/``, ``serve/``):

* ``jax.device_get(...)`` — the canonical batched pull;
* ``.item()`` / ``.tolist()`` on receivers not statically known to be
  host data (see below);
* ``float(...)`` / ``int(...)`` / ``bool(...)`` wrapping a ``jnp.*``
  computation — a scalar pull;
* ``np.asarray/np.array(...)`` of a ``jnp.*`` expression or of frame
  device state (``._data`` / ``._mask``) — a whole-array pull;
* ``jax.pure_callback`` / ``jax.experimental.io_callback`` /
  ``jax.debug.print``/``debug_callback`` call sites — sync-bearing: a
  host round-trip EVERY execution of the jitted body they are staged
  into (the jaxpr-level ``audit-sync`` detector in ``analysis/program``
  is the ground truth for these; this source rule catches them before
  the program is ever cached).

A site is sanctioned when its enclosing function is a **counted
wrapper** — it increments ``frame.host_sync`` itself or delegates to one
(``collect`` / ``to_pydict`` / ``_host_pair`` / ``_host_mask``) — or
when it carries a reasoned ``# dqlint: ok(host-sync): ...`` pragma.

Host-data tracking (to keep numpy post-processing quiet): a receiver is
known-host when its expression is rooted at ``np.`` / ``numpy.``, at a
``jax.device_get`` result, or at a name assigned from such an expression
in the same function (flow-insensitive single-assignment tracking).
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Finding, Rule, SourceFile, attr_chain, call_name

_SCOPE_DIRS = ("frame/", "ops/", "models/", "sql/", "parallel/", "serve/")
_PKG = "sparkdq4ml_tpu/"

#: Functions whose call makes the *caller* a counted wrapper: each counts
#: its one batched transfer internally.
_COUNTED_CALLS = frozenset({"collect", "to_pydict", "_host_pair",
                            "_host_mask", "host_fetch", "toPandas",
                            "to_pandas"})
_NP_ROOTS = ("np", "numpy")
_JNP_ROOTS = ("jnp",)

#: Callback-staging calls: sync-bearing at every execution of the jitted
#: body. ``debug_print`` covers ``jax.debug.print`` via the attr-chain
#: check below (bare ``print`` must not match).
_CALLBACK_CALLS = frozenset({"pure_callback", "io_callback",
                             "debug_callback"})
#: Dotted suffixes that make a ``print`` call the jax.debug one.
_DEBUG_PRINT_CHAINS = ("jax.debug.print", "debug.print")


def _in_scope(rel: str) -> bool:
    return rel.startswith(_PKG) and any(
        rel[len(_PKG):].startswith(d) for d in _SCOPE_DIRS)


def _root_name(node: ast.AST) -> Optional[str]:
    """Leftmost name of an attribute/call/subscript chain."""
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


def _contains_jnp_call(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _root_name(n.func) in _JNP_ROOTS:
            return True
    return False


def _contains_device_state(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in ("_data", "_mask"):
            return True
    return False


def _is_increment(node: ast.Call) -> bool:
    return (call_name(node) == "increment" and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == "frame.host_sync")


class _FnInfo:
    """Per-function facts: counted-wrapper status and host-rooted names."""

    def __init__(self, fn: ast.AST, nodes: list,
                 module_aliases: frozenset = frozenset()):
        self.counted = False
        self.host_names: set[str] = set()
        self._module_aliases = module_aliases
        for n in nodes:
            if isinstance(n, ast.Call):
                if _is_increment(n) or self._counted_call(n):
                    self.counted = True
        # parameters annotated as host numpy are host data by signature
        args_obj = getattr(fn, "args", None)
        if args_obj is not None:
            for a in (args_obj.posonlyargs + args_obj.args
                      + args_obj.kwonlyargs):
                ann = a.annotation
                if ann is not None and _root_name(ann) in _NP_ROOTS:
                    self.host_names.add(a.arg)
        # flow-insensitive: iterate assignments until the host-rooted name
        # set stops growing (handles a = np.x(...); b = a[0])
        grew = True
        while grew:
            grew = False
            for n in nodes:
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name):
                    name = n.targets[0].id
                    if name not in self.host_names \
                            and self.is_host(n.value):
                        self.host_names.add(name)
                        grew = True

    def _counted_call(self, n: ast.Call) -> bool:
        """A delegation to a counted wrapper — with the receiver
        qualified so e.g. ``gc.collect()`` (a call on an imported
        MODULE, not a Frame) can never sanction unrelated syncs."""
        if call_name(n) not in _COUNTED_CALLS:
            return False
        f = n.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in self._module_aliases:
            return False
        return True

    def is_host(self, node: ast.AST) -> bool:
        """Expression statically known to produce HOST data."""
        if isinstance(node, ast.Call):
            nm = call_name(node)
            if nm == "device_get" or self._counted_call(node):
                return True
            root = _root_name(node.func)
            if root in _NP_ROOTS:
                return True
            # method chain on a host expression (arr.ravel(), a.astype())
            if isinstance(node.func, ast.Attribute):
                return self.is_host(node.func.value)
            return False
        if isinstance(node, ast.Attribute):
            return self.is_host(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_host(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_host(node.left) or self.is_host(node.right)
        if isinstance(node, ast.Name):
            return node.id in self.host_names or node.id in _NP_ROOTS
        return False


class HostSyncRule(Rule):
    name = "host-sync"
    description = ("device->host transfers (device_get/.item()/.tolist()/"
                   "float(jnp...)/np.asarray(jnp...)) only inside counted"
                   " wrappers that increment frame.host_sync")

    def visit(self, src: SourceFile):
        if not _in_scope(src.rel):
            return ()
        out: list[Finding] = []
        # names bound by plain `import X [as Y]` — the receiver
        # qualification for counted-wrapper calls
        module_aliases = frozenset(
            (a.asname or a.name.split(".")[0])
            for n in ast.walk(src.tree) if isinstance(n, ast.Import)
            for a in n.names)

        def emit(node, what):
            f = src.finding(
                self.name, node,
                f"{what} is a device->host transfer outside a counted"
                " wrapper — increment('frame.host_sync') in this function"
                " (or route through collect()/to_pydict()/_host_pair),"
                " or carry a reasoned '# dqlint: ok(host-sync): ...'"
                " pragma if the data is host-resident by construction")
            if f:
                out.append(f)

        def scan_function(fn: ast.AST, stack_counted: bool):
            # counted status considers the whole subtree (an increment in
            # a nested helper sanctions the factory around it — lenient
            # by design: the wrapper boundary is the outermost function);
            # host-name tracking and the site scan stay per-body
            nested = []

            def body_nodes(node):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        nested.append(child)
                        continue
                    yield child
                    yield from body_nodes(child)

            body = list(body_nodes(fn))
            is_func = isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            subtree = list(ast.walk(fn)) if is_func else body
            info = _FnInfo(fn, subtree, module_aliases)
            if is_func and (stack_counted or info.counted):
                return   # counted wrapper: entire subtree sanctioned
            # module level has no wrapper by definition — every site is a
            # finding; its nested functions are still scanned below
            emit_here = is_func or not info.counted
            info = _FnInfo(fn, body, module_aliases)
            for node in body if emit_here else ():
                if not isinstance(node, ast.Call):
                    continue
                nm = call_name(node)
                if nm == "device_get":
                    emit(node, "jax.device_get(...)")
                elif nm in ("item", "tolist") and not node.args:
                    recv = node.func.value \
                        if isinstance(node.func, ast.Attribute) else None
                    if recv is not None and not info.is_host(recv):
                        emit(node, f".{nm}()")
                elif nm in ("float", "int", "bool") \
                        and isinstance(node.func, ast.Name) \
                        and len(node.args) == 1 \
                        and _contains_jnp_call(node.args[0]):
                    emit(node, f"{nm}(<jnp expression>)")
                elif nm in ("asarray", "array") \
                        and _root_name(node.func) in _NP_ROOTS \
                        and node.args \
                        and (_contains_jnp_call(node.args[0])
                             or _contains_device_state(node.args[0])):
                    emit(node, f"np.{nm}(<device expression>)")
                elif nm in _CALLBACK_CALLS:
                    emit(node, f"{nm}(...) (host callback staged into a"
                               " jitted body)")
                elif nm == "print":
                    chain = attr_chain(node.func) \
                        if isinstance(node.func, ast.Attribute) else None
                    if chain and (chain in _DEBUG_PRINT_CHAINS
                                  or chain.endswith(".debug.print")):
                        emit(node, "jax.debug.print(...) (host callback"
                                   " staged into a jitted body)")
            for sub in nested:
                scan_function(sub, False)

        # one pass from the module node: scans module-level statements
        # (import-time transfers are uncounted by definition) and recurses
        # into every function/method it collects along the way
        scan_function(src.tree, False)
        return out
