"""Rule ``numpy-free`` — ``ops/segments.py`` stays numpy-free outside its
marked host-fallback region (framework port of the PR-4
``scripts/check_segments_np.py`` lint; that script now delegates here).

Why: the module's whole point is that grouped execution never leaves the
device between frame input and the single group-count sync. A stray
``np.asarray`` in the compute path silently reintroduces the host
round-trip — and nothing else would catch it, because results stay
correct.

Rules: any ``np.<attr>`` / ``numpy.<attr>`` access and any ``import
numpy`` is only allowed between the literal ``# --- BEGIN HOST
FALLBACK`` / ``# --- END HOST FALLBACK`` markers; ``from numpy import
x`` is flagged outright everywhere.
"""

from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile

BEGIN = "# --- BEGIN HOST FALLBACK"
END = "# --- END HOST FALLBACK"
_NP_NAMES = ("np", "numpy")
TARGET = "sparkdq4ml_tpu/ops/segments.py"


def _fallback_lines(text: str) -> set[int]:
    allowed: set[int] = set()
    inside = False
    for i, line in enumerate(text.splitlines(), start=1):
        if line.strip().startswith(BEGIN):
            inside = True
        if inside:
            allowed.add(i)
        if line.strip().startswith(END):
            inside = False
    return allowed


class NumpyFreeRule(Rule):
    name = "numpy-free"
    description = ("ops/segments.py must not touch numpy outside its "
                   "marked host-fallback region (device path stays "
                   "device-resident)")

    def visit(self, src: SourceFile):
        if src.rel != TARGET:
            return ()
        allowed = _fallback_lines(src.text)
        out: list[Finding] = []

        def emit(node, msg):
            f = src.finding(self.name, node, msg)
            if f:
                out.append(f)

        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom) and node.module in _NP_NAMES:
                emit(node, "'from numpy import ...' hides uses from this"
                     " lint; use 'import numpy as np' inside the"
                     " host-fallback region")
            elif isinstance(node, ast.Import) and any(
                    a.name in _NP_NAMES for a in node.names):
                if node.lineno not in allowed:
                    emit(node, "numpy imported outside the host-fallback"
                         " region")
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in _NP_NAMES:
                if node.lineno not in allowed:
                    emit(node, f"np.{node.attr} outside the host-fallback"
                         " region (device path must stay device-resident;"
                         " move host work between the"
                         f" '{BEGIN}' / '{END}' markers)")
        return out
