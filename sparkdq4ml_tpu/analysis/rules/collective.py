"""Rule ``collective-guard`` — every mesh-bearing jit factory routes its
dispatch through the process-wide collective guard.

The invariant this closes statically: XLA:CPU's intra-process
collectives rendezvous participant threads per (device set, op); two
overlapping executions of psum-bearing programs interleave their
participants and BOTH hang forever (the PR-6 serving deadlock, fixed
then by enumerating every factory by hand). Any function that builds a
sharded program (``parallel.mesh.shard_map``) or emits a collective
(``lax.psum`` / ``psum_scatter`` / ``all_gather``) must also route its
dispatch through ``serialize_collectives`` or ``collective_guard`` —
otherwise a future concurrent caller re-creates the deadlock class.

Attribution scope is the **outermost enclosing function**: factories
routinely build the sharded body in a nested helper and wrap the jitted
program at their tail, which is exactly the sanctioned pattern. A
factory that intentionally returns an unwrapped program for its caller
to guard documents that with ``# dqlint: ok(collective-guard): reason``.
``parallel/mesh.py`` itself (which defines the guard machinery) is
exempt.
"""

from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile, call_name, walk_functions

#: Collective-emitting call names (rightmost attr): building one of these
#: into a program makes the program mesh-bearing.
_COLLECTIVE_CALLS = frozenset(
    {"psum", "psum_scatter", "all_gather", "all_to_all", "pmean", "ppermute"})
#: Sanctioning call names: routing dispatch through either satisfies the
#: invariant (``serialize_collectives`` wraps jitted callables; a
#: ``collective_guard`` context manages the dispatch inline).
_GUARDS = frozenset({"serialize_collectives", "collective_guard"})

_EXEMPT = ("sparkdq4ml_tpu/parallel/mesh.py",)


class CollectiveGuardRule(Rule):
    name = "collective-guard"
    description = ("functions that build shard_map/psum programs must "
                   "route dispatch through serialize_collectives / "
                   "collective_guard (XLA:CPU overlapping-collective "
                   "deadlock class)")

    def visit(self, src: SourceFile):
        if src.rel in _EXEMPT:
            return ()
        out: list[Finding] = []
        for fn, nodes in walk_functions(src.tree):
            collectives: list[tuple[ast.AST, str]] = []
            builds_program = False
            jits = False
            guarded = False
            for node in nodes:
                if isinstance(node, ast.Call):
                    nm = call_name(node)
                    if nm in ("shard_map", "pmap"):
                        builds_program = True
                        collectives.append((node, nm))
                    elif nm == "jit":
                        jits = True
                    elif nm in _COLLECTIVE_CALLS:
                        collectives.append((node, nm))
                    elif nm in _GUARDS:
                        guarded = True
                # `with collective_guard(...)` shows up as a Call inside
                # the withitem, already covered above.
            # A helper that merely EMITS a collective into a function it
            # returns (the `_core` local-objective pattern) is not a
            # dispatch site; the factory that shard_maps / jits it is.
            triggers = collectives if (builds_program or jits) else []
            if triggers and not guarded:
                where = (f"function {fn.name!r}" if fn is not None
                         else "module level")
                for node, nm in triggers:
                    f = src.finding(
                        self.name, node,
                        f"{nm}(...) in {where} builds a mesh-bearing "
                        "program but the function never routes dispatch "
                        "through parallel.mesh.serialize_collectives / "
                        "collective_guard — overlapping executions of "
                        "collective programs deadlock XLA:CPU; wrap the "
                        "jitted program (or guard the dispatch) before "
                        "returning it")
                    if f:
                        out.append(f)
        return out
