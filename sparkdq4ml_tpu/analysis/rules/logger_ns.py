"""Rule ``logger-ns`` — every ``logging.getLogger`` stays in the
``sparkdq4ml_tpu.`` namespace (framework port of the PR-2
``scripts/check_logger_ns.py`` lint; that script now delegates here).

Why: ``utils.logging.configure_logging`` tiers log levels by namespace
(framework at DEBUG, root at INFO, jax at WARNING) — a logger created
outside ``sparkdq4ml_tpu.*`` silently escapes that tiering and the "one
namespace to scrape" observability story breaks one module at a time.

Allowed spellings: a string literal starting with ``sparkdq4ml_tpu``,
``__name__``, or a call carrying the legacy ``# logger-ns: ok`` pragma
(still honored) or a ``# dqlint: ok(logger-ns)`` pragma.
``from logging import getLogger`` is flagged outright — a bare-name
alias would hide later calls from the lint.
"""

from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile

LEGACY_PRAGMA = "logger-ns: ok"


def _is_getlogger_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "getLogger"
            and isinstance(f.value, ast.Name) and f.value.id == "logging")


def _arg_ok(node: ast.Call) -> tuple[bool, str]:
    if not node.args:
        return False, "<root>"
    a = node.args[0]
    if isinstance(a, ast.Name) and a.id == "__name__":
        return True, "__name__"
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        ok = (a.value == "sparkdq4ml_tpu"
              or a.value.startswith("sparkdq4ml_tpu."))
        return ok, repr(a.value)
    return False, ast.dump(a)


class LoggerNamespaceRule(Rule):
    name = "logger-ns"
    description = ("logging.getLogger must stay in the sparkdq4ml_tpu "
                   "namespace (or __name__); bare-name getLogger imports "
                   "are flagged outright")

    def _legacy_pragma(self, src: SourceFile, node: ast.AST) -> bool:
        end = getattr(node, "end_lineno", node.lineno) or node.lineno
        return any(LEGACY_PRAGMA in src.lines[i - 1]
                   for i in range(node.lineno,
                                  min(end, len(src.lines)) + 1))

    def visit(self, src: SourceFile):
        out: list[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "logging" \
                    and any(a.name == "getLogger" for a in node.names):
                f = src.finding(
                    self.name, node,
                    "'from logging import getLogger' hides calls from this"
                    " lint; use 'import logging' + logging.getLogger(...)")
                if f:
                    out.append(f)
            elif isinstance(node, ast.Call) and _is_getlogger_call(node):
                if self._legacy_pragma(src, node):
                    continue
                ok, arg = _arg_ok(node)
                if not ok:
                    f = src.finding(
                        self.name, node,
                        f"logging.getLogger({arg}) is outside the"
                        " sparkdq4ml_tpu namespace (use"
                        " 'sparkdq4ml_tpu.<module>', __name__, or a"
                        f" '# {LEGACY_PRAGMA}' pragma)")
                    if f:
                        out.append(f)
        return out
