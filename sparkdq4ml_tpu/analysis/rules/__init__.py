"""dqlint rule registry — one module per invariant.

Adding a rule: subclass :class:`..core.Rule`, implement ``visit`` (and
``finalize`` for cross-file state), list it here. ``scripts/
check_static.py --list-rules`` renders this catalog.
"""

from __future__ import annotations

from .collective import CollectiveGuardRule
from .conf_keys import ConfKeyRule
from .fault_sites import FaultSiteRule
from .host_sync import HostSyncRule
from .locks import LockOrderRule
from .logger_ns import LoggerNamespaceRule
from .metric_names import MetricNameRule
from .noop import NoopContractRule
from .numpy_free import NumpyFreeRule
from .program_handles import ProgramHandleRule

#: Instantiation order = report order; every rule runs in the tier-1 gate.
ALL_RULES = (
    HostSyncRule,
    CollectiveGuardRule,
    ConfKeyRule,
    NoopContractRule,
    LockOrderRule,
    FaultSiteRule,
    MetricNameRule,
    ProgramHandleRule,
    LoggerNamespaceRule,
    NumpyFreeRule,
)


def get_rules(names=None):
    """Instantiate the requested rules (all by default)."""
    classes = ALL_RULES
    if names:
        wanted = set(names)
        classes = [c for c in ALL_RULES if c.name in wanted]
        unknown = wanted - {c.name for c in classes}
        if unknown:
            known = ", ".join(c.name for c in ALL_RULES)
            raise ValueError(
                f"unknown rule(s) {sorted(unknown)}; known: {known}")
    return [c() for c in classes]
