"""Rule ``metric-name`` — every metric literal resolves to the registry.

Counters, gauges, and histograms are matched BY NAME at runtime: a
typo'd ``counters.increment("pipleine.hit")`` compiles, runs, and
silently creates a ghost series no dashboard, bench gate, or test ever
reads — while the real series quietly stops moving. The registry is
``utils/observability.py::METRIC_NAMES`` (name → (type, help)) plus
``METRIC_NAME_PREFIXES`` for the dynamic per-site/per-tenant families
(``recovery.<action>``, ``serve.e2e_ms.<tenant>``, …) — both pure
literals, parsed statically like the conf-key registry parses
``config.CONF_KEYS``.

Checks, receiver-qualified (an unrelated object's ``observe`` method
cannot trip the rule):

1. **Literal name**: every ``counters.increment(name)`` (receiver chain
   ending in ``counters``) and every ``METRICS.set_gauge/observe/
   histogram(name)`` (receiver chain ending in ``METRICS``) must pass a
   string literal, an f-string whose literal head starts with a declared
   prefix family, or a conditional whose arms are both literal — a fully
   computed name cannot be statically checked.
2. **Registered name**: a plain literal must be a ``METRIC_NAMES`` key
   or start with a ``METRIC_NAME_PREFIXES`` family prefix.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Finding, Rule, SourceFile, attr_chain

_OBS_REL = "sparkdq4ml_tpu/utils/observability.py"

#: hook method name → receiver-chain tail that qualifies it
_HOOKS = {
    "increment": ("counters",),
    "set_gauge": ("METRICS",),
    "observe": ("METRICS",),
    "histogram": ("METRICS",),
}


def _literal_head(node: ast.JoinedStr) -> Optional[str]:
    if node.values and isinstance(node.values[0], ast.Constant) \
            and isinstance(node.values[0].value, str):
        return node.values[0].value
    return None


class MetricNameRule(Rule):
    name = "metric-name"
    description = ("counters.increment / METRICS.set_gauge/observe/"
                   "histogram literal names must be registered in"
                   " observability.METRIC_NAMES (or a declared prefix"
                   " family) — a typo'd name creates a ghost series")

    def __init__(self):
        # (src, call_node, hook, name_node)
        self._usages: list = []
        self._obs_src: Optional[SourceFile] = None

    # -- per-file collection ------------------------------------------------
    def visit(self, src: SourceFile):
        if src.rel == _OBS_REL:
            self._obs_src = src
            # the registry file still CONTAINS call sites (span_ms
            # histograms, trace.dropped_spans) — fall through and check
            # them like any other module
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and f.attr in _HOOKS):
                continue
            chain = attr_chain(f.value)
            if chain is None:
                continue
            tail = chain.split(".")[-1]
            if tail not in _HOOKS[f.attr]:
                continue
            kwargs = {k.arg: k.value for k in node.keywords if k.arg}
            name = node.args[0] if node.args else kwargs.get("name")
            if name is None:
                continue
            self._usages.append((src, node, f.attr, name))
        return ()

    # -- registry parse -----------------------------------------------------
    @staticmethod
    def _parse_registry(src: SourceFile):
        names: dict = {}
        prefixes: dict = {}
        for node in src.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                    or not isinstance(node.targets[0], ast.Name):
                continue
            target = node.targets[0].id
            if target not in ("METRIC_NAMES", "METRIC_NAME_PREFIXES"):
                continue
            try:
                value = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                continue
            if target == "METRIC_NAMES" and isinstance(value, dict):
                names = value
            elif target == "METRIC_NAME_PREFIXES" \
                    and isinstance(value, dict):
                prefixes = value
        return names, prefixes

    # -- cross-file check ---------------------------------------------------
    def finalize(self, files):
        out: list[Finding] = []
        if self._obs_src is None:
            return out   # partial trees in tests: nothing to check against
        names, prefixes = self._parse_registry(self._obs_src)
        if not names:
            out.append(Finding(
                rule=self.name, path=self._obs_src.rel, line=0,
                message="utils/observability.py declares no METRIC_NAMES"
                        " literal registry — every metric name must be"
                        " declared there"))
            return out

        def literal_values(node) -> Optional[list]:
            """Fully-literal name candidates of a name argument: a
            constant, or a conditional whose arms both resolve. None =
            not statically checkable."""
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                return [node.value]
            if isinstance(node, ast.IfExp):
                a = literal_values(node.body)
                b = literal_values(node.orelse)
                if a is not None and b is not None:
                    return a + b
            return None

        for src, call, hook, name_node in self._usages:
            if isinstance(name_node, ast.JoinedStr):
                head = _literal_head(name_node)
                if head and any(head.startswith(p) or p.startswith(head)
                                for p in prefixes):
                    continue
                f = src.finding(
                    self.name, call,
                    f"dynamic metric name in {hook}(...) must start with"
                    " a family prefix declared in"
                    " observability.METRIC_NAME_PREFIXES — an undeclared"
                    " family is unscrapable cardinality with no help"
                    " text")
                if f:
                    out.append(f)
                continue
            values = literal_values(name_node)
            if values is None:
                f = src.finding(
                    self.name, call,
                    f"metric name in {hook}(...) must be a string"
                    " LITERAL (or an f-string with a declared family"
                    " head) — a computed name cannot be statically"
                    " checked and a typo creates a ghost series")
                if f:
                    out.append(f)
                continue
            for value in values:
                if value in names or any(value.startswith(p)
                                         for p in prefixes):
                    continue
                f = src.finding(
                    self.name, call,
                    f"metric name {value!r} is not registered in"
                    " observability.METRIC_NAMES (nor covered by a"
                    " METRIC_NAME_PREFIXES family) — register it with"
                    " its type/help or fix the typo")
                if f:
                    out.append(f)
        return out
