"""Rule ``noop`` — disabled-mode observability must stay allocation-free.

The PR-2 contract (dynamically asserted by test_observability, statically
pinned here): when tracing is off, a span site costs one flag read and
returns the shared ``_NOOP`` singleton — **no Span allocation, no string
formatting**. The subtle leak is at call sites: arguments to
``span(...)`` / ``TRACER.span(...)`` / ``current_span().set(...)``
evaluate *before* the enabled check inside the callee, so an f-string or
``.format`` in the argument list allocates on every disabled-mode call.

Flagged, in any engine file (``utils/observability.py`` itself is
exempt — it owns the gate):

* a span-sink call (``span`` / ``fit_span`` / ``begin`` / ``.set`` on a
  span) whose argument contains eager string formatting (f-string with a
  hole, ``%`` / ``+`` on a string literal, ``.format(...)``, or
  ``", ".join(...)``), unless the call is statically guarded by an
  enclosing ``if ... enabled ...`` branch (or a preceding
  ``if not ... enabled ...: return`` early-out);
* direct ``Span(...)`` construction outside the tracer.
"""

from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile

_EXEMPT = ("sparkdq4ml_tpu/utils/observability.py",)

#: Call names that hand their arguments to the span layer.
_SINK_NAMES = frozenset({"span", "fit_span", "begin"})


def _mentions_enabled(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == "enabled":
            return True
        if isinstance(n, ast.Name) and n.id == "enabled":
            return True
    return False


def _formats_string(node: ast.AST) -> bool:
    """Does evaluating this expression allocate a formatted string?"""
    for n in ast.walk(node):
        if isinstance(n, ast.JoinedStr) and any(
                isinstance(v, ast.FormattedValue) for v in n.values):
            return True
        if isinstance(n, ast.BinOp) and isinstance(n.op, (ast.Mod, ast.Add)):
            for side in (n.left, n.right):
                if isinstance(side, ast.Constant) \
                        and isinstance(side.value, str):
                    return True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in ("format", "join"):
            recv = n.func.value
            if n.func.attr == "format" or (
                    isinstance(recv, ast.Constant)
                    and isinstance(recv.value, str)):
                return True
    return False


class NoopContractRule(Rule):
    name = "noop"
    description = ("span-site arguments must not format strings (they "
                   "evaluate before the enabled gate) and Span objects "
                   "are only allocated by the tracer — the disabled-mode "
                   "near-zero no-op contract")

    def visit(self, src: SourceFile):
        if src.rel in _EXEMPT:
            return ()
        out: list[Finding] = []

        def is_sink(call: ast.Call, span_vars: set) -> str:
            f = call.func
            if isinstance(f, ast.Name) and f.id in _SINK_NAMES:
                return f.id
            if isinstance(f, ast.Attribute):
                if f.attr in _SINK_NAMES:
                    return f.attr
                if f.attr == "set":
                    recv = f.value
                    if isinstance(recv, ast.Call):
                        rf = recv.func
                        rname = rf.attr if isinstance(rf, ast.Attribute) \
                            else getattr(rf, "id", "")
                        if rname == "current_span":
                            return "current_span().set"
                    if isinstance(recv, ast.Name) and recv.id in span_vars:
                        return f"{recv.id}.set"
            return ""

        def scan(stmts, guarded, span_vars):
            """Walk a statement list tracking (a) enabled-guarded regions
            and (b) names bound to spans by ``with span(...) as s``."""
            for stmt in stmts:
                g = guarded
                if isinstance(stmt, ast.If):
                    test = stmt.test
                    body_guarded = g or _mentions_enabled(test)
                    scan(stmt.body, body_guarded, span_vars)
                    scan(stmt.orelse, g, span_vars)
                    # early-out: `if not ...enabled...: return` guards the
                    # rest of the suite
                    if (isinstance(test, ast.UnaryOp)
                            and isinstance(test.op, ast.Not)
                            and _mentions_enabled(test.operand)
                            and stmt.body
                            and isinstance(stmt.body[-1],
                                           (ast.Return, ast.Raise))
                            and not stmt.orelse):
                        guarded = True
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    vars_here = set(span_vars)
                    for item in stmt.items:
                        ce = item.context_expr
                        if isinstance(ce, ast.Call) \
                                and is_sink(ce, span_vars) \
                                and isinstance(item.optional_vars, ast.Name):
                            vars_here.add(item.optional_vars.id)
                        check_exprs(ce, g, span_vars)
                    scan(stmt.body, g, vars_here)
                    continue
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scan(stmt.body, False, set())
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                    header = stmt.iter if isinstance(
                        stmt, (ast.For, ast.AsyncFor)) else stmt.test
                    check_exprs(header, g, span_vars)
                    scan(stmt.body, g, span_vars)
                    scan(stmt.orelse, g, span_vars)
                    continue
                if isinstance(stmt, ast.Try):
                    scan(stmt.body, g, span_vars)
                    for h in stmt.handlers:
                        scan(h.body, g, span_vars)
                    scan(stmt.orelse, g, span_vars)
                    scan(stmt.finalbody, g, span_vars)
                    continue
                if isinstance(stmt, ast.ClassDef):
                    scan(stmt.body, False, set())
                    continue
                check_exprs(stmt, g, span_vars)

        def check_exprs(node, guarded, span_vars):
            for n in ast.walk(node):
                if not isinstance(n, ast.Call):
                    continue
                sink = is_sink(n, span_vars)
                if sink and not guarded:
                    for arg in list(n.args) + [k.value for k in n.keywords]:
                        if _formats_string(arg):
                            f = src.finding(
                                self.name, n,
                                f"argument of {sink}(...) formats a string"
                                " eagerly — it evaluates even when tracing"
                                " is disabled, breaking the near-zero"
                                " no-op contract; guard the call with"
                                " `if ...enabled` or pass raw values")
                            if f:
                                out.append(f)
                            break
                fn = n.func
                if isinstance(fn, ast.Name) and fn.id == "Span":
                    f = src.finding(
                        self.name, n,
                        "direct Span(...) allocation outside the tracer —"
                        " spans must come from TRACER.span()/begin() so"
                        " the disabled path allocates nothing")
                    if f:
                        out.append(f)

        scan(src.tree.body, False, set())
        return out
