"""Rule ``lock-order`` — a static lock-acquisition graph over the
threaded layers, flagging inconsistent orderings and unguarded acquires.

The threaded surface has grown every PR: the serve scheduler condition,
the metrics series lock, the compiler flush/cache locks, the ingest
buffer pool, the process-wide collective lock. Each pair of locks taken
in both orders on different code paths is a latent deadlock that no unit
test reliably reproduces — the classic "works until the serving load
finds the interleave" bug. This rule builds the acquisition graph
statically and fails on cycles while the orderings are still fresh.

Model (intra-procedural with one level of same-module call propagation):

* lock objects: module-level ``NAME = threading.Lock/RLock/Condition()``
  and ``self.attr = threading.…`` instance locks, identified as
  ``module::NAME`` / ``module::Class.attr``;
* acquisition: ``with <lock>:`` items (including multi-item ``with``)
  and bare ``<lock>.acquire()`` calls;
* edge A→B: B acquired while A is held — directly nested ``with``, or a
  call made under A to a same-module function/method that acquires B at
  its top level;
* findings: every edge pair {A→B, B→A} (an ordering inversion =
  potential deadlock), plus bare ``.acquire()`` calls outside a
  ``try/finally`` release discipline. Reentrant self-edges are ignored
  (RLock is the documented pattern for them).
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Finding, Rule, SourceFile, attr_chain, call_name

_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"})


def _is_lock_ctor(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _LOCK_CTORS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in ("threading", "_threading"))


class _FileFacts:
    def __init__(self, src: SourceFile):
        self.src = src
        self.module = src.rel.rsplit("/", 1)[-1][:-3]   # stem
        self.module_locks: set[str] = set()             # bare names
        self.class_locks: dict[str, set[str]] = {}      # Class -> attrs
        # function qualname -> list[(lock_id, node)] acquired directly
        self.fn_acquires: dict[str, list] = {}
        # edges: (held_id, acquired_id, node)
        self.edges: list[tuple[str, str, ast.AST]] = []
        # calls made while holding a lock: (held_id, callee_name, node)
        self.held_calls: list[tuple[str, str, ast.AST]] = []
        # bare .acquire() sites outside try/finally: (node, lock_id)
        self.bare_acquires: list[tuple[ast.AST, str]] = []


class LockOrderRule(Rule):
    name = "lock-order"
    description = ("static lock-acquisition graph over the threaded "
                   "layers; inconsistent lock orderings (A->B and B->A) "
                   "and unguarded .acquire() calls are flagged")

    def __init__(self):
        self._facts: list[_FileFacts] = []

    # -- collection ---------------------------------------------------------
    def visit(self, src: SourceFile):
        facts = _FileFacts(src)
        tree = src.tree
        # 1) lock definitions
        for node in tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        facts.module_locks.add(t.id)
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) \
                        and _is_lock_ctor(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            facts.class_locks.setdefault(
                                cls.name, set()).add(t.attr)

        # 2) per-function acquisition scan
        def resolve(expr, cls_name: Optional[str]) -> Optional[str]:
            """Lock identity of a with/acquire target, or None."""
            if isinstance(expr, ast.Name) \
                    and expr.id in facts.module_locks:
                return f"{facts.module}::{expr.id}"
            if isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name):
                if expr.value.id == "self" and cls_name \
                        and expr.attr in facts.class_locks.get(cls_name,
                                                               ()):
                    return f"{facts.module}::{cls_name}.{expr.attr}"
                # mod._LOCK style cross-module reference: resolved in
                # finalize (by module stem), record symbolically
                chain = attr_chain(expr)
                if chain and ("LOCK" in expr.attr.upper()
                              or "COND" in expr.attr.upper()):
                    return f"?{chain}"
            return None

        def scan_fn(fn, qualname: str, cls_name: Optional[str]):
            acquires: list = []

            def walk(stmts, held: tuple):
                # explicit acquire()/release() within this suite extend /
                # shrink the held set for the statements that follow, so
                # ordering edges through acquire-style locking (the
                # Condition idiom) are seen too
                held_extra: list = []
                for stmt in stmts:
                    held_now = held + tuple(held_extra)
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        continue   # nested defs scanned separately
                    if isinstance(stmt, (ast.With, ast.AsyncWith)):
                        here = list(held_now)
                        for item in stmt.items:
                            lid = resolve(item.context_expr, cls_name)
                            if lid:
                                for h in here:
                                    if h != lid:
                                        facts.edges.append((h, lid, stmt))
                                here.append(lid)
                                acquires.append((lid, stmt))
                        walk(stmt.body, tuple(here))
                        continue
                    # record calls + bare acquires in this statement
                    for n in ast.walk(stmt):
                        if not isinstance(n, ast.Call):
                            continue
                        if call_name(n) in ("acquire", "release"):
                            recv = n.func.value if isinstance(
                                n.func, ast.Attribute) else None
                            lid = resolve(recv, cls_name) if recv is not \
                                None else None
                            if lid is None:
                                pass
                            elif call_name(n) == "release":
                                if lid in held_extra:
                                    held_extra.remove(lid)
                            else:
                                acquires.append((lid, n))
                                for h in held_now:
                                    if h != lid:
                                        facts.edges.append((h, lid, n))
                                held_extra.append(lid)
                                if not _in_try_with_release(stmt, stmts):
                                    facts.bare_acquires.append((n, lid))
                        elif held_now:
                            # qualify the callee so dict.clear() on some
                            # attribute can never alias a lock-taking
                            # method of another class: propagate only
                            # self.m() (same class) and bare f() (same
                            # module) calls
                            f = n.func
                            callee = None
                            if isinstance(f, ast.Name):
                                callee = f.id
                            elif isinstance(f, ast.Attribute) \
                                    and isinstance(f.value, ast.Name) \
                                    and f.value.id == "self" and cls_name:
                                callee = f"{cls_name}.{f.attr}"
                            if callee:
                                for h in held_now:
                                    facts.held_calls.append((h, callee, n))
                    for blocks in _sub_blocks(stmt):
                        walk(blocks, held_now)

            walk(fn.body, ())
            facts.fn_acquires.setdefault(qualname, []).extend(acquires)
            if cls_name:
                # self.m() resolves as Class.m even under nested prefixes
                facts.fn_acquires.setdefault(f"{cls_name}.{fn.name}",
                                             []).extend(acquires)

        def _sub_blocks(stmt):
            for attr in ("body", "orelse", "finalbody"):
                b = getattr(stmt, attr, None)
                if isinstance(b, list) and not isinstance(
                        stmt, (ast.With, ast.AsyncWith)):
                    yield b
            for h in getattr(stmt, "handlers", []) or []:
                yield h.body

        def _in_try_with_release(stmt, stmts) -> bool:
            """acquire() sanctioned when a try/finally in the same suite
            releases, or the acquire is itself inside the try of one."""
            for s in stmts:
                if isinstance(s, ast.Try) and any(
                        isinstance(n, ast.Call)
                        and call_name(n) == "release"
                        for fb in [s.finalbody] for st in fb
                        for n in ast.walk(st)):
                    return True
            return any(isinstance(n, ast.Call)
                       and call_name(n) == "release"
                       for n in ast.walk(stmt))

        def visit_scope(node, cls_name, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit_scope(child, child.name, f"{prefix}{child.name}.")
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    scan_fn(child, f"{prefix}{child.name}", cls_name)
                    visit_scope(child, cls_name, f"{prefix}{child.name}.")

        visit_scope(tree, None, "")
        self._facts.append(facts)
        return ()

    # -- graph assembly -----------------------------------------------------
    def finalize(self, files):
        out: list[Finding] = []
        by_rel = {f.src.rel: f for f in self._facts}
        # resolve symbolic ?mod.NAME references against definitions
        all_locks: dict[str, list[str]] = {}
        for facts in self._facts:
            for name in facts.module_locks:
                all_locks.setdefault(name, []).append(
                    f"{facts.module}::{name}")

        def canon(lid: str) -> Optional[str]:
            if not lid.startswith("?"):
                return lid
            chain = lid[1:]
            base, _, name = chain.rpartition(".")
            cands = all_locks.get(name, [])
            if len(cands) == 1:
                return cands[0]
            stem = base.rsplit(".", 1)[-1].lstrip("_")
            for c in cands:
                if c.split("::")[0] == stem:
                    return c
            return None

        edges: dict[tuple[str, str], tuple[str, int]] = {}
        def add_edge(a, b, src, node):
            a, b = canon(a), canon(b)
            if a and b and a != b and (a, b) not in edges:
                edges[(a, b)] = (src.rel, getattr(node, "lineno", 0))

        for facts in self._facts:
            for a, b, node in facts.edges:
                add_edge(a, b, facts.src, node)
            # one-level call propagation within the module
            for held, callee, node in facts.held_calls:
                for lid, _n in facts.fn_acquires.get(callee, []):
                    add_edge(held, lid, facts.src, node)

        # inversions: both orders present
        seen = set()
        for (a, b), (rel, line) in sorted(edges.items()):
            if (b, a) in edges and frozenset((a, b)) not in seen:
                seen.add(frozenset((a, b)))
                rel2, line2 = edges[(b, a)]
                out.append(Finding(
                    rule=self.name, path=rel, line=line,
                    message=f"lock-order inversion: {a} -> {b} here but"
                            f" {b} -> {a} at {rel2}:{line2} — two threads"
                            " taking these in opposite orders deadlock;"
                            " pick one order (or collapse to one lock)"))

        reported: set[tuple[str, int, str]] = set()
        for facts in self._facts:
            for node, lid in facts.bare_acquires:
                key = (facts.src.rel, getattr(node, "lineno", 0), lid)
                if key in reported:
                    continue
                reported.add(key)
                f = facts.src.finding(
                    self.name, node,
                    f"bare {lid}.acquire() without a try/finally release"
                    " — an exception between acquire and release wedges"
                    " every future acquirer; use `with` or try/finally")
                if f:
                    out.append(f)
        del by_rel
        return out
