"""Rule ``fault-site`` — every chaos hook call names a registered site.

The fault plan (``utils/faults.py``) matches sites by STRING equality at
runtime: a typo'd site in a ``faults.inject("pipleine_flush")`` call
compiles, runs, and silently never fires — the chaos test asserting that
degradation ladder then passes vacuously, which is exactly the class of
rot a robustness gate must not allow. The registry is
``utils/faults.py::FAULT_SITES`` (a pure literal, parsed statically like
the conf-key registry parses ``config.CONF_KEYS``).

Checks:

1. **Literal site**: every call to a faults hook — ``inject`` /
   ``corrupt`` / ``fired`` / ``shrunk_budget`` / ``degrade_mesh`` —
   whose receiver resolves to the faults module (``faults.X`` /
   ``_faults.X``, or a name imported from ``utils.faults``) must pass a
   string LITERAL as the site argument; a computed site cannot be
   checked and is flagged.
2. **Registered site**: the literal must be a key of ``FAULT_SITES``.
3. **Registered kind**: for ``fired(site, kind)``, a literal kind must
   be among the kinds registered for that site — a hook asking for a
   kind the site never schedules is the same silent-never-fires bug.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Finding, Rule, SourceFile, attr_chain

_FAULTS_REL = "sparkdq4ml_tpu/utils/faults.py"

#: hook name → index of the site argument
_HOOKS = {"inject": 0, "corrupt": 0, "fired": 0, "shrunk_budget": 0,
          "degrade_mesh": 0}


class FaultSiteRule(Rule):
    name = "fault-site"
    description = ("faults.inject/corrupt/fired/shrunk_budget/degrade_mesh"
                   " call sites must name a string literal registered in"
                   " faults.FAULT_SITES (typo'd sites silently never fire)")

    def __init__(self):
        # (src, call_node, hook, site_node, kind_node)
        self._usages: list = []
        self._faults_src: Optional[SourceFile] = None

    # -- per-file collection ------------------------------------------------
    def visit(self, src: SourceFile):
        if src.rel == _FAULTS_REL:
            self._faults_src = src
            return ()   # the registry + hook definitions, not usages
        # names imported straight from the faults module (aliased or not)
        local_hooks: dict[str, str] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.split(".")[-1] == "faults":
                for alias in node.names:
                    if alias.name in _HOOKS:
                        local_hooks[alias.asname or alias.name] = alias.name
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            hook = None
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _HOOKS:
                chain = attr_chain(f.value)
                if chain is not None and chain.split(".")[-1] in (
                        "faults", "_faults"):
                    hook = f.attr
            elif isinstance(f, ast.Name) and f.id in local_hooks:
                hook = local_hooks[f.id]
            if hook is None:
                continue
            kwargs = {k.arg: k.value for k in node.keywords if k.arg}
            args = node.args
            site = (args[_HOOKS[hook]] if len(args) > _HOOKS[hook]
                    else kwargs.get("site"))
            kind = None
            if hook == "fired":
                kind = args[1] if len(args) > 1 else kwargs.get("kind")
            self._usages.append((src, node, hook, site, kind))
        return ()

    # -- registry parse -----------------------------------------------------
    @staticmethod
    def _parse_registry(src: SourceFile) -> dict:
        for node in src.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "FAULT_SITES":
                try:
                    value = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return {}
                return value if isinstance(value, dict) else {}
        return {}

    # -- cross-file check ---------------------------------------------------
    def finalize(self, files):
        out: list[Finding] = []
        if self._faults_src is None:
            return out   # partial trees in tests: nothing to check against
        sites = self._parse_registry(self._faults_src)
        if not sites:
            out.append(Finding(
                rule=self.name, path=self._faults_src.rel, line=0,
                message="utils/faults.py declares no FAULT_SITES literal"
                        " registry — every chaos hook site must be"
                        " declared there"))
            return out
        for src, call, hook, site, kind in self._usages:
            if not (isinstance(site, ast.Constant)
                    and isinstance(site.value, str)):
                f = src.finding(
                    self.name, call,
                    f"faults.{hook}(...) site must be a string LITERAL"
                    " registered in faults.FAULT_SITES — a computed site"
                    " cannot be statically checked and a typo would"
                    " silently never fire")
                if f:
                    out.append(f)
                continue
            if site.value not in sites:
                f = src.finding(
                    self.name, call,
                    f"fault site {site.value!r} is not registered in"
                    " faults.FAULT_SITES — register it (with its kinds)"
                    " or fix the typo; an unregistered site silently"
                    " never fires")
                if f:
                    out.append(f)
                continue
            if kind is not None and isinstance(kind, ast.Constant) \
                    and isinstance(kind.value, str) \
                    and kind.value not in tuple(sites[site.value]):
                f = src.finding(
                    self.name, call,
                    f"fault kind {kind.value!r} is not registered for"
                    f" site {site.value!r} in faults.FAULT_SITES"
                    f" (registered: {tuple(sites[site.value])}) — the"
                    " hook would never fire")
                if f:
                    out.append(f)
        return out
