"""Rule ``program-handle`` — no silently unprofilable program caches.

The program auditor (dqaudit) and the device-cost observatory
(``utils/costprof.py``) both consume ``observability.CACHES.programs()``:
every compiled-program cache must register an enumerator whose
:class:`~...utils.observability.ProgramHandle` records carry a traceable,
UN-counted body (``trace_body``) — that body is what gets abstractly
re-traced by the auditor and AOT lower+compiled by the cost extractor.
Two ways a producer silently drops out of both surfaces:

1. **Stats without programs**: a module calls ``CACHES.register(name,
   stats_fn)`` but never ``CACHES.register_programs`` — the cache shows
   up in ``cache_report()`` yet none of its programs can be audited or
   cost-profiled. Flagged per module (receiver-qualified on the
   ``CACHES`` chain tail, so an unrelated registry cannot trip it).

2. **The counted entry instead of the trace body**: a
   ``ProgramHandle(...)`` construction whose ``fn`` argument is missing,
   a literal ``None``, or an attribute access ending in ``.fn`` — the
   producers' convention is that ``.fn`` is the COUNTED jitted dispatch
   entry (replay verdicts + compile counters hang off it), while the
   handle must carry the raw body (``.trace_body`` / the un-wrapped
   callable): auditing through ``.fn`` distorts the very statistics the
   observatory reads (phantom compiles, fake replay hits).
"""

from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile, attr_chain

#: receiver-chain tails that qualify a CACHES registration call
_REGISTRY_TAILS = ("CACHES",)


class ProgramHandleRule(Rule):
    name = "program-handle"
    description = ("every CACHES.register(...) producer module must also"
                   " register_programs(...), and every ProgramHandle must"
                   " carry a traceable UN-counted body (not the counted"
                   " .fn entry) — an unprofilable cache is invisible to"
                   " dqaudit and the device-cost observatory")

    def visit(self, src: SourceFile):
        out = []
        registers = []              # CACHES.register(...) call nodes
        has_programs = False
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute):
                chain = attr_chain(f.value)
                tail = chain.split(".")[-1] if chain else ""
                if tail in _REGISTRY_TAILS:
                    if f.attr == "register":
                        registers.append(node)
                    elif f.attr == "register_programs":
                        has_programs = True
            if self._is_handle_ctor(node):
                bad = self._bad_fn_arg(node)
                if bad is not None:
                    finding = src.finding(self.name, node, bad)
                    if finding:
                        out.append(finding)
        if registers and not has_programs:
            for node in registers:
                finding = src.finding(
                    self.name, node,
                    "CACHES.register(...) without a matching"
                    " CACHES.register_programs(...) in this module —"
                    " the cache's programs cannot be audited (dqaudit)"
                    " or cost-profiled (utils/costprof): register an"
                    " enumerator yielding ProgramHandle records with"
                    " their un-counted trace bodies")
                if finding:
                    out.append(finding)
        return out

    @staticmethod
    def _is_handle_ctor(node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Name):
            return f.id == "ProgramHandle"
        if isinstance(f, ast.Attribute):
            return f.attr == "ProgramHandle"
        return False

    @staticmethod
    def _bad_fn_arg(node: ast.Call):
        """None = fine; else the finding message for a missing/None/
        counted-entry ``fn`` argument (signature:
        ``ProgramHandle(cache, program_key, fn, ...)``)."""
        fn_arg = None
        if len(node.args) >= 3:
            fn_arg = node.args[2]
        else:
            for kw in node.keywords:
                if kw.arg == "fn":
                    fn_arg = kw.value
                    break
        if fn_arg is None:
            return ("ProgramHandle(...) without an fn argument — the"
                    " handle is untraceable: pass the producer's"
                    " un-counted trace body")
        if isinstance(fn_arg, ast.Constant) and fn_arg.value is None:
            return ("ProgramHandle(..., fn=None) — the handle is"
                    " untraceable: pass the producer's un-counted trace"
                    " body")
        if isinstance(fn_arg, ast.Attribute) and fn_arg.attr == "fn":
            return ("ProgramHandle fn argument is the COUNTED '.fn'"
                    " dispatch entry — auditing/cost-extracting through"
                    " it distorts compile counters and replay verdicts;"
                    " hand over '.trace_body' (the raw un-counted"
                    " program)")
        return None
