"""Rule ``conf-key`` — every ``spark.*`` conf key is declared, scoped,
and parsed with the shared truthiness vocabulary.

Three past review rounds fixed leaks of exactly this shape: a key read
somewhere deep in the engine that ``config.py`` never declared, that
``session._init_pipeline`` never save/restored (so one session's setting
leaked process-wide), or that grew its own ad-hoc ``("true", "1")``
spelling which silently diverged from ``config.CONF_TRUE``/``CONF_FALSE``.

Checks (cross-file, so they run in ``finalize``):

1. **Declared**: every ``"spark.*"`` string literal in the package must
   resolve against the ``config.CONF_KEYS`` registry — an exact key, a
   declared dynamic prefix (``CONF_KEY_PREFIXES``), or a namespace probe
   (a literal like ``"spark.pipeline."`` that prefixes declared keys).
   f-strings resolve by their literal head (``f"spark.serve.{k}"``).
2. **Session-scoped**: keys the registry tags ``"session"`` must appear
   inside ``session.py::_init_pipeline`` — the single save/restore point
   that keeps conf session-scoped instead of a process-wide leak.
3. **Shared vocabulary**: inside any function that reads conf or
   environment values, an inline membership test against a literal tuple
   drawn from the truthiness vocabulary (``("true", "1")``-style) is
   flagged — spellings must come from ``config.CONF_TRUE`` /
   ``CONF_FALSE`` so a new spelling cannot diverge between parsers.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Finding, Rule, SourceFile, attr_chain

_CONFIG_REL = "sparkdq4ml_tpu/config.py"
_SESSION_REL = "sparkdq4ml_tpu/session.py"


def _literal_head(node: ast.JoinedStr) -> Optional[str]:
    """Leading literal text of an f-string (before the first hole)."""
    if node.values and isinstance(node.values[0], ast.Constant) \
            and isinstance(node.values[0].value, str):
        return node.values[0].value
    return None


class ConfKeyRule(Rule):
    name = "conf-key"
    description = ("spark.* conf keys must be declared in config.CONF_KEYS"
                   " (session-scoped ones handled by _init_pipeline) and"
                   " truthiness parsed via config.CONF_TRUE/CONF_FALSE")

    def __init__(self):
        # (src, node, literal) usages of spark.* string constants
        self._usages: list[tuple[SourceFile, ast.AST, str]] = []
        # inline truthiness tuples in conf-reading functions
        self._vocab_sites: list[tuple[SourceFile, ast.AST, tuple]] = []
        # spark.* literals that appear inside session._init_pipeline
        self._init_pipeline_keys: set[str] = set()
        self._config_src: Optional[SourceFile] = None

    # -- per-file collection ------------------------------------------------
    def visit(self, src: SourceFile):
        if src.rel == _CONFIG_REL:
            self._config_src = src
            return ()   # declarations, not usages
        in_init_pipeline = False

        def collect(tree, in_init):
            for node in ast.iter_child_nodes(tree):
                is_init = (isinstance(node, ast.FunctionDef)
                           and node.name == "_init_pipeline"
                           and src.rel == _SESSION_REL)
                collect(node, in_init or is_init)
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and node.value.startswith("spark."):
                    self._usages.append((src, node, node.value))
                    if in_init or is_init:
                        self._init_pipeline_keys.add(node.value)
                elif isinstance(node, ast.JoinedStr):
                    head = _literal_head(node)
                    if head and head.startswith("spark."):
                        self._usages.append((src, node, head))

        collect(src.tree, False)
        del in_init_pipeline

        # vocabulary sites: functions touching conf/environ
        for fn in ast.walk(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            reads_conf = any(
                (isinstance(n, ast.Attribute) and n.attr in ("conf",
                                                             "environ"))
                or (isinstance(n, ast.Name) and n.id in ("conf", "environ"))
                for n in ast.walk(fn))
            if not reads_conf:
                continue
            for cmp_ in ast.walk(fn):
                if not isinstance(cmp_, ast.Compare):
                    continue
                if not any(isinstance(op, (ast.In, ast.NotIn))
                           for op in cmp_.ops):
                    continue
                for comparator in cmp_.comparators:
                    if isinstance(comparator, ast.Tuple) \
                            and len(comparator.elts) >= 2 \
                            and all(isinstance(e, ast.Constant)
                                    and isinstance(e.value, str)
                                    for e in comparator.elts):
                        vals = tuple(e.value for e in comparator.elts)
                        self._vocab_sites.append((src, cmp_, vals))
        return ()

    # -- registry parse -----------------------------------------------------
    @staticmethod
    def _parse_registry(src: SourceFile):
        keys: dict[str, str] = {}
        prefixes: tuple = ()
        true_vals: tuple = ()
        false_vals: tuple = ()
        for node in src.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                    or not isinstance(node.targets[0], ast.Name):
                continue
            name = node.targets[0].id
            try:
                value = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                continue
            if name == "CONF_KEYS" and isinstance(value, dict):
                keys = value
            elif name == "CONF_KEY_PREFIXES" and isinstance(value,
                                                            (tuple, list)):
                prefixes = tuple(value)
            elif name == "CONF_TRUE":
                true_vals = tuple(value)
            elif name == "CONF_FALSE":
                false_vals = tuple(value)
        return keys, prefixes, true_vals, false_vals

    # -- cross-file checks --------------------------------------------------
    def finalize(self, files):
        out: list[Finding] = []
        if self._config_src is None:
            return out   # nothing to check against (partial trees in tests)
        keys, prefixes, true_vals, false_vals = self._parse_registry(
            self._config_src)
        if not keys:
            out.append(Finding(
                rule=self.name, path=self._config_src.rel, line=0,
                message="config.py declares no CONF_KEYS registry — every"
                        " spark.* key must be declared there"))
            return out
        vocab = set(true_vals) | set(false_vals)

        for src, node, literal in self._usages:
            # namespace probes must end with '.' — a bare prefix match
            # would sanction truncated/typo'd keys (e.g. a dropped final
            # character still prefixes the declared key)
            is_probe = literal.endswith(".")
            ok = (literal in keys
                  or any(literal.startswith(p) for p in prefixes)
                  or (is_probe and any(k.startswith(literal)
                                       for k in keys))
                  or (is_probe and any(p.startswith(literal)
                                       for p in prefixes)))
            if not ok:
                f = src.finding(
                    self.name, node,
                    f"conf key {literal!r} is not declared in"
                    " config.CONF_KEYS (nor covered by a declared"
                    " CONF_KEY_PREFIXES family) — declare it with its"
                    " scope tag so save/restore and docs can't drift")
                if f:
                    out.append(f)

        for key, tag in keys.items():
            if tag == "session" and key not in self._init_pipeline_keys:
                out.append(Finding(
                    rule=self.name, path=_SESSION_REL, line=0,
                    message=f"conf key {key!r} is declared session-scoped"
                            " but session._init_pipeline never handles it"
                            " — its setting would leak process-wide"))

        for src, node, vals in self._vocab_sites:
            if vocab and len(vals) >= 2 and all(v in vocab for v in vals):
                f = src.finding(
                    self.name, node,
                    f"inline truthiness tuple {vals!r} in a conf/env"
                    " parser — use config.CONF_TRUE / config.CONF_FALSE"
                    " so spellings cannot diverge between parsers")
                if f:
                    out.append(f)
        return out
