"""Static per-plan memory bounds for EXPLAIN — the ``est peak`` column.

EXPLAIN ANALYZE (PR 5) measures peak bytes *after the fact*; this module
computes an upper bound BEFORE anything runs, from static shape/dtype
metadata plus one abstract trace of the fused pipeline stage
(``jax.make_jaxpr`` — zero compiles, zero device execution, zero counted
host syncs). The bound is deliberately conservative: every operator's
working set assumes inputs and outputs coexist, filters keep every row,
and no buffer aliasing is credited — so ``est peak ≥ measured peak``
holds on the headline workload (test-pinned, with a documented slack
factor on CPU).

Per-node model (bytes; ``in`` = sum of child output estimates):

========================  =====================================
node                      working-set estimate
========================  =====================================
Scan                      frame bytes (columns + mask, static)
FusedStage                ``in`` + traced jaxpr liveness peak
Filter/Project/Having     ``2 × in`` (input + output)
Aggregate variants        ``3 × in`` (input + keys/sort + output)
Sort variants / Distinct  ``3 × in``
Join                      ``2 × (left + right)``
Limit/Offset              ``in``
SetOps                    ``2 × in`` (concatenation)
========================  =====================================

``est_peak`` at a node is the running maximum over its subtree — the
root's figure is the whole plan's bound, checked against the device
budget × ``spark.audit.memoryFraction`` by the caller.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from . import jaxpr_tools as JT

__all__ = ["annotate_plan", "frame_static_bytes"]

_FACTORS = {
    "Filter": 2.0, "Project": 2.0, "Having": 2.0,
    "Aggregate": 3.0, "SegmentedAggregate": 3.0,
    "Sort": 3.0, "DeviceSort": 3.0, "Distinct": 3.0,
    "Limit": 1.0, "Offset": 1.0,
    "CreateView": 1.0, "With": 1.0,
}


def frame_static_bytes(frame) -> int:
    """Static footprint of a frame's device state: stored columns + mask
    + one engine-float column per pending pipeline step output. Reads
    ``_data_store``/``_mask_store`` directly — sizing must never flush
    the pending pipeline (EXPLAIN executes nothing)."""
    from ...config import float_dtype

    total = 0
    for arr in frame._data_store.values():
        shape = getattr(arr, "shape", None)
        dtype = getattr(arr, "dtype", None)
        if shape is None or dtype is None:
            continue
        try:
            total += int(np.prod(shape, dtype=np.int64)) \
                * np.dtype(dtype).itemsize
        except Exception:
            continue
    n = int(frame._n)
    total += n * np.dtype(bool).itemsize                     # mask
    total += len(frame._pending_names()) * n \
        * np.dtype(float_dtype()).itemsize
    return total


def _fused_stage_peak(frame, q) -> Optional[int]:
    """Abstract-trace the FusedStage program (WHERE + compilable
    projections) exactly as the pipeline compiler would build it —
    ``_linearize`` for literal hoisting, ``Expr.eval`` against the
    tracer-frame shim — and run the liveness walk. Returns None when the
    stage is not statically traceable (the caller falls back to the
    factor model)."""
    import jax
    import jax.numpy as jnp

    from ...config import float_dtype
    from ...ops import compiler as C
    from ...ops import expressions as E

    data = frame._data_store
    pending = frame._pending_names()
    n = int(frame._n)
    b = C.bucket_size(n)
    fdt = np.dtype(float_dtype())
    schema: dict = {}
    for name, arr in data.items():
        schema[name] = C._col_spec(arr)
    for name in pending:
        # pending outputs are engine-float by construction for the
        # estimation schema: the bound treats them as materialized
        schema[name] = fdt.str
    if q.where is None or not C.is_compilable(q.where, schema):
        return None
    steps = (("filter", q.where),)
    extra = tuple(
        (f"__est{i}", it) for i, it in enumerate(q.items)
        if not isinstance(it, str) and isinstance(it, E.Expr)
        and C.is_compilable(it, schema))
    _key, lits, lsteps, lextra, refs = C._linearize(
        steps, extra, dict(schema))
    lit_vals = tuple(
        v.value.item() if hasattr(v.value, "item") else v.value
        for v in lits)

    def prog(cols, mask, lit_args):
        C._RUNTIME_LITS.lits = lit_args
        try:
            fr = C._TraceFrame(dict(zip(refs, cols)), b)
            m = mask
            for st in lsteps:
                m = jnp.logical_and(
                    m, E.predicate_keep_mask(st[1].eval(fr)))
            return m, tuple(ex.eval(fr) for _name, ex in lextra)
        finally:
            C._RUNTIME_LITS.lits = ()

    col_specs = []
    for name in refs:
        arr = data.get(name)
        if arr is not None:
            shape = (b,) + tuple(arr.shape[1:])
            col_specs.append(jax.ShapeDtypeStruct(shape, arr.dtype))
        else:
            col_specs.append(jax.ShapeDtypeStruct((b,), fdt))
    closed = jax.make_jaxpr(prog)(
        tuple(col_specs), jax.ShapeDtypeStruct((b,), np.dtype(bool)),
        lit_vals)
    return JT.peak_bytes(closed)


def _estimate(node, cat) -> Optional[tuple]:
    """Bottom-up (out_bytes, peak) per node; annotates ``est_peak`` into
    ``node.stats``. Returns None when the subtree cannot be sized (an
    unregistered view, a DDL leaf) — ancestors then stay unannotated
    rather than reporting a false bound."""
    child_vals = [_estimate(c, cat) for c in node.children]
    known = [v for v in child_vals if v is not None]
    op = node.op

    if op == "Scan":
        if child_vals and child_vals[0] is not None:
            out, peak = child_vals[0]       # derived table: its subquery
        else:
            view = node.meta.get("view")
            if not isinstance(view, str):
                return None
            try:
                frame = cat.lookup(view)
            except Exception:
                return None
            out = frame_static_bytes(frame)
            peak = out
    elif op == "DropView":
        out, peak = 0, 0
    elif op == "Join":
        if len(known) < len(child_vals) or not known:
            return None
        in_bytes = sum(o for o, _p in known)
        out = in_bytes
        peak = max(max(p for _o, p in known), 2.0 * in_bytes)
    elif op == "SetOps":
        if len(known) < len(child_vals) or not known:
            return None
        in_bytes = sum(o for o, _p in known)
        out = in_bytes
        peak = max(max(p for _o, p in known), 2.0 * in_bytes)
    elif op == "FusedStage":
        if not known:
            return None
        in_bytes, child_peak = known[0]
        stage = None
        q = node.meta.get("query")
        frame = node.meta.get("frame")
        if frame is None and q is not None:
            view = getattr(q, "view", None)
            if isinstance(view, str):
                try:
                    frame = cat.lookup(view)
                except Exception:
                    frame = None
        if frame is not None and q is not None:
            try:
                stage = _fused_stage_peak(frame, q)
            except Exception:
                stage = None
        if stage is not None:
            node.stats["est_stage"] = int(stage)
            peak = max(child_peak, in_bytes + stage)
        else:
            peak = max(child_peak, 2.0 * in_bytes)
        out = in_bytes
    else:
        if not known:
            return None
        in_bytes = sum(o for o, _p in known)
        factor = _FACTORS.get(op, 2.0)
        out = in_bytes
        peak = max(max(p for _o, p in known), factor * in_bytes)

    node.stats["est_peak"] = int(peak)
    return out, peak


def annotate_plan(tree, cat) -> Optional[int]:
    """Annotate ``est_peak`` bottom-up over an EXPLAIN plan tree;
    returns the root bound (None when the tree cannot be sized). Never
    raises — estimation is advisory and must not break EXPLAIN."""
    try:
        result = _estimate(tree, cat)
    except Exception:
        return None
    return int(result[1]) if result is not None else None
