"""Shared jaxpr machinery for the program auditor (dqaudit).

Everything here operates on the output of ``jax.make_jaxpr`` — pure
abstract evaluation: no XLA compile, no device execution, no host sync.
That property is the audit tier's whole contract ("Memory Safe
Computations with XLA", arxiv 2206.14148: program properties worth
gating on can be computed from the IR, before anything runs).

Three tools:

* :func:`trace` — abstract-trace a cached program from its recorded
  calling convention (``ShapeDtypeStruct`` leaves + host scalars);
* :func:`structural_signature` — a canonical hash of the program's
  STRUCTURE: primitive sequence, operand/output dtypes, nested jaxprs,
  and captured-constant skeleton, with concrete dimension sizes erased
  so the same plan traced at two shape buckets hashes identically
  (a difference ⇒ the program specializes on shape ⇒ steady-state
  retraces in serving);
* :func:`peak_bytes` — a liveness walk over eqn outvars: allocate each
  equation's outputs, free operands past their last use, track the
  running high-water mark. Aliasing/donation is deliberately ignored,
  so the result is an UPPER bound on XLA's buffer peak.
"""

from __future__ import annotations

import hashlib
from typing import Iterator, Optional

import jax
import numpy as np

__all__ = [
    "trace", "structural_signature", "peak_bytes", "iter_eqns",
    "collective_eqns", "callback_eqns",
    "COLLECTIVE_PRIMS", "CALLBACK_PRIMS",
]

#: Cross-device communication primitives — every one must resolve its
#: axis names against the installed mesh (collective-topology detector).
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmin", "pmax", "pmean", "all_gather",
    "all_to_all", "reduce_scatter", "ppermute", "pbroadcast",
})

#: Host-callback primitives — a hidden host round-trip inside a jitted
#: body (hidden-sync detector). ``debug_callback`` is what
#: ``jax.debug.print`` lowers to.
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback",
})


def trace(fn, args=(), kwargs=None):
    """``jax.make_jaxpr`` over a recorded calling convention. Keyword
    arguments are closed over (make_jaxpr only maps positional args to
    avals); array-spec leaves stay abstract throughout — nothing
    compiles, nothing executes."""
    kwargs = kwargs or {}
    if kwargs:
        return jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    return jax.make_jaxpr(fn)(*args)


def _sub_jaxprs(value) -> Iterator:
    """Nested jaxprs inside one eqn param value (pjit/scan carry a
    ClosedJaxpr, cond a tuple of branches, shard_map an open Jaxpr)."""
    if hasattr(value, "jaxpr") and hasattr(value, "consts"):
        yield value                       # ClosedJaxpr
    elif hasattr(value, "eqns") and hasattr(value, "invars"):
        yield value                       # open Jaxpr
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _sub_jaxprs(v)


def _open(j):
    """The open Jaxpr under either representation."""
    return j.jaxpr if hasattr(j, "jaxpr") else j


def iter_eqns(closed) -> Iterator:
    """Every eqn of the program, recursing through nested jaxprs
    (pjit bodies, scan/while/cond carriers, shard_map regions)."""
    stack = [_open(closed)]
    while stack:
        jaxpr = stack.pop()
        for eqn in jaxpr.eqns:
            yield eqn
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    stack.append(_open(sub))


def collective_eqns(closed) -> list:
    """``(primitive_name, axis_names)`` per collective eqn. Axis names
    come from the ``axes``/``axis_name`` params; integer (positional)
    axes are dropped — only named axes bind to a mesh."""
    out = []
    for eqn in iter_eqns(closed):
        if eqn.primitive.name not in COLLECTIVE_PRIMS:
            continue
        axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
        if isinstance(axes, str):
            axes = (axes,)
        names = tuple(a for a in (axes or ()) if isinstance(a, str))
        out.append((eqn.primitive.name, names))
    return out


def callback_eqns(closed) -> list:
    """Callback primitive names present in the program (with their
    callback target where the param exposes one)."""
    out = []
    for eqn in iter_eqns(closed):
        if eqn.primitive.name in CALLBACK_PRIMS:
            target = eqn.params.get("callback",
                                    eqn.params.get("callback_func"))
            out.append((eqn.primitive.name,
                        getattr(target, "__name__", None)
                        or type(target).__name__ if target is not None
                        else ""))
    return out


# ---------------------------------------------------------------------------
# Structural signature
# ---------------------------------------------------------------------------

#: Eqn params whose VALUES are structural (axis selections, dtype
#: targets, comparison directions) rather than size-dependent. Every
#: other param contributes its key only — a param like ``iota``'s
#: ``shape`` or ``dynamic_slice`` sizes would otherwise leak concrete
#: bucket dimensions into the hash.
_STRUCTURAL_PARAMS = frozenset({
    "axis", "axis_name", "axis_index_groups", "new_dtype", "weak_type",
    "direction", "is_stable", "num_keys", "dimension", "comparator",
    "preferred_element_type", "reverse", "unroll", "accuracy",
})


def _aval_sig(aval) -> str:
    if aval is None:
        return "?"
    dtype = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", None)
    weak = "~" if getattr(aval, "weak_type", False) else ""
    rank = len(shape) if shape is not None else -1
    return f"{dtype}{weak}r{rank}"


def _const_sig(c, with_values: bool) -> str:
    shape = tuple(getattr(c, "shape", ()))
    dtype = getattr(c, "dtype", type(c).__name__)
    sig = f"{dtype}r{len(shape)}"
    if with_values and int(np.prod(shape or (1,))) <= 64:
        try:
            sig += ":" + hashlib.sha1(
                np.asarray(c).tobytes()).hexdigest()[:12]
        except Exception:
            pass
    return sig


def _sig_lines(jaxpr, lines: list, with_const_values: bool) -> None:
    lines.append("in=" + ",".join(_aval_sig(v.aval)
                                  for v in jaxpr.invars))
    for eqn in jaxpr.eqns:
        parts = [eqn.primitive.name]
        ins = []
        for v in eqn.invars:
            if hasattr(v, "val"):         # Literal: dtype only — values
                ins.append("lit:" + _aval_sig(v.aval))  # may encode sizes
            else:
                ins.append(_aval_sig(getattr(v, "aval", None)))
        parts.append("(" + ",".join(ins) + ")")
        parts.append("->" + ",".join(_aval_sig(v.aval)
                                     for v in eqn.outvars))
        for k in sorted(eqn.params):
            v = eqn.params[k]
            subs = list(_sub_jaxprs(v))
            if subs:
                parts.append(f"{k}=[")
                for sub in subs:
                    op = _open(sub)
                    _sig_lines(op, lines, with_const_values)
                    consts = getattr(sub, "consts", ())
                    for c in consts:
                        lines.append("const=" + _const_sig(
                            c, with_const_values))
                parts.append("]")
            elif k in _STRUCTURAL_PARAMS:
                parts.append(f"{k}={v!r}")
            else:
                parts.append(k)
        lines.append(" ".join(parts))
    lines.append("out=" + ",".join(
        _aval_sig(getattr(v, "aval", None)) for v in jaxpr.outvars))


def structural_signature(closed, with_const_values: bool = False) -> str:
    """Canonical structural hash: stable across shape buckets (concrete
    sizes are erased — dtypes, ranks, primitive order, structural params
    and the captured-constant skeleton remain). Two traces of one
    healthy plan at different buckets hash identically; a program that
    branches on shape, weak-type, or a baked literal does not."""
    lines: list = []
    _sig_lines(_open(closed), lines, with_const_values)
    for c in getattr(closed, "consts", ()):
        lines.append("const=" + _const_sig(c, with_const_values))
    return hashlib.sha1("\n".join(lines).encode()).hexdigest()


# ---------------------------------------------------------------------------
# Static peak-memory bound (liveness walk)
# ---------------------------------------------------------------------------

def _nbytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    except Exception:
        return 0


def peak_bytes(closed) -> int:
    """Upper-bound peak device bytes of one program: a liveness walk
    over the (recursively flattened) eqn list. Entry cost is the args +
    captured consts; each eqn allocates its outputs on top of the live
    set, operands free at their last use; nested jaxprs contribute
    their own peak *minus* their entry (their inputs alias buffers the
    outer walk already counts). No aliasing/donation credit — the bound
    only ever over-counts."""
    jaxpr = _open(closed)
    entry = sum(_nbytes(v.aval) for v in jaxpr.invars)
    constvars = getattr(jaxpr, "constvars", ())
    entry += sum(_nbytes(v.aval) for v in constvars)
    if not constvars:
        # a ClosedJaxpr binds its consts to the constvars above — count
        # the concrete arrays only when no constvars carry their avals
        # (counting both would double every captured constant)
        entry += sum(_nbytes(c) for c in getattr(closed, "consts", ()))
    eqns = jaxpr.eqns
    last_use: dict = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not hasattr(v, "val"):
                last_use[v] = i
    # outvars may contain Literals (a program returning a constant) —
    # they carry no buffer and are unhashable; only real Vars matter
    outvars = {v for v in jaxpr.outvars if not hasattr(v, "val")}
    for v in outvars:
        last_use[v] = len(eqns)
    live = entry
    peak = entry
    freed: set = set()
    for i, eqn in enumerate(eqns):
        inner_extra = 0
        for pv in eqn.params.values():
            for sub in _sub_jaxprs(pv):
                sj = _open(sub)
                sub_entry = sum(_nbytes(v.aval) for v in sj.invars)
                sub_entry += sum(_nbytes(v.aval)
                                 for v in getattr(sj, "constvars", ()))
                inner_extra = max(inner_extra,
                                  peak_bytes(sub) - sub_entry)
        out_bytes = sum(_nbytes(v.aval) for v in eqn.outvars)
        live += out_bytes
        peak = max(peak, live + max(inner_extra, 0))
        for v in eqn.invars:
            if hasattr(v, "val") or v in freed or v in outvars:
                continue
            if last_use.get(v) == i:
                live -= _nbytes(v.aval)
                freed.add(v)
    return int(peak)
