"""dqaudit driver — run the four detectors over every enumerable cached
program.

The auditor is strictly OFFLINE/on-demand: nothing in the serving or
query hot path imports this package (test-pinned). Entry points:

* :func:`audit_programs` — detectors over a handle list (defaults to
  ``observability.CACHES.programs()``, i.e. everything the engine has
  cached so far in this process);
* :func:`audit_report` — the ``session.audit_report()`` payload;
* :func:`run_headline_workload` — populate the caches with the paper's
  headline DQ + Lasso flow (used by ``scripts/check_static.py --tier
  program`` so the audited program set is the serving-representative
  one, not whatever happened to run first).

A program whose BASELINE abstract trace raises is reported as *skipped*
(with the error), not as a finding: on exotic backends tracing may be
impossible for environmental reasons, and the CLI must SKIP cleanly
rather than fail the gate. Variant-trace failures after a successful
baseline trace ARE findings (the retrace detector's job).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .detectors import ALL_DETECTORS, AuditContext, get_detectors

__all__ = ["AuditResult", "audit_programs", "audit_report",
           "run_headline_workload"]


@dataclasses.dataclass
class AuditResult:
    findings: list            # live Finding records
    programs: int             # handles audited (traced successfully)
    skipped: list             # (program_key, error) — baseline trace failed
    enum_errors: dict         # producer name → enumerator error
    program_stats: dict       # program_key → detector facts (est peak, …)

    def as_dict(self) -> dict:
        return {
            "findings": [f.as_dict() for f in self.findings],
            "programs": self.programs,
            "skipped": [list(s) for s in self.skipped],
            "enum_errors": dict(self.enum_errors),
            "program_stats": self.program_stats,
        }


def audit_programs(handles=None, detectors=None,
                   ctx: Optional[AuditContext] = None) -> AuditResult:
    """Run ``detectors`` (default: all four) over ``handles`` (default:
    every program in ``observability.CACHES``). Zero device execution,
    zero compiles, zero counted host syncs — abstract evaluation only."""
    from ...utils import observability as _obs

    enum_errors: dict = {}
    if handles is None:
        handles, enum_errors = _obs.CACHES.programs()
    if detectors is None:
        detectors = get_detectors()
    if ctx is None:
        ctx = AuditContext.from_config()
    findings: list = []
    skipped: list = []
    traced: list = []
    for h in handles:
        try:
            ctx.trace(h)
        except Exception as e:
            skipped.append((h.program_key,
                            f"{type(e).__name__}: {e}"))
            continue
        traced.append(h)
        for det in detectors:
            findings.extend(det.check(h, ctx))
    for det in detectors:
        findings.extend(det.finalize(traced, ctx))
    audited = len(traced)
    findings.sort(key=lambda f: (f.path, f.rule, f.fingerprint))
    return AuditResult(findings=findings, programs=audited,
                       skipped=skipped, enum_errors=enum_errors,
                       program_stats=ctx.program_stats)


def audit_report(detectors=None) -> dict:
    """The ``session.audit_report()`` payload: findings + per-program
    facts over everything currently cached in this process."""
    result = audit_programs(detectors=detectors)
    by_detector: dict = {c.name: 0 for c in ALL_DETECTORS}
    for f in result.findings:
        by_detector[f.rule] = by_detector.get(f.rule, 0) + 1
    doc = result.as_dict()
    doc["by_detector"] = by_detector
    doc["clean"] = not result.findings
    return doc


def run_headline_workload(data_path: str) -> dict:
    """Populate every plan cache with the paper's headline flow — the
    DQ rules + SQL filters over the pricing CSV, a grouped aggregate,
    and the Lasso fit (maxIter=40, regParam=1, elasticNetParam=1) — and
    return the golden observables so the caller can assert the workload
    actually ran (count 24 on dataset-abstract). Device execution
    happens HERE, before the audit; the audit itself stays abstract."""
    import sparkdq4ml_tpu as dq
    from ...models import LinearRegression, VectorAssembler

    spark = dq.TpuSession.builder().app_name("dqaudit").master(
        "local[*]").get_or_create()
    try:
        dq.register_builtin_rules()
        df = (spark.read.format("csv")
              .option("inferSchema", "true").option("header", "false")
              .load(data_path))
        df = df.with_column_renamed("_c0", "guest")
        df = df.with_column_renamed("_c1", "price")
        df = df.with_column(
            "price_no_min", dq.call_udf("minimumPriceRule",
                                        dq.col("price")))
        df.create_or_replace_temp_view("price")
        df = spark.sql(
            "SELECT cast(guest as int) guest, price_no_min AS price "
            "FROM price WHERE price_no_min > 0")
        df = df.with_column(
            "price_correct_correl",
            dq.call_udf("priceCorrelationRule", dq.col("price"),
                        dq.col("guest")))
        df.create_or_replace_temp_view("price")
        df = spark.sql("SELECT guest, price_correct_correl AS price "
                       "FROM price WHERE price_correct_correl > 0")
        count = df.count()
        # grouped-execution plan (segment reduction) for the audit set
        df.create_or_replace_temp_view("clean")
        spark.sql("SELECT guest, count(*) c, avg(price) m FROM clean "
                  "GROUP BY guest ORDER BY guest").count()
        df = df.with_column("label", df.col("price"))
        df = VectorAssembler(["guest"], "features").transform(df)
        lr = LinearRegression(max_iter=40, reg_param=1.0,
                              elastic_net_param=1.0)
        model = lr.fit(df)
        return {"count": int(count),
                "coefficients": [float(c)
                                 for c in model.coefficients.tolist()]}
    finally:
        spark.stop()
