"""dqaudit — the jaxpr-level program-audit tier (ISSUE 9).

dqlint (``analysis/rules``) enforces the engine's invariants at the
SOURCE level; this package enforces them at the level of the *traced
program* — the properties that actually burn a serving fleet are in the
jaxpr, invisible to an AST walk: a fused plan whose intermediates exceed
HBM, a hidden host callback inside a jitted body, a collective whose
axis doesn't bind to the mesh, a plan that silently retraces per shape
bucket. ("Memory Safe Computations with XLA", arxiv 2206.14148: static
per-program bounds computed from the IR, treated as first-class plan
constraints.)

The audit surface is ``observability.CACHES.programs()`` — every
compiled-program cache (pipeline compiler, segment-reduction engine,
solver jit entries, packed sharded fits) registers traceable
:class:`~...utils.observability.ProgramHandle` records, so the auditor
(and the ROADMAP item 4 cost-based optimizer after it) enumerates
cached programs without private imports.

Everything here is abstract evaluation (``jax.make_jaxpr`` /
``jax.eval_shape``): zero compiles, zero device execution, zero counted
host syncs — strictly offline/on-demand, never on the serving hot path
(test-pinned). Entry points: ``scripts/check_static.py --tier program``
(the tier-1 gate arm), ``session.audit_report()``, and the EXPLAIN
``est peak`` column (:mod:`.static_mem`).
"""

from .audit import (AuditResult, audit_programs, audit_report,
                    run_headline_workload)
from .detectors import (ALL_DETECTORS, AuditContext, Detector,
                        get_detectors, program_finding)
from .jaxpr_tools import peak_bytes, structural_signature, trace

__all__ = [
    "ALL_DETECTORS", "AuditContext", "AuditResult", "Detector",
    "audit_programs", "audit_report", "get_detectors", "peak_bytes",
    "program_finding", "run_headline_workload", "structural_signature",
    "trace",
]
