"""AOT cost-analysis extraction — the device-cost observatory's sensor.

The audit tier (``detectors.py``) abstract-evaluates cached programs and
bounds their MEMORY; this module asks the compiler what each program
COSTS: ``jax.jit(trace_body).lower(*example).compile()`` produces an XLA
executable whose ``cost_analysis()`` reports FLOPs, transcendentals, and
bytes accessed, and whose ``memory_analysis()`` reports the generated
code's argument/output/temp footprint — the utilization lens of "Large
Scale Distributed Linear Algebra With TPUs" (arxiv 2112.09017), and the
profile ROADMAP item 1's EQuARX headroom note requires before a
quantized all-reduce can be justified.

Contract (mirrors the audit tier's):

* **zero device execution** — the program is lowered and compiled, never
  dispatched; nothing allocates on device, nothing runs;
* **zero counted host syncs** — no ``device_get``, no ``.item()``;
* **zero counted compiles** — extraction targets the producer's
  UN-counted ``trace_body`` (the ``ProgramHandle`` contract), so
  ``pipeline.compile``/``grouped.compile`` and the per-plan replay
  verdicts never move (test-pinned). The XLA compile is real host work —
  which is why extraction runs lazily on cold surfaces only and the
  result is cached per structural key (``utils/costprof.py``) and
  persisted into the statstore.

Collective traffic is accounted from the abstract trace, not the
executable (XLA:CPU's cost model does not itemize collectives): each
collective eqn's per-device operand bytes × the mesh device count = the
aggregate payload entering that collective across the mesh. A static
figure by construction — the shapes are in the jaxpr.

CPU-sandbox honesty: the FLOP/byte counts are the compiler's static
accounting and are chip-independent; *achieved* GFLOP/s / GB/s derived
from them (``utils/costprof.py``) divide by measured wall-clock, which
on the CPU sandbox reflects host dispatch, so those numbers are
structural there and meaningful on TPU captures.
"""

from __future__ import annotations

import time
from typing import Optional

from . import jaxpr_tools as JT

__all__ = ["extract", "collective_bytes"]

#: Collective primitive aliases folded onto their canonical family name
#: (legacy shard_map lowers psum as ``psum2``).
_COLLECTIVE_ALIASES = {"psum2": "psum"}


def _mesh_devices(handle) -> int:
    mesh = getattr(handle, "mesh", None)
    size = getattr(getattr(mesh, "devices", None), "size", None)
    return int(size) if size else 1


def collective_bytes(handle, closed=None) -> dict:
    """``{collective: aggregate_bytes}`` over the program's collective
    eqns — per-device operand bytes × mesh size, from the abstract trace
    (zero compiles beyond the caller's, zero device work)."""
    if closed is None:
        closed = JT.trace(handle.fn, handle.args, handle.kwargs)
    devices = _mesh_devices(handle)
    out: dict = {}
    for eqn in JT.iter_eqns(closed):
        prim = eqn.primitive.name
        if prim not in JT.COLLECTIVE_PRIMS:
            continue
        name = _COLLECTIVE_ALIASES.get(prim, prim)
        nb = sum(JT._nbytes(getattr(v, "aval", None))
                 for v in eqn.invars if not hasattr(v, "val"))
        out[name] = out.get(name, 0) + nb * devices
    return out


def _first_module(ca) -> dict:
    """``Compiled.cost_analysis()`` returns a flat dict on modern jax
    and a one-element list of dicts on 0.4.x — normalize to the dict."""
    if isinstance(ca, (list, tuple)):
        return dict(ca[0]) if ca else {}
    return dict(ca or {})


def extract(handle) -> Optional[dict]:
    """AOT-extract one cached program's cost profile; returns the raw
    document ``utils/costprof.CostProfile`` consumes, or None when the
    backend exposes no cost model. Raises on lowering/compile failure —
    the caller (``costprof._extract``) owns the degradation ladder."""
    import jax

    t0 = time.perf_counter()
    fn = handle.fn
    if handle.kwargs:
        kwargs = dict(handle.kwargs)

        def fn(*a, _inner=handle.fn, _kw=kwargs):
            return _inner(*a, **_kw)

    lowered = jax.jit(fn).lower(*handle.args)
    compiled = lowered.compile()
    ca = _first_module(compiled.cost_analysis())
    doc = {
        "flops": float(ca.get("flops", 0.0)),
        "transcendentals": float(ca.get("transcendentals", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "output_bytes": float(ca.get("bytes accessedout{}", 0.0)),
        "devices": _mesh_devices(handle),
    }
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        try:
            doc["argument_bytes"] = int(ma.argument_size_in_bytes)
            # the generated code's resident footprint past its inputs:
            # temps + outputs + the executable itself
            doc["peak_bytes"] = int(ma.temp_size_in_bytes
                                    + ma.output_size_in_bytes
                                    + ma.generated_code_size_in_bytes)
        except Exception:
            pass
    try:
        colls = collective_bytes(handle)
    except Exception:
        colls = {}
    if colls:
        doc["collectives"] = {k: int(v) for k, v in sorted(colls.items())}
    doc["extract_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
    return doc
