"""dqaudit detectors — the four jaxpr-level program invariants.

Each detector inspects ONE cached program (an
``observability.ProgramHandle``) through its abstract trace and emits
:class:`~..core.Finding` records. Findings address programs, not source
lines: ``path`` is ``program:<cache>`` and the baseline fingerprint is
the stable ``program_key``, so the PR-8 baseline/suppression workflow
(``dqlint_baseline.json``, stale-entry reporting) applies unchanged.

The source-level dqlint rules (``analysis/rules``) police what the code
SAYS; these detectors police what the traced program actually IS — the
jaxpr is ground truth for hidden transfers, collective topology, baked
literals, and memory shape that no AST walk can see.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..core import Finding
from . import jaxpr_tools as JT

__all__ = ["AuditContext", "Detector", "ALL_DETECTORS",
           "audit_budget_bytes", "get_detectors", "program_finding"]


def program_finding(rule: str, handle, message: str) -> Finding:
    """A finding addressed to a cached program: path names the producer
    cache, fingerprint is the stable program key (baseline identity)."""
    return Finding(rule=rule, path=f"program:{handle.cache}", line=0,
                   message=message, fingerprint=handle.program_key)


def _key_prefix(handle, n: int = 72) -> str:
    k = handle.program_key
    return k if len(k) <= n else k[:n] + "…"


def audit_budget_bytes(explicit: int = 0) -> Optional[int]:
    """THE device byte budget the static-memory gate checks against —
    one definition shared by the audit-memory detector and EXPLAIN's
    ``!! est peak`` warning (they must never disagree about the same
    plan): ``spark.audit.deviceBudget`` when set, else the smallest
    allocator ``bytes_limit`` the backend exposes (None on XLA:CPU,
    which reports no allocator stats — the bound is still surfaced,
    just not gated)."""
    if explicit > 0:
        return int(explicit)
    from ...utils import meminfo

    limits = [s["bytes_limit"] for s in meminfo.device_stats()
              if "bytes_limit" in s]
    return min(limits) if limits else None


@dataclasses.dataclass
class AuditContext:
    """Shared per-audit state: conf thresholds, the device budget, and a
    trace cache so four detectors cost one ``make_jaxpr`` per program."""

    memory_fraction: float = 0.9
    device_budget: int = 0           # explicit bytes; 0 = allocator limit
    const_bytes: int = 4096
    _traces: dict = dataclasses.field(default_factory=dict)
    #: program_key → facts the detectors computed (est peak bytes, trace
    #: status, signatures) — the audit_report() payload.
    program_stats: dict = dataclasses.field(default_factory=dict)

    @classmethod
    def from_config(cls) -> "AuditContext":
        from ...config import config

        return cls(
            memory_fraction=float(config.audit_memory_fraction),
            device_budget=int(config.audit_device_budget),
            const_bytes=int(config.audit_const_bytes))

    def trace(self, handle):
        """Abstract-trace ``handle`` once; later detectors reuse it."""
        key = id(handle)
        if key not in self._traces:
            self._traces[key] = JT.trace(handle.fn, handle.args,
                                         handle.kwargs)
        return self._traces[key]

    def stats_for(self, handle) -> dict:
        return self.program_stats.setdefault(
            handle.program_key, {"cache": handle.cache})

    def budget_bytes(self) -> Optional[int]:
        """See :func:`audit_budget_bytes` (the shared definition)."""
        return audit_budget_bytes(self.device_budget)


class Detector:
    name = "detector"
    description = ""

    def check(self, handle, ctx: AuditContext) -> list:
        return []

    def finalize(self, handles, ctx: AuditContext) -> list:
        """Cross-program pass over every successfully-traced handle
        (for invariants one program alone cannot witness)."""
        return []


class StaticMemoryDetector(Detector):
    """Liveness walk over eqn outvars → peak-bytes upper bound, checked
    against the device budget × ``spark.audit.memoryFraction``. The
    bound is recorded in ``ctx.program_stats`` either way — it is the
    ``est peak`` figure EXPLAIN surfaces and the constraint the future
    cost-based optimizer consumes."""

    name = "audit-memory"
    description = ("static per-program peak-bytes bound (liveness walk"
                   " over the jaxpr) must fit spark.audit.memoryFraction"
                   " of the device byte budget")

    def check(self, handle, ctx: AuditContext):
        closed = ctx.trace(handle)
        peak = JT.peak_bytes(closed)
        ctx.stats_for(handle)["est_peak_bytes"] = peak
        budget = ctx.budget_bytes()
        if budget is None:
            return []
        limit = int(ctx.memory_fraction * budget)
        if peak <= limit:
            return []
        return [program_finding(
            self.name, handle,
            f"static peak estimate {peak} bytes exceeds "
            f"{ctx.memory_fraction:g} of the device budget ({budget}"
            f" bytes) — chunk the plan or raise spark.audit."
            f"memoryFraction [{_key_prefix(handle)}]")]


class HiddenSyncDetector(Detector):
    """Callback primitives and large captured constants inside jitted
    bodies. A ``pure_callback``/``io_callback``/``debug_callback`` eqn
    is a host round-trip every execution — invisible to the source-level
    host-sync rule when smuggled through a helper. A large captured
    constant is host data baked into the program: it re-ships with every
    compile and usually means frame data leaked into a plan closure."""

    name = "audit-sync"
    description = ("no callback primitives (pure_callback/io_callback/"
                   "debug prints) and no large host constants captured"
                   " inside cached jitted programs")

    def check(self, handle, ctx: AuditContext):
        closed = ctx.trace(handle)
        out = []
        callbacks = JT.callback_eqns(closed)
        for prim, target in callbacks:
            what = f"{prim}" + (f" -> {target}" if target else "")
            out.append(program_finding(
                self.name, handle,
                f"hidden host callback inside jitted body: {what} — a"
                " device->host round-trip on every execution; hoist it"
                f" out of the program [{_key_prefix(handle)}]"))
        for c in getattr(closed, "consts", ()):
            nb = JT._nbytes(c)
            if nb > ctx.const_bytes:
                shape = tuple(getattr(c, "shape", ()))
                out.append(program_finding(
                    self.name, handle,
                    f"host constant capture: {nb}-byte const "
                    f"{shape} baked into the jaxpr (> spark.audit."
                    f"constBytes={ctx.const_bytes}) — a cache-key-miss"
                    " symptom: pass it as a program input"
                    f" [{_key_prefix(handle)}]"))
        ctx.stats_for(handle)["callbacks"] = len(callbacks)
        return out


class CollectiveTopologyDetector(Detector):
    """Every collective eqn's axis names must resolve against the
    handle's mesh, and any collective-bearing program on a multi-device
    mesh must be declared ``collective_guard``-wrapped — closing the
    PR-6 gap where a guarded factory jits an *unguarded* inner
    collective (overlapping psum dispatch deadlocks XLA:CPU)."""

    name = "audit-collective"
    description = ("collective eqn axis names resolve against the"
                   " installed mesh; multi-device collective programs"
                   " declare collective_guard wrapping")

    def check(self, handle, ctx: AuditContext):
        closed = ctx.trace(handle)
        colls = JT.collective_eqns(closed)
        ctx.stats_for(handle)["collectives"] = len(colls)
        if not colls:
            return []
        out = []
        mesh = handle.mesh
        axis_names = set(getattr(mesh, "axis_names", ()) or ())
        multi = mesh is not None and getattr(
            getattr(mesh, "devices", None), "size", 1) > 1
        for prim, names in colls:
            missing = [n for n in names if n not in axis_names]
            if missing or not names:
                where = (f"axis {missing} not on the mesh"
                         if names else "no named axis")
                have = sorted(axis_names) if axis_names else "none"
                out.append(program_finding(
                    self.name, handle,
                    f"collective {prim} cannot bind: {where}"
                    f" (mesh axes: {have}) — the program would fail or"
                    " silently reduce over the wrong topology"
                    f" [{_key_prefix(handle)}]"))
        if multi and handle.guarded is not True:
            out.append(program_finding(
                self.name, handle,
                f"{len(colls)} collective eqn(s) on a multi-device mesh"
                " but the producer does not declare collective_guard"
                " wrapping — overlapping dispatch deadlocks XLA:CPU"
                " (route the entry through mesh.serialize_collectives)"
                f" [{_key_prefix(handle)}]"))
        return out


class RetraceHazardDetector(Detector):
    """Steady-state recompile hazards, three ways:

    * the producer's trace accounting shows MORE compiles than distinct
      shape signatures served (a weak-type/dtype flip is retracing a
      plan the cache thinks it replays);
    * re-tracing at a producer-declared variant (second shape bucket,
      weak-type literal twin, wider Gramian) changes the structural
      jaxpr hash — the program specializes on shape/weak-type and will
      recompile per size in serving;
    * two cached entries in one cache whose producer-declared
      literal-erased keys (``meta["dedup_key"]``) collide — the same
      program cached once per literal VALUE, the classic
      literal-hoisting regression in ``ops/compiler.py`` (``price < 3``
      and ``price < 4`` must share one compiled program). Known
      limitation: CaseWhen branch literals are deliberately un-hoisted
      (constant-folding wins there), so intentional literal-variant
      CASE plans need a baseline entry.
    """

    name = "audit-retrace"
    description = ("structural jaxpr hash stable across shape-bucket/"
                   "weak-type re-traces; no excess observed traces; no"
                   " scalar consts in literal-hoisting plans")

    def check(self, handle, ctx: AuditContext):
        out = []
        closed = ctx.trace(handle)
        base_sig = JT.structural_signature(closed)
        ctx.stats_for(handle)["signature"] = base_sig[:16]
        exp = handle.meta.get("expected_traces")
        obs = handle.meta.get("observed_traces")
        if exp is not None and obs is not None and obs > exp:
            out.append(program_finding(
                self.name, handle,
                f"{obs} observed trace(s) for {exp} distinct shape"
                " signature(s) served — something beyond shape (weak"
                " types, dtype flips) is re-tracing this plan in steady"
                f" state [{_key_prefix(handle)}]"))
        for vname, spec in sorted(handle.variants.items()):
            # one (args, kwargs) pair → compare against the base trace;
            # a LIST of pairs → compare the fresh variant traces among
            # themselves. The list form is what real producers declare
            # (bucket x2 vs x4): jax serves the base avals from its
            # internal trace cache, which may predate a config flip
            # (e.g. the pallas dispatch mode) — two FRESH traces under
            # the current config are the apples-to-apples comparison.
            pairs = spec if isinstance(spec, list) else [spec]
            ref_sig, ref_name = base_sig, "base"
            for i, (vargs, vkwargs) in enumerate(pairs):
                try:
                    vjaxpr = JT.trace(handle.fn, vargs, vkwargs)
                except Exception as e:
                    out.append(program_finding(
                        self.name, handle,
                        f"re-trace at variant {vname!r} raised"
                        f" {type(e).__name__}: {e} — the plan cannot"
                        " serve its next shape bucket"
                        f" [{_key_prefix(handle)}]"))
                    break
                vsig = JT.structural_signature(vjaxpr)
                if len(pairs) > 1 and i == 0:
                    ref_sig, ref_name = vsig, f"{vname}[0]"
                    continue
                if vsig != ref_sig:
                    out.append(program_finding(
                        self.name, handle,
                        f"structural jaxpr hash changed between"
                        f" {ref_name} and variant {vname!r}"
                        f" ({ref_sig[:12]} -> {vsig[:12]}) — the"
                        " program specializes on shape/weak-type and"
                        " will retrace per bucket in serving"
                        f" [{_key_prefix(handle)}]"))
        return out

    def finalize(self, handles, ctx: AuditContext):
        """Literal-hoisting regression: group by the producer's
        literal-erased key — more than one cached program in a group
        means the cache compiles once per literal value."""
        groups: dict = {}
        for h in handles:
            dk = h.meta.get("dedup_key")
            if dk:
                groups.setdefault((h.cache, dk), []).append(h)
        out = []
        for (_cache, dk), members in sorted(groups.items()):
            if len(members) < 2:
                continue
            for h in members:
                out.append(program_finding(
                    self.name, h,
                    f"{len(members)} cached programs share one"
                    " literal-erased plan shape — the literal is in the"
                    " cache key instead of a hoisted runtime argument,"
                    " so every new literal value recompiles"
                    f" [{_key_prefix(h)}]"))
        return out


ALL_DETECTORS = (
    StaticMemoryDetector,
    HiddenSyncDetector,
    CollectiveTopologyDetector,
    RetraceHazardDetector,
)


def get_detectors(names=None):
    """Instantiate the requested detectors (all four by default)."""
    classes = ALL_DETECTORS
    if names:
        wanted = set(names)
        classes = [c for c in ALL_DETECTORS if c.name in wanted]
        unknown = wanted - {c.name for c in classes}
        if unknown:
            known = ", ".join(c.name for c in ALL_DETECTORS)
            raise ValueError(
                f"unknown detector(s) {sorted(unknown)}; known: {known}")
    return [c() for c in classes]
