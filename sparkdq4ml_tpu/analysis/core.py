"""dqlint framework core: shared parse, pragmas, baseline, rule driver.

Design constraints (the reasons this is not five ad-hoc scripts):

* **Single parse per file.** Five AST rules over ~30k lines must not
  cost five parses; :class:`SourceFile` parses once and every rule walks
  the same tree.
* **Reasoned suppression, never silent.** A finding is silenced either
  by an in-source pragma (visible at the site, carries its reason) or by
  a baseline entry (grandfathered debt, tracked in one reviewable file).
  Baseline entries that no longer match anything are reported as stale
  so the file can only shrink.
* **Line-drift-proof baseline.** Entries fingerprint the *stripped
  source line text*, not the line number — reformatting an unrelated
  region never resurrects grandfathered findings.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Iterable, Optional

#: Line pragma: ``# dqlint: ok(rule)`` or ``# dqlint: ok(rule): reason``
#: (several rules comma-separate: ``# dqlint: ok(host-sync, noop): ...``).
_PRAGMA_RE = re.compile(r"#\s*dqlint:\s*ok\(([^)]*)\)")
#: Module pragma — same syntax with ``ok-file``; applies to every line.
_FILE_PRAGMA_RE = re.compile(r"#\s*dqlint:\s*ok-file\(([^)]*)\)")

#: Package-root-relative directories every rule skips: the analyzers
#: must not lint their own rule sources (they embed offender-shaped
#: strings as documentation and detection patterns). Matched at the top
#: level only — a future engine subpackage that happens to be named
#: ``analysis`` deeper in the tree is still linted.
_SKIP_DIRS = ("analysis",)


@dataclasses.dataclass
class Finding:
    """One diagnostic: where, which invariant, what to do about it."""

    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str
    fingerprint: str = ""   # stripped source line (baseline identity)
    baselined: bool = False

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class SourceFile:
    """One parsed module: text, lines, AST, and its pragma index.

    Parsed exactly once; rules receive the same instance. A syntax error
    does not raise — it becomes a finding from every rule's driver pass
    (``parse_error``), because an unparseable engine file is itself a
    tree-health failure.
    """

    def __init__(self, path: str, rel: str, text: Optional[str] = None):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        if text is None:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:   # pragma: no cover - engine files parse
            self.parse_error = f"unparseable ({e.msg})"
        self.line_pragmas: dict[int, set[str]] = {}
        self.file_pragmas: set[str] = set()
        comment_pragmas: list[tuple[int, set[str]]] = []
        for i, line in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                names = {p.strip() for p in m.group(1).split(",") if p.strip()}
                self.line_pragmas.setdefault(i, set()).update(names)
                if line.strip().startswith("#"):
                    comment_pragmas.append((i, names))
            m = _FILE_PRAGMA_RE.search(line)
            if m:
                self.file_pragmas.update(
                    p.strip() for p in m.group(1).split(",") if p.strip())
        # A pragma on a comment-only line covers the whole statement it
        # precedes or sits inside (a same-line pragma covers only its own
        # line): collect statement spans once, then widen.
        if comment_pragmas and self.tree is not None:
            spans = [(n.lineno, n.end_lineno or n.lineno)
                     for n in ast.walk(self.tree)
                     if isinstance(n, ast.stmt)]
            for p, names in comment_pragmas:
                nxt = p + 1
                while nxt <= len(self.lines) and (
                        not self.lines[nxt - 1].strip()
                        or self.lines[nxt - 1].strip().startswith("#")):
                    nxt += 1
                covered: list[tuple[int, int]] = [
                    (a, b) for a, b in spans
                    if (a <= p <= b) or a == nxt]
                if covered:
                    # the smallest enclosing/following statement wins (a
                    # pragma inside a function must not blanket the whole
                    # function body)
                    a, b = min(covered, key=lambda s: s[1] - s[0])
                    for i in range(a, b + 1):
                        self.line_pragmas.setdefault(i, set()).update(names)

    # -- suppression --------------------------------------------------------
    def pragma_covers(self, rule: str, node: ast.AST) -> bool:
        """True when a ``dqlint: ok`` pragma for ``rule`` (or ``*``) sits on
        any line the node spans, or a file pragma covers the module."""
        if rule in self.file_pragmas or "*" in self.file_pragmas:
            return True
        start = getattr(node, "lineno", 0) or 0
        end = getattr(node, "end_lineno", start) or start
        for i in range(start, min(end, len(self.lines)) + 1):
            names = self.line_pragmas.get(i)
            if names and (rule in names or "*" in names):
                return True
        return False

    def finding(self, rule: str, node: ast.AST, message: str
                ) -> Optional[Finding]:
        """Build a finding at ``node`` unless a pragma suppresses it."""
        if self.pragma_covers(rule, node):
            return None
        line = getattr(node, "lineno", 0) or 0
        fp = self.lines[line - 1].strip() if 0 < line <= len(self.lines) \
            else ""
        return Finding(rule=rule, path=self.rel, line=line, message=message,
                       fingerprint=fp)


class Rule:
    """Base analyzer. ``visit`` runs once per file; ``finalize`` once per
    tree with every file already seen (for cross-file invariants like the
    conf-key registry and the lock graph)."""

    name = "rule"
    description = ""

    def visit(self, src: SourceFile) -> Iterable[Finding]:
        return ()

    def finalize(self, files: list[SourceFile]) -> Iterable[Finding]:
        return ()


class Baseline:
    """Grandfathered findings, keyed by (rule, path, stripped line text).

    JSON shape::

        {"entries": [{"rule": ..., "path": ..., "fingerprint": ...}, ...]}
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.entries: set[tuple[str, str, str]] = set()
        if path and os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            for e in doc.get("entries", []):
                self.entries.add((e["rule"], e["path"], e["fingerprint"]))

    def key(self, f: Finding) -> tuple[str, str, str]:
        return (f.rule, f.path, f.fingerprint)

    def apply(self, findings: list[Finding]) -> list[tuple[str, str, str]]:
        """Mark baselined findings; return entries that matched nothing
        (stale — candidates for deletion)."""
        used = set()
        for f in findings:
            k = self.key(f)
            if k in self.entries:
                f.baselined = True
                used.add(k)
        return sorted(self.entries - used)

    def write(self, findings: list[Finding]) -> None:
        doc = {"entries": [
            {"rule": f.rule, "path": f.path, "fingerprint": f.fingerprint}
            for f in sorted(findings, key=lambda f: (f.rule, f.path, f.line))
        ]}
        with open(self.path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")


def load_tree(root: str, package: str = "sparkdq4ml_tpu"
              ) -> list[SourceFile]:
    """Parse every ``*.py`` under ``root/package`` once (skipping the
    analyzer's own sources), sorted for deterministic output."""
    pkg = os.path.join(root, package)
    out: list[SourceFile] = []
    for dirpath, dirs, files in os.walk(pkg):
        dirs[:] = sorted(d for d in dirs
                         if d != "__pycache__"
                         and not (dirpath == pkg and d in _SKIP_DIRS))
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            out.append(SourceFile(path, os.path.relpath(path, root)))
    return out


def run_rules(root: str, rules: Iterable[Rule],
              baseline: Optional[Baseline] = None
              ) -> tuple[list[Finding], list[tuple[str, str, str]]]:
    """Drive ``rules`` over the tree at ``root``.

    Returns ``(findings, stale_baseline_entries)``; findings carry a
    ``baselined`` flag rather than being dropped, so callers can render
    the full picture and gate only on live ones.
    """
    files = load_tree(root)
    findings: list[Finding] = []
    rules = list(rules)
    for src in files:
        if src.parse_error:
            findings.append(Finding(rule="parse", path=src.rel, line=0,
                                    message=src.parse_error))
            continue
        for rule in rules:
            findings.extend(f for f in rule.visit(src) if f is not None)
    for rule in rules:
        findings.extend(f for f in rule.finalize(files) if f is not None)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    stale = baseline.apply(findings) if baseline else []
    return findings, stale


# ---------------------------------------------------------------------------
# Shared AST helpers (used by several rules)
# ---------------------------------------------------------------------------

def attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted name of an attribute/name chain (``a.b.c``) or None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> str:
    """Rightmost name of the called object (``x.y.z(...)`` → ``z``)."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def walk_functions(tree: ast.AST):
    """Yield every (outermost_function, all_nodes_in_it) pair plus the
    module-level remainder as ``(None, nodes)``. Nested defs/lambdas are
    folded into their outermost function — the attribution scope for
    "does this factory guard its dispatch" style questions."""
    outer: list[ast.AST] = []
    module_nodes: list[ast.AST] = []

    def top(node, in_func):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and not in_func:
                outer.append(child)
                top(child, True)
            else:
                if not in_func:
                    module_nodes.append(child)
                top(child, in_func)

    top(tree, False)
    for fn in outer:
        yield fn, list(ast.walk(fn))
    yield None, module_nodes
