"""TpuSession — the ``SparkSession`` equivalent.

Covers the session surface the reference exercises
(`DataQuality4MachineLearningApp.java:38-49`): builder with
``appName``/``master``/``getOrCreate``, the UDF registry, the reader, SQL over
temp views, and — the TPU-native part — the device mesh that replaces Spark's
executor pool (SURVEY.md §3.1). There is no session daemon: "starting" a
session is discovering devices and building a ``jax.sharding.Mesh``.

Threading model (session vs server)
-----------------------------------

* The **session is a process singleton** (Spark ``getOrCreate``
  semantics). ``builder().get_or_create()`` is thread-safe — a
  double-checked lock (:data:`_ACTIVE_LOCK`) guarantees racing threads
  get ONE session object, never two half-initialized ones.
* **Frames and queries are safe to share across threads**: frame flushes
  serialize on the pipeline flush lock, the plan/jit caches and metric
  registries are lock-protected, and grouped execution serializes its
  device path. Concurrent ``session.sql`` calls against the SAME catalog
  are safe for reads; concurrent DDL (``CREATE VIEW``) on one catalog
  last-writer-wins like Spark temp views.
* **Multi-tenant concurrency belongs to the serving layer**:
  :meth:`TpuSession.serve` returns the process :class:`~sparkdq4ml_tpu.
  serve.QueryServer`, which gives each tenant its own temp-view catalog,
  admission control, and SLO metrics over the shared engine. Prefer it
  over hand-rolled threads when callers are independent workloads.
* **Conf mutation is session-scoped and lock-protected**: the
  ``_init_pipeline`` save/restore of process config
  (:data:`_CONF_LOCK`) cannot interleave with a concurrent ``stop()``
  restoring it. ``stop()`` drains the serving layer FIRST, so in-flight
  served queries never observe a half-restored config.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

import jax

from .frame.csv import DataFrameReader
from .ops.rules import register_builtin_rules
from .ops.udf import UDFRegistry, default_registry
from .parallel.mesh import make_mesh, parse_master
from .sql.catalog import Catalog, default_catalog
from .sql.parser import execute as _sql_execute

logger = logging.getLogger("sparkdq4ml_tpu.session")

_ACTIVE: Optional["TpuSession"] = None
#: Guards the active-session singleton (builder/get_or_create/stop): the
#: double-checked lock behind Spark's one-session-per-process contract.
_ACTIVE_LOCK = threading.Lock()
#: Guards the session-scoped config save/restore (_init_pipeline/stop):
#: a builder re-init on one thread and a stop() on another must not
#: interleave their read-modify-write of the process config.
_CONF_LOCK = threading.Lock()

#: Conf boolean spellings (session-scoped keys) — the shared vocabulary
#: from config.py, so spark.serve.enabled=no and the serve layer's own
#: parser can never disagree.
from .config import CONF_FALSE as _CONF_FALSE  # noqa: E402
from .config import CONF_TRUE as _CONF_TRUE  # noqa: E402


def host_cache_tag() -> str:
    """Short fingerprint keying the persistent XLA cache dir: host CPU
    feature set (x86 exposes a ``flags`` line in /proc/cpuinfo, ARM a
    ``Features`` line; fall back to the processor string) **plus the
    jax/jaxlib versions**. XLA:CPU AOT entries embed the *compile-time*
    target-feature string, which carries XLA/LLVM-internal flags (e.g.
    ``+prefer-no-scatter``) that no cpuinfo hash can see but that change
    with the jaxlib build — so the version pair must be part of the key
    or a jaxlib upgrade serves feature-mismatched binaries (error spam
    today, SIGILL one skew away; VERDICT r4 item 4)."""
    import hashlib
    import platform

    import jax
    import jaxlib

    try:
        with open("/proc/cpuinfo") as f:
            feat = next((ln for ln in f
                         if ln.startswith(("flags", "Features"))), "")
    except OSError:
        feat = platform.processor()
    return hashlib.sha1(
        (platform.machine() + feat + jax.__version__
         + jaxlib.__version__).encode()).hexdigest()[:8]


def _validate_cache_dir(cache_dir: str, tag: str) -> None:
    """Stamp ``cache_dir`` with the host tag and invalidate foreign
    entries (the load-side guard VERDICT r4 item 4 asks for): a dir whose
    stamp mismatches — or a non-empty dir with no stamp at all, i.e.
    entries of unverifiable provenance, which is exactly what produced
    round 4's ``cpu_aot_loader`` error spam — gets its entry files
    removed before XLA ever reloads one. Best-effort: cache hygiene must
    never take a session down.

    Only files that LOOK like XLA cache entries (``jit_*`` / ``pjit_*`` /
    ``*-cache``) are ever deleted — a user can point
    ``spark.compilation.cacheDir`` at a directory that holds other files,
    and provenance hygiene must not become data loss there."""
    import json

    def _is_cache_entry(name: str) -> bool:
        return (name.startswith(("jit_", "pjit_"))
                or name.endswith("-cache"))

    stamp_path = os.path.join(cache_dir, "host_key.json")
    try:
        entries = [n for n in os.listdir(cache_dir)
                   if n != "host_key.json" and _is_cache_entry(n)]
        stale = False
        try:
            with open(stamp_path) as f:
                stale = json.load(f).get("tag") != tag
        except FileNotFoundError:
            stale = bool(entries)     # unstamped + non-empty: can't trust
        except Exception:
            stale = True              # unreadable stamp: can't trust
        if stale:
            removed = 0
            for name in entries:
                p = os.path.join(cache_dir, name)
                if os.path.isfile(p):
                    os.remove(p)
                    removed += 1
            logger.warning(
                "compilation cache %s was written by a different "
                "host/jaxlib (or has no provenance stamp); invalidated "
                "%d entr%s to avoid AOT feature-mismatched binaries",
                cache_dir, removed, "y" if removed == 1 else "ies")
        tmp = f"{stamp_path}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"tag": tag}, f)
        os.replace(tmp, stamp_path)
    except Exception as e:
        logger.debug("cache-dir validation skipped: %s", e)


def _prune_stale_cache_dirs(base: str, keep: str,
                            max_age_days: float = 30.0) -> None:
    """Best-effort cleanup of orphaned host-tag cache dirs (a kernel or VM
    migration that changes one cpuinfo flag re-keys the dir; the old ones
    would otherwise accumulate forever). Only dirs matching our own
    ``xla*`` naming under ``base`` are touched, and only when untouched
    for ``max_age_days``."""
    import glob
    import shutil
    import time

    cutoff = time.time() - max_age_days * 86400.0
    try:
        for p in glob.glob(os.path.join(base, "xla*")):
            if p != keep and os.path.isdir(p) and os.path.getmtime(p) < cutoff:
                shutil.rmtree(p, ignore_errors=True)
    except Exception:
        pass


class TpuSession:
    """Entry point: device mesh + catalog + UDF registry + reader."""

    def __init__(self, app_name: str = "sparkdq4ml-tpu",
                 master: Optional[str] = None,
                 conf: Optional[dict] = None,
                 register_rules: bool = False):
        self.app_name = app_name
        self.master = master
        self.conf: dict[str, str] = dict(conf or {})
        self._init_faults()
        self._ensure_backend()
        self._init_distributed()
        n = parse_master(master)
        self.mesh = make_mesh(n)
        # Chaos hook: a scheduled ``mesh:device_drop`` spec shrinks the
        # session mesh — the lost-worker scenario, exercised end-to-end by
        # the resilience suite. No-op without an active fault plan.
        from .utils import faults as _faults

        self.mesh = _faults.degrade_mesh("mesh", self.mesh)
        self.catalog: Catalog = default_catalog()
        self.udf: UDFRegistry = default_registry()
        if register_rules:
            register_builtin_rules(self.udf)
        self._init_compilation_cache()
        self._init_observability()
        self._init_pipeline()
        logger.debug("session %r: %d device(s), platform=%s", app_name,
                     self.num_devices, jax.devices()[0].platform)

    def _init_pipeline(self) -> None:
        """Configure the fused expression-pipeline compiler
        (``ops/compiler.py``) from session conf — ON by default:

            .config("spark.pipeline.enabled", "false")   # exact eager path
            .config("spark.pipeline.minBucket", 8)       # padding floor
            .config("spark.pipeline.cacheSize", 256)     # plan-key LRU

        Flipping ``enabled`` also clears the plan-keyed jit cache so a
        disable→enable cycle never serves plans compiled under different
        bucket settings. Settings this session changes are remembered
        and restored by :meth:`stop` — pipeline conf is session-scoped
        like the fault plan, never a process-wide leak."""
        from .config import config as _cfg
        from .ops import compiler as _compiler

        with _CONF_LOCK:
            saved = getattr(self, "_pipeline_saved", None) or {}

            def _set(attr, value):
                saved.setdefault(attr, getattr(_cfg, attr))
                setattr(_cfg, attr, value)

            val = str(self.conf.get("spark.pipeline.enabled", "")).lower()
            if val in _CONF_FALSE:
                _set("pipeline", False)
                _compiler.clear_cache()
            elif val in _CONF_TRUE:
                _set("pipeline", True)
            if "spark.pipeline.minBucket" in self.conf:
                _set("pipeline_min_bucket",
                     int(self.conf["spark.pipeline.minBucket"]))
                _compiler.clear_cache()
            if "spark.pipeline.cacheSize" in self.conf:
                _set("pipeline_cache_size",
                     int(self.conf["spark.pipeline.cacheSize"]))
            # Device-resident grouped execution (ops/segments.py) rides the
            # same session-scoped save/restore:
            #     .config("spark.groupedExec.enabled", "false") # host groupBy
            gval = str(self.conf.get("spark.groupedExec.enabled", "")).lower()
            if gval in _CONF_FALSE:
                from .ops import segments as _segments

                _set("grouped_exec", False)
                _segments.clear_cache()
            elif gval in _CONF_TRUE:
                _set("grouped_exec", True)
            # EXPLAIN ANALYZE knobs (sql/parser.py) and the serving-layer
            # gate (serve/) ride the same session-scoped save/restore:
            #     .config("spark.explain.memory", "false")  # no mem sampling
            #     .config("spark.explain.caches", "false")  # no cache section
            #     .config("spark.serve.enabled", "false")   # serve() refuses
            for conf_key, attr in (
                    ("spark.explain.memory", "explain_memory"),
                    ("spark.explain.caches", "explain_caches"),
                    ("spark.serve.enabled", "serve_enabled"),
                    ("spark.audit.enabled", "audit_enabled"),
                    ("spark.ingest.streaming", "ingest_streaming")):
                v = str(self.conf.get(conf_key, "")).lower()
                if v in _CONF_FALSE:
                    _set(attr, False)
                elif v in _CONF_TRUE:
                    _set(attr, True)
            # Network serving front end (serve/net.py + serve/client.py),
            # session-scoped like everything above:
            #     .config("spark.serve.net.enabled", "true")  # socket on
            #     .config("spark.serve.net.port", 8765)       # 0=ephemeral
            #     .config("spark.serve.net.host", "0.0.0.0")  # widen bind
            #     .config("spark.serve.net.connTimeoutMs", 5000)
            #     .config("spark.serve.net.maxFrameBytes", 1 << 20)
            #     .config("spark.serve.net.streamPageRows", 1024)
            #     .config("spark.serve.client.retries", 5)
            #     .config("spark.serve.client.backoffMs", 25)
            #     .config("spark.serve.client.hedging", "true")
            nval = str(self.conf.get("spark.serve.net.enabled",
                                     "")).lower()
            if nval in _CONF_FALSE:
                _set("serve_net_enabled", False)
            elif nval in _CONF_TRUE:
                _set("serve_net_enabled", True)
            if "spark.serve.net.port" in self.conf:
                _set("serve_net_port",
                     int(self.conf["spark.serve.net.port"]))
            if "spark.serve.net.host" in self.conf:
                _set("serve_net_host",
                     str(self.conf["spark.serve.net.host"]))
            if "spark.serve.net.backlog" in self.conf:
                _set("serve_net_backlog",
                     int(self.conf["spark.serve.net.backlog"]))
            if "spark.serve.net.connTimeoutMs" in self.conf:
                _set("serve_net_conn_timeout_ms",
                     int(self.conf["spark.serve.net.connTimeoutMs"]))
            if "spark.serve.net.maxFrameBytes" in self.conf:
                _set("serve_net_max_frame_bytes",
                     int(self.conf["spark.serve.net.maxFrameBytes"]))
            if "spark.serve.net.streamPageRows" in self.conf:
                _set("serve_net_stream_page_rows",
                     int(self.conf["spark.serve.net.streamPageRows"]))
            if "spark.serve.client.retries" in self.conf:
                _set("serve_client_retries",
                     int(self.conf["spark.serve.client.retries"]))
            if "spark.serve.client.backoffMs" in self.conf:
                _set("serve_client_backoff_ms",
                     float(self.conf["spark.serve.client.backoffMs"]))
            hval = str(self.conf.get("spark.serve.client.hedging",
                                     "")).lower()
            if hval in _CONF_FALSE:
                _set("serve_client_hedging", False)
            elif hval in _CONF_TRUE:
                _set("serve_client_hedging", True)
            # Cross-request plan coalescing (serve/coalesce.py),
            # session-scoped like the net front end above:
            #     .config("spark.serve.coalesce.enabled", "true")
            #     .config("spark.serve.coalesce.maxDelayMs", 2)
            #     .config("spark.serve.coalesce.maxBatch", 8)
            #     .config("spark.serve.coalesce.minQueueDepth", 2)
            coval = str(self.conf.get("spark.serve.coalesce.enabled",
                                      "")).lower()
            if coval in _CONF_FALSE:
                _set("serve_coalesce_enabled", False)
            elif coval in _CONF_TRUE:
                _set("serve_coalesce_enabled", True)
            if "spark.serve.coalesce.maxDelayMs" in self.conf:
                _set("serve_coalesce_max_delay_ms",
                     float(self.conf["spark.serve.coalesce.maxDelayMs"]))
            if "spark.serve.coalesce.maxBatch" in self.conf:
                _set("serve_coalesce_max_batch",
                     int(self.conf["spark.serve.coalesce.maxBatch"]))
            if "spark.serve.coalesce.minQueueDepth" in self.conf:
                _set("serve_coalesce_min_queue_depth",
                     int(self.conf["spark.serve.coalesce.minQueueDepth"]))
            # dqaudit thresholds (analysis/program/), session-scoped like
            # everything above:
            #     .config("spark.audit.enabled", "false")  # no est peak
            #     .config("spark.audit.memoryFraction", 0.8)
            #     .config("spark.audit.deviceBudget", 8 << 30)  # bytes
            #     .config("spark.audit.constBytes", 65536)
            if "spark.audit.memoryFraction" in self.conf:
                _set("audit_memory_fraction",
                     float(self.conf["spark.audit.memoryFraction"]))
            if "spark.audit.deviceBudget" in self.conf:
                _set("audit_device_budget",
                     int(self.conf["spark.audit.deviceBudget"]))
            if "spark.audit.constBytes" in self.conf:
                _set("audit_const_bytes",
                     int(self.conf["spark.audit.constBytes"]))
            # Streaming-ingest tuning (frame/native_csv.py), session-scoped
            # like everything above:
            #     .config("spark.ingest.streaming", "false") # legacy one-shot
            #     .config("spark.ingest.threads", 4)         # parse threads
            #     .config("spark.ingest.chunkBytes", 1 << 20) # chunk bound
            #     .config("spark.ingest.prefetch", 2)        # queue depth
            #     .config("spark.ingest.simd", "off")        # scalar tier
            if "spark.ingest.threads" in self.conf:
                _set("ingest_threads", int(self.conf["spark.ingest.threads"]))
            if "spark.ingest.chunkBytes" in self.conf:
                _set("ingest_chunk_bytes",
                     int(self.conf["spark.ingest.chunkBytes"]))
            if "spark.ingest.prefetch" in self.conf:
                _set("ingest_prefetch",
                     int(self.conf["spark.ingest.prefetch"]))
            if "spark.ingest.simd" in self.conf:
                _set("ingest_simd",
                     str(self.conf["spark.ingest.simd"]).lower())
            # Chaos-soak defaults (scripts/chaos_soak.py), session-scoped
            # like everything above:
            #     .config("spark.chaos.seed", 7)        # schedule base
            #     .config("spark.chaos.seeds", 50)      # seeds to sweep
            #     .config("spark.chaos.soakSeconds", 30) # per-seed floor
            if "spark.chaos.seed" in self.conf:
                _set("chaos_seed", int(self.conf["spark.chaos.seed"]))
            if "spark.chaos.seeds" in self.conf:
                _set("chaos_seeds", int(self.conf["spark.chaos.seeds"]))
            if "spark.chaos.soakSeconds" in self.conf:
                _set("chaos_soak_s",
                     float(self.conf["spark.chaos.soakSeconds"]))
            # Cost-based plan optimizer (sql/optimizer.py), session-scoped
            # like everything above:
            #     .config("spark.optimizer.enabled", "false") # literal plans
            #     .config("spark.optimizer.level", 2)  # + reorder/split
            oval = str(self.conf.get("spark.optimizer.enabled",
                                     "")).lower()
            if oval in _CONF_FALSE:
                _set("optimizer_enabled", False)
            elif oval in _CONF_TRUE:
                _set("optimizer_enabled", True)
            if "spark.optimizer.level" in self.conf:
                _set("optimizer_level",
                     int(self.conf["spark.optimizer.level"]))
            # Adaptive query execution (sql/adaptive.py), session-scoped
            # like everything above:
            #     .config("spark.aqe.enabled", "false")  # static plans
            #     .config("spark.aqe.driftFactor", 8.0)  # replan trigger
            #     .config("spark.aqe.broadcastThreshold", 1 << 20)
            #     .config("spark.aqe.skewFactor", 2.0)   # split trigger
            aval = str(self.conf.get("spark.aqe.enabled", "")).lower()
            if aval in _CONF_FALSE:
                _set("aqe_enabled", False)
            elif aval in _CONF_TRUE:
                _set("aqe_enabled", True)
            if "spark.aqe.driftFactor" in self.conf:
                _set("aqe_drift_factor",
                     float(self.conf["spark.aqe.driftFactor"]))
            if "spark.aqe.broadcastThreshold" in self.conf:
                _set("aqe_broadcast_threshold",
                     int(self.conf["spark.aqe.broadcastThreshold"]))
            if "spark.aqe.skewFactor" in self.conf:
                _set("aqe_skew_factor",
                     float(self.conf["spark.aqe.skewFactor"]))
            # Plan-stats observatory (utils/statstore.py), session-scoped
            # like everything above:
            #     .config("spark.stats.enabled", "false")   # hooks no-op
            #     .config("spark.stats.path", "/x/stats.jsonl")  # persist
            #     .config("spark.stats.maxEntries", 1024)   # entry bound
            #     .config("spark.stats.flushOnStop", "false")
            sval = str(self.conf.get("spark.stats.enabled", "")).lower()
            if sval in _CONF_FALSE:
                _set("stats_enabled", False)
            elif sval in _CONF_TRUE:
                _set("stats_enabled", True)
            if "spark.stats.path" in self.conf:
                _set("stats_path", str(self.conf["spark.stats.path"]))
            if "spark.stats.maxEntries" in self.conf:
                _set("stats_max_entries",
                     int(self.conf["spark.stats.maxEntries"]))
            fval = str(self.conf.get("spark.stats.flushOnStop", "")).lower()
            if fval in _CONF_FALSE:
                _set("stats_flush_on_stop", False)
            elif fval in _CONF_TRUE:
                _set("stats_flush_on_stop", True)
            # Row-sharded frames (parallel/shard.py), session-scoped
            # like everything above:
            #     .config("spark.shard.enabled", "true")  # shard frames
            #     .config("spark.shard.minRows", 65536)   # host fallback
            #     .config("spark.shard.devices", 4)       # mesh cap
            shval = str(self.conf.get("spark.shard.enabled", "")).lower()
            if shval in _CONF_FALSE:
                _set("shard_enabled", False)
            elif shval in _CONF_TRUE:
                _set("shard_enabled", True)
            if "spark.shard.minRows" in self.conf:
                _set("shard_min_rows",
                     int(self.conf["spark.shard.minRows"]))
            if "spark.shard.devices" in self.conf:
                _set("shard_devices",
                     int(self.conf["spark.shard.devices"]))
            # Device-cost observatory (utils/costprof.py), session-scoped
            # like everything above:
            #     .config("spark.costprof.enabled", "false") # no profiles
            #     .config("spark.costprof.ridge", 12.0)  # flops/byte
            #     .config("spark.profiling.maxCaptures", 8)
            cval = str(self.conf.get("spark.costprof.enabled",
                                     "")).lower()
            if cval in _CONF_FALSE:
                _set("costprof_enabled", False)
            elif cval in _CONF_TRUE:
                _set("costprof_enabled", True)
            if "spark.costprof.ridge" in self.conf:
                _set("costprof_ridge",
                     float(self.conf["spark.costprof.ridge"]))
            if "spark.profiling.maxCaptures" in self.conf:
                _set("profiling_max_captures",
                     int(self.conf["spark.profiling.maxCaptures"]))
            # Tail sampler + incident flight recorder (utils/observability
            # .py, utils/incidents.py), session-scoped like everything
            # above:
            #     .config("spark.trace.ringSize", 256)     # recent trees
            #     .config("spark.trace.retainedSize", 64)  # kept trees
            #     .config("spark.trace.exemplars", "true") # /metrics ids
            #     .config("spark.incident.enabled", "true")
            #     .config("spark.incident.dir", "/x/incidents")
            #     .config("spark.incident.maxBundles", 32)
            #     .config("spark.incident.cooldownS", 5.0)
            #     .config("spark.incident.sloBurnThreshold", 8.0)
            if "spark.trace.ringSize" in self.conf:
                _set("trace_ring_size",
                     int(self.conf["spark.trace.ringSize"]))
            if "spark.trace.retainedSize" in self.conf:
                _set("trace_retained_size",
                     int(self.conf["spark.trace.retainedSize"]))
            xval = str(self.conf.get("spark.trace.exemplars",
                                     "")).lower()
            if xval in _CONF_FALSE:
                _set("trace_exemplars", False)
            elif xval in _CONF_TRUE:
                _set("trace_exemplars", True)
            ival = str(self.conf.get("spark.incident.enabled",
                                     "")).lower()
            if ival in _CONF_FALSE:
                _set("incident_enabled", False)
            elif ival in _CONF_TRUE:
                _set("incident_enabled", True)
            if "spark.incident.dir" in self.conf:
                _set("incident_dir",
                     str(self.conf["spark.incident.dir"]))
            if "spark.incident.maxBundles" in self.conf:
                _set("incident_max_bundles",
                     int(self.conf["spark.incident.maxBundles"]))
            if "spark.incident.cooldownS" in self.conf:
                _set("incident_cooldown_s",
                     float(self.conf["spark.incident.cooldownS"]))
            if "spark.incident.sloBurnThreshold" in self.conf:
                _set("incident_slo_burn_threshold",
                     float(self.conf["spark.incident.sloBurnThreshold"]))
            # Data-quality observatory (utils/dqprof.py), session-scoped
            # like everything above:
            #     .config("spark.dq.profile.enabled", "false")
            #     .config("spark.dq.histogramBins", 32)
            #     .config("spark.dq.driftThreshold", 0.25)
            #     .config("spark.dq.baselineMode", "persisted")
            dval = str(self.conf.get("spark.dq.profile.enabled",
                                     "")).lower()
            if dval in _CONF_FALSE:
                _set("dq_profile_enabled", False)
            elif dval in _CONF_TRUE:
                _set("dq_profile_enabled", True)
            if "spark.dq.histogramBins" in self.conf:
                _set("dq_histogram_bins",
                     int(self.conf["spark.dq.histogramBins"]))
            if "spark.dq.driftThreshold" in self.conf:
                _set("dq_drift_threshold",
                     float(self.conf["spark.dq.driftThreshold"]))
            if "spark.dq.baselineMode" in self.conf:
                _set("dq_baseline_mode",
                     str(self.conf["spark.dq.baselineMode"]))
            if saved:
                self._pipeline_saved = saved
        # Install the shard context over THIS session's mesh (outside
        # _CONF_LOCK — mesh construction never holds the conf lock;
        # stop() tears it down via shard.reset()). The enabled flag
        # gates every read, so configuring with sharding off costs
        # nothing.
        from .parallel import shard as _shard_mod

        _shard_mod.configure(self.mesh)
        # Adopt persisted plan-statistics history (outside _CONF_LOCK —
        # file I/O never holds the conf lock). Merge is winner-per-key,
        # so a builder re-init re-loading the same snapshot is a no-op.
        from .config import config as _cfg2

        if _cfg2.stats_enabled and _cfg2.stats_path:
            from .utils import statstore as _statstore

            _statstore.STORE.load(_cfg2.stats_path)
        # Apply the (possibly just-overridden) trace/incident bounds to
        # the process-global tail sampler and flight recorder (outside
        # _CONF_LOCK — both take only their own locks).
        from .utils import incidents as _incidents
        from .utils import observability as _obs3

        _obs3.TAIL.configure(ring_size=_cfg2.trace_ring_size,
                             retained_size=_cfg2.trace_retained_size)
        _incidents.RECORDER.configure(
            enabled=_cfg2.incident_enabled,
            directory=_cfg2.incident_dir,
            max_bundles=_cfg2.incident_max_bundles,
            cooldown_s=_cfg2.incident_cooldown_s,
            slo_burn_threshold=_cfg2.incident_slo_burn_threshold)

    def _init_observability(self) -> None:
        """Install the tracing/metrics subsystem (``utils.observability``)
        from session conf or environment — off by default (the hot fused
        paths keep their zero-host-sync contract):

            .config("spark.observability.enabled", "true")
            .config("spark.observability.maxSpans", 50000)
            .config("spark.observability.logSpans", "true")   # logfmt lines

        or ``SPARKDQ4ML_OBS=1`` in the environment. When enabled, a root
        ``session`` span is opened (ended by ``stop()``); everything the
        session touches — SQL queries, frame ops, fits, solver blocks,
        sharded Gramians — nests under it. Read back via
        :meth:`metrics`, :meth:`trace_report`, :meth:`dump_trace`."""
        from .utils import observability as _obs

        conf_val = str(self.conf.get("spark.observability.enabled",
                                     "")).lower()
        # same truthiness vocabulary as the conf key — "SPARKDQ4ML_OBS=off"
        # must not ENABLE tracing
        env_on = os.environ.get(_obs.ENV_VAR, "").strip().lower() not in (
            ("",) + _CONF_FALSE)
        if conf_val in _CONF_TRUE or (conf_val == "" and env_on):
            _obs.enable(
                max_spans=int(self.conf.get("spark.observability.maxSpans",
                                            10_000)),
                log_spans=str(self.conf.get("spark.observability.logSpans",
                                            "")).lower() in _CONF_TRUE)
            self._obs_enabled_here = True
            if getattr(self, "_session_span", None) is None:
                self._session_span = _obs.TRACER.begin(
                    "session", cat="session", app=self.app_name,
                    devices=self.num_devices,
                    platform=jax.devices()[0].platform)
        elif conf_val in _CONF_FALSE:
            # explicit opt-out wins over a programmatic/env enable — the
            # same session-scoped-override rule as spark.compilation.cache
            _obs.disable()

    # -- observability surface ---------------------------------------------
    def metrics(self) -> dict:
        """One merged metrics snapshot: every monotonic counter (solver
        fits/iterations, jit trace hits/misses, ``recovery.*`` from the
        resilience layer, collective dispatch counts), every gauge
        (``mesh.devices``), and every latency histogram
        (``span_ms.<category>``) — flat by name."""
        from .utils import observability as _obs

        return _obs.metrics_snapshot()

    def metrics_text(self) -> str:
        """Prometheus text-format rendering of :meth:`metrics` (counters,
        gauges, and cumulative-bucket histograms), scrape-ready."""
        from .utils import observability as _obs

        return _obs.prometheus_text()

    def trace_report(self) -> str:
        """Human-readable span tree of everything traced so far (empty
        string when observability was never enabled)."""
        from .utils import observability as _obs

        return _obs.trace_report()

    def dump_trace(self, path: str) -> str:
        """Write the Chrome trace-event JSON (Perfetto /
        ``chrome://tracing`` loadable) to ``path``; returns the path."""
        from .utils import observability as _obs

        return _obs.dump_chrome_trace(path)

    def incident_report(self) -> dict:
        """Flight-recorder view: recorder state (dir, disk-ladder rung,
        bundle counts), the bounded incident index (id, trigger, time,
        joining trace id), and the tail sampler's retention counters.
        Full bundles come from ``utils.incidents.RECORDER.get(id)`` or
        the telemetry server's ``/incidents/<id>`` route."""
        from .utils import incidents as _incidents
        from .utils import observability as _obs

        doc = _incidents.RECORDER.report()
        doc["incidents"] = _incidents.RECORDER.list()
        doc["tail"] = _obs.TAIL.report()
        return doc

    def memory_report(self, top: int = 5) -> dict:
        """Device-memory accounting snapshot (``utils.meminfo``): live/
        peak bytes, live-array census by dtype, the ``top`` largest
        buffers, and per-device allocator stats where the backend exposes
        them. Host-side metadata only — never a device sync."""
        from .utils import meminfo as _meminfo

        return _meminfo.memory_report(top=top)

    def cache_report(self) -> dict:
        """Unified jit-cache introspection (``observability.CACHES``):
        per-cache size/hits/misses/evictions and per-entry detail for the
        pipeline compiler, the grouped-execution engine, the solver jit
        entry points, and the packed-fit factories."""
        from .utils import observability as _obs

        return _obs.cache_report()

    def audit_report(self) -> dict:
        """dqaudit over every cached program of this process
        (``analysis/program``): the four jaxpr-level detectors —
        static-memory bound, hidden-sync (callback/const capture),
        collective-topology, retrace-hazard — run by abstract evaluation
        (zero compiles, zero device execution, zero counted host syncs).
        Returns findings + per-program facts (``est_peak_bytes``,
        structural signature, collective/callback counts). Strictly
        on-demand: the audit package imports only when this is called.
        ``spark.audit.enabled=false`` makes it refuse."""
        from .config import config as _cfg

        if not _cfg.audit_enabled:
            return {"enabled": False, "clean": None, "findings": [],
                    "programs": 0}
        from .analysis.program import audit_report as _audit_report

        doc = _audit_report()
        doc["enabled"] = True
        return doc

    def stats_report(self) -> dict:
        """The plan-statistics observatory view (``utils.statstore``):
        one row per structural plan key — observed selectivity,
        wall/compile-ms digest summaries, host syncs, est/measured peak
        bytes — accumulated across every flush of this process PLUS any
        history loaded from ``spark.stats.path``. This is the memory the
        EXPLAIN ``est rows`` column and (ROADMAP item 4) the cost-based
        optimizer read. Draining the deferred selectivity scalars costs
        one counted batched device pull. ``spark.stats.enabled=false``
        makes it refuse."""
        from .config import config as _cfg

        if not _cfg.stats_enabled:
            return {"enabled": False, "entries": [], "size": 0}
        from .utils import statstore as _statstore

        doc = _statstore.STORE.report()
        doc["enabled"] = True
        doc["path"] = _cfg.stats_path or None
        return doc

    def profile_report(self, top: Optional[int] = None) -> dict:
        """The device-cost observatory's fleet-wide roofline table
        (``utils.costprof``): one row per registry-enumerable cached
        program — AOT-extracted flops/bytes/collective traffic, the
        statstore-joined achieved GFLOP/s / GB/s, and the roofline
        ``bound`` verdict — ranked by device-time share. COLD surface:
        a first call may pay bounded lower+compile extractions (zero
        device execution, zero counted host syncs/compiles) and one
        counted statstore drain. ``spark.costprof.enabled=false`` makes
        it refuse. Achieved numbers are structural on the CPU sandbox
        and meaningful on TPU captures (README "Device-cost
        observatory")."""
        from .config import config as _cfg

        if not _cfg.costprof_enabled:
            return {"enabled": False, "entries": [], "size": 0,
                    "pending": 0}
        from .utils import costprof as _costprof

        return _costprof.report(top=top)

    def dq_report(self, top: Optional[int] = None) -> dict:
        """The data-quality observatory view (``utils.dqprof``): one
        row per profiled column — count/null/min/max/mean/variance
        sketch fields, fixed-bucket histogram, PSI drift vs the pinned
        baseline — plus per-rule violation tallies and rates. COLD
        surface: pays the module's one counted deferred-sketch drain
        (``dq.drain_sync``). ``spark.dq.profile.enabled=false`` makes
        it refuse (README "Data-quality observatory")."""
        from .config import config as _cfg

        if not _cfg.dq_profile_enabled:
            return {"enabled": False, "columns": [], "rules": [],
                    "size": 0, "pending": 0}
        from .utils import dqprof as _dqprof

        return _dqprof.report(top=top)

    def _init_faults(self) -> None:
        """Install the fault-injection plan (``utils.faults``) from session
        conf or environment — chaos-in-production is opt-in and explicit:

            .config("spark.faults", "gram_sharded:device_error:1")
            .config("spark.faults.seed", 7)

        or ``SPARKDQ4ML_FAULTS`` in the environment. The recovery policy
        the injected failures exercise is likewise conf-driven
        (``spark.recovery.maxAttempts``, ``.backoffBase``, ``.backoffMax``,
        ``.backoffFactor``, ``.jitter``, ``.attemptDeadline``,
        ``.totalDeadline``, ``.validate`` — see
        ``utils.recovery.RetryPolicy.from_conf``). With neither conf key
        nor env var set this is a no-op and leaves any programmatically
        installed plan alone."""
        from .utils import faults as _faults

        seed = int(self.conf.get("spark.faults.seed", 0))
        spec = self.conf.get("spark.faults")
        if spec:
            # remembered so stop() can uninstall: chaos configured on one
            # session must never leak into the next one
            self._fault_plan = _faults.install_plan(
                _faults.parse_plan(spec, seed=seed))
        elif os.environ.get(_faults.ENV_VAR):
            self._fault_plan = _faults.install_from_env(seed=seed)

    def _is_multihost(self) -> bool:
        """Single predicate for "this session bootstraps a multi-host
        runtime" — shared by the probe skip and ``_init_distributed`` so
        the two can never disagree (a rank that probe-falls-back to CPU
        while its peers claim accelerators would desync the mesh)."""
        return (self.master or "").strip().lower() in ("pod", "pod[*]") or \
            bool(self.conf.get("spark.distributed.coordinator"))

    def _ensure_backend(self) -> None:
        """Session init must come up even when the device tunnel is wedged
        (`DataQuality4MachineLearningApp.java:38-41` always succeeds): probe
        the backend in a subprocess and pin this process to CPU on failure
        instead of hanging forever in PJRT init. Opt out (e.g. multi-host
        pods, where every process MUST claim its accelerator) with
        ``.config("spark.backend.probe", "off")``; tune the probe window
        with ``.config("spark.backend.probeTimeout", seconds)``."""
        if str(self.conf.get("spark.backend.probe", "on")).lower() \
                in _CONF_FALSE:
            return
        if self._is_multihost():
            return  # multi-host bootstrap: CPU fallback would desync ranks
        from .utils import debug as _debug

        timeout = float(self.conf.get("spark.backend.probeTimeout", 150))
        if (self.master or "").strip().lower().startswith("tpu"):
            # The user explicitly demanded the accelerator — a silent CPU
            # fallback would betray that. First: if THIS process is
            # already on CPU (an earlier wedged-tunnel fallback pinned it,
            # or a CPU backend initialized first), no probe can help —
            # backends are per-process; fail with the real cause instead
            # of the downstream device-count error.
            if _debug.process_on_cpu():
                if _debug.fell_back_to_cpu():
                    raise RuntimeError(
                        f"master={self.master!r} requested the TPU backend "
                        "but this process already fell back to CPU after a "
                        "wedged-tunnel probe; start a fresh process to "
                        "claim the TPU")
                raise RuntimeError(
                    f"master={self.master!r} requested the TPU backend but "
                    "the CPU backend initialized first in this process "
                    "(backends are per-process); if this machine has a "
                    "TPU, create the session before other jax use or "
                    "start a fresh process")
            # Probe FRESH (a stale cached healthy verdict would walk
            # straight into the hang; a stale cached 'cpu' would wrongly
            # refuse a recovered TPU) and WITHOUT the pin-to-CPU latch so
            # a later retry in this process can still succeed. The
            # platform distinguishes "wedged" from "no TPU here".
            plat = _debug.probe_backend_platform(timeout)
            if plat is None:
                raise RuntimeError(
                    f"master={self.master!r} requested the TPU backend but "
                    f"it did not initialize within {timeout:.0f} s (wedged "
                    "device tunnel?); retry later, or use "
                    "master='local[*]' to accept a CPU fallback")
            if plat in ("cpu", "gpu", "cuda", "rocm"):
                # Known non-TPU platforms fail with the real cause; unknown
                # names pass — tunneled TPU plugins report under their own
                # platform name (e.g. "axon"), and refusing those would
                # break exactly the hardware this path is for.
                raise RuntimeError(
                    f"master={self.master!r} requested the TPU backend but "
                    f"the default backend here is {plat!r}; "
                    "use master='local[*]' to run on the local backend")
            # Healthy fresh probe ≠ safe in-process init (the wedge is
            # intermittent): bound the REAL init too. On expiry this
            # re-execs pinned to CPU, where this strict path then raises
            # with the fell-back-after-wedge cause — an error, never a hang.
            _debug.bounded_backend_init(timeout)
            return
        _debug.ensure_backend(timeout)
        # on fallback, ensure_backend already warned

    def _init_distributed(self) -> None:
        """Multi-host runtime init — the cluster-master analogue of Spark's
        ``master("spark://host:port")``. After ``jax.distributed.initialize``
        the session mesh spans every host's devices and the fit-path psum
        rides ICI within a slice / DCN across slices (parallel/mesh.py).

        Triggered by ``master("pod")`` (TPU pod auto-bootstrap: coordinator
        and process ranks come from the TPU metadata/env) or explicitly:

            .master("pod")
            .config("spark.distributed.coordinator", "host:1234")
            .config("spark.distributed.numProcesses", 4)
            .config("spark.distributed.processId", 0)

        Idempotent: a no-op when the distributed client already exists.
        """
        if not self._is_multihost():
            return
        coord = self.conf.get("spark.distributed.coordinator")
        try:
            from jax._src import distributed as _dist

            if getattr(_dist.global_state, "client", None) is not None:
                return  # already initialized (e.g. a prior session)
        except Exception:
            pass
        kwargs = {}
        if coord:
            kwargs["coordinator_address"] = coord
        if "spark.distributed.numProcesses" in self.conf:
            kwargs["num_processes"] = int(
                self.conf["spark.distributed.numProcesses"])
        if "spark.distributed.processId" in self.conf:
            kwargs["process_id"] = int(self.conf["spark.distributed.processId"])
        jax.distributed.initialize(**kwargs)

    def _init_compilation_cache(self) -> None:
        """Enable XLA's persistent compilation cache (the TPU analogue of a
        warm JVM: first-run compiles land on disk and later sessions reuse
        them, eliminating the multi-second trace+compile cost that dominates
        this workload's wall-clock). Opt out with
        ``.config("spark.compilation.cache", "off")``; override the
        directory with ``.config("spark.compilation.cacheDir", path)``."""
        import os

        from jax.experimental.compilation_cache import compilation_cache as _cc

        if str(self.conf.get("spark.compilation.cache", "on")).lower() \
                in _CONF_FALSE:
            try:
                # A previous session may have pointed the process-global
                # cache at its directory; opting out must actually stop
                # caching, not just skip re-enabling it. Restore jax's
                # stock thresholds too (we force-cache every compile below).
                jax.config.update("jax_compilation_cache_dir", None)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1.0)
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", 0)
                _cc.reset_cache()
            except Exception as e:
                logger.debug("compilation cache opt-out: %s", e)
            return
        # Key the default dir by a host fingerprint: XLA:CPU caches AOT
        # results with the COMPILE machine's feature set, and loading them
        # on a different host spams feature-mismatch warnings (and risks
        # SIGILL). A per-host dir keeps entries where they are valid.
        # SPARKDQ4ML_CACHE_DIR overrides (the test suite uses it so test
        # kernels never land in the production cache).
        base = os.path.join(os.path.expanduser("~"), ".cache",
                            "sparkdq4ml_tpu")
        env_dir = os.environ.get("SPARKDQ4ML_CACHE_DIR")
        default_dir = env_dir or os.path.join(
            base, f"xla-{host_cache_tag()}")
        cache_dir = self.conf.get("spark.compilation.cacheDir", default_dir)
        if cache_dir == default_dir and not env_dir:
            _prune_stale_cache_dirs(base, keep=default_dir)
        # Per-BACKEND subdir: under a tunneled accelerator the plugin's
        # server compiles the session's CPU-side AOT executables with the
        # SERVER machine's feature set (+amx…, +prefer-no-scatter) and the
        # client stores them locally — same host, same jaxlib, same tag,
        # still poisonous to a later pure-CPU session (observed live in r5
        # the moment the tunnel came healthy). Splitting by backend keeps
        # the two writer populations apart without invalidation thrash.
        cache_dir = os.path.join(cache_dir, jax.default_backend())
        try:
            os.makedirs(cache_dir, exist_ok=True)
            _validate_cache_dir(cache_dir, host_cache_tag())
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            aggressive = (jax.default_backend() != "cpu"
                          or os.environ.get("SPARKDQ4ML_CACHE_EVERYTHING")
                          == "1")
            if aggressive:
                # Accelerator compiles ride a tunnel and cost 20-40 s:
                # cache every compile (the default only caches "long"
                # ones). The env override exists for the test suite, whose
                # thousands of tiny repeated CPU compiles are exactly the
                # case worth caching (stderr noise is captured there).
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", -1)
            else:
                # Stock thresholds on CPU: compiles are fast, and
                # persisting every tiny kernel floods XLA's AOT reload
                # with spurious feature-mismatch warnings; only long
                # compiles persist.
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1.0)
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", 0)
            # jax latches "is the cache enabled?" process-globally at the
            # first compile; a compile before this session was built would
            # have pinned it to off. Reset the latch so our dir takes effect.
            _cc.reset_cache()
        except Exception as e:  # cache is an optimization, never fatal
            logger.debug("compilation cache disabled: %s", e)

    # -- builder (mirrors SparkSession.builder()...getOrCreate()) ----------
    class Builder:
        def __init__(self):
            self._app_name = "sparkdq4ml-tpu"
            self._master: Optional[str] = None
            self._conf: dict[str, str] = {}

        def app_name(self, name: str) -> "TpuSession.Builder":
            self._app_name = name
            return self

        appName = app_name

        def master(self, master: str) -> "TpuSession.Builder":
            self._master = master
            return self

        def config(self, key: str, value) -> "TpuSession.Builder":
            self._conf[key] = str(value)
            return self

        def get_or_create(self) -> "TpuSession":
            # Thread-safe singleton (double-checked): concurrent callers —
            # e.g. serving-layer clients racing at process start — get ONE
            # fully-constructed session; the conf-update path is likewise
            # serialized so two builders cannot interleave re-inits.
            global _ACTIVE
            with _ACTIVE_LOCK:
                if _ACTIVE is None:
                    _ACTIVE = TpuSession(self._app_name, self._master,
                                         self._conf)
                    return _ACTIVE
                _ACTIVE.conf.update(self._conf)  # Spark getOrCreate semantics
                if any(k.startswith("spark.compilation.") for k in self._conf):
                    _ACTIVE._init_compilation_cache()
                if any(k.startswith("spark.faults") for k in self._conf):
                    _ACTIVE._init_faults()   # late chaos conf still installs
                if any(k.startswith("spark.observability.")
                       for k in self._conf):
                    _ACTIVE._init_observability()
                if any(k.startswith(("spark.pipeline.", "spark.groupedExec.",
                                     "spark.explain.", "spark.serve.",
                                     "spark.ingest.", "spark.audit.",
                                     "spark.chaos.", "spark.stats.",
                                     "spark.shard.", "spark.costprof.",
                                     "spark.profiling.", "spark.trace.",
                                     "spark.incident.", "spark.dq."))
                       for k in self._conf):
                    _ACTIVE._init_pipeline()
                return _ACTIVE

        getOrCreate = get_or_create

    @classmethod
    def builder(cls) -> "TpuSession.Builder":
        return cls.Builder()

    @classmethod
    def active(cls) -> Optional["TpuSession"]:
        return _ACTIVE

    getActiveSession = active  # Spark 3.x name

    # -- surface ------------------------------------------------------------
    @property
    def devices(self):
        return list(self.mesh.devices.flat)

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def read(self) -> DataFrameReader:
        return DataFrameReader(self)

    def sql(self, query: str):
        """Run the SQL subset against this session's temp views
        (`DataQuality4MachineLearningApp.java:77,89`)."""
        return _sql_execute(query, self.catalog)

    def serve(self, **overrides):
        """The session's :class:`~sparkdq4ml_tpu.serve.QueryServer` —
        started on first call from ``spark.serve.*`` conf keys (workers,
        maxQueue, maxInFlight, maxQueuedPerTenant, memoryLimitBytes,
        defaultDeadline, sharedPlanCache, breakerThreshold,
        breakerCooldown), keyword ``overrides`` winning. Subsequent
        calls return the same running server; :meth:`stop` drains and
        stops it. ``spark.serve.enabled=false`` makes this raise — the
        serving layer is otherwise pay-for-use (no server, no threads,
        no metrics). See README § "Serving"."""
        from .config import config as _cfg

        with _ACTIVE_LOCK:
            server = getattr(self, "_server", None)
            if server is not None and server.running:
                return server
            if not _cfg.serve_enabled:
                raise RuntimeError(
                    "query serving is disabled "
                    "(spark.serve.enabled=false on this session)")
            from .serve import QueryServer

            self._server = QueryServer.from_conf(self, self.conf,
                                                 **overrides).start()
            return self._server

    def table(self, name: str):
        """Spark's ``spark.table(name)`` — the registered temp view."""
        return self.catalog.lookup(name)

    def create_data_frame(self, data, names=None):
        from .frame.frame import Frame

        if isinstance(data, dict):
            return Frame(data)
        return Frame.from_rows(data, names)

    createDataFrame = create_data_frame

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              num_partitions: Optional[int] = None) -> "Frame":
        """Spark ``spark.range``: a Frame with one integer ``id`` column.
        ``range(n)`` counts 0..n-1; ``range(start, end, step)`` like
        Python's. ``num_partitions`` is accepted and ignored (this engine
        shards at fit time, like the ``repartition`` no-op shim). ids are
        int64 under ``jax_enable_x64``; without it the device dtype is
        int32, so out-of-int32 bounds raise instead of silently
        wrapping."""
        import numpy as np

        from .frame.frame import Frame

        if step == 0:
            raise ValueError("range step must not be zero")
        if end is None:
            start, end = 0, start
        ids = np.arange(start, end, step, dtype=np.int64)
        import jax as _jax

        if not _jax.config.jax_enable_x64 and ids.size > 0:
            # arange is monotone: the extremes are its endpoints (O(1))
            lo, hi = sorted((int(ids[0]), int(ids[-1])))
            if lo < -(2 ** 31) or hi >= 2 ** 31:
                raise ValueError(
                    f"range ids [{lo}, {hi}] exceed int32 and x64 is "
                    "disabled; enable jax_enable_x64 for 64-bit ids")
        return Frame({"id": ids})

    @property
    def recovery_log(self):
        """The process-global structured recovery-event log (retries,
        backoffs, fallbacks, circuit-breaker trips, preemption resumes)
        — ``utils.recovery.RECOVERY_LOG``. Empty on a clean run; the
        observable side of the resilience layer (README § "Failure model
        & fault injection")."""
        from .utils.recovery import RECOVERY_LOG

        return RECOVERY_LOG

    @property
    def version(self) -> str:
        """Engine version string (Spark ``spark.version`` analogue)."""
        from . import __version__

        return __version__

    def stop(self) -> None:
        global _ACTIVE
        # The server handle is swapped out under the SAME lock serve()
        # creates it under — a serve() racing this stop() either lands
        # before (its server is the one drained below) or after (it
        # starts a fresh server on a stopped-but-usable session object);
        # it can never start one that stop() silently ignores.
        with _ACTIVE_LOCK:
            if _ACTIVE is self:
                _ACTIVE = None
            server = getattr(self, "_server", None)
            self._server = None
        # Drain the serving layer FIRST (outside the lock — draining can
        # take a while): in-flight served queries finish against the
        # session's still-installed config; only then is the
        # session-scoped conf restored below (the stop-vs-query race the
        # threading-model doc pins down).
        if server is not None:
            server.stop(drain=True)
        # Persist the plan-statistics history while the session conf is
        # still installed (the path/enabled flags restore below). The
        # save merges-don't-clobber and degrades to in-memory-only on
        # any I/O failure (stats_persist ladder) — stop() never raises
        # over statistics.
        from .config import config as _cfg

        if (_cfg.stats_enabled and _cfg.stats_path
                and _cfg.stats_flush_on_stop):
            from .utils import statstore as _statstore

            _statstore.STORE.save(_cfg.stats_path, merge=True)
        self.catalog.clear()
        # Close the root session span and stop recording if THIS session
        # turned tracing on (same session-scoped rule as the fault plan).
        # Already-recorded spans stay exportable: dump_trace/trace_report
        # after stop() still work (post-mortem analysis is the point).
        span = getattr(self, "_session_span", None)
        if span is not None:
            from .utils import observability as _obs

            _obs.TRACER.end(span)
            self._session_span = None
        if getattr(self, "_obs_enabled_here", False):
            from .utils import observability as _obs

            _obs.disable()
            self._obs_enabled_here = False
        # Restore pipeline-compiler settings THIS session changed (same
        # session-scoped rule as the fault plan): a session that disabled
        # the pipeline must not leave the process on the eager path.
        # Under _CONF_LOCK so a concurrent builder re-init cannot
        # interleave with (and then clobber) this restore.
        with _CONF_LOCK:
            saved = getattr(self, "_pipeline_saved", None)
            if saved:
                from .config import config as _cfg
                from .ops import compiler as _compiler

                for attr, value in saved.items():
                    setattr(_cfg, attr, value)
                self._pipeline_saved = None
                _compiler.clear_cache()
                from .ops import segments as _segments

                _segments.clear_cache()
        # Tear down the shard context THIS session installed (the mesh
        # belongs to the session; a later session re-configures its own).
        from .parallel import shard as _shard_mod

        _shard_mod.reset()
        # Uninstall the fault plan THIS session installed (conf/env):
        # chaos is session-scoped opt-in; a later chaos-free session (or
        # plain library use) must not keep injecting this one's faults.
        plan = getattr(self, "_fault_plan", None)
        if plan is not None:
            from .utils import faults as _faults

            if _faults.active() is plan:
                _faults.clear()
            self._fault_plan = None
