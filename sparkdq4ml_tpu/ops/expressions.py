"""Column expression trees.

This is the framework's equivalent of the Spark column-expression surface the
reference app exercises (``df.col``, ``callUDF``, ``cast``, comparisons in SQL
``WHERE`` — `DataQuality4MachineLearningApp.java:68-90`). An ``Expr`` is a
small host-side tree; evaluating it against a :class:`~sparkdq4ml_tpu.frame.Frame`
produces a device array over *all* row slots (filtering is a validity mask, so
shapes stay static for XLA — see SURVEY.md §7 step 1).

Unlike Spark, where a UDF crosses the codegen→JVM-object boundary per row (the
"UDF tax", SURVEY.md §3.2), every expression here is a vectorized jnp op that
XLA fuses — the per-row boundary does not exist.
"""

from __future__ import annotations

import base64 as _b64
import builtins
import functools
import hashlib
import math
import re
from typing import Any, Callable, Sequence

import jax.numpy as jnp
import numpy as np

from ..config import float_dtype, int_dtype

# Spark SQL type name → dtype factory. Mirrors the names printSchema uses.
_TYPE_NAMES: dict[str, Callable[[], Any]] = {
    "int": int_dtype,
    "integer": int_dtype,
    "long": lambda: jnp.int64 if jnp.zeros((), jnp.int64).dtype == jnp.int64 else jnp.int32,
    "float": lambda: jnp.float32,
    "double": float_dtype,
    "boolean": lambda: jnp.bool_,
    "string": lambda: np.dtype(object),
}


def spark_type_name(dtype) -> str:
    """dtype → Spark printSchema type name (integer/long/float/double/boolean/string)."""
    dt = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    if dt == np.int32 or dt == np.int16 or dt == np.int8:
        return "integer"
    if dt == np.int64:
        return "long"
    if dt == np.float32:
        return "float"
    if dt == np.float64:
        return "double"
    if dt == np.bool_:
        return "boolean"
    return "string"


def resolve_type_name(name: str):
    try:
        return _TYPE_NAMES[name.lower()]()
    except KeyError:
        raise ValueError(f"unknown SQL type name: {name!r}") from None


class Expr:
    """Base column expression. Supports Python operators like Spark's Column."""

    def eval(self, frame):  # pragma: no cover - abstract
        raise NotImplementedError

    @property
    def name(self) -> str:
        """Default output-column name (Spark derives one from the expr string)."""
        return str(self)

    # -- fluent API (Spark Column methods) --------------------------------
    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def cast(self, type_name: str) -> "Cast":
        return Cast(self, type_name)

    astype = cast   # PySpark alias

    def isin(self, *values) -> "Expr":
        """Membership test — ``col.isin(1, 2, 3)`` / SQL ``IN (…)``."""
        if len(values) == 1 and isinstance(values[0], (list, tuple, set)):
            values = tuple(values[0])
        return InList(self, [v if isinstance(v, Expr) else Lit(v)
                             for v in values])

    def between(self, lower, upper) -> "Expr":
        """``lower <= col <= upper`` (inclusive) — SQL ``BETWEEN``."""
        return (self >= lower) & (self <= upper)

    def like(self, pattern: str) -> "Expr":
        """SQL LIKE: ``%`` any run, ``_`` one char (string columns)."""
        return StringMatch("like", self, pattern)

    def rlike(self, pattern: str) -> "Expr":
        """Regex search (Spark ``rlike``)."""
        return StringMatch("rlike", self, pattern)

    def contains(self, sub: str) -> "Expr":
        return StringMatch("contains", self, sub)

    def startswith(self, prefix: str) -> "Expr":
        return StringMatch("startswith", self, prefix)

    def endswith(self, suffix: str) -> "Expr":
        return StringMatch("endswith", self, suffix)

    def is_null(self) -> "Expr":
        return UnaryOp("isnull", self)

    def is_not_null(self) -> "Expr":
        return UnaryOp("isnotnull", self)

    # Spark Column camelCase names
    isNull = is_null
    isNotNull = is_not_null

    def ilike(self, pattern: str) -> "Expr":
        """Case-insensitive LIKE (Spark ``ilike``): lower both sides."""
        return StringMatch("like", fn("lower", self), pattern.lower())

    def eq_null_safe(self, other) -> "Expr":
        """Null-safe equality (Spark ``eqNullSafe`` / SQL ``<=>``): true
        when both sides are null, false when exactly one is — composed
        from == and is_null, so NaN-null float columns and None-null
        string columns both follow Spark's truth table."""
        other = other if isinstance(other, Expr) else Lit(other)
        return (self == other) | (self.is_null() & other.is_null())

    eqNullSafe = eq_null_safe

    def substr(self, startPos, length) -> "Expr":
        """Spark ``col.substr(pos, len)`` (1-based) — the method form of
        ``substring``. pos/len may be ints or Columns (Spark's
        ``substr(Column, Column)`` overload); a null pos/len yields
        null."""
        p = startPos if isinstance(startPos, Expr) else Lit(startPos)
        ln = length if isinstance(length, Expr) else Lit(length)
        return fn("substring", self, p, ln)

    def get_item(self, key: int) -> "Expr":
        """Spark ``getItem``: 0-based array element; negative or
        out-of-range ordinals yield null (GetArrayItem semantics —
        ``element_at`` is the 1-based SQL form where negatives count from
        the end)."""
        return fn("get_item", self, Lit(int(key)))

    getItem = get_item

    def asc(self) -> "SortOrder":
        """Ascending sort marker for ``sort``/``orderBy``/window specs.
        Default null placement is Spark's: nulls first ascending, nulls
        last descending (the _nulls_first/_nulls_last variants pin it)."""
        return SortOrder(self, True)

    def desc(self) -> "SortOrder":
        """Descending sort marker (see ``asc`` for null placement)."""
        return SortOrder(self, False)

    def asc_nulls_first(self) -> "SortOrder":
        return SortOrder(self, True, nulls_first=True)

    def asc_nulls_last(self) -> "SortOrder":
        return SortOrder(self, True, nulls_first=False)

    def desc_nulls_first(self) -> "SortOrder":
        return SortOrder(self, False, nulls_first=True)

    def desc_nulls_last(self) -> "SortOrder":
        return SortOrder(self, False, nulls_first=False)

    # -- operators --------------------------------------------------------
    def _bin(self, op, other, reverse=False):
        other = other if isinstance(other, Expr) else Lit(other)
        return BinOp(op, other, self) if reverse else BinOp(op, self, other)

    def __add__(self, o):  return self._bin("+", o)
    def __radd__(self, o): return self._bin("+", o, True)
    def __sub__(self, o):  return self._bin("-", o)
    def __rsub__(self, o): return self._bin("-", o, True)
    def __mul__(self, o):  return self._bin("*", o)
    def __rmul__(self, o): return self._bin("*", o, True)
    def __truediv__(self, o):  return self._bin("/", o)
    def __rtruediv__(self, o): return self._bin("/", o, True)
    def __mod__(self, o):      return self._bin("%", o)
    def __rmod__(self, o):     return self._bin("%", o, True)
    def __neg__(self):     return UnaryOp("-", self)
    def __lt__(self, o):   return self._bin("<", o)
    def __le__(self, o):   return self._bin("<=", o)
    def __gt__(self, o):   return self._bin(">", o)
    def __ge__(self, o):   return self._bin(">=", o)
    def __eq__(self, o):   return self._bin("==", o)  # type: ignore[override]
    def __ne__(self, o):   return self._bin("!=", o)  # type: ignore[override]
    def __and__(self, o):  return self._bin("&", o)
    def __rand__(self, o): return self._bin("&", o, True)
    def __or__(self, o):   return self._bin("|", o)
    def __ror__(self, o):  return self._bin("|", o, True)
    def __invert__(self):  return UnaryOp("!", self)

    __hash__ = object.__hash__  # __eq__ is overloaded; keep Exprs hashable


class SortOrder:
    """Sort-direction marker from ``col.asc()`` / ``col.desc()`` (and the
    ``*_nulls_first/last`` variants) — consumed by ``Frame.sort``; not an
    evaluable expression. ``nulls_first=None`` means the Spark default
    for the direction: first when ascending, last when descending."""

    def __init__(self, child: "Expr", ascending: bool, nulls_first=None):
        self.child = child
        self.ascending = ascending
        self.nulls_first = nulls_first

    @property
    def name(self) -> str:
        return self.child.name


class Col(Expr):
    def __init__(self, name: str):
        self._name = name

    def eval(self, frame):
        return frame._column_values(self._name)

    @property
    def name(self) -> str:
        return self._name

    def __str__(self):
        return self._name


class Lit(Expr):
    def __init__(self, value):
        self.value = value

    def eval(self, frame):
        n = frame.num_slots
        if isinstance(self.value, bool):
            return jnp.full((n,), self.value, dtype=jnp.bool_)
        if isinstance(self.value, int):
            return jnp.full((n,), self.value, dtype=int_dtype())
        if isinstance(self.value, float):
            return jnp.full((n,), self.value, dtype=float_dtype())
        return np.full((n,), self.value, dtype=object)

    def __str__(self):
        return repr(self.value)


class Alias(Expr):
    def __init__(self, child: Expr, name: str):
        self.child = child
        self._name = name

    def eval(self, frame):
        return self.child.eval(frame)

    @property
    def name(self) -> str:
        return self._name

    def __str__(self):
        return f"{self.child} AS {self._name}"


def predicate_keep_mask(cond):
    """SQL WHERE truthiness of a predicate column: a NULL predicate (NaN
    in this engine's float encoding) drops the row — three-valued logic,
    where a bare ``NaN.astype(bool)`` would be True — and nonzero
    numerics are true. THE single definition shared by
    ``Frame._filter_eager`` and the pipeline compiler's fused filter, so
    the eager and compiled paths cannot diverge on null rows."""
    cond = jnp.asarray(cond)
    if jnp.issubdtype(cond.dtype, jnp.floating):
        return jnp.logical_and(jnp.logical_not(jnp.isnan(cond)), cond != 0)
    return cond.astype(jnp.bool_)


def _sql_divide(a, b):
    """Spark's non-ANSI division: x / 0 is NULL (incl. 0 / 0)."""
    return jnp.where(b == 0, jnp.nan, jnp.divide(a, b))


def _sql_mod(a, b):
    """Spark's % / mod(): sign follows the dividend; x % 0 is NULL."""
    return jnp.where(b == 0, jnp.nan, jnp.fmod(a, b))


_BIN_FNS = {
    "+": jnp.add,
    "-": jnp.subtract,
    "*": jnp.multiply,
    "/": _sql_divide,
    "%": _sql_mod,
    "<": jnp.less,
    "<=": jnp.less_equal,
    ">": jnp.greater,
    ">=": jnp.greater_equal,
    "==": jnp.equal,
    "!=": jnp.not_equal,
    "&": jnp.logical_and,
    "|": jnp.logical_or,
}


def _is_object(a) -> bool:
    return isinstance(a, np.ndarray) and a.dtype == object


def _promote(a, b):
    """Numeric promotion for mixed host/device operands."""
    return jnp.asarray(a), jnp.asarray(b)


class BinOp(Expr):
    def __init__(self, op: str, left: Expr, right: Expr):
        self.op, self.left, self.right = op, left, right

    def eval(self, frame):
        a, b = self.left.eval(frame), self.right.eval(frame)
        if _is_object(a) or _is_object(b):
            # String columns live on host; comparisons stay in numpy.
            np_fns = {"==": np.equal, "!=": np.not_equal}
            if self.op not in np_fns:
                raise TypeError(f"operator {self.op!r} unsupported on strings")
            return np_fns[self.op](np.asarray(a, object), np.asarray(b, object)
                                   ).astype(bool)
        a, b = _promote(a, b)
        if self.op in ("/", "%"):
            # Spark's / always yields double; % needs float for the
            # NULL-on-zero-divisor result
            a = jnp.asarray(a, float_dtype())
            b = jnp.asarray(b, float_dtype())
        return _BIN_FNS[self.op](a, b)

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


class UnaryOp(Expr):
    def __init__(self, op: str, child: Expr):
        self.op, self.child = op, child

    def eval(self, frame):
        v = self.child.eval(frame)
        if self.op == "-":
            return jnp.negative(v)
        if self.op == "!":
            return jnp.logical_not(v)
        if self.op in ("isnull", "isnotnull"):
            if _is_object(v):  # string columns: None marks null
                nulls = np.asarray([x is None for x in v], dtype=bool)
                nulls = jnp.asarray(nulls)
            elif hasattr(v, "dtype") and np.issubdtype(np.dtype(v.dtype), np.floating):
                nulls = jnp.isnan(v)
            else:
                nulls = jnp.zeros(v.shape[:1], jnp.bool_)
            return nulls if self.op == "isnull" else jnp.logical_not(nulls)
        raise ValueError(self.op)

    def __str__(self):
        return f"({self.op}{self.child})"


class Cast(Expr):
    """CAST(expr AS type) — Spark semantics: double→int truncates toward zero."""

    def __init__(self, child: Expr, type_name: str):
        self.child = child
        self.type_name = type_name

    _BOOL_TRUE = frozenset(("true", "t", "yes", "y", "1"))
    _BOOL_FALSE = frozenset(("false", "f", "no", "n", "0"))

    def eval(self, frame):
        v = self.child.eval(frame)
        dt = resolve_type_name(self.type_name)
        if isinstance(dt, np.dtype) and dt == object:
            # to string: null stays null (numeric NaN is this engine's
            # null, so it maps to None too, not the text 'nan')
            a = v if _is_object(v) else np.asarray(v)
            return np.asarray(
                [None if x is None
                 or (isinstance(x, (float, np.floating)) and np.isnan(x))
                 else str(x) for x in a], dtype=object)
        if _is_object(v):
            return self._cast_strings(v, dt)
        return jnp.asarray(v).astype(dt)

    def _cast_strings(self, v, dt):
        """Spark string→numeric/boolean cast: trim, parse; unparseable /
        null → null (NaN-float representation when nulls force it).
        Booleans accept the word literals; integer targets parse integral
        strings EXACTLY (no 2^53 float corruption) and truncate decimal
        forms toward zero; underscores and non-finite values are rejected
        for integer targets the way Spark rejects them."""
        if np.dtype(dt) == np.bool_:
            vals = []
            for x in v:
                if x is None:
                    vals.append(None)
                    continue
                s = str(x).strip().lower()
                vals.append(True if s in self._BOOL_TRUE else
                            False if s in self._BOOL_FALSE else None)
            if any(b is None for b in vals):
                return jnp.asarray(np.asarray(
                    [np.nan if b is None else float(b) for b in vals],
                    np.float64), float_dtype())
            return jnp.asarray(np.asarray(vals, np.bool_))

        int_target = np.issubdtype(np.dtype(dt), np.integer)
        parsed = np.empty(len(v), np.float64)
        exact = np.zeros(len(v), np.int64)
        all_exact_int = True
        for i, x in enumerate(v):
            if x is None:
                parsed[i] = np.nan
                all_exact_int = False
                continue
            s = str(x).strip()
            if "_" in s:                  # Python literal syntax, not SQL
                parsed[i] = np.nan
                all_exact_int = False
                continue
            try:
                exact[i] = int(s)         # exact (beyond 2^53) integral
                parsed[i] = float(exact[i])
                continue
            except (ValueError, OverflowError):
                all_exact_int = False
            try:
                parsed[i] = float(s)
            except ValueError:
                parsed[i] = np.nan
        if int_target:
            if all_exact_int:
                return jnp.asarray(exact.astype(dt))
            finite = np.isfinite(parsed)
            whole = np.where(finite, np.trunc(parsed), np.nan)
            return jnp.asarray(whole, float_dtype())
        return jnp.asarray(parsed, dt)

    @property
    def name(self) -> str:
        return f"CAST({self.child} AS {self.type_name.upper()})"

    def __str__(self):
        return self.name


class InList(Expr):
    """``expr IN (v1, v2, …)`` — vectorized membership, no row loop.

    Numeric columns fold to an OR-reduction of equalities on device; string
    columns test with host numpy. Null rows (None / NaN) are never members
    (SQL three-valued logic collapses to False in a WHERE mask).

    A NULL *in the value set* follows SQL three-valued logic too (Spark
    parity): ``x NOT IN (…, NULL)`` can never be TRUE (``x <> NULL`` is
    unknown), so NOT IN filters every row; plain ``IN`` drops the NULL
    from the list — a match still passes, a non-match becomes unknown and
    filters, which the boolean mask already expresses as False.
    """

    def __init__(self, child: Expr, values: Sequence[Expr],
                 negated: bool = False):
        self.child = child
        self.values = list(values)
        self.negated = negated

    @staticmethod
    def _is_null_lit(x) -> bool:
        return isinstance(x, Lit) and (
            x.value is None or (isinstance(x.value, float)
                                and math.isnan(x.value)))

    def eval(self, frame):
        values = self.values
        if any(self._is_null_lit(x) for x in values):
            if self.negated:
                return jnp.zeros((frame.num_slots,), jnp.bool_)
            values = [x for x in values if not self._is_null_lit(x)]
            if not values:      # IN (NULL): unknown for every row
                return jnp.zeros((frame.num_slots,), jnp.bool_)
        v = self.child.eval(frame)
        vals = [x.eval(frame) for x in values]
        if _is_object(v) or any(_is_object(x) for x in vals):
            va = np.asarray(v, object)
            hit = np.zeros(va.shape[0], bool)
            for x in vals:
                hit |= np.equal(va, np.asarray(x, object)).astype(bool)
            hit = jnp.asarray(hit)
            notnull = jnp.asarray(
                np.asarray([x is not None for x in va], bool))
        else:
            v = jnp.asarray(v)
            hit = functools.reduce(
                jnp.logical_or, [jnp.equal(v, jnp.asarray(x)) for x in vals])
            notnull = (jnp.logical_not(jnp.isnan(v))
                       if jnp.issubdtype(v.dtype, jnp.floating)
                       else jnp.ones(v.shape[:1], jnp.bool_))
        # NULL [NOT] IN (...) is NULL — False in a WHERE mask either way.
        out = jnp.logical_not(hit) if self.negated else hit
        return jnp.logical_and(out, notnull)

    def __str__(self):
        op = "NOT IN" if self.negated else "IN"
        return f"({self.child} {op} ({', '.join(map(str, self.values))}))"


class StringMatch(Expr):
    """LIKE / RLIKE / contains / startswith / endswith on string columns.

    Strings live host-side (object arrays), so matching runs in numpy; null
    (None) rows are False, mirroring SQL null semantics in WHERE.
    """

    def __init__(self, kind: str, child: Expr, pattern: str,
                 negated: bool = False):
        self.kind = kind
        self.child = child
        self.pattern = pattern
        self.negated = negated

    def _matcher(self):
        import re as _re

        if self.kind == "like":
            # Escape regex metachars, then translate SQL wildcards.
            pat = _re.escape(self.pattern).replace("%", ".*").replace("_", ".")
            rx = _re.compile(pat, _re.DOTALL)
            return lambda s: rx.fullmatch(s) is not None
        if self.kind == "rlike":
            rx = _re.compile(self.pattern)
            return lambda s: rx.search(s) is not None
        if self.kind == "contains":
            return lambda s: self.pattern in s
        if self.kind == "startswith":
            return lambda s: s.startswith(self.pattern)
        if self.kind == "endswith":
            return lambda s: s.endswith(self.pattern)
        raise ValueError(self.kind)

    def eval(self, frame):
        v = self.child.eval(frame)
        va = np.asarray(v, object) if not _is_object(v) else v
        match = self._matcher()
        notnull = np.asarray([x is not None for x in va], bool)
        hit = np.asarray([x is not None and match(str(x)) for x in va], bool)
        # NULL [NOT] LIKE ... is NULL — False in a WHERE mask either way.
        out = (~hit if self.negated else hit) & notnull
        return jnp.asarray(out)

    def __str__(self):
        neg = "NOT " if self.negated else ""
        return f"({self.child} {neg}{self.kind.upper()} {self.pattern!r})"


class UdfCall(Expr):
    """Invocation of a registered UDF by name — ``callUDF`` equivalent.

    Resolution happens at eval time against the registry, matching Spark's
    name-based lookup (`DataQuality4MachineLearningApp.java:68-69,86-87`).
    """

    def __init__(self, udf_name: str, args: Sequence[Expr], registry=None):
        self.udf_name = udf_name
        self.args = list(args)
        self._registry = registry

    def eval(self, frame):
        from .udf import default_registry

        reg = self._registry if self._registry is not None else default_registry()
        try:
            fn, return_dtype = reg.lookup(self.udf_name)
        except KeyError:
            # Name-based fallback to the builtin function table, so SQL
            # `abs(x)`, `upper(s)` etc. resolve without UDF registration
            # (Spark's FunctionRegistry builtins behave the same way).
            key = self.udf_name.lower()
            if key in _ROW_FNS:     # frame-aware: need the row count
                return _ROW_FNS[key](frame, self.args)
            if key in _BUILTIN_FNS:
                return Func(key, self.args).eval(frame)
            raise
        vals = [a.eval(frame) for a in self.args]
        out = fn(*vals)
        if return_dtype is not None:
            out = jnp.asarray(out, return_dtype)
        # Data-quality observatory gate (utils/dqprof.py): ONE flag
        # read; record_eval skips tracers itself, so a traced flush
        # accounts through the compiler hook instead — never twice.
        from ..config import config as _cfg

        if _cfg.dq_profile_enabled:
            from ..utils import dqprof as _dqprof

            _dqprof.record_eval(self.udf_name, out)
        return out

    @property
    def name(self) -> str:
        return f"{self.udf_name}({', '.join(str(a) for a in self.args)})"

    def __str__(self):
        return self.name


def _null_mask(v):
    """Per-row null indicator: None for strings, NaN for floats."""
    if _is_object(v):
        return np.asarray([x is None for x in v], dtype=bool)
    if hasattr(v, "dtype") and np.issubdtype(np.dtype(v.dtype), np.floating):
        return jnp.isnan(v)
    return jnp.zeros(np.shape(v)[:1], jnp.bool_)


def _str_map(fn, *arrays):
    """Apply a per-row Python fn over host string columns (null-safe:
    None AND float NaN — a NULL literal reaches here as NaN — yield
    NULL instead of feeding a float into a str method)."""
    def null(x):
        return x is None or (isinstance(x, float) and x != x)

    out = []
    for row in zip(*[np.asarray(a, object) for a in arrays]):
        out.append(None if any(null(x) for x in row) else fn(*row))
    return np.asarray(out, dtype=object)


def _fn_coalesce(*vals):
    out = vals[-1]
    for v in reversed(vals[:-1]):
        m = _null_mask(v)
        if _is_object(v) or _is_object(out):
            out = np.where(np.asarray(m), np.asarray(out, object),
                           np.asarray(v, object))
        else:
            out = jnp.where(m, jnp.asarray(out, float_dtype()),
                            jnp.asarray(v, float_dtype()))
    return out


def _fn_round(v, digits=None):
    # Spark's round() is HALF_UP; jnp.round is half-even. Implement half-up
    # on device: floor(x * 10^d + 0.5 * sign(x)) / 10^d.
    d = int(np.asarray(digits)[0]) if digits is not None else 0
    v = jnp.asarray(v, float_dtype())
    scale = 10.0 ** d
    scaled = v * scale
    return jnp.where(v >= 0, jnp.floor(scaled + 0.5),
                     jnp.ceil(scaled - 0.5)) / scale


def _fn_length(s):
    """Spark ``length``: null → null. Results are int32; a column
    containing nulls promotes to float with NaN (the engine's numeric-null
    convention — same promotion as ``lag`` on ints). Numeric columns cast
    to their string rendering first, like Spark."""
    if _is_object(s):
        lens = [None if x is None else len(str(x)) for x in s]
    else:
        a = np.asarray(s)
        if np.issubdtype(a.dtype, np.floating):
            # str(numpy scalar) keeps the dtype's short repr; float(x)
            # would upcast f32→f64 and render the rounding error
            # ('0.10000000149011612' instead of '0.1')
            lens = [None if np.isnan(x) else len(str(x)) for x in a]
        elif np.issubdtype(a.dtype, np.bool_):
            lens = [len(str(bool(x))) for x in a]
        else:
            lens = [len(str(int(x))) for x in a]
    return _int_or_null(lens)


def _fn_sha2(s, n):
    """Spark ``sha2(col, bitLength)``: bitLength in {0, 224, 256, 384,
    512} (0 means 256); anything else yields null per row (Spark's
    behavior), validated ONCE — not a per-row hashlib error."""
    bits = _scalar_int(n)
    if bits == 0:
        bits = 256
    if bits not in (224, 256, 384, 512):
        a = np.asarray(s, object)
        return np.full(len(a), None, dtype=object)
    algo = f"sha{bits}"
    return _str_map(lambda x: hashlib.new(algo, x.encode()).hexdigest(), s)


def _fn_substring(s, pos, length):
    # Spark substring is 1-based; pos 0 behaves like 1. pos/length may be
    # scalar literals (broadcast columns) or per-row columns (Spark's
    # substr(Column, Column) overload); a null pos/length yields null.
    pa = np.asarray(pos).ravel()
    la = np.asarray(length).ravel()

    def _at(a, i):
        v = a[i] if a.size > 1 else a[0]
        if isinstance(v, (float, np.floating)) and np.isnan(v):
            return None
        return int(v)

    out = []
    for i, x in enumerate(s):
        p, ln = _at(pa, i), _at(la, i)
        if x is None or p is None or ln is None:
            out.append(None)
            continue
        start = max(p - 1, 0)
        out.append(x[start:start + ln])
    return np.asarray(out, object)


def _scalar_value(v):
    """A literal argument of any type, row-broadcast by Lit.eval — take
    the scalar back out. A column-valued argument (more than one distinct
    value) is rejected rather than silently collapsed to row 0's value.
    Single base for :func:`_scalar_str` / :func:`_scalar_int`."""
    arr = np.asarray(v, object).ravel()
    if len(arr) > 1 and any(x != arr[0] for x in arr[1:]):
        raise ValueError(
            "this function argument must be a literal, not a column "
            "(per-row values are not supported)")
    x = arr[0]
    return x.item() if hasattr(x, "item") else x


def _scalar_str(v) -> str:
    return _scalar_value(v)


def _scalar_int(v) -> int:
    return int(_scalar_value(v))


def _fn_concat(*ss):
    """Spark concat: NULL if ANY argument is null (None or float NaN —
    the engine's numeric null stringifies as 'nan' otherwise)."""
    def null(x):
        return x is None or (isinstance(x, float) and x != x)

    out = []
    for row in zip(*[np.asarray(a, object) for a in ss]):
        out.append(None if any(null(x) for x in row)
                   else "".join(str(x) for x in row))
    return np.asarray(out, dtype=object)


def _fn_concat_ws(sep, *ss):
    s = _scalar_str(sep)

    def null(x):
        # None (string null) or NaN (this engine's numeric null)
        return x is None or (isinstance(x, float) and x != x)

    out = []
    for row in zip(*[np.asarray(a, object) for a in ss]):
        # Spark concat_ws SKIPS nulls instead of nulling the result
        out.append(s.join(str(x) for x in row if not null(x)))
    return np.asarray(out, dtype=object)


def _fn_split(s, pattern):
    pat = re.compile(_scalar_str(pattern))
    return _str_map(lambda x: pat.split(x), s)


def _require_array_cells(arr, fn_name):
    """Spark's analyzer rejects array functions on non-array input; the
    equivalent here is a host check on the first non-null cell (a plain
    string column would otherwise give plausible character-level
    results)."""
    a = np.asarray(arr, object)
    for cell in a:
        if cell is None:
            continue
        if not isinstance(cell, (list, tuple, np.ndarray)):
            raise ValueError(
                f"{fn_name}() expects an array column (e.g. split() or "
                f"collect_list() output), got a {type(cell).__name__} cell")
        break
    return a


def _fn_array_contains(arr, value):
    """Spark ``array_contains(col, value)``: null cell → null; the value
    is a literal scalar. List cells come from ``split``/``collect_list``."""
    v = _scalar_value(value)
    out = []
    for cell in _require_array_cells(arr, "array_contains"):
        out.append(None if cell is None else bool(v in cell))
    if any(x is None for x in out):
        return jnp.asarray(np.asarray(
            [np.nan if x is None else float(x) for x in out], np.float64),
            float_dtype())
    return jnp.asarray(np.asarray(out, np.bool_))


def _fn_element_at(arr, index):
    """Spark ``element_at(col, i)``: 1-based, negative counts from the
    end, out-of-bounds / null cell → null."""
    i = _scalar_int(index)
    if i == 0:
        raise ValueError("element_at index is 1-based; 0 is invalid")
    out = []
    for cell in _require_array_cells(arr, "element_at"):
        if cell is None:
            out.append(None)
            continue
        pos = i - 1 if i > 0 else len(cell) + i
        out.append(cell[pos] if 0 <= pos < len(cell) else None)
    return np.asarray(out, object)


def _fn_array(*cols):
    """``array(c1, c2, …)``: one array cell per row from scalar columns.
    Nulls become None inside the cell — including float NaN-nulls, so
    array_join/array_distinct/sort_array see them as nulls, not
    values."""
    if not cols:
        raise ValueError("array() needs at least one column")
    host = [np.asarray(c) for c in cols]  # one device→host fetch per column
    n = len(host[0])
    out = np.empty(n, object)
    for i in range(n):
        out[i] = np.asarray(
            [None if _cell_is_null(h[i]) else h[i] for h in host], object)
    return out


def _fn_sort_array(arr, *asc):
    """``sort_array``: nulls first ascending / last descending (Spark);
    SQL's second argument is optional, defaulting to ascending."""
    up = bool(np.asarray(asc[0]).ravel()[0]) if asc else True
    out = []
    for cell in _require_array_cells(arr, "sort_array"):
        if cell is None:
            out.append(None)
            continue
        vals = [v for v in cell if v is not None]
        nulls = [None] * (len(cell) - len(vals))
        vals.sort(reverse=not up)
        out.append(np.asarray(nulls + vals if up else vals + nulls, object))
    return np.asarray(out, object)


def _fn_array_distinct(arr):
    out = []
    for cell in _require_array_cells(arr, "array_distinct"):
        if cell is None:
            out.append(None)
            continue
        seen, vals = set(), []
        for v in cell:
            k = _elem_key(v)
            if k not in seen:
                seen.add(k)
                vals.append(v)
        out.append(np.asarray(vals, object))
    return np.asarray(out, object)


def _fn_array_join(arr, delim, *null_replacement):
    """``array_join(col, delim[, nullReplacement])``: nulls are dropped
    unless a replacement is given (Spark)."""
    d = str(np.asarray(delim).ravel()[0])
    rep = (str(np.asarray(null_replacement[0]).ravel()[0])
           if null_replacement else None)
    out = []
    for cell in _require_array_cells(arr, "array_join"):
        if cell is None:
            out.append(None)
            continue
        parts = [(rep if v is None else str(v)) for v in cell
                 if v is not None or rep is not None]
        out.append(d.join(parts))
    return np.asarray(out, object)


def _fn_slice(arr, start, length):
    """``slice(col, start, length)``: 1-based; negative start counts from
    the end; start 0 errors (Spark)."""
    s = _scalar_int(start)
    ln = _scalar_int(length)
    if s == 0:
        raise ValueError("slice start index is 1-based; 0 is invalid")
    if ln < 0:
        raise ValueError("slice length must be >= 0")
    out = []
    for cell in _require_array_cells(arr, "slice"):
        if cell is None:
            out.append(None)
            continue
        pos = s - 1 if s > 0 else len(cell) + s
        if pos < 0:
            out.append(np.asarray([], object))
        else:
            out.append(np.asarray(list(cell[pos:pos + ln]), object))
    return np.asarray(out, object)


def _fn_flatten(arr):
    """``flatten``: one level of nesting removed; a null inner array
    nulls the whole result cell (Spark). Requires array<array> input —
    a flat array column (whose inner cells are scalars/strings) is
    rejected like Spark's analyzer would, instead of silently exploding
    strings into characters."""
    out = []
    for cell in _require_array_cells(arr, "flatten"):
        if cell is None:
            out.append(None)
            continue
        vals: list = []
        for inner in cell:
            if inner is None:
                vals = None
                break
            if not isinstance(inner, (list, tuple, np.ndarray)):
                raise ValueError(
                    "flatten() expects an array-of-arrays column; inner "
                    f"cells here are {type(inner).__name__}")
            vals.extend(inner)
        out.append(None if vals is None else np.asarray(vals, object))
    return np.asarray(out, object)


def _fn_nanvl(a, b):
    """``nanvl(a, b)``: b where a is NaN (numeric columns; XLA fuses)."""
    a = jnp.asarray(a)
    return jnp.where(jnp.isnan(a), jnp.asarray(b, a.dtype), a)


def _fn_format_number(x, d):
    nd = _scalar_int(d)
    if nd < 0:
        raise ValueError("format_number decimal places must be >= 0")
    vals = np.asarray(x, np.float64)
    return np.asarray([None if np.isnan(v) else format(v, f",.{nd}f")
                       for v in vals], object)


def _fn_format_string(fmt, *cols):
    """printf formatting; a null argument in a row nulls that row's
    result (the engine's general null-propagation rule — Java's
    String.format would render %s nulls as 'null' but throw on %d)."""
    fa = np.asarray(fmt, object).ravel()  # Lit: frame-length column
    f = fa[0] if fa.size else ""
    host = [np.asarray(c, object) for c in cols]
    out = []
    for i in range(len(fa)):
        args = tuple(h[i] for h in host)
        if any(_cell_is_null(v) for v in args):
            out.append(None)
            continue
        out.append(f % args)
    return np.asarray(out, object)


def _cell_is_null(v) -> bool:
    return v is None or (isinstance(v, (float, np.floating)) and np.isnan(v))


def _fn_levenshtein(l, r):  # noqa: E741 - Spark's own argument names
    def dist(a, b):
        if a is None or b is None:
            return None
        if len(a) < len(b):
            a, b = b, a
        prev = list(range(len(b) + 1))
        for i, ca in enumerate(a, 1):
            cur = [i]
            for j, cb in enumerate(b, 1):
                cur.append(min(prev[j] + 1, cur[-1] + 1,
                               prev[j - 1] + (ca != cb)))
            prev = cur
        return prev[-1]

    la = np.asarray(l, object)
    ra = np.asarray(r, object)
    out = [dist(a, b) for a, b in zip(la, ra)]
    if any(v is None for v in out):
        return np.asarray(out, object)
    return np.asarray(out, np.int32)


def _fn_get_item(arr, index):
    """Spark ``getItem``: 0-based ordinal; negative or out-of-range (or a
    null cell) → null — Spark's GetArrayItem truth table, unlike
    ``element_at`` where negatives count from the end."""
    i = _scalar_int(index)
    out = []
    for cell in _require_array_cells(arr, "getItem"):
        if cell is None or i < 0 or i >= len(cell):
            out.append(None)
        else:
            out.append(cell[i])
    return np.asarray(out, object)


def _fn_array_size(arr):
    """Spark ``size(col)``: length of a list cell; null → -1. This is
    Spark 2.4's sizeOfNull=true default — the parity target here is the
    reference's pinned Spark 2.4.4 (`pom.xml:14`); Spark 3 flipped the
    default to null."""
    return jnp.asarray(np.asarray(
        [-1 if cell is None else len(cell)
         for cell in _require_array_cells(arr, "size")], np.int32))


def _elem_key(v):
    """Hashable identity for array-set operations: Spark's set functions
    (union/intersect/except/distinct) treat null as equal to null."""
    if v is None:
        return ("\0null",)
    if isinstance(v, (float, np.floating)) and np.isnan(v):
        return ("\0nan",)
    return v


def _fn_array_position(arr, value):
    """Spark ``array_position(col, value)``: 1-based index of the FIRST
    element equal to the literal; 0 when absent; null cell → null. Null
    elements never match (Spark's null-safe scan skips them)."""
    v = _scalar_value(value)
    out = []
    for cell in _require_array_cells(arr, "array_position"):
        if cell is None or v is None:
            out.append(None)
            continue
        pos = 0
        for i, x in enumerate(cell):
            if x is not None and x == v:
                pos = i + 1
                break
        out.append(pos)
    if any(x is None for x in out):
        return np.asarray(out, object)
    return jnp.asarray(np.asarray(out, np.int64))


def _fn_array_remove(arr, element):
    """Spark ``array_remove(col, element)``: drop ALL elements equal to
    the literal; null elements are kept (they compare null, not equal);
    null cell or null element → null."""
    v = _scalar_value(element)
    out = []
    for cell in _require_array_cells(arr, "array_remove"):
        if cell is None or v is None:
            out.append(None)
        else:
            out.append(np.asarray(
                [x for x in cell if x is None or x != v], object))
    return np.asarray(out, object)


def _array_set_op(name, candidates, keep):
    """Shared scan for the three array-set functions: one dedup pass over
    ``candidates(la, lb)`` keeping elements whose key passes
    ``keep(key, right_keyset)``; null ≡ null; either cell null → null."""

    def f(a, b):
        ca = _require_array_cells(a, name)
        cb = _require_array_cells(b, name)
        out = []
        for la, lb in zip(ca, cb):
            if la is None or lb is None:
                out.append(None)
                continue
            right = {_elem_key(x) for x in lb}
            seen, vals = set(), []
            for x in candidates(la, lb):
                k = _elem_key(x)
                if k not in seen and keep(k, right):
                    seen.add(k)
                    vals.append(x)
            out.append(np.asarray(vals, object))
        return np.asarray(out, object)

    return f


# Spark ``array_union``: a's first occurrences in order, then b's unseen
# ones. ``array_intersect``/``array_except``: deduplicated elements of a
# (in a's order) present/absent in b.
_fn_array_union = _array_set_op(
    "array_union", lambda la, lb: list(la) + list(lb), lambda k, r: True)
_fn_array_intersect = _array_set_op(
    "array_intersect", lambda la, lb: la, lambda k, r: k in r)
_fn_array_except = _array_set_op(
    "array_except", lambda la, lb: la, lambda k, r: k not in r)


def _fn_arrays_overlap(a, b):
    """Spark ``arrays_overlap``: true on a shared non-null element; if
    none and both sides are non-empty but either holds a null, the
    answer is unknowable → null; otherwise false. Null cell → null."""
    ca = _require_array_cells(a, "arrays_overlap")
    cb = _require_array_cells(b, "arrays_overlap")
    out = []
    for la, lb in zip(ca, cb):
        if la is None or lb is None:
            out.append(None)
            continue
        sa = {_elem_key(x) for x in la if x is not None}
        has_null = any(x is None for x in la) or any(x is None for x in lb)
        if any(x is not None and _elem_key(x) in sa for x in lb):
            out.append(True)
        elif len(la) and len(lb) and has_null:
            out.append(None)
        else:
            out.append(False)
    if any(x is None for x in out):
        return jnp.asarray(np.asarray(
            [np.nan if x is None else float(x) for x in out], np.float64),
            float_dtype())
    return jnp.asarray(np.asarray(out, np.bool_))


def _array_extreme(which):
    """``array_min`` / ``array_max``: null elements skipped; empty or
    all-null or null cell → null (Spark)."""
    pick = min if which == "min" else max

    def f(arr):
        out = []
        for cell in _require_array_cells(arr, f"array_{which}"):
            vals = (None if cell is None
                    else [x for x in cell if x is not None])
            out.append(pick(vals) if vals else None)
        if all(isinstance(x, str) for x in out if x is not None):
            return np.asarray(out, object)
        return jnp.asarray(np.asarray(
            [np.nan if x is None else float(x) for x in out], np.float64),
            float_dtype())

    return f


def _fn_array_repeat(elem, count):
    """Spark ``array_repeat(col, count)``: one array cell per row holding
    the row's (scalar) value ``count`` times; negative count → empty."""
    n = builtins.max(0, _scalar_int(count))
    host = np.asarray(elem, object) if _is_object(np.asarray(elem)) \
        else np.asarray(elem)
    out = np.empty(len(host), object)
    for i, x in enumerate(host):
        v = None if _cell_is_null(x) else x
        out[i] = np.asarray([v] * n, object)
    return out


def _fn_sequence(start, stop, *step):
    """Spark ``sequence(start, stop[, step])``: inclusive integer range
    per row; the default step is ±1 toward stop; a step of 0 or one
    pointing away from stop errors like Spark's runtime check."""
    sa = np.asarray(start, np.float64)
    so = np.asarray(stop, np.float64)
    st = np.asarray(step[0], np.float64) if step else None
    out = np.empty(len(sa), object)
    for i in range(len(sa)):
        if np.isnan(sa[i]) or np.isnan(so[i]) or \
                (st is not None and np.isnan(st[i])):
            out[i] = None
            continue
        lo, hi = int(sa[i]), int(so[i])
        s = int(st[i]) if st is not None else (1 if hi >= lo else -1)
        if s == 0 or (hi > lo and s < 0) or (hi < lo and s > 0):
            raise ValueError(
                f"sequence boundaries: {lo} to {hi} by {s} — the step "
                "must move toward stop (Spark's requirement)")
        out[i] = np.asarray(list(range(lo, hi + (1 if s > 0 else -1), s)),
                            object)
    return out


def _fn_arrays_zip(*arrs):
    """Spark ``arrays_zip``: element-wise tuples, padded with null to the
    longest input. Spark's cells are structs; struct columns do not
    exist in this engine, so each zipped element is a fixed-width list —
    positional access (`getItem`) behaves identically."""
    cells = [_require_array_cells(a, "arrays_zip") for a in arrs]
    out = []
    for row in zip(*cells):
        if any(c is None for c in row):
            out.append(None)
            continue
        width = builtins.max((len(c) for c in row), default=0)
        out.append(np.asarray(
            [np.asarray([c[j] if j < len(c) else None for c in row], object)
             for j in range(width)], object))
    return np.asarray(out, object)


def _fn_shuffle(arr, *seed):
    """Spark ``shuffle(col)``: random permutation per cell. Spark's is
    nondeterministic per query; here a seed of −1 (or SQL's one-argument
    form) means "draw one from the OS" and any other value makes the
    column reproducible (the same extension ``rand(seed)`` exposes)."""
    s = _scalar_int(seed[0]) if seed else -1
    rng = np.random.default_rng(None if s == -1 else s)
    out = []
    for cell in _require_array_cells(arr, "shuffle"):
        if cell is None:
            out.append(None)
        else:
            out.append(np.asarray(
                [cell[j] for j in rng.permutation(len(cell))], object))
    return np.asarray(out, object)


def _fn_reverse(v):
    """Spark ``reverse``: strings reverse characterwise, arrays
    elementwise — dispatched on the first non-null cell like the other
    array/string dual functions."""
    a = np.asarray(v, object)
    first = next((c for c in a if c is not None), None)
    if isinstance(first, (list, tuple, np.ndarray)):
        return np.asarray(
            [None if c is None else np.asarray(list(c)[::-1], object)
             for c in a], object)
    return _str_map(lambda x: x[::-1], v)


class Explode(Expr):
    """Marker expression for ``F.explode(col_or_expr)`` — a GENERATOR,
    not a scalar column: it multiplies rows, so only ``Frame.select``
    (one per select, Spark's rule) and ``Frame.explode`` understand it;
    evaluating it like a column raises. ``source`` is a column name or
    any array-valued expression (``explode(split(...))``)."""

    def __init__(self, source, outer: bool = False,
                 with_position: bool = False):
        self.source = source            # str | Expr
        self.outer = outer              # explode_outer: keep null rows
        self.with_position = with_position  # posexplode: (pos, col)

    def eval(self, frame):
        raise ValueError(
            "explode() is a generator — use it inside select() (one per "
            "select) or call Frame.explode(column) directly")

    def source_values(self, frame):
        """The array column being exploded, resolved against ``frame``."""
        if isinstance(self.source, str):
            return frame._column_values(self.source)  # friendly KeyError
        return self.source.eval(frame)

    @property
    def name(self) -> str:
        return "col"                    # Spark's default generator name

    def __str__(self):
        src = self.source if isinstance(self.source, str) else str(self.source)
        fn = "posexplode" if self.with_position else             ("explode_outer" if self.outer else "explode")
        return f"{fn}({src})"


def explode(col_) -> Explode:
    return Explode(col_ if isinstance(col_, str) else col_)


def explode_outer(col_) -> Explode:
    """Like ``explode`` but null/empty cells yield one null-element row."""
    return Explode(col_ if isinstance(col_, str) else col_, outer=True)


def posexplode(col_) -> Explode:
    """``explode`` plus a 0-based element position column ``pos``
    (Spark's default (pos, col) naming)."""
    return Explode(col_ if isinstance(col_, str) else col_,
                   with_position=True)


def _fn_regexp_replace(s, pattern, replacement):
    pat = re.compile(_scalar_str(pattern))
    rep = _scalar_str(replacement)
    return _str_map(lambda x: pat.sub(rep, x), s)


def _fn_regexp_extract(s, pattern, idx):
    pat = re.compile(_scalar_str(pattern))
    gi = _scalar_int(idx)

    def one(x):
        m = pat.search(x)
        return "" if m is None else (m.group(gi) or "")

    return _str_map(one, s)


def _int_or_null(vals):
    """int32 column, NaN-promoting to float when nulls are present (the
    engine's numeric-null convention; Spark: null in → null out)."""
    if any(v is None for v in vals):
        return jnp.asarray(np.asarray(
            [np.nan if v is None else float(v) for v in vals], np.float64),
            float_dtype())
    return jnp.asarray(np.asarray(vals, np.int32))


def _fn_instr(s, sub):
    needle = _scalar_str(sub)
    arr = np.asarray(s, object)
    return _int_or_null(
        [None if x is None else x.find(needle) + 1 for x in arr])


def _fn_locate(sub, s, pos=None):
    # Spark: locate(substr, str[, pos]) — note the flipped argument order
    needle = _scalar_str(sub)
    start = (_scalar_int(pos) if pos is not None else 1)
    arr = np.asarray(s, object)
    return _int_or_null(
        [None if x is None else x.find(needle, max(start - 1, 0)) + 1
         for x in arr])


def _fn_lpad(s, length, pad):
    ln = _scalar_int(length)
    p = _scalar_str(pad)

    def one(x):
        if ln <= 0:
            return ""                         # Spark: non-positive len → ""
        if len(x) >= ln:
            return x[:ln]
        fill = (p * ln)[:ln - len(x)] if p else ""
        return fill + x

    return _str_map(one, s)


def _fn_rpad(s, length, pad):
    ln = _scalar_int(length)
    p = _scalar_str(pad)

    def one(x):
        if ln <= 0:
            return ""                         # Spark: non-positive len → ""
        if len(x) >= ln:
            return x[:ln]
        fill = (p * ln)[:ln - len(x)] if p else ""
        return x + fill

    return _str_map(one, s)


def _fn_translate(s, matching, replace):
    # first occurrence of a repeated matching char wins (Spark semantics)
    mapping: dict = {}
    rep = _scalar_str(replace)
    for i, a in enumerate(_scalar_str(matching)):
        if a not in mapping:
            mapping[a] = rep[i] if i < len(rep) else None
    table = str.maketrans(mapping)
    return _str_map(lambda x: x.translate(table), s)


# Frame-aware nullary/row functions reached by NAME from SQL (the fluent
# constructors build RowFunc nodes directly): they need the row count or
# the evaluated argument's dtype, so they bypass the value-only builtin
# table and receive (frame, arg_exprs) from UdfCall.eval.
def _lit_arg(expr, what):
    if isinstance(expr, Lit):
        return expr.value
    if isinstance(expr, UnaryOp) and expr.op == "-" \
            and isinstance(expr.child, Lit):
        return -expr.child.value
    raise ValueError(f"{what} must be a literal")


def _row_generator(sql_name, kind, takes_seed=False):
    def f(frame, args):
        if not takes_seed and args:
            raise ValueError(f"{sql_name}() takes no arguments")
        if args and len(args) > 1:
            raise ValueError(f"{sql_name}([seed]) takes at most one "
                             "argument")
        seed = int(_lit_arg(args[0], f"{sql_name} seed")) if args else None
        # RowFunc.eval folds negative seeds, so SQL and fluent paths
        # produce identical streams for the same seed
        return RowFunc(kind, seed).eval(frame)
    return f


def _row_uuid(frame, args):
    if args:
        raise ValueError("uuid() takes no arguments")
    import uuid as _uuid

    return np.asarray([str(_uuid.uuid4()) for _ in range(frame.num_slots)],
                      dtype=object)


def _row_typeof(frame, args):
    if len(args) != 1:
        raise ValueError("typeof(expr) takes one argument")
    v = args[0].eval(frame)
    if _is_object(v):
        name = "string"
    else:
        dt = jnp.asarray(v).dtype
        name = ("boolean" if dt == jnp.bool_
                else "int" if jnp.issubdtype(dt, jnp.integer)
                else "double")
    return np.asarray([name] * frame.num_slots, dtype=object)


_ROW_FNS = {
    "monotonically_increasing_id":
        _row_generator("monotonically_increasing_id", "id"),
    "spark_partition_id": _row_generator("spark_partition_id",
                                         "partition_id"),
    "rand": _row_generator("rand", "rand", takes_seed=True),
    "randn": _row_generator("randn", "randn", takes_seed=True),
    "uuid": _row_uuid,
    "typeof": _row_typeof,
}


_BUILTIN_FNS = {
    # numeric (device, elementwise — XLA fuses into neighbors)
    "abs": lambda v: jnp.abs(v),
    "sqrt": lambda v: jnp.sqrt(jnp.asarray(v, float_dtype())),
    "exp": lambda v: jnp.exp(jnp.asarray(v, float_dtype())),
    "log": lambda v: jnp.log(jnp.asarray(v, float_dtype())),
    "log10": lambda v: jnp.log10(jnp.asarray(v, float_dtype())),
    "pow": lambda a, b: jnp.power(jnp.asarray(a, float_dtype()),
                                  jnp.asarray(b, float_dtype())),
    "power": lambda a, b: jnp.power(jnp.asarray(a, float_dtype()),
                                    jnp.asarray(b, float_dtype())),
    "floor": lambda v: jnp.floor(jnp.asarray(v, float_dtype())),
    "ceil": lambda v: jnp.ceil(jnp.asarray(v, float_dtype())),
    "round": _fn_round,
    "sign": lambda v: jnp.sign(jnp.asarray(v, float_dtype())),
    "signum": lambda v: jnp.sign(jnp.asarray(v, float_dtype())),
    # fmax/fmin skip NaN (Spark: greatest/least ignore nulls, NULL only
    # when every operand is null)
    "greatest": lambda *vs: functools.reduce(jnp.fmax,
                                             [jnp.asarray(v) for v in vs]),
    "least": lambda *vs: functools.reduce(jnp.fmin,
                                          [jnp.asarray(v) for v in vs]),
    "isnan": lambda v: jnp.isnan(jnp.asarray(v, float_dtype())),
    "coalesce": _fn_coalesce,
    "sin": lambda v: jnp.sin(jnp.asarray(v, float_dtype())),
    "cos": lambda v: jnp.cos(jnp.asarray(v, float_dtype())),
    "tan": lambda v: jnp.tan(jnp.asarray(v, float_dtype())),
    "asin": lambda v: jnp.arcsin(jnp.asarray(v, float_dtype())),
    "acos": lambda v: jnp.arccos(jnp.asarray(v, float_dtype())),
    "atan": lambda v: jnp.arctan(jnp.asarray(v, float_dtype())),
    "atan2": lambda a, b: jnp.arctan2(jnp.asarray(a, float_dtype()),
                                      jnp.asarray(b, float_dtype())),
    "sinh": lambda v: jnp.sinh(jnp.asarray(v, float_dtype())),
    "cosh": lambda v: jnp.cosh(jnp.asarray(v, float_dtype())),
    "tanh": lambda v: jnp.tanh(jnp.asarray(v, float_dtype())),
    "degrees": lambda v: jnp.degrees(jnp.asarray(v, float_dtype())),
    "radians": lambda v: jnp.radians(jnp.asarray(v, float_dtype())),
    "cbrt": lambda v: jnp.cbrt(jnp.asarray(v, float_dtype())),
    "expm1": lambda v: jnp.expm1(jnp.asarray(v, float_dtype())),
    "log1p": lambda v: jnp.log1p(jnp.asarray(v, float_dtype())),
    "log2": lambda v: jnp.log2(jnp.asarray(v, float_dtype())),
    "mod": lambda a, b: _sql_mod(jnp.asarray(a, float_dtype()),
                                 jnp.asarray(b, float_dtype())),
    # positive modulus (Spark pmod): result sign follows the DIVISOR
    "pmod": lambda a, b: jnp.where(
        jnp.asarray(b, float_dtype()) == 0, jnp.nan,
        jnp.mod(jnp.asarray(a, float_dtype()),
                jnp.asarray(b, float_dtype()))),
    "hypot": lambda a, b: jnp.hypot(jnp.asarray(a, float_dtype()),
                                    jnp.asarray(b, float_dtype())),
    "rint": lambda v: jnp.round(jnp.asarray(v, float_dtype())),
    # string (host object arrays; TPUs do not hold strings)
    "upper": lambda s: _str_map(str.upper, s),
    "lower": lambda s: _str_map(str.lower, s),
    "trim": lambda s: _str_map(str.strip, s),
    "ltrim": lambda s: _str_map(str.lstrip, s),
    "rtrim": lambda s: _str_map(str.rstrip, s),
    "length": _fn_length,
    "concat": lambda *ss: _fn_concat(*ss),
    "md5": lambda s: _str_map(
        lambda x: hashlib.md5(x.encode()).hexdigest(), s),
    "sha1": lambda s: _str_map(
        lambda x: hashlib.sha1(x.encode()).hexdigest(), s),
    "sha2": _fn_sha2,
    "base64": lambda s: _str_map(
        lambda x: _b64.b64encode(x.encode()).decode(), s),
    # Spark's unbase64 yields BINARY; string cells here hold the bytes as
    # latin-1 (lossless byte-per-char), so non-UTF8 payloads can't crash
    "unbase64": lambda s: _str_map(
        lambda x: _b64.b64decode(x.encode()).decode("latin-1"), s),
    "substring": _fn_substring,
    "substr": _fn_substring,
    "concat_ws": _fn_concat_ws,
    "split": _fn_split,
    "array_contains": _fn_array_contains,
    "element_at": _fn_element_at,
    "get_item": _fn_get_item,
    "array": _fn_array,
    "sort_array": _fn_sort_array,
    "array_distinct": _fn_array_distinct,
    "array_join": _fn_array_join,
    "slice": _fn_slice,
    "flatten": _fn_flatten,
    "nanvl": _fn_nanvl,
    "format_number": _fn_format_number,
    "format_string": _fn_format_string,
    "levenshtein": _fn_levenshtein,
    "size": _fn_array_size,
    "regexp_replace": _fn_regexp_replace,
    "regexp_extract": _fn_regexp_extract,
    "instr": _fn_instr,
    "locate": _fn_locate,
    "lpad": _fn_lpad,
    "rpad": _fn_rpad,
    # left/right are SQL keywords (join types); the parser special-cases
    # the call forms LEFT(s, n) / RIGHT(s, n) into these
    "left": lambda s, n: _str_map(
        lambda x: x[:_scalar_int(n)] if _scalar_int(n) > 0 else "", s),
    "right": lambda s, n: _str_map(
        lambda x: x[-_scalar_int(n):] if _scalar_int(n) > 0 else "", s),
    "overlay": lambda s, r, pos, ln=None: _str_map(
        lambda x, y: x[:_scalar_int(pos) - 1] + y
        + x[_scalar_int(pos) - 1
            + (_scalar_int(ln) if ln is not None else len(y)):], s, r),
    "repeat": lambda s, n: _str_map(
        lambda x: x * _scalar_int(n), s),
    "reverse": _fn_reverse,
    "array_position": _fn_array_position,
    "array_remove": _fn_array_remove,
    "array_union": _fn_array_union,
    "array_intersect": _fn_array_intersect,
    "array_except": _fn_array_except,
    "arrays_overlap": _fn_arrays_overlap,
    "array_min": _array_extreme("min"),
    "array_max": _array_extreme("max"),
    "array_repeat": _fn_array_repeat,
    "sequence": _fn_sequence,
    "arrays_zip": _fn_arrays_zip,
    "shuffle": _fn_shuffle,
    "initcap": lambda s: _str_map(
        lambda x: " ".join(w.capitalize() for w in x.split(" ")), s),
    "translate": _fn_translate,
}


class Func(Expr):
    """Builtin scalar function call (the ``org.apache.spark.sql.functions``
    scalar set). Numeric fns are jnp ops XLA fuses into neighboring
    expressions; string fns run host-side on object columns."""

    def __init__(self, fn_name: str, args: Sequence[Expr]):
        key = fn_name.lower()
        if key not in _BUILTIN_FNS:
            raise ValueError(f"unknown function {fn_name!r}")
        self.fn_name = key
        self.args = list(args)

    def eval(self, frame):
        vals = [a.eval(frame) for a in self.args]
        return _BUILTIN_FNS[self.fn_name](*vals)

    @property
    def name(self) -> str:
        return f"{self.fn_name}({', '.join(str(a) for a in self.args)})"

    def __str__(self):
        return self.name


class CaseWhen(Expr):
    """``when(cond, value).when(...).otherwise(value)`` / SQL CASE WHEN.

    Folds into nested ``jnp.where`` (one fused select chain on device).
    A missing ELSE yields null (NaN for numeric, None for strings) —
    Spark semantics.
    """

    def __init__(self, branches, otherwise=None):
        self.branches = list(branches)  # [(cond Expr, value Expr), ...]
        self.otherwise_expr = otherwise

    def when(self, condition: Expr, value) -> "CaseWhen":
        value = value if isinstance(value, Expr) else Lit(value)
        return CaseWhen(self.branches + [(condition, value)],
                        self.otherwise_expr)

    def otherwise(self, value) -> "CaseWhen":
        value = value if isinstance(value, Expr) else Lit(value)
        return CaseWhen(self.branches, value)

    def eval(self, frame):
        conds = [c.eval(frame) for c, _ in self.branches]
        vals = [v.eval(frame) for _, v in self.branches]
        stringy = any(_is_object(v) for v in vals)
        if self.otherwise_expr is not None:
            out = self.otherwise_expr.eval(frame)
            stringy = stringy or _is_object(out)
        elif stringy:
            out = np.full((frame.num_slots,), None, dtype=object)
        else:
            out = jnp.full((frame.num_slots,), jnp.nan, float_dtype())
        if stringy:
            out = np.asarray(out, object)
            for c, v in zip(reversed(conds), reversed(vals)):
                out = np.where(np.asarray(c, bool), np.asarray(v, object), out)
            return out
        for c, v in zip(reversed(conds), reversed(vals)):
            v = jnp.asarray(v)
            if jnp.issubdtype(jnp.asarray(out).dtype, jnp.floating) or \
                    jnp.issubdtype(v.dtype, jnp.floating):
                v = jnp.asarray(v, float_dtype())
                out = jnp.asarray(out, float_dtype())
            out = jnp.where(jnp.asarray(c), v, out)
        return out

    @property
    def name(self) -> str:
        parts = " ".join(f"WHEN {c} THEN {v}" for c, v in self.branches)
        tail = f" ELSE {self.otherwise_expr}" if self.otherwise_expr is not None else ""
        return f"CASE {parts}{tail} END"

    def __str__(self):
        return self.name


# -- public constructors (mirrors org.apache.spark.sql.functions) ----------

def col(name: str) -> Col:
    return Col(name)


def lit(value) -> Lit:
    return Lit(value)


def call_udf(name: str, *args) -> UdfCall:
    """``functions.callUDF`` equivalent; accepts Exprs or column names."""
    exprs = [a if isinstance(a, Expr) else Col(a) if isinstance(a, str) else Lit(a)
             for a in args]
    return UdfCall(name, exprs)


# Spark naming alias
callUDF = call_udf


def _coerce(a) -> Expr:
    return a if isinstance(a, Expr) else Col(a) if isinstance(a, str) else Lit(a)


def fn(name: str, *args) -> Func:
    """Builtin scalar function by name (``functions.expr``-style escape)."""
    return Func(name, [_coerce(a) for a in args])


def when(condition: Expr, value) -> CaseWhen:
    """``functions.when`` — start a CASE chain; extend with ``.when`` and
    close with ``.otherwise`` (missing otherwise ⇒ null)."""
    return CaseWhen([]).when(condition, value)


def _make_fn(fname: str):
    def f(*args):
        return fn(fname, *args)

    f.__name__ = fname
    f.__qualname__ = fname
    f.__doc__ = f"``functions.{fname}`` equivalent (builtin scalar fn)."
    return f


sql_abs = _make_fn("abs")
sqrt = _make_fn("sqrt")
exp = _make_fn("exp")
log = _make_fn("log")
log10 = _make_fn("log10")
pow = _make_fn("pow")
floor = _make_fn("floor")
ceil = _make_fn("ceil")
sql_round = _make_fn("round")
signum = _make_fn("signum")
greatest = _make_fn("greatest")
least = _make_fn("least")
isnan = _make_fn("isnan")
coalesce = _make_fn("coalesce")
nvl = _make_fn("coalesce")          # Spark: nvl(a, b) == coalesce(a, b)
md5 = _make_fn("md5")
sha1 = _make_fn("sha1")
sha2 = _make_fn("sha2")
base64 = _make_fn("base64")
def array_contains(col_, value) -> Func:
    """PySpark shape: the value is a plain literal (or a Lit), never a
    column reference."""
    return Func("array_contains",
                [_coerce(col_), value if isinstance(value, Expr)
                 else Lit(value)])


def element_at(col_, index: int) -> Func:
    return Func("element_at", [_coerce(col_), Lit(int(index))])


def size(col_) -> Func:
    return Func("size", [_coerce(col_)])
unbase64 = _make_fn("unbase64")
upper = _make_fn("upper")
lower = _make_fn("lower")
trim = _make_fn("trim")
ltrim = _make_fn("ltrim")
rtrim = _make_fn("rtrim")
length = _make_fn("length")
concat = _make_fn("concat")
substring = _make_fn("substring")
array = _make_fn("array")
array_distinct = _make_fn("array_distinct")
flatten = _make_fn("flatten")
nanvl = _make_fn("nanvl")
format_number = _make_fn("format_number")
levenshtein = _make_fn("levenshtein")


def format_string(fmt: str, *cols) -> "Func":
    """``format_string('%s: %d', c1, c2)`` — printf formatting; the
    format is a literal, not a column name (``fn`` would coerce a bare
    string to a Col)."""
    return fn("format_string", Lit(fmt), *cols)


def sort_array(col_, asc: bool = True) -> "Func":
    """``sort_array(col[, asc])``: nulls first ascending / last
    descending (Spark)."""
    return fn("sort_array", col_, Lit(bool(asc)))


def array_join(col_, delimiter: str, null_replacement=None) -> "Func":
    """``array_join(col, delim[, nullReplacement])``: nulls dropped
    unless a replacement is given (Spark)."""
    if null_replacement is None:
        return fn("array_join", col_, Lit(delimiter))
    return fn("array_join", col_, Lit(delimiter), Lit(null_replacement))


def slice(col_, start: int, length: int) -> "Func":  # noqa: A001 - Spark name
    """``slice(col, start, length)``: 1-based, negative start counts from
    the end (Spark)."""
    return fn("slice", col_, Lit(int(start)), Lit(int(length)))


def array_position(col_, value) -> Func:
    """``array_position(col, value)`` — 1-based first match, 0 if absent."""
    return Func("array_position",
                [_coerce(col_), value if isinstance(value, Expr)
                 else Lit(value)])


def array_remove(col_, element) -> Func:
    """``array_remove(col, element)`` — drop every equal element."""
    return Func("array_remove",
                [_coerce(col_), element if isinstance(element, Expr)
                 else Lit(element)])


array_union = _make_fn("array_union")
array_intersect = _make_fn("array_intersect")
array_except = _make_fn("array_except")
arrays_overlap = _make_fn("arrays_overlap")
array_min = _make_fn("array_min")
array_max = _make_fn("array_max")
arrays_zip = _make_fn("arrays_zip")


def array_repeat(col_, count: int) -> Func:
    """``array_repeat(col, count)`` — the count is a literal."""
    return Func("array_repeat", [_coerce(col_), Lit(int(count))])


def sequence(start, stop, step=None) -> Func:
    """``sequence(start, stop[, step])`` — inclusive range per row."""
    args = [_coerce(start), _coerce(stop)]
    if step is not None:
        args.append(_coerce(step))
    return Func("sequence", args)


def shuffle(col_, seed: int = None) -> Func:
    """``shuffle(col)`` — random per-cell permutation; the optional seed
    is an extension (Spark's is always nondeterministic)."""
    return Func("shuffle",
                [_coerce(col_), Lit(-1 if seed is None else int(seed))])


class RowFunc(Expr):
    """Frame-length generator column (``rand``/``randn``/row ids): knows
    nothing about other columns, only how many row slots the frame has.
    Seeded generators are deterministic per expression instance, like
    Spark's ``rand(seed)`` per plan node."""

    _KINDS = ("rand", "randn", "id", "partition_id")

    def __init__(self, kind: str, seed=None):
        if kind not in self._KINDS:
            raise ValueError(f"unknown row generator {kind!r}")
        self.kind = kind
        self.seed = seed

    def eval(self, frame):
        n = frame.num_slots
        if self.kind == "id":
            return jnp.arange(n, dtype=int_dtype())
        if self.kind == "partition_id":
            # one logical partition: the id is 0 everywhere (the same
            # no-op stance as repartition/coalesce)
            return jnp.zeros((n,), dtype=int_dtype())
        seed = self.seed
        if seed is not None and int(seed) < 0:
            # numpy's default_rng rejects negatives; fold deterministically
            seed = int(seed) & 0x7FFFFFFFFFFFFFFF
        rng = np.random.default_rng(seed)
        host = (rng.uniform(size=n) if self.kind == "rand"
                else rng.standard_normal(size=n))
        return jnp.asarray(host.astype(np.dtype(float_dtype())))

    @property
    def name(self) -> str:
        if self.kind == "id":
            return "monotonically_increasing_id()"
        if self.kind == "partition_id":
            return "spark_partition_id()"
        seed = "" if self.seed is None else str(self.seed)
        return f"{self.kind}({seed})"

    def __str__(self):
        return self.name


def rand(seed=None) -> RowFunc:
    """Uniform [0, 1) column (Spark ``rand``); deterministic per seed."""
    return RowFunc("rand", seed)


def randn(seed=None) -> RowFunc:
    """Standard-normal column (Spark ``randn``)."""
    return RowFunc("randn", seed)


def monotonically_increasing_id() -> RowFunc:
    """Row ids 0..n-1 (Spark's are only partition-monotone; one logical
    partition here makes them consecutive)."""
    return RowFunc("id")


def spark_partition_id() -> RowFunc:
    """Always 0 — one logical partition (see repartition's no-op note)."""
    return RowFunc("partition_id")


def expr(sql_text: str) -> Expr:
    """Spark ``F.expr``: one SQL expression (the same grammar as
    ``selectExpr`` items — CAST, arithmetic, functions, AS alias).
    Aggregates/window items are not scalar expressions; use
    ``selectExpr``/``session.sql`` for those."""
    from ..sql.parser import _Parser, tokenize

    p = _Parser(tokenize(sql_text))
    item = p.parse_select_item()
    p.expect("eof")  # trailing tokens = a typo, not a second expression
    if not isinstance(item, Expr):
        raise ValueError(
            f"expr({sql_text!r}) is not a scalar expression; use "
            "selectExpr()/session.sql() for aggregates and window items")
    return item


sin = _make_fn("sin")
cos = _make_fn("cos")
tan = _make_fn("tan")
asin = _make_fn("asin")
acos = _make_fn("acos")
atan = _make_fn("atan")
atan2 = _make_fn("atan2")
sinh = _make_fn("sinh")
cosh = _make_fn("cosh")
tanh = _make_fn("tanh")
degrees = _make_fn("degrees")
radians = _make_fn("radians")
cbrt = _make_fn("cbrt")
expm1 = _make_fn("expm1")
log1p = _make_fn("log1p")
log2 = _make_fn("log2")
hypot = _make_fn("hypot")
rint = _make_fn("rint")
repeat = _make_fn("repeat")
reverse = _make_fn("reverse")
initcap = _make_fn("initcap")


# String functions whose pattern/pad/separator arguments are LITERALS in
# Spark's signatures — a bare str there must not coerce to a column ref.
def concat_ws(sep: str, *cols) -> Func:
    return Func("concat_ws", [Lit(sep)] + [_coerce(c) for c in cols])


def split(col_, pattern: str) -> Func:
    return Func("split", [_coerce(col_), Lit(pattern)])


def regexp_replace(col_, pattern: str, replacement: str) -> Func:
    return Func("regexp_replace",
                [_coerce(col_), Lit(pattern), Lit(replacement)])


def regexp_extract(col_, pattern: str, idx: int) -> Func:
    return Func("regexp_extract", [_coerce(col_), Lit(pattern), Lit(idx)])


def instr(col_, substr: str) -> Func:
    return Func("instr", [_coerce(col_), Lit(substr)])


def locate(substr: str, col_, pos: int = 1) -> Func:
    return Func("locate", [Lit(substr), _coerce(col_), Lit(pos)])


def lpad(col_, length: int, pad: str) -> Func:
    return Func("lpad", [_coerce(col_), Lit(length), Lit(pad)])


def rpad(col_, length: int, pad: str) -> Func:
    return Func("rpad", [_coerce(col_), Lit(length), Lit(pad)])


def translate(col_, matching: str, replace: str) -> Func:
    return Func("translate", [_coerce(col_), Lit(matching), Lit(replace)])


def isnull(c) -> Expr:
    return _coerce(c).is_null()


# ---------------------------------------------------------------------------
# Date / time functions
#
# TPU-native representation: a DATE is a float device column of days since
# the Unix epoch with NaN as null — the engine's numeric-null convention,
# so null dates are visible to isnull()/filters/aggregates (an int
# sentinel would silently pass comparisons). Day counts are exact in
# float32 far past any calendar. Field extraction (year/month/day...) is
# vectorized integer math ON DEVICE (civil-from-days, Hinnant's
# algorithm), not a host datetime loop; fields come back float with NaN
# propagated. Parsing and formatting cross the host boundary like every
# string op. Epoch SECONDS exceed float32's exact-integer range, so
# unix_timestamp requires the x64 mode and yields float64.
# ---------------------------------------------------------------------------


def _strptime_format(java_fmt: str) -> str:
    """Translate a Spark/Java date pattern into strptime, run by run.
    Unsupported pattern letters raise instead of silently producing
    all-null columns."""
    runs = {"yyyy": "%Y", "yy": "%y", "MM": "%m", "M": "%m",
            "dd": "%d", "d": "%d", "HH": "%H", "H": "%H",
            "mm": "%M", "m": "%M", "ss": "%S", "s": "%S"}
    out = []
    i = 0
    while i < len(java_fmt):
        c = java_fmt[i]
        if c.isalpha():
            j = i
            while j < len(java_fmt) and java_fmt[j] == c:
                j += 1
            run = java_fmt[i:j]
            if run not in runs:
                raise ValueError(
                    f"unsupported date-format token {run!r} in "
                    f"{java_fmt!r} (supported: {sorted(runs)})")
            out.append(runs[run])
            i = j
        else:
            out.append("%%" if c == "%" else c)
            i += 1
    return "".join(out)


def _parse_dates(s, fmt: str, unit_seconds: bool):
    """Host parse of a string column → epoch days (engine float, NaN null)
    or epoch seconds (float64, x64 required). Unparseable / null rows →
    NaN (Spark yields null)."""
    import datetime as _dt

    py_fmt = _strptime_format(fmt)
    arr = np.asarray(s, object)
    out = np.empty(len(arr), np.float64)
    epoch = _dt.datetime(1970, 1, 1)
    for i, x in enumerate(arr):
        if x is None:
            out[i] = np.nan
            continue
        try:
            t = _dt.datetime.strptime(str(x).strip(), py_fmt)
        except ValueError:
            out[i] = np.nan
            continue
        delta = t - epoch
        out[i] = delta.total_seconds() if unit_seconds else delta.days
    if unit_seconds:
        import jax

        if not jax.config.jax_enable_x64:
            raise ValueError(
                "unix_timestamp requires jax_enable_x64: epoch seconds "
                "exceed float32's exact-integer range (use to_date for "
                "day-resolution work)")
        return jnp.asarray(out, jnp.float64)
    return jnp.asarray(out, float_dtype())


def _civil_from_days(z):
    """days-since-epoch → (year, month, day), vectorized integer device math
    (Howard Hinnant's civil_from_days)."""
    z = z + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097                                   # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)          # [0, 365]
    mp = (5 * doy + 2) // 153                                # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                        # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)                   # [1, 12]
    return jnp.where(m <= 2, y + 1, y), m, d


def _days_from_civil(y, m, d):
    """(year, month, day) → days since epoch, device integer math."""
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _fn_to_date(s, fmt=None):
    f = _scalar_str(fmt) if fmt is not None else "yyyy-MM-dd"
    return _parse_dates(s, f, unit_seconds=False)


def _fn_unix_timestamp(s, fmt=None):
    f = _scalar_str(fmt) if fmt is not None else "yyyy-MM-dd HH:mm:ss"
    return _parse_dates(s, f, unit_seconds=True)


def _date_field(which: str):
    def f(days):
        days = _days_of(days)
        null = jnp.isnan(days)
        z = jnp.where(null, 0, days).astype(jnp.int32)
        y, m, d = _civil_from_days(z)
        if which == "year":
            v = y
        elif which == "month":
            v = m
        elif which == "dayofmonth":
            v = d
        elif which == "quarter":
            v = (m - 1) // 3 + 1
        elif which == "dayofweek":
            # Spark: 1 = Sunday ... 7 = Saturday; epoch day 0 was a Thursday
            v = (z + 4) % 7 + 1
        else:  # dayofyear
            v = z - _days_from_civil(y, jnp.ones_like(y),
                                     jnp.ones_like(y)) + 1
        return jnp.where(null, jnp.nan, v.astype(days.dtype))
    return f


_DATETIME_RE = None


def _parse_datetime_cell(x):
    """Spark's lenient implicit string→timestamp cast for one cell:
    ``yyyy[-M[-d]][ T hh:mm[:ss[.fff]]][anything]`` — partial dates
    default missing fields to 01/midnight, and trailing content
    (timezone suffixes, junk after a complete prefix) is ignored like
    Spark's ``stringToDate``/``stringToTimestamp``. Returns a datetime
    or None."""
    import datetime as _dt
    import re

    global _DATETIME_RE
    if _DATETIME_RE is None:
        _DATETIME_RE = re.compile(
            r"^(\d{4})(?:-(\d{1,2})(?:-(\d{1,2})"
            r"(?:[ T](\d{1,2}):(\d{2})(?::(\d{2})(?:\.\d+)?)?)?)?)?")
    if x is None:
        return None
    s = str(x).strip()
    m = _DATETIME_RE.match(s)
    if not m:
        return None
    y, mo, d, hh, mi, ss = m.groups()
    try:
        return _dt.datetime(int(y), int(mo or 1), int(d or 1),
                            int(hh or 0), int(mi or 0), int(ss or 0))
    except ValueError:          # e.g. month 13 / day 32
        return None


# Numeric date/time values carry no type tag in this engine (dates are
# epoch DAYS — to_date's output; timestamps epoch SECONDS —
# to_timestamp/unix_timestamp's output), so mixed compositions like
# hour(to_timestamp(s)) disambiguate by magnitude: |v| ≥ 1e8 is seconds
# (1e8 s = 1973-03-03; 1e8 days is year 275760, far past Spark's own
# 9999-12-31 ceiling). The one ambiguous window — timestamps inside
# 1966-10-31..1973-03-03 — would need day-resolution fallbacks; Spark's
# typed DATE/TIMESTAMP split has no such window, which is the cost of a
# float-only column model and is documented here deliberately.
_SECONDS_CUTOFF = 1e8


def _days_of(v):
    """Epoch-day view of a date operand with Spark's implicit cast: string
    (object) columns accept full dates, timestamp-shaped strings (the
    time part is dropped for day math), and partial 'yyyy[-MM]' forms —
    unparseable/null → NaN; numeric columns are epoch days (``to_date``)
    or epoch seconds (``to_timestamp``), split at ``_SECONDS_CUTOFF``."""
    if _is_object(v):
        import datetime as _dt

        epoch = _dt.date(1970, 1, 1)
        out = np.empty(len(v), np.float64)
        for i, x in enumerate(v):
            t = _parse_datetime_cell(x)
            out[i] = np.nan if t is None else (t.date() - epoch).days
        return jnp.asarray(out, float_dtype())
    arr = jnp.asarray(v, float_dtype())
    return jnp.where(jnp.abs(arr) >= _SECONDS_CUTOFF,
                     jnp.floor(arr / 86400.0), arr)


def _fn_datediff(end, start):
    return _days_of(end) - _days_of(start)         # NaN propagates


def _fn_date_add(days, n):
    return _days_of(days) + _scalar_int(n)


def _fn_date_sub(days, n):
    return _days_of(days) - _scalar_int(n)


def _fn_date_format(days, fmt):
    import datetime as _dt

    py_fmt = _strptime_format(_scalar_str(fmt))
    if _is_object(days):
        # string input: Spark casts to TIMESTAMP, so time-of-day survives
        # into HH/mm/ss format tokens
        return np.asarray(
            [None if (t := _parse_datetime_cell(x)) is None
             else t.strftime(py_fmt) for x in days], object)
    arr = np.asarray(days, np.float64)
    epoch = _dt.date(1970, 1, 1)
    return np.asarray(
        [None if np.isnan(v)
         else (epoch + _dt.timedelta(days=int(v))).strftime(py_fmt)
         for v in arr], object)


def _fn_from_unixtime(secs, fmt=None):
    import datetime as _dt

    py_fmt = _strptime_format(
        _scalar_str(fmt) if fmt is not None else "yyyy-MM-dd HH:mm:ss")
    arr = np.asarray(secs, np.float64)
    epoch = _dt.datetime(1970, 1, 1)
    return np.asarray(
        [None if np.isnan(v)
         else (epoch + _dt.timedelta(seconds=int(v))).strftime(py_fmt)
         for v in arr], object)


_BUILTIN_FNS.update({
    "to_date": _fn_to_date,
    "unix_timestamp": _fn_unix_timestamp,
    "from_unixtime": _fn_from_unixtime,
    "date_format": _fn_date_format,
    "datediff": _fn_datediff,
    "date_add": _fn_date_add,
    "date_sub": _fn_date_sub,
    "year": _date_field("year"),
    "month": _date_field("month"),
    "dayofmonth": _date_field("dayofmonth"),
    "dayofweek": _date_field("dayofweek"),
    "dayofyear": _date_field("dayofyear"),
    "quarter": _date_field("quarter"),
})


def to_date(col_, fmt: str = None) -> Func:
    args = [_coerce(col_)] + ([Lit(fmt)] if fmt is not None else [])
    return Func("to_date", args)


def unix_timestamp(col_, fmt: str = None) -> Func:
    args = [_coerce(col_)] + ([Lit(fmt)] if fmt is not None else [])
    return Func("unix_timestamp", args)


def from_unixtime(col_, fmt: str = None) -> Func:
    args = [_coerce(col_)] + ([Lit(fmt)] if fmt is not None else [])
    return Func("from_unixtime", args)


def date_format(col_, fmt: str) -> Func:
    return Func("date_format", [_coerce(col_), Lit(fmt)])


def date_add(col_, n: int) -> Func:
    return Func("date_add", [_coerce(col_), Lit(n)])


def date_sub(col_, n: int) -> Func:
    return Func("date_sub", [_coerce(col_), Lit(n)])


datediff = _make_fn("datediff")
year = _make_fn("year")
month = _make_fn("month")
dayofmonth = _make_fn("dayofmonth")
dayofweek = _make_fn("dayofweek")
dayofyear = _make_fn("dayofyear")
quarter = _make_fn("quarter")


def current_date() -> Expr:
    """Today as epoch days (host clock, evaluated at call time)."""
    import datetime as _dt

    return Lit(float((_dt.date.today() - _dt.date(1970, 1, 1)).days))


# -- timestamp-resolution family ------------------------------------------
# Date values are epoch DAYS (to_date's output); timestamps are epoch
# SECONDS and require jax_enable_x64 (seconds exceed float32's exact
# range — the same contract unix_timestamp enforces). A numeric input to
# the time-of-day extractors is epoch days, i.e. midnight, so
# hour/minute/second are 0 — exactly Spark's hour(CAST(x AS DATE)).


def _time_field(which: str):
    def f(v):
        if _is_object(v):
            sel = {"hour": lambda t: t.hour, "minute": lambda t: t.minute,
                   "second": lambda t: t.second}[which]
            out = [None if (t := _parse_datetime_cell(x)) is None else sel(t)
                   for x in np.asarray(v, object)]
            return jnp.asarray(np.asarray(
                [np.nan if x is None else float(x) for x in out], np.float64),
                float_dtype())
        # numeric: epoch seconds carry time-of-day; epoch days (below the
        # magnitude cutoff) are midnight ⇒ 0, Spark's hour(CAST AS DATE)
        host = np.asarray(v, np.float64)
        if np.any(np.abs(host[~np.isnan(host)]) >= _SECONDS_CUTOFF):
            # time-of-day of an epoch-second value needs sub-second
            # precision the f32 column cannot carry — same contract as
            # to_timestamp/unix_timestamp, raised instead of silently
            # returning minutes/seconds that are off by the f32 quantum
            _require_x64(f"{which}() on epoch-second (timestamp) values")
        arr = jnp.asarray(v, jnp.float64)
        sod = jnp.where(jnp.abs(arr) >= _SECONDS_CUTOFF,
                        jnp.mod(arr, 86400.0), 0.0)
        val = {"hour": sod // 3600.0,
               "minute": jnp.mod(sod, 3600.0) // 60.0,
               "second": jnp.mod(sod, 60.0) // 1.0}[which]
        return jnp.where(jnp.isnan(arr), jnp.nan,
                         val).astype(float_dtype())
    return f


def _fn_weekofyear(v):
    """ISO-8601 week number (Spark's WEEKOFYEAR). Host calendar math —
    the ISO rule (week containing the year's first Thursday) is not
    worth a branchless device expression for frame-sized date columns."""
    import datetime as _dt

    days = np.asarray(_days_of(v), np.float64)
    epoch = _dt.date(1970, 1, 1)
    out = [np.nan if np.isnan(d)
           else float((epoch + _dt.timedelta(days=int(d))).isocalendar()[1])
           for d in days]
    return jnp.asarray(np.asarray(out, np.float64), float_dtype())


def _fn_last_day(v):
    """``last_day(date)``: last day of the date's month, device civil
    math — the 1st of the next month minus one day."""
    days = _days_of(v)
    null = jnp.isnan(days)
    z = jnp.where(null, 0, days).astype(jnp.int32)
    y, m, _ = _civil_from_days(z)
    ny = jnp.where(m == 12, y + 1, y)
    nm = jnp.where(m == 12, 1, m + 1)
    out = _days_from_civil(ny, nm, jnp.ones_like(ny)) - 1
    return jnp.where(null, jnp.nan, out.astype(days.dtype))


def _days_in_month(y, m):
    ny = jnp.where(m == 12, y + 1, y)
    nm = jnp.where(m == 12, 1, m + 1)
    one = jnp.ones_like(y)
    return (_days_from_civil(ny, nm, one) - _days_from_civil(y, m, one))


def _fn_add_months(v, n):
    """``add_months(date, n)``: calendar month shift with Spark's
    day-of-month clamp (Jan 31 + 1 month = Feb 28/29)."""
    k = _scalar_int(n)
    days = _days_of(v)
    null = jnp.isnan(days)
    z = jnp.where(null, 0, days).astype(jnp.int32)
    y, m, d = _civil_from_days(z)
    total = y * 12 + (m - 1) + k
    ny = total // 12
    nm = total % 12 + 1
    nd = jnp.minimum(d, _days_in_month(ny, nm))
    out = _days_from_civil(ny, nm, nd)
    return jnp.where(null, jnp.nan, out.astype(days.dtype))


def _fn_months_between(end, start, *round_off):
    """Spark ``months_between``: whole calendar months when both dates
    fall on the same day-of-month or both on month-ends; otherwise the
    fractional remainder uses Spark's fixed /31 divisor. Day resolution
    (this engine's date values carry no time-of-day); roundOff (default
    true) rounds to 8 places like Spark."""
    ro = bool(_scalar_value(round_off[0])) if round_off else True
    d1 = _days_of(end)
    d2 = _days_of(start)
    null = jnp.isnan(d1) | jnp.isnan(d2)
    z1 = jnp.where(null, 0, d1).astype(jnp.int32)
    z2 = jnp.where(null, 0, d2).astype(jnp.int32)
    y1, m1, dd1 = _civil_from_days(z1)
    y2, m2, dd2 = _civil_from_days(z2)
    months = ((y1 - y2) * 12 + (m1 - m2)).astype(jnp.float64)
    both_last = (dd1 == _days_in_month(y1, m1)) & \
                (dd2 == _days_in_month(y2, m2))
    whole = (dd1 == dd2) | both_last
    frac = (dd1 - dd2).astype(jnp.float64) / 31.0
    out = jnp.where(whole, months, months + frac)
    if ro:
        out = jnp.round(out * 1e8) / 1e8
    return jnp.where(null, jnp.nan, out.astype(float_dtype()))


_DOW_NAMES = {"su": 1, "sun": 1, "sunday": 1, "mo": 2, "mon": 2,
              "monday": 2, "tu": 3, "tue": 3, "tuesday": 3, "we": 4,
              "wed": 4, "wednesday": 4, "th": 5, "thu": 5, "thursday": 5,
              "fr": 6, "fri": 6, "friday": 6, "sa": 7, "sat": 7,
              "saturday": 7}


def _fn_next_day(v, day_name):
    """``next_day(date, 'Mon')``: the first named weekday STRICTLY after
    the date; an unrecognized name yields null (Spark 2.4's behavior,
    not an error)."""
    name = str(_scalar_value(day_name) or "").strip().lower()
    target = _DOW_NAMES.get(name)
    days = _days_of(v)
    null = jnp.isnan(days)
    if target is None:
        return jnp.full_like(days, jnp.nan)
    z = jnp.where(null, 0, days).astype(jnp.int32)
    dow = (z + 4) % 7 + 1              # 1 = Sunday (epoch day 0: Thursday)
    delta = (target - dow) % 7
    delta = jnp.where(delta == 0, 7, delta)
    return jnp.where(null, jnp.nan, (z + delta).astype(days.dtype))


def _fn_trunc(v, fmt):
    """``trunc(date, fmt)``: year/month truncation to epoch days; an
    unsupported format yields null (Spark)."""
    f = str(_scalar_str(fmt)).lower()
    days = _days_of(v)
    null = jnp.isnan(days)
    z = jnp.where(null, 0, days).astype(jnp.int32)
    y, m, _ = _civil_from_days(z)
    one = jnp.ones_like(y)
    if f in ("year", "yyyy", "yy"):
        out = _days_from_civil(y, one, one)
    elif f in ("month", "mon", "mm"):
        out = _days_from_civil(y, m, one)
    else:
        return jnp.full_like(days, jnp.nan)
    return jnp.where(null, jnp.nan, out.astype(days.dtype))


def _require_x64(what: str):
    import jax

    if not jax.config.jax_enable_x64:
        raise ValueError(
            f"{what} requires jax_enable_x64: epoch seconds exceed "
            "float32's exact-integer range (use to_date/trunc for "
            "day-resolution work)")


def _seconds_of(v):
    """Epoch-seconds view: strings via the lenient timestamp cast;
    numeric epoch seconds pass through, epoch days (below the magnitude
    cutoff) are midnight of that day."""
    if _is_object(v):
        import datetime as _dt

        out = np.empty(len(v), np.float64)
        epoch = _dt.datetime(1970, 1, 1)
        for i, x in enumerate(np.asarray(v, object)):
            t = _parse_datetime_cell(x)
            out[i] = np.nan if t is None else (t - epoch).total_seconds()
        return out
    arr = np.asarray(v, np.float64)
    return np.where(np.abs(arr) >= _SECONDS_CUTOFF, arr, arr * 86400.0)


def _fn_to_timestamp(s, *fmt):
    """``to_timestamp(col[, fmt])`` → epoch seconds (float64, x64
    required). Without a format the lenient cast accepts partial
    dates/timestamps like Spark; with one, strict strptime like
    unix_timestamp."""
    _require_x64("to_timestamp")
    if fmt:
        return _parse_dates(s, _scalar_str(fmt[0]), unit_seconds=True)
    return jnp.asarray(_seconds_of(s), jnp.float64)


def _fn_date_trunc(fmt, v):
    """``date_trunc(fmt, col)`` → truncated epoch seconds (x64). Spark's
    argument order (format first) — the reverse of ``trunc``."""
    _require_x64("date_trunc")
    f = str(_scalar_str(fmt)).lower()
    secs = jnp.asarray(_seconds_of(v), jnp.float64)
    null = jnp.isnan(secs)
    if f in ("second", "minute", "hour", "day", "week"):
        width = {"second": 1.0, "minute": 60.0, "hour": 3600.0,
                 "day": 86400.0, "week": 7 * 86400.0}[f]
        # epoch day 0 is a Thursday; ISO weeks start Monday (epoch day 4)
        shift = 4 * 86400.0 if f == "week" else 0.0
        out = jnp.floor((secs - shift) / width) * width + shift
    elif f in ("year", "yyyy", "yy", "month", "mon", "mm", "quarter"):
        z = jnp.where(null, 0, jnp.floor(secs / 86400.0)).astype(jnp.int32)
        y, m, _ = _civil_from_days(z)
        one = jnp.ones_like(y)
        tm = one if f in ("year", "yyyy", "yy") else (
            ((m - 1) // 3) * 3 + 1 if f == "quarter" else m)
        out = _days_from_civil(y, tm, one).astype(jnp.float64) * 86400.0
    else:
        return jnp.full_like(secs, jnp.nan)
    return jnp.where(null, jnp.nan, out)


_BUILTIN_FNS.update({
    "hour": _time_field("hour"),
    "minute": _time_field("minute"),
    "second": _time_field("second"),
    "weekofyear": _fn_weekofyear,
    "last_day": _fn_last_day,
    "add_months": _fn_add_months,
    "months_between": _fn_months_between,
    "next_day": _fn_next_day,
    "trunc": _fn_trunc,
    "to_timestamp": _fn_to_timestamp,
    "date_trunc": _fn_date_trunc,
})


hour = _make_fn("hour")
minute = _make_fn("minute")
second = _make_fn("second")
weekofyear = _make_fn("weekofyear")
last_day = _make_fn("last_day")


def add_months(col_, n: int) -> Func:
    return Func("add_months", [_coerce(col_), Lit(int(n))])


def months_between(end, start, roundOff: bool = True) -> Func:  # noqa: N803
    return Func("months_between",
                [_coerce(end), _coerce(start), Lit(bool(roundOff))])


def next_day(col_, day_of_week: str) -> Func:
    return Func("next_day", [_coerce(col_), Lit(str(day_of_week))])


def trunc(col_, fmt: str) -> Func:
    return Func("trunc", [_coerce(col_), Lit(str(fmt))])


def date_trunc(fmt: str, col_) -> Func:
    return Func("date_trunc", [Lit(str(fmt)), _coerce(col_)])


def to_timestamp(col_, fmt: str = None) -> Func:
    args = [_coerce(col_)] + ([Lit(fmt)] if fmt is not None else [])
    return Func("to_timestamp", args)


def current_timestamp() -> Expr:
    """Now as epoch seconds (host clock, evaluated at call time). Exact
    under jax_enable_x64; under float32 the value quantizes to ~±64 s —
    use x64 for timestamp work (the same caveat as unix_timestamp)."""
    import time as _time

    return Lit(float(int(_time.time())))


# -- math / bitwise batch --------------------------------------------------


def _fn_bround(v, *digits):
    """Spark ``bround``: HALF_EVEN (banker's) rounding — jnp.round's
    native mode, unlike ``round``'s HALF_UP."""
    d = _scalar_int(digits[0]) if digits else 0
    v = jnp.asarray(v, float_dtype())
    scale = 10.0 ** d
    return jnp.round(v * scale) / scale


def _exact_int64_col(vals):
    """Column of 64-bit ints (Nones allowed). With x64 off, jnp would
    silently wrap these to int32 (the conftest turns x64 on, so the wrap
    would only bite library users) — exact host objects instead."""
    import jax

    if any(x is None for x in vals):
        return np.asarray(vals, object)
    if jax.config.jax_enable_x64:
        return jnp.asarray(np.asarray(vals, np.int64))
    return np.asarray(vals, object)


def _fn_factorial(v):
    """Spark ``factorial``: defined on 0..20 (long range), anything else
    → null. Host exact integers — 20! exceeds float64's exact range, so
    device float math would corrupt the top values."""
    import math

    arr = np.asarray(v, np.float64)
    out = [None if (np.isnan(x) or x < 0 or x > 20 or x != int(x))
           else math.factorial(int(x)) for x in arr]
    return _exact_int64_col(out)


def _int64_of(v):
    """Two's-complement int64 view of a numeric column (bit ops / radix
    formatting); NaN rows tracked separately by the caller."""
    arr = np.asarray(v, np.float64)
    mask = np.isnan(arr)
    return np.where(mask, 0, arr).astype(np.int64), mask


def _fn_hex(v):
    """Spark ``hex``: numbers → uppercase hex of the two's-complement
    long; strings → hex of the UTF-8 bytes."""
    a = np.asarray(v, object) if _is_object(v) else None
    if a is not None:
        return _str_map(lambda x: x.encode().hex().upper(), v)
    z, mask = _int64_of(v)
    return np.asarray(
        [None if m else format(int(x) & _MASK64, "X")
         for x, m in zip(z, mask)], object)


def _fn_unhex(s):
    """Spark ``unhex``: hex string → BINARY; bytes surface as latin-1
    text (the ``unbase64`` convention); malformed input → null."""
    def u(x):
        try:
            return bytes.fromhex(x).decode("latin-1")
        except ValueError:
            return None
    return _str_map(u, s)


def _fn_bin(v):
    """Spark ``bin``: binary text of the two's-complement long
    (Java ``Long.toBinaryString``)."""
    z, mask = _int64_of(v)
    return np.asarray(
        [None if m else format(int(x) & _MASK64, "b")
         for x, m in zip(z, mask)], object)


def _fn_conv(s, from_base, to_base):
    """Spark ``conv(num, fromBase, toBase)``: radix conversion over
    string digits, uppercase output, malformed input → null. A negative
    toBase renders signed output; otherwise the value is treated as an
    unsigned 64-bit quantity (Spark/Hive semantics)."""
    fb = _scalar_int(from_base)
    tb = _scalar_int(to_base)
    digits = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ"
    if not (2 <= fb <= 36 and 2 <= builtins.abs(tb) <= 36):
        return np.asarray([None] * len(np.asarray(s, object)), object)

    def one(x):
        t = str(x).strip().upper()
        neg = t.startswith("-")
        if neg:
            t = t[1:]
        try:
            val = int(t, fb) if t else None
        except ValueError:
            # Hive keeps the longest valid prefix
            for j in range(len(t), 0, -1):
                try:
                    val = int(t[:j], fb)
                    break
                except ValueError:
                    continue
            else:
                val = None
        if val is None:
            return None
        if neg:
            val = -val
        if tb > 0:
            val &= 0xFFFFFFFFFFFFFFFF          # unsigned 64-bit view
            base, sign = tb, ""
        else:
            if val < -(1 << 63) or val >= (1 << 63):
                val &= 0xFFFFFFFFFFFFFFFF
                val -= (1 << 64) if val >= (1 << 63) else 0
            base, sign = -tb, ("-" if val < 0 else "")
            val = builtins.abs(val)
        if val == 0:
            return "0"
        out = []
        while val:
            val, r = divmod(val, base)
            out.append(digits[r])
        return sign + "".join(reversed(out))

    return _str_map(one, s)


def _nullable_int32_col(vals):
    """Column of small ints with Nones: object array when any null,
    else a device int32 column (the 32-bit sibling of _exact_int64_col)."""
    if any(x is None for x in vals):
        return np.asarray(vals, object)
    return jnp.asarray(np.asarray(vals, np.int32))


def _fn_ascii(s):
    """Spark ``ascii``: code point of the first character; '' → 0."""
    return _nullable_int32_col(
        [None if x is None else (ord(str(x)[0]) if str(x) else 0)
         for x in np.asarray(s, object)])


def _fn_crc32(s):
    import zlib

    out = [None if x is None else zlib.crc32(str(x).encode())
           for x in np.asarray(s, object)]
    return _exact_int64_col(out)  # crc32 > 2^31 must not wrap int32


def _shift_fn(which: str):
    """shiftleft / shiftright (arithmetic) / shiftrightunsigned (logical)
    over the int32 view (Spark's int overloads; its long overloads need
    explicit casts there too)."""

    def f(v, n):
        k = _scalar_int(n) % 32
        arr = np.asarray(v, np.float64)
        mask = np.isnan(arr)
        z = np.where(mask, 0, arr).astype(np.int32)
        if which == "left":
            r = np.left_shift(z, k)
        elif which == "right":
            r = np.right_shift(z, k)
        else:
            r = np.right_shift(z.view(np.uint32), k).view(np.int32)
        out = r.astype(np.float64)
        return jnp.asarray(np.where(mask, np.nan, out), float_dtype()) \
            if mask.any() else jnp.asarray(r)

    return f


def _fn_bitwise_not(v):
    arr = np.asarray(v, np.float64)
    mask = np.isnan(arr)
    r = ~np.where(mask, 0, arr).astype(np.int32)
    if mask.any():
        return jnp.asarray(np.where(mask, np.nan, r.astype(np.float64)),
                           float_dtype())
    return jnp.asarray(r)


def _fn_nullif(a, b):
    """SQL ``nullif(a, b)``: null where equal, else a."""
    if _is_object(a) or _is_object(b):
        va = np.asarray(a, object)
        vb = np.asarray(b, object)
        return np.asarray(
            [None if (x is not None and y is not None and x == y) else x
             for x, y in zip(va, vb)], object)
    va = jnp.asarray(a, float_dtype())
    vb = jnp.asarray(b, float_dtype())
    return jnp.where(va == vb, jnp.nan, va)


def _fn_nvl2(a, b, c):
    """Spark ``nvl2(a, b, c)``: b where a is not null, else c."""
    nulls = _null_mask(a)
    if _is_object(b) or _is_object(c):
        vb = np.asarray(b, object)
        vc = np.asarray(c, object)
        m = np.asarray(nulls)
        return np.asarray([y if keep else x
                           for x, y, keep in zip(vc, vb, ~m)], object)
    return jnp.where(nulls, jnp.asarray(c, float_dtype()),
                     jnp.asarray(b, float_dtype()))


def _fn_substring_index(s, delim, count):
    """Spark ``substring_index(str, delim, count)``: everything before
    the count-th delimiter (from the left for positive counts, from the
    right for negative); count 0 → ''."""
    d = _scalar_str(delim)
    k = _scalar_int(count)

    def one(x):
        if k == 0 or not d:
            return ""
        parts = x.split(d)
        if k > 0:
            return d.join(parts[:k])
        return d.join(parts[builtins.max(len(parts) + k, 0):])

    return _str_map(one, s)


_SOUNDEX_CODES = {**{c: "1" for c in "BFPV"}, **{c: "2" for c in "CGJKQSXZ"},
                  **{c: "3" for c in "DT"}, "L": "4",
                  **{c: "5" for c in "MN"}, "R": "6"}


def _fn_soundex(s):
    """American Soundex (Spark/Hive variant): 4 chars, H/W transparent
    between same-coded consonants, non-alpha input passed through."""
    def one(x):
        if not x or not x[0].isalpha():
            return x
        u = x.upper()
        code = [u[0]]
        prev = _SOUNDEX_CODES.get(u[0], "")
        for ch in u[1:]:
            c = _SOUNDEX_CODES.get(ch)
            if c is None:
                # vowels reset the run; H/W do not
                if ch not in "HW":
                    prev = ""
                continue
            if c != prev:
                code.append(c)
                if len(code) == 4:
                    break
            prev = c
        return "".join(code).ljust(4, "0")

    return _str_map(one, s)


def _fn_encode(s, charset):
    cs = _scalar_str(charset)
    return _str_map(lambda x: x.encode(cs).decode("latin-1"), s)


def _fn_decode(s, charset):
    cs = _scalar_str(charset)
    return _str_map(lambda x: x.encode("latin-1").decode(cs), s)


def _fn_octet_length(s):
    return _nullable_int32_col(
        [None if x is None else len(str(x).encode())
         for x in np.asarray(s, object)])


def _fn_bit_length(s):
    return _nullable_int32_col(
        [None if x is None else len(str(x).encode()) * 8
         for x in np.asarray(s, object)])


# -- Spark hash functions --------------------------------------------------
# Spark's Murmur3_x86_32 (seed 42) and XxHash64 (seed 42), bit-exact to
# the JVM implementations for the types this engine holds: numeric
# columns hash as DOUBLE (doubleToLongBits → hashLong), strings as their
# UTF-8 bytes. Null children are skipped (the running hash passes
# through), like Spark's HashExpression.

_M3_C1 = 0xCC9E2D51
_M3_C2 = 0x1B873593
_MASK32 = 0xFFFFFFFF
_MASK64 = 0xFFFFFFFFFFFFFFFF


def _rotl32(x, r):
    return ((x << r) | (x >> (32 - r))) & _MASK32


def _m3_mix_k1(k1):
    k1 = (k1 * _M3_C1) & _MASK32
    k1 = _rotl32(k1, 15)
    return (k1 * _M3_C2) & _MASK32


def _m3_mix_h1(h1, k1):
    h1 ^= k1
    h1 = _rotl32(h1, 13)
    return (h1 * 5 + 0xE6546B64) & _MASK32


def _m3_fmix(h1, length):
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _MASK32
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _MASK32
    return h1 ^ (h1 >> 16)


def _m3_hash_long(value, seed):
    low = value & _MASK32
    high = (value >> 32) & _MASK32
    h1 = _m3_mix_h1(seed, _m3_mix_k1(low))
    h1 = _m3_mix_h1(h1, _m3_mix_k1(high))
    return _m3_fmix(h1, 8)


def _m3_hash_bytes(data: bytes, seed: int) -> int:
    """Spark's hashUnsafeBytes: 4-byte little-endian blocks, then each
    remaining byte runs a FULL mix round on its SIGNED value — not the
    standard murmur3 tail, so only aligned inputs match public vectors."""
    h1 = seed
    n_aligned = len(data) - len(data) % 4
    for i in range(0, n_aligned, 4):
        block = int.from_bytes(data[i:i + 4], "little")
        h1 = _m3_mix_h1(h1, _m3_mix_k1(block))
    for i in range(n_aligned, len(data)):
        b = data[i]
        signed = b - 256 if b >= 128 else b
        h1 = _m3_mix_h1(h1, _m3_mix_k1(signed & _MASK32))
    return _m3_fmix(h1, len(data))


_XX_P1 = 0x9E3779B185EBCA87
_XX_P2 = 0xC2B2AE3D27D4EB4F
_XX_P3 = 0x165667B19E3779F9
_XX_P4 = 0x85EBCA77C2B2AE63
_XX_P5 = 0x27D4EB2F165667C5


def _rotl64(x, r):
    return ((x << r) | (x >> (64 - r))) & _MASK64


def _xx_fmix(h):
    h ^= h >> 33
    h = (h * _XX_P2) & _MASK64
    h ^= h >> 29
    h = (h * _XX_P3) & _MASK64
    return h ^ (h >> 32)


def _xx_round(acc, inp):
    acc = (acc + inp * _XX_P2) & _MASK64
    return (_rotl64(acc, 31) * _XX_P1) & _MASK64


def _xx_hash_long(value, seed):
    h = (seed + _XX_P5 + 8) & _MASK64
    h ^= _xx_round(0, value & _MASK64)
    h = (_rotl64(h, 27) * _XX_P1 + _XX_P4) & _MASK64
    return _xx_fmix(h)


def _xx_hash_bytes(data: bytes, seed: int) -> int:
    n = len(data)
    if n >= 32:
        v1 = (seed + _XX_P1 + _XX_P2) & _MASK64
        v2 = (seed + _XX_P2) & _MASK64
        v3 = seed
        v4 = (seed - _XX_P1) & _MASK64
        i = 0
        while i <= n - 32:
            v1 = _xx_round(v1, int.from_bytes(data[i:i + 8], "little"))
            v2 = _xx_round(v2, int.from_bytes(data[i + 8:i + 16], "little"))
            v3 = _xx_round(v3, int.from_bytes(data[i + 16:i + 24], "little"))
            v4 = _xx_round(v4, int.from_bytes(data[i + 24:i + 32], "little"))
            i += 32
        h = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12)
             + _rotl64(v4, 18)) & _MASK64
        for v in (v1, v2, v3, v4):
            h = ((h ^ _xx_round(0, v)) * _XX_P1 + _XX_P4) & _MASK64
    else:
        h = (seed + _XX_P5) & _MASK64
        i = 0
    h = (h + n) & _MASK64
    while i <= n - 8:
        h ^= _xx_round(0, int.from_bytes(data[i:i + 8], "little"))
        h = (_rotl64(h, 27) * _XX_P1 + _XX_P4) & _MASK64
        i += 8
    if i <= n - 4:
        h ^= (int.from_bytes(data[i:i + 4], "little") * _XX_P1) & _MASK64
        h = (_rotl64(h, 23) * _XX_P2 + _XX_P3) & _MASK64
        i += 4
    while i < n:
        h ^= (data[i] * _XX_P5) & _MASK64
        h = (_rotl64(h, 11) * _XX_P1) & _MASK64
        i += 1
    return _xx_fmix(h)


def _spark_hash(cols, seed, hash_long, hash_bytes, signed_bits):
    """The HashExpression fold: the running hash seeds each child's hash;
    null children pass through."""
    import struct

    host = [np.asarray(c, object) if _is_object(c) else np.asarray(c)
            for c in cols]
    n = len(host[0]) if host else 0
    out = []
    for i in range(n):
        h = seed
        for col_vals in host:
            x = col_vals[i]
            if x is None or (isinstance(x, (float, np.floating))
                             and np.isnan(x)):
                continue
            if isinstance(x, str):
                h = hash_bytes(x.encode(), h)
            else:
                bits = struct.unpack("<q", struct.pack("<d", float(x)))[0]
                h = hash_long(bits, h)
        # two's-complement back to signed
        if h >= (1 << (signed_bits - 1)):
            h -= (1 << signed_bits)
        out.append(h)
    if signed_bits == 32:
        return jnp.asarray(np.asarray(out, np.int32))
    return _exact_int64_col(out)  # 64-bit hashes must not wrap under x64-off


def _fn_hash(*cols):
    return _spark_hash(cols, 42, _m3_hash_long, _m3_hash_bytes, 32)


def _fn_xxhash64(*cols):
    return _spark_hash(cols, 42, _xx_hash_long, _xx_hash_bytes, 64)


# -- JSON ------------------------------------------------------------------


_JSON_SEG_RE = None


def _json_traverse(doc, path: str):
    """Walk ``$.key[idx].key…``; returns a sentinel-wrapped value, or None
    for missing values AND malformed paths — every character of the path
    must belong to a valid segment (Spark yields null on bad paths, so a
    skipped-garbage walk like finditer would invent answers)."""
    import re as _re

    global _JSON_SEG_RE
    if _JSON_SEG_RE is None:
        _JSON_SEG_RE = _re.compile(
            r"\.([A-Za-z_][A-Za-z0-9_]*)|\[(\d+)\]")
    if not path.startswith("$"):
        return None
    cur = doc
    pos = 1
    while pos < len(path):
        m = _JSON_SEG_RE.match(path, pos)
        if m is None:
            return None                      # malformed residue
        pos = m.end()
        key, idx = m.group(1), m.group(2)
        if key is not None:
            if not isinstance(cur, dict) or key not in cur:
                return None
            cur = cur[key]
        else:
            j = int(idx)
            if not isinstance(cur, list) or j >= len(cur):
                return None
            cur = cur[j]
    return (cur,)


def _json_render(v):
    """Spark's get_json_object rendering: strings bare, scalars via
    their JSON lexeme, containers as compact JSON text."""
    import json as _json

    if v is None:
        return None
    if isinstance(v, str):
        return v
    if v is True or v is False:
        return "true" if v else "false"
    if isinstance(v, (dict, list)):
        return _json.dumps(v, separators=(",", ":"))
    return repr(v) if not isinstance(v, float) else _json.dumps(v)


def _fn_get_json_object(s, path):
    import json as _json

    p = _scalar_str(path)

    def one(x):
        try:
            doc = _json.loads(x)
        except (ValueError, TypeError):
            return None
        hit = _json_traverse(doc, p)
        return None if hit is None else _json_render(hit[0])

    return _str_map(one, s)


_BUILTIN_FNS.update({
    "bround": _fn_bround,
    "factorial": _fn_factorial,
    "hex": _fn_hex,
    "unhex": _fn_unhex,
    "bin": _fn_bin,
    "conv": _fn_conv,
    "ascii": _fn_ascii,
    "crc32": _fn_crc32,
    "shiftleft": _shift_fn("left"),
    "shiftright": _shift_fn("right"),
    "shiftrightunsigned": _shift_fn("unsigned"),
    "bitwise_not": _fn_bitwise_not,
    "nullif": _fn_nullif,
    "nvl2": _fn_nvl2,
    "ifnull": _fn_coalesce,
    "nvl": _fn_coalesce,
    "substring_index": _fn_substring_index,
    "soundex": _fn_soundex,
    "encode": _fn_encode,
    "decode": _fn_decode,
    "bit_length": _fn_bit_length,
    "octet_length": _fn_octet_length,
    "hash": _fn_hash,
    "xxhash64": _fn_xxhash64,
    "get_json_object": _fn_get_json_object,
})


def bround(col_, scale: int = 0) -> Func:
    return Func("bround", [_coerce(col_), Lit(int(scale))])


factorial = _make_fn("factorial")
hex = _make_fn("hex")  # noqa: A001 - Spark name
unhex = _make_fn("unhex")
bin = _make_fn("bin")  # noqa: A001 - Spark name
ascii = _make_fn("ascii")  # noqa: A001 - Spark name
crc32 = _make_fn("crc32")
soundex = _make_fn("soundex")
bit_length = _make_fn("bit_length")
octet_length = _make_fn("octet_length")
hash = _make_fn("hash")  # noqa: A001 - Spark name
xxhash64 = _make_fn("xxhash64")
nullif = _make_fn("nullif")
nvl2 = _make_fn("nvl2")
ifnull = _make_fn("ifnull")


def conv(col_, from_base: int, to_base: int) -> Func:
    return Func("conv", [_coerce(col_), Lit(int(from_base)),
                         Lit(int(to_base))])


def shiftleft(col_, n: int) -> Func:
    return Func("shiftleft", [_coerce(col_), Lit(int(n))])


def shiftright(col_, n: int) -> Func:
    return Func("shiftright", [_coerce(col_), Lit(int(n))])


def shiftrightunsigned(col_, n: int) -> Func:
    return Func("shiftrightunsigned", [_coerce(col_), Lit(int(n))])


def bitwiseNOT(col_) -> Func:  # noqa: N802 - Spark name
    return Func("bitwise_not", [_coerce(col_)])


def substring_index(col_, delim: str, count: int) -> Func:
    return Func("substring_index",
                [_coerce(col_), Lit(str(delim)), Lit(int(count))])


def encode(col_, charset: str) -> Func:
    return Func("encode", [_coerce(col_), Lit(str(charset))])


def decode(col_, charset: str) -> Func:
    return Func("decode", [_coerce(col_), Lit(str(charset))])


def get_json_object(col_, path: str) -> Func:
    return Func("get_json_object", [_coerce(col_), Lit(str(path))])


class JsonTuple(Expr):
    """``json_tuple(col, 'f1', 'f2', …)`` — a multi-COLUMN generator
    (Spark's only non-row-multiplying generator): one output column per
    requested top-level field, default names c0…cN. ``Frame.select``
    expands it; evaluating it as a scalar column raises, like Explode."""

    def __init__(self, source, fields):
        self.source = _coerce(source)
        self.fields = [str(f) for f in fields]
        if not self.fields:
            raise ValueError("json_tuple needs at least one field name")

    def eval(self, frame):
        raise ValueError(
            "json_tuple() is a generator producing multiple columns — "
            "use it as a top-level select item")

    def columns(self, frame):
        """→ [(name, object-array), …] for Frame.select."""
        import json as _json

        src = np.asarray(self.source.eval(frame), object)
        cols = {f: np.empty(len(src), object) for f in self.fields}
        for i, x in enumerate(src):
            try:
                doc = _json.loads(x) if x is not None else None
            except (ValueError, TypeError):
                doc = None
            for f in self.fields:
                v = None
                if isinstance(doc, dict) and f in doc:
                    v = _json_render(doc[f])
                cols[f][i] = v
        return [(f"c{j}", cols[f]) for j, f in enumerate(self.fields)]


def json_tuple(col_, *fields) -> JsonTuple:
    return JsonTuple(col_, fields)


# -- higher-order array functions (Spark 2.4's lambda family) --------------
#
# transform/filter/exists evaluate the lambda body ONCE, vectorized, over
# a scope frame holding every element of every cell flattened into one
# column (outer columns repeat per element, so `x -> x + other_col`
# works); results regroup by cell length. aggregate folds over element
# POSITIONS — one vectorized body eval per position j updating the rows
# whose cells reach j — so the eval count is max_len, not total
# elements. Array cells are host objects, so this is host orchestration
# around device-capable body evals, the same split as the rest of the
# array family.


class Lambda:
    """``x -> body`` / ``(acc, x) -> body``: parameter names plus a body
    Expr in which the parameters appear as Col references (the scope
    frame binds them, shadowing outer columns like Spark)."""

    def __init__(self, params, body: Expr):
        self.params = [str(p) for p in params]
        self.body = body


_LAM_COUNTER = [0]


def _fresh_lambda(fn, n_params):
    """PySpark-3-style fluent lambda: the Python callable receives Col
    expressions for freshly named parameters and returns the body."""
    names = []
    for _ in range(n_params):
        names.append(f"_lam_x{_LAM_COUNTER[0]}")
        _LAM_COUNTER[0] += 1
    body = fn(*[Col(n) for n in names])
    if not isinstance(body, Expr):
        body = Lit(body)
    return Lambda(names, body)


def _host_col(vals):
    return np.asarray(vals, object) if _is_object(vals) else np.asarray(vals)


def _column_from_elems(elems):
    """Element list (Nones allowed) → engine column: strings stay host
    objects, everything else becomes a NaN-null float column."""
    if any(isinstance(v, str) for v in elems):
        return np.asarray(elems, object)
    return jnp.asarray(np.asarray(
        [np.nan if v is None or (isinstance(v, (float, np.floating))
                                 and np.isnan(v)) else float(v)
         for v in elems], np.float64), float_dtype())


def _referenced_cols(e, out: set):
    """Col names reachable from an Expr tree — generic attribute walk, so
    new Expr kinds are covered without registration. Used to repeat only
    the outer columns a lambda body actually touches."""
    if isinstance(e, Col):
        out.add(e.name)
        return
    if not isinstance(e, Expr):
        return
    for v in vars(e).values():
        if isinstance(v, Expr):
            _referenced_cols(v, out)
        elif isinstance(v, (list, tuple)):
            for x in v:
                if isinstance(x, (list, tuple)):
                    for y in x:
                        _referenced_cols(y, out)
                else:
                    _referenced_cols(x, out)


_NULL_ABSORBERS = {"isnull", "isnan", "coalesce", "ifnull", "nvl", "nvl2",
                   "nullif"}


def _null_defined_on(body: Expr, param: str) -> bool:
    """True iff the body's value on a null ``param`` is itself non-null —
    conservatively: every reference to the param is wrapped in a
    null-absorbing function. A bare comparison like ``x > 4`` is
    null-propagating, so exists() must report unknown for null elements;
    ``NOT isnull(x)`` is defined (false) on null, so computed values are
    the truth."""
    def ok(e) -> bool:
        if isinstance(e, Col):
            return e.name != param
        if isinstance(e, Func) and e.fn_name in _NULL_ABSORBERS:
            return True
        if isinstance(e, UnaryOp) and e.op in ("isnull", "isnotnull"):
            return True
        if isinstance(e, UdfCall) and e.udf_name.lower() in _NULL_ABSORBERS:
            return True
        if not isinstance(e, Expr):
            return True
        for v in vars(e).values():
            kids = v if isinstance(v, (list, tuple)) else [v]
            for k in kids:
                inner = k if isinstance(k, (list, tuple)) else [k]
                for x in inner:
                    if isinstance(x, Expr) and not ok(x):
                        return False
        return True

    return ok(body)


def _scope_frame(parent, lens, bindings, needed=None):
    """Per-element scope: outer columns repeated by cell length, lambda
    params appended last so they shadow same-named outer columns.
    ``needed`` limits the repeat to the columns the body references
    (repeating a wide frame per element for an ``x -> x + 1`` lambda
    would multiply host copies by the column count for nothing)."""
    from ..frame.frame import Frame

    reps = np.asarray(lens, np.int64)
    data = {}
    for name, vals in parent._data.items():
        if needed is not None and name not in needed:
            continue
        data[name] = np.repeat(_host_col(vals), reps, axis=0)
    data.update(bindings)
    return Frame(data)


def _row_frame(parent, bindings, needed=None):
    """Per-row scope (aggregate): outer columns as-is, params appended.
    ``needed`` matters doubly here — this frame is rebuilt once per
    element position."""
    from ..frame.frame import Frame

    data = {name: _host_col(vals) for name, vals in parent._data.items()
            if needed is None or name in needed}
    data.update(bindings)
    return Frame(data)


def _elem_of(out_host, k):
    v = out_host[k]
    if v is None or (isinstance(v, (float, np.floating)) and np.isnan(v)):
        return None
    return v


class HigherOrder(Expr):
    """transform / filter (element predicate) / exists / aggregate."""

    _KINDS = ("transform", "filter", "exists", "aggregate")

    def __init__(self, kind, source, lam: Lambda, init: Expr = None,
                 finish: Lambda = None):
        if kind not in self._KINDS:
            raise ValueError(f"unknown higher-order function {kind!r}")
        want = 2 if kind == "aggregate" else 1
        if len(lam.params) != want:
            raise ValueError(
                f"{kind}() lambda takes {want} parameter(s), "
                f"got {len(lam.params)}")
        self.kind = kind
        self.source = _coerce(source)
        self.lam = lam
        self.init = init
        self.finish = finish

    def eval(self, frame):
        cells = _require_array_cells(
            np.asarray(self.source.eval(frame), object), self.kind)
        if self.kind == "aggregate":
            return self._eval_aggregate(frame, cells)
        lens = [0 if c is None else len(c) for c in cells]
        flat = [e for c in cells if c is not None for e in c]
        bindings = {self.lam.params[0]: _column_from_elems(flat)}
        needed: set = set()
        _referenced_cols(self.lam.body, needed)
        try:
            out = self.lam.body.eval(
                _scope_frame(frame, lens, bindings, needed=needed))
        except KeyError:
            # an Expr kind the attribute walk missed referenced a column
            # indirectly — fall back to the full (correct, wider) scope
            out = self.lam.body.eval(_scope_frame(frame, lens, bindings))
        # exists needs to know whether the predicate is DEFINED on null
        # (isnull-style bodies return a real boolean for a null element;
        # comparisons return null, which NaN math renders as False — an
        # evaluation probe cannot tell the two Falses apart, so the check
        # is structural: every reference to the param must sit under a
        # null-absorbing function).
        null_defined = (self.kind == "exists"
                        and _null_defined_on(self.lam.body,
                                             self.lam.params[0]))
        out_host = _host_col(out)
        results = []
        k = 0
        for c, ln in zip(cells, lens):
            if c is None:
                results.append(None)
                continue
            start, k = k, k + ln
            seg = range(start, start + ln)
            if self.kind == "transform":
                results.append(np.asarray(
                    [_elem_of(out_host, j) for j in seg], object))
            elif self.kind == "filter":
                results.append(np.asarray(
                    [c[j - start] for j in seg
                     if (v := _elem_of(out_host, j)) is not None and bool(v)],
                    object))
            else:  # exists — three-valued like SQL ANY
                vals = [_elem_of(out_host, j) for j in seg]
                # a null INPUT element makes the predicate unknown —
                # unless the null-probe above showed the body is defined
                # on null (isnull-style), in which case the computed
                # values are the truth
                null_in = (not null_defined
                           and any(_cell_is_null(x) for x in c))
                if any(v is not None and bool(v) for v in vals):
                    results.append(True)
                elif null_in or any(v is None for v in vals):
                    results.append(None)
                else:
                    results.append(False)
        if self.kind == "exists":
            if any(r is None for r in results):
                return jnp.asarray(np.asarray(
                    [np.nan if r is None else float(r) for r in results],
                    np.float64), float_dtype())
            return jnp.asarray(np.asarray(results, np.bool_))
        return np.asarray(results, object)

    def _eval_aggregate(self, frame, cells):
        acc_name, x_name = self.lam.params
        acc = _host_col(self.init.eval(frame) if self.init is not None
                        else Lit(0.0).eval(frame))
        max_len = builtins.max((0 if c is None else len(c) for c in cells),
                               default=0)
        needed: set = set()
        _referenced_cols(self.lam.body, needed)
        if self.finish is not None:
            _referenced_cols(self.finish.body, needed)
        needed |= {acc_name, x_name}
        for j in range(max_len):
            xj = [None if c is None or j >= len(c) else c[j] for c in cells]
            bindings = {acc_name: acc, x_name: _column_from_elems(xj)}
            try:
                env = _row_frame(frame, bindings, needed=needed)
                new_acc = _host_col(self.lam.body.eval(env))
            except KeyError:   # attribute walk missed a reference
                needed = None
                env = _row_frame(frame, bindings)
                new_acc = _host_col(self.lam.body.eval(env))
            active = np.asarray(
                [c is not None and j < len(c) for c in cells])
            if _is_object(acc) or _is_object(new_acc):
                acc = np.asarray(
                    [n if a else o
                     for o, n, a in zip(acc, new_acc, active)], object)
            else:
                acc = np.where(active, new_acc, acc)
        if self.finish is not None:
            env = _row_frame(frame, {self.finish.params[0]: acc})
            acc = _host_col(self.finish.body.eval(env))
        # null cells → null result
        null_rows = np.asarray([c is None for c in cells])
        if _is_object(acc):
            return np.asarray([None if nr else v
                               for v, nr in zip(acc, null_rows)], object)
        out = np.asarray(acc, np.float64)
        return jnp.asarray(np.where(null_rows, np.nan, out), float_dtype())


def transform(col_, f) -> HigherOrder:
    """``transform(col, x -> …)`` — per-element map. ``f`` is a Python
    callable over a Col (PySpark-3 shape) or a prebuilt Lambda."""
    lam = f if isinstance(f, Lambda) else _fresh_lambda(f, 1)
    return HigherOrder("transform", col_, lam)


def filter(col_, f) -> HigherOrder:  # noqa: A001 - Spark name
    """``filter(col, x -> predicate)`` — keep matching elements; a null
    predicate drops the element (SQL semantics)."""
    lam = f if isinstance(f, Lambda) else _fresh_lambda(f, 1)
    return HigherOrder("filter", col_, lam)


def exists(col_, f) -> HigherOrder:
    """``exists(col, x -> predicate)`` — three-valued ANY over the
    elements."""
    lam = f if isinstance(f, Lambda) else _fresh_lambda(f, 1)
    return HigherOrder("exists", col_, lam)


def aggregate(col_, initial_value, merge, finish=None) -> HigherOrder:
    """``aggregate(col, init, (acc, x) -> …[, acc -> …])`` — sequential
    fold per cell, vectorized across rows by element position."""
    lam = merge if isinstance(merge, Lambda) else _fresh_lambda(merge, 2)
    fin = None
    if finish is not None:
        fin = finish if isinstance(finish, Lambda) \
            else _fresh_lambda(finish, 1)
    init = initial_value if isinstance(initial_value, Expr) \
        else Lit(initial_value)
    return HigherOrder("aggregate", col_, lam, init=init, finish=fin)
