"""Column expression trees.

This is the framework's equivalent of the Spark column-expression surface the
reference app exercises (``df.col``, ``callUDF``, ``cast``, comparisons in SQL
``WHERE`` — `DataQuality4MachineLearningApp.java:68-90`). An ``Expr`` is a
small host-side tree; evaluating it against a :class:`~sparkdq4ml_tpu.frame.Frame`
produces a device array over *all* row slots (filtering is a validity mask, so
shapes stay static for XLA — see SURVEY.md §7 step 1).

Unlike Spark, where a UDF crosses the codegen→JVM-object boundary per row (the
"UDF tax", SURVEY.md §3.2), every expression here is a vectorized jnp op that
XLA fuses — the per-row boundary does not exist.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax.numpy as jnp
import numpy as np

from ..config import float_dtype, int_dtype

# Spark SQL type name → dtype factory. Mirrors the names printSchema uses.
_TYPE_NAMES: dict[str, Callable[[], Any]] = {
    "int": int_dtype,
    "integer": int_dtype,
    "long": lambda: jnp.int64 if jnp.zeros((), jnp.int64).dtype == jnp.int64 else jnp.int32,
    "float": lambda: jnp.float32,
    "double": float_dtype,
    "boolean": lambda: jnp.bool_,
    "string": lambda: np.dtype(object),
}


def spark_type_name(dtype) -> str:
    """dtype → Spark printSchema type name (integer/long/float/double/boolean/string)."""
    dt = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    if dt == np.int32 or dt == np.int16 or dt == np.int8:
        return "integer"
    if dt == np.int64:
        return "long"
    if dt == np.float32:
        return "float"
    if dt == np.float64:
        return "double"
    if dt == np.bool_:
        return "boolean"
    return "string"


def resolve_type_name(name: str):
    try:
        return _TYPE_NAMES[name.lower()]()
    except KeyError:
        raise ValueError(f"unknown SQL type name: {name!r}") from None


class Expr:
    """Base column expression. Supports Python operators like Spark's Column."""

    def eval(self, frame):  # pragma: no cover - abstract
        raise NotImplementedError

    @property
    def name(self) -> str:
        """Default output-column name (Spark derives one from the expr string)."""
        return str(self)

    # -- fluent API (Spark Column methods) --------------------------------
    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def cast(self, type_name: str) -> "Cast":
        return Cast(self, type_name)

    def is_null(self) -> "Expr":
        return UnaryOp("isnull", self)

    def is_not_null(self) -> "Expr":
        return UnaryOp("isnotnull", self)

    # -- operators --------------------------------------------------------
    def _bin(self, op, other, reverse=False):
        other = other if isinstance(other, Expr) else Lit(other)
        return BinOp(op, other, self) if reverse else BinOp(op, self, other)

    def __add__(self, o):  return self._bin("+", o)
    def __radd__(self, o): return self._bin("+", o, True)
    def __sub__(self, o):  return self._bin("-", o)
    def __rsub__(self, o): return self._bin("-", o, True)
    def __mul__(self, o):  return self._bin("*", o)
    def __rmul__(self, o): return self._bin("*", o, True)
    def __truediv__(self, o):  return self._bin("/", o)
    def __rtruediv__(self, o): return self._bin("/", o, True)
    def __neg__(self):     return UnaryOp("-", self)
    def __lt__(self, o):   return self._bin("<", o)
    def __le__(self, o):   return self._bin("<=", o)
    def __gt__(self, o):   return self._bin(">", o)
    def __ge__(self, o):   return self._bin(">=", o)
    def __eq__(self, o):   return self._bin("==", o)  # type: ignore[override]
    def __ne__(self, o):   return self._bin("!=", o)  # type: ignore[override]
    def __and__(self, o):  return self._bin("&", o)
    def __rand__(self, o): return self._bin("&", o, True)
    def __or__(self, o):   return self._bin("|", o)
    def __ror__(self, o):  return self._bin("|", o, True)
    def __invert__(self):  return UnaryOp("!", self)

    __hash__ = object.__hash__  # __eq__ is overloaded; keep Exprs hashable


class Col(Expr):
    def __init__(self, name: str):
        self._name = name

    def eval(self, frame):
        return frame._column_values(self._name)

    @property
    def name(self) -> str:
        return self._name

    def __str__(self):
        return self._name


class Lit(Expr):
    def __init__(self, value):
        self.value = value

    def eval(self, frame):
        n = frame.num_slots
        if isinstance(self.value, bool):
            return jnp.full((n,), self.value, dtype=jnp.bool_)
        if isinstance(self.value, int):
            return jnp.full((n,), self.value, dtype=int_dtype())
        if isinstance(self.value, float):
            return jnp.full((n,), self.value, dtype=float_dtype())
        return np.full((n,), self.value, dtype=object)

    def __str__(self):
        return repr(self.value)


class Alias(Expr):
    def __init__(self, child: Expr, name: str):
        self.child = child
        self._name = name

    def eval(self, frame):
        return self.child.eval(frame)

    @property
    def name(self) -> str:
        return self._name

    def __str__(self):
        return f"{self.child} AS {self._name}"


_BIN_FNS = {
    "+": jnp.add,
    "-": jnp.subtract,
    "*": jnp.multiply,
    "/": jnp.divide,
    "<": jnp.less,
    "<=": jnp.less_equal,
    ">": jnp.greater,
    ">=": jnp.greater_equal,
    "==": jnp.equal,
    "!=": jnp.not_equal,
    "&": jnp.logical_and,
    "|": jnp.logical_or,
}


def _is_object(a) -> bool:
    return isinstance(a, np.ndarray) and a.dtype == object


def _promote(a, b):
    """Numeric promotion for mixed host/device operands."""
    return jnp.asarray(a), jnp.asarray(b)


class BinOp(Expr):
    def __init__(self, op: str, left: Expr, right: Expr):
        self.op, self.left, self.right = op, left, right

    def eval(self, frame):
        a, b = self.left.eval(frame), self.right.eval(frame)
        if _is_object(a) or _is_object(b):
            # String columns live on host; comparisons stay in numpy.
            np_fns = {"==": np.equal, "!=": np.not_equal}
            if self.op not in np_fns:
                raise TypeError(f"operator {self.op!r} unsupported on strings")
            return np_fns[self.op](np.asarray(a, object), np.asarray(b, object)
                                   ).astype(bool)
        a, b = _promote(a, b)
        if self.op == "/":
            # Spark's / always yields double
            a = jnp.asarray(a, float_dtype())
            b = jnp.asarray(b, float_dtype())
        return _BIN_FNS[self.op](a, b)

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


class UnaryOp(Expr):
    def __init__(self, op: str, child: Expr):
        self.op, self.child = op, child

    def eval(self, frame):
        v = self.child.eval(frame)
        if self.op == "-":
            return jnp.negative(v)
        if self.op == "!":
            return jnp.logical_not(v)
        if self.op in ("isnull", "isnotnull"):
            if _is_object(v):  # string columns: None marks null
                nulls = np.asarray([x is None for x in v], dtype=bool)
                nulls = jnp.asarray(nulls)
            elif hasattr(v, "dtype") and np.issubdtype(np.dtype(v.dtype), np.floating):
                nulls = jnp.isnan(v)
            else:
                nulls = jnp.zeros(v.shape[:1], jnp.bool_)
            return nulls if self.op == "isnull" else jnp.logical_not(nulls)
        raise ValueError(self.op)

    def __str__(self):
        return f"({self.op}{self.child})"


class Cast(Expr):
    """CAST(expr AS type) — Spark semantics: double→int truncates toward zero."""

    def __init__(self, child: Expr, type_name: str):
        self.child = child
        self.type_name = type_name

    def eval(self, frame):
        v = self.child.eval(frame)
        dt = resolve_type_name(self.type_name)
        if isinstance(dt, np.dtype) and dt == object:
            return np.asarray([str(x) for x in np.asarray(v)], dtype=object)
        return jnp.asarray(v).astype(dt)

    @property
    def name(self) -> str:
        return f"CAST({self.child} AS {self.type_name.upper()})"

    def __str__(self):
        return self.name


class UdfCall(Expr):
    """Invocation of a registered UDF by name — ``callUDF`` equivalent.

    Resolution happens at eval time against the registry, matching Spark's
    name-based lookup (`DataQuality4MachineLearningApp.java:68-69,86-87`).
    """

    def __init__(self, udf_name: str, args: Sequence[Expr], registry=None):
        self.udf_name = udf_name
        self.args = list(args)
        self._registry = registry

    def eval(self, frame):
        from .udf import default_registry

        reg = self._registry if self._registry is not None else default_registry()
        fn, return_dtype = reg.lookup(self.udf_name)
        vals = [a.eval(frame) for a in self.args]
        out = fn(*vals)
        if return_dtype is not None:
            out = jnp.asarray(out, return_dtype)
        return out

    @property
    def name(self) -> str:
        return f"{self.udf_name}({', '.join(str(a) for a in self.args)})"

    def __str__(self):
        return self.name


# -- public constructors (mirrors org.apache.spark.sql.functions) ----------

def col(name: str) -> Col:
    return Col(name)


def lit(value) -> Lit:
    return Lit(value)


def call_udf(name: str, *args) -> UdfCall:
    """``functions.callUDF`` equivalent; accepts Exprs or column names."""
    exprs = [a if isinstance(a, Expr) else Col(a) if isinstance(a, str) else Lit(a)
             for a in args]
    return UdfCall(name, exprs)


# Spark naming alias
callUDF = call_udf
