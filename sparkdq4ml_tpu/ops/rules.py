"""Pure, vectorized data-quality rule functions.

The reference's one architectural idea (SURVEY.md §1) is the split between
pure rule logic (`dq/service/*.java`) and engine adapters (`dq/udf/*.java`).
This module is the service layer: plain jnp functions with zero framework
dependencies, testable outside any frame/session, exactly like the reference's
static service methods. The adapter step is just ``register_udf`` (see
``register_builtin_rules``), because vectorized fns plug straight into the
column engine — no per-row wrapper class is needed on TPU.

Null semantics use NaN as the null analogue and mirror the reference's
asymmetry (SURVEY.md §2.1):

* ``minimum_price_rule`` has *no* null guard — a NaN price propagates to the
  output (the analogue of `MinimumPriceDataQualityUdf.java:11-13`, which NPEs
  on a null ``Double``: garbage in, failure out).
* ``price_correlation_rule`` is null-safe: NaN in either input → ``-1.0``
  (mirrors the explicit guard at `PriceCorrelationDataQualityUdf.java:12-14`).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..config import float_dtype

# Threshold constants from the reference services.
MIN_PRICE = 20.0            # MinimumPriceDataQualityService.java:5
CORRELATION_MAX_GUESTS = 14  # PriceCorrelationDataQualityService.java:6
CORRELATION_MAX_PRICE = 90.0  # PriceCorrelationDataQualityService.java:6
BAD_ROW_SENTINEL = -1.0


def minimum_price_rule(price):
    """price < 20 → −1 else price (`MinimumPriceDataQualityService.java:7-13`).

    Vectorized: one fused ``jnp.where`` over the column. NaN propagates
    (NaN < 20 is False, so NaN is returned unchanged — the poison analogue of
    the reference UDF1's NPE on null).
    """
    price = jnp.asarray(price, float_dtype())
    return jnp.where(price < MIN_PRICE, jnp.asarray(BAD_ROW_SENTINEL, price.dtype), price)


def price_correlation_rule(price, guest):
    """guest < 14 AND price > 90 → −1 else price
    (`PriceCorrelationDataQualityService.java:5-10`), with the adapter's
    null guard folded in: NaN price/guest → −1.0
    (`PriceCorrelationDataQualityUdf.java:12-14`).
    """
    price = jnp.asarray(price, float_dtype())
    guest_f = jnp.asarray(guest, float_dtype())
    bad = jnp.logical_and(guest_f < CORRELATION_MAX_GUESTS, price > CORRELATION_MAX_PRICE)
    null = jnp.logical_or(jnp.isnan(price), jnp.isnan(guest_f))
    sentinel = jnp.asarray(BAD_ROW_SENTINEL, price.dtype)
    return jnp.where(jnp.logical_or(bad, null), sentinel, price)


def dq_rules_fused(price, guest):
    """One-pass fused DQ chain: ``(price_no_min, price_correct_correl, keep)``.

    Collapses the reference's four stages — rule 1, ``WHERE > 0``, rule 2,
    ``WHERE > 0`` (`DataQuality4MachineLearningApp.java:68-95`) — into a
    single elementwise pass; the two filters commute into one conjunction
    because filtering is mask composition. Dispatches to the Pallas kernel
    (``ops/pallas_kernels.py``) when ``config.pallas`` selects it, else runs
    the fused XLA expression below (identical semantics, incl. the NaN
    asymmetry of the two rules).
    """
    from . import pallas_kernels

    price = jnp.asarray(price, float_dtype())
    guest = jnp.asarray(guest, float_dtype())
    if pallas_kernels.dispatch_to_pallas(price, guest):
        return pallas_kernels.dq_rules_pallas(price, guest)
    pnm = minimum_price_rule(price)
    pcc = price_correlation_rule(price, guest)
    keep = jnp.logical_and(pnm > 0, pcc > 0)
    return pnm, pcc, keep


def register_builtin_rules(registry=None) -> None:
    """Register both rules under the names the reference app uses
    (`DataQuality4MachineLearningApp.java:46-49`)."""
    from .udf import default_registry

    reg = registry if registry is not None else default_registry()
    reg.register("minimumPriceRule", minimum_price_rule, "double")
    reg.register("priceCorrelationRule", price_correlation_rule, "double")
