"""UDF registry — the engine capability behind ``spark.udf().register``.

The reference registers two data-quality UDFs with an explicit return dtype
(`DataQuality4MachineLearningApp.java:46-49`); registered names are callable
from column expressions (``call_udf``) and from the SQL subset. Functions must
be vectorized array→array (jnp) functions: the per-row boxed-object UDF call
path of Spark (SURVEY.md §3.2) is replaced by whole-column ops XLA can fuse.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ..config import float_dtype
from .expressions import resolve_type_name


class UDFRegistry:
    """Name → (vectorized fn, return dtype). One per session; a process-wide
    default registry backs sessions and bare ``call_udf`` use."""

    def __init__(self):
        self._fns: dict[str, tuple[Callable, Optional[np.dtype]]] = {}

    def register(self, name: str, fn: Callable, return_type=None) -> Callable:
        """Register ``fn`` under ``name``.

        ``return_type`` may be a Spark SQL type name ("double", "integer", …)
        — mirroring ``DataTypes.DoubleType`` at the registration site — or a
        numpy/jnp dtype, or None to keep the fn's natural dtype.
        """
        if isinstance(return_type, str):
            return_type = resolve_type_name(return_type)
        self._fns[name] = (fn, return_type)
        return fn

    def lookup(self, name: str):
        try:
            return self._fns[name]
        except KeyError:
            raise KeyError(
                f"UDF {name!r} is not registered "
                f"(registered: {sorted(self._fns)})") from None

    def __contains__(self, name: str) -> bool:
        return name in self._fns

    def names(self):
        return sorted(self._fns)


_DEFAULT = UDFRegistry()


def default_registry() -> UDFRegistry:
    return _DEFAULT


def register_udf(name: str, fn: Callable, return_type=None) -> Callable:
    """Module-level convenience mirroring ``spark.udf().register(name, fn, type)``."""
    return _DEFAULT.register(name, fn, return_type)
