from .compiler import bucket_size, clear_cache, is_compilable, run_pipeline
from .expressions import Col, Expr, call_udf, callUDF, col, lit
from .rules import (minimum_price_rule, price_correlation_rule,
                    dq_rules_fused, register_builtin_rules, MIN_PRICE)
from .udf import UDFRegistry, default_registry, register_udf
