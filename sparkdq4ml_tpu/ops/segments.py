"""Device-resident grouped execution: segment-reduction groupBy/sort/distinct.

``frame/aggregates.py`` documents the host boundary the seed design chose:
group discovery is data-dependent (dynamic shapes), so grouping, sorting,
and dedup all round-tripped device→host→device with numpy loops. This
module removes that boundary for the numeric surface, the same way the
pipeline compiler (``ops/compiler.py``) removed it for expression chains:

* **One jitted program per plan shape.** ``group_by(...).agg(...)`` lowers
  to a single XLA computation. Two lowerings share one calling convention:

  - the **dense** program (the common case: integer-valued keys whose
    packed range fits a bounded table) maps each row's key tuple straight
    to a dense lexicographic slot — NO row sort at all — and computes
    every aggregate with ``jax.ops.segment_*`` reductions whose additive
    members stack into one ``(n, C)`` scatter (per-element scatter
    overhead amortizes across aggregates). Table→group compaction is
    gather-based (``searchsorted`` over the presence prefix-sum), because
    gathers are fast on every backend while scatters are not.
  - the **sorted** program (arbitrary float keys, and any plan containing
    ``count_distinct``/``sum_distinct``, which need sorted-run counting)
    does an on-device lexicographic sort (``jax.lax.sort`` over null-flag/
    value key components with a row-index tiebreaker, exactly mirroring
    the host ``_group_plan`` lexsort) and reduces over the discovered
    segment boundaries.

  The only dynamic quantity — the group count (plus the dense path's
  "did the range fit" verdict) — leaves the device as ONE scalar sync at
  the very end; outputs are computed at static length and sliced on the
  way out. A dense-range miss costs one extra sync (the verdict) before
  the sorted program runs.

* **Plan-keyed jit cache.** Programs cache under a structural key (key
  dtypes, aggregate set with value-column slots, engine dtype tag) in a
  bounded LRU, with the same shape-bucketed row padding as the pipeline
  compiler (``bucket_size``/``pad_rows`` are imported from it), so repeated
  SQL ``GROUP BY`` queries and different-length CSV loads replay an
  already-compiled program: ``grouped.compile`` counts traces,
  ``grouped.hit`` counts replays, ``grouped.fallback`` counts host-path
  bailouts, ``grouped.dense_miss`` counts range-overflow reroutes.

* **Mask-weighted semantics identical to the host path.** Masked-out rows
  carry zero weight in every reduction; NaN keys form one null group that
  sorts first (Spark's NULLS FIRST, like the host ``_key_parts``); NaN
  values are skipped by aggregates (SQL semantics) with the same
  empty→NULL and n<2→NULL variance rules ``_np_agg`` implements.

``Frame.sort`` rides the same engine: on accelerators the permutation is
a pure-device ``lax.sort`` program; on XLA:CPU — whose sort lowers to a
scalar comparator loop ~5x slower than numpy's — the *plan* (the
permutation) comes from a host lexsort over just the key columns (one
batched pull) while the payload gather stays device-side ``jnp.take``,
the same "plan on host, materialize on device" split as ``Frame.join``.
``distinct``/``drop_duplicates`` use the sorted program's boundary
discovery and keep first-occurrence output order.

The compilable surface: numeric/bool 1-D key columns and the aggregate
family count/sum/avg/min/max/variance/stddev (sample + population),
first/last (with ignoreNulls), count_distinct, sum_distinct. Everything
else — string keys, host-object aggregates (``collect_list``,
``percentile_approx``, ``median``, the two-column family), grouped-map
UDFs — returns ``None`` here and the caller takes the legacy numpy path
unchanged. ``config.grouped_exec`` (session conf
``spark.groupedExec.enabled``, default on) gates the whole module; off
restores the exact seed behavior.

The module is deliberately numpy-free outside the marked host-fallback
region at the bottom (``scripts/check_segments_np.py`` enforces this):
everything between frame input and the final group-count sync must stay
on device, except the explicitly-host plans (string-payload gathers, the
CPU-backend sort permutation).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..config import config, float_dtype, int_dtype
from ..utils import faults as _faults
from ..utils import observability as _obs
from ..utils.profiling import counters
from .compiler import bucket_size, dtype_tag, pad_rows, plan_namespace_tag

logger = logging.getLogger("sparkdq4ml_tpu.ops.segments")

__all__ = [
    "DEVICE_AGG_FNS", "agg_lowerable", "try_device", "grouped_agg",
    "device_sort", "device_unique", "clear_cache", "cache_len",
]


def try_device(op: str, thunk):
    """THE fallback protocol for every device-path entry (grouped agg,
    sort, distinct, dropDuplicates): run ``thunk`` when grouped execution
    is enabled; an ineligible plan (``None``) or any internal failure
    yields ``None`` with a ``grouped.fallback`` increment, and the caller
    takes its legacy host path — the optimization layer must never
    change results. Centralized so the protocol (counter, logging,
    exception policy) lives in exactly one place.

    Executions serialize on ``_EXEC_LOCK`` — the grouped analogue of the
    pipeline compiler's flush lock: without it, two threads racing the
    same plan key would both trace (one compile wasted) and the
    compile-delta heuristic behind ``grouped.compile``/``grouped.hit``
    attribution would cross-label their counters and span verdicts.

    Degradation ladder (ISSUE 11): a DEVICE fault in the segment-reduce
    program — a real ``XlaRuntimeError`` at the group-count sync, or an
    injected ``grouped_flush`` fault — degrades THIS op one level to the
    host-numpy lowering, recorded as a ``recovery.fallback`` event (site
    ``grouped_flush``, rung ``host``) + ``grouped.fault_fallback``; the
    query lives. No fault plan installed = one ``is None`` check."""
    if not config.grouped_exec:
        return None
    try:
        with _EXEC_LOCK:
            _faults.inject("grouped_flush")
            out = thunk()
    except jax.errors.JaxRuntimeError as e:
        from ..utils.recovery import RECOVERY_LOG

        RECOVERY_LOG.record(
            "grouped_flush", "fallback", rung="host",
            cause=f"{type(e).__name__}: {e}",
            detail=f"device {op} degraded to the host-numpy lowering")
        counters.increment("grouped.fault_fallback")
        out = None
    except Exception as e:
        logger.debug("device %s fell back to host: %s", op, e)
        out = None
    if out is None:
        counters.increment("grouped.fallback")
    return out

def _record_grouped_stats(key: str, rows_in: int, rows_out: int,
                          wall_ms: float, compiles: int,
                          host_syncs: int,
                          card_key: Optional[str] = None) -> None:
    """Plan-stats observatory hand-off for the grouped engine: the group
    count is already host-known (the engine's one counted sync), so both
    the flush digest AND the rows-in→groups-out selectivity record
    directly — no deferred drain. ``card_key`` additionally records the
    observed OUTPUT CARDINALITY under a query-addressable name+dtype key
    (:func:`cardinality_history_key`) — the aggregate/distinct
    ``est_rows`` evidence ROADMAP item 4 named as headroom (only filters
    carried selectivity history before). Called only when
    ``spark.stats.enabled``; failures never take a flush down."""
    from ..utils import statstore as _stats

    try:
        _stats.STORE.record_flush(key, "grouped", wall_ms=wall_ms,
                                  compiled=compiles > 0,
                                  host_syncs=host_syncs)
        if rows_out >= 0:
            _stats.STORE.record_rows(key, "grouped", rows_in, rows_out)
            if card_key is not None:
                _stats.STORE.record_rows(card_key, "cardinality",
                                         rows_in, rows_out)
    except Exception:
        logger.debug("stats hand-off failed", exc_info=True)


def cardinality_history_key(op: str, names, arrs) -> Optional[str]:
    """Query-addressable output-cardinality key: ``op`` (``g`` group-by /
    ``d`` distinct) + the SORTED key column names with their device
    dtypes + the engine dtype tag. Name-addressed (unlike the structural
    plan keys) so EXPLAIN can rebuild the same key from a parsed query's
    GROUP BY / DISTINCT list against the catalog frame — zero execution.
    Like the filter-selectivity entries, cardinality is treated as a
    data property: the same key names/dtypes on two views share one
    entry (accepted estimation noise; the estimate is advisory). None
    when any column is missing or host-typed (those plans fall back and
    record nothing)."""
    parts = []
    for name, arr in sorted(zip(names, arrs), key=lambda p: p[0]):
        if arr is None or _is_host_col(arr):
            return None
        parts.append(f"{name}:{_col_kind_spec(arr)}")
    if not parts:
        return None
    return f"card|{dtype_tag()}|{op}|" + ",".join(parts)


# Aggregates this engine lowers to segment reductions. The names mirror
# frame.aggregates._AGGS (post `mean`→`avg` normalization).
DEVICE_AGG_FNS = frozenset({
    "count", "sum", "avg", "min", "max", "stddev", "variance",
    "stddev_pop", "var_pop", "first", "last", "count_distinct",
    "sum_distinct",
})

_DISTINCT_FNS = frozenset({"count_distinct", "sum_distinct"})


def agg_lowerable(agg) -> bool:
    """Structural eligibility of ONE AggExpr for this engine — shared by
    the executor (:func:`grouped_agg`) and the SQL plan-summary marker
    (``sql.parser``), so the ``SegmentedAggregate`` rendering can never
    drift from what actually lowers. Column dtypes are checked later at
    bind time; this is the fn-shape predicate only."""
    return (agg.fn in DEVICE_AGG_FNS and agg.column2 is None
            and agg.param is None)

# Dense-table ceiling: the packed key range must fit min(this, 2*bucket)
# slots or the plan reroutes to the sorted program. 2^17 keeps the table
# comfortably cache/VMEM-sized while covering the 100k-group regime.
_DENSE_MAX = 1 << 17


# ---------------------------------------------------------------------------
# Plan cache (same bounded-LRU discipline as ops/compiler.py)
# ---------------------------------------------------------------------------

_CACHE: "OrderedDict[str, object]" = OrderedDict()
#: Per-plan replay stats, keyed like _CACHE (observability.CACHES /
#: EXPLAIN ANALYZE per-program lines); mutated under _CACHE_LOCK only.
_PLAN_STATS: dict[str, dict] = {}
_CACHE_LOCK = threading.Lock()
# Serializes device-path executions (plan fetch → program call → counter
# attribution) across threads; see try_device. RLock: a thunk may itself
# re-enter try_device via a nested frame op.
_EXEC_LOCK = threading.RLock()


def clear_cache() -> None:
    """Drop every compiled grouped/sort/unique plan (tests; conf flips)."""
    with _CACHE_LOCK:
        _CACHE.clear()
        _PLAN_STATS.clear()


def cache_len() -> int:
    with _CACHE_LOCK:
        return len(_CACHE)


def abstract_specs(tree):
    """Pytree of abstract call specs: array-like leaves (anything with
    ``shape``+``dtype``) become ``jax.ShapeDtypeStruct``; host scalars
    pass through. Shape/dtype metadata only — never a device read.
    Shared by every plan-cache producer that records an example calling
    convention for the program auditor (``observability.ProgramHandle``)."""
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
        if hasattr(a, "shape") and hasattr(a, "dtype") else a, tree)


class _PlanEntry:
    """One cached grouped/sort/unique program: the counted jitted entry
    plus the UN-counted trace body and the abstract example calling
    convention recorded on first execution — the re-trace surface the
    program auditor enumerates (it must be able to ``make_jaxpr`` the
    plan without bumping ``grouped.compile`` or the replay stats)."""

    __slots__ = ("fn", "trace_body", "example", "shape_sigs", "mesh",
                 "key", "stats_key")

    def __init__(self, raw, mesh=None):
        self.trace_body = raw
        self.mesh = mesh
        # full cache key (namespace-prefixed) — set by _cached_plan;
        # the cost observatory's join handle (flush spans carry it)
        self.key = ""
        # the statstore key this plan's flushes record under (grouped
        # aggregation keys stats by struct, "G|...", across the
        # dense/sorted lowerings) — the cost observatory joins wall
        # history through it; set at the execution sites, "" until the
        # plan has run under stats
        self.stats_key = ""

        def counted(*args):
            # Runs at trace time only → counts XLA compiles (the single
            # home of the increment the four program builders shared).
            counters.increment("grouped.compile")
            return raw(*args)

        jitted = jax.jit(counted)
        if mesh is not None:
            # sharded programs (the cross-shard merge collective)
            # dispatch-to-completion under the process-wide collective
            # lock — the PR-6 overlapping-psum deadlock discipline
            from ..parallel.mesh import serialize_collectives

            jitted = serialize_collectives(jitted, mesh)
        self.fn = jitted
        self.example = None
        self.shape_sigs: set = set()

    def __call__(self, *args):
        if self.example is None:
            self.example = abstract_specs(args)
        # distinct shape signatures served → the retrace detector's
        # expected compile count (cheap: leaf-shape tuple, no tree_map
        # allocation; grouped dispatch already pays one host sync)
        self.shape_sigs.add(
            tuple(a.shape for a in jax.tree_util.tree_leaves(args)
                  if hasattr(a, "shape")))
        return self.fn(*args)


def _cached_plan(key: str, build, mesh=None):
    # Namespace prefix (ops/compiler.plan_namespace): empty in the shared
    # process-wide mode; the serving layer's isolated-cache mode salts it
    # per tenant so both plan-cache engines partition together.
    key = plan_namespace_tag() + key
    with _CACHE_LOCK:
        fn = _CACHE.get(key)
        if fn is not None:
            _CACHE.move_to_end(key)
            _PLAN_STATS.setdefault(key, {"hits": 0, "builds": 0})[
                "hits"] += 1
            return fn
    fn = _PlanEntry(build(), mesh=mesh)
    fn.key = key
    with _CACHE_LOCK:
        # Insert-if-absent (same rule as the pipeline cache): a build race
        # keeps the first inserted program so replay stats stay coherent.
        existing = _CACHE.get(key)
        if existing is not None:
            _CACHE.move_to_end(key)
            _PLAN_STATS.setdefault(key, {"hits": 0, "builds": 0})[
                "hits"] += 1
            return existing
        _CACHE[key] = fn
        _PLAN_STATS.setdefault(key, {"hits": 0, "builds": 0})["builds"] += 1
        while len(_CACHE) > int(config.pipeline_cache_size):
            evicted, _ = _CACHE.popitem(last=False)
            _PLAN_STATS.pop(evicted, None)
            counters.increment("grouped.evict")
    return fn


def cache_stats() -> dict:
    """Registry callback (observability.CACHES): size/capacity, the
    grouped.* counters, and one entry per cached program (with its
    stable ``program_key``)."""
    with _CACHE_LOCK:
        entries = [{"key": k[:160], "program_key": k, **dict(v)}
                   for k, v in _PLAN_STATS.items()]
        size = len(_CACHE)
    return {
        "kind": "plan-keyed jit cache (segment-reduction grouped exec)",
        "size": size,
        "capacity": int(config.pipeline_cache_size),
        "hits": counters.get("grouped.hit"),
        "misses": counters.get("grouped.compile"),
        "evictions": counters.get("grouped.evict"),
        "fallbacks": counters.get("grouped.fallback"),
        "dense_misses": counters.get("grouped.dense_miss"),
        "entries": entries,
    }


def _scale_rows(spec, factor: int):
    """Example specs with every array's row axis scaled — every plan in
    this cache pads all its inputs to one shared bucket, so this is "the
    same plan at a later shape bucket". Two factors (x2/x4) give the
    retrace detector a pair of FRESH traces to compare (jax may serve
    the recorded shape from a trace cache predating a config flip)."""
    return jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(
            (s.shape[0] * factor,) + tuple(s.shape[1:]), s.dtype)
        if hasattr(s, "shape") and s.shape else s, spec)


def program_handles() -> list:
    """Registry callback (CACHES.register_programs): one traceable
    handle per cached grouped/sort/unique program that has executed."""
    with _CACHE_LOCK:
        items = list(_CACHE.items())
    out = []
    for key, entry in items:
        if entry.example is None:
            continue
        observed = None
        try:
            observed = int(entry.fn._cache_size())
        except Exception:
            pass
        meta = {"expected_traces": max(len(entry.shape_sigs), 1)}
        if observed is not None:
            meta["observed_traces"] = observed
        if entry.stats_key:
            # grouped flushes record wall history under the struct key
            # ("G|..."), not the per-lowering cache key — declare the
            # join handle so the cost observatory's report can find it
            meta["stats_key"] = entry.stats_key
        out.append(_obs.ProgramHandle(
            "grouped", key, entry.trace_body, args=entry.example,
            variants={"bucket": [(_scale_rows(entry.example, 2), {}),
                                 (_scale_rows(entry.example, 4), {})]},
            mesh=entry.mesh,
            guarded=True if entry.mesh is not None else None, meta=meta))
    return out


_obs.CACHES.register("grouped", cache_stats)
_obs.CACHES.register_programs("grouped", program_handles)


# ---------------------------------------------------------------------------
# Column classification (device-side metadata probes; no data movement)
# ---------------------------------------------------------------------------

def _is_host_col(arr) -> bool:
    # object-dtype numpy arrays are the engine's string/host columns; a
    # dtype comparison needs no numpy import (np.dtype('O') == object)
    return getattr(arr, "dtype", None) == object


def _key_kind(arr) -> Optional[str]:
    """Sort/group component kind for a 1-D device column: ``f`` float
    (null-flag + neutralized value, NaN = SQL NULL), ``b`` bool (cast to
    int8, numpy-lexsort parity), ``i`` other numeric. None = ineligible."""
    if _is_host_col(arr):
        return None
    a = jnp.asarray(arr)
    if a.ndim != 1:
        return None
    if jnp.issubdtype(a.dtype, jnp.floating):
        return "f"
    if a.dtype == jnp.bool_:
        return "b"
    if jnp.issubdtype(a.dtype, jnp.integer):
        return "i"
    return None


def _acc_dtype():
    """Float accumulator dtype: the widest the backend canonicalizes
    (float64 under x64 — matching the host path's float64 numpy compute —
    else float32)."""
    return jax.dtypes.canonicalize_dtype(jnp.float64)


def _col_kind_spec(arr) -> str:
    return str(jnp.asarray(arr).dtype)


def _key_components(arr, kind: str):
    """lax.sort operands for one group key, highest priority first — the
    device mirror of ``window._key_parts``: a not-null flag partitions
    nulls from values (flag False sorts first, so nulls lead — Spark's
    NULLS FIRST group order), and the value component is NaN-neutralized
    so the flag alone decides null placement."""
    a = jnp.asarray(arr)
    if kind == "b":
        a = a.astype(jnp.int8)
    if kind == "f":
        null = jnp.isnan(a)
        return [jnp.logical_not(null),
                jnp.where(null, jnp.zeros_like(a), a)]
    return [a]


def _sorted_neq(comps_sorted) -> jnp.ndarray:
    """Adjacent-row "key changed" flags over sorted key components (the
    device ``window._neq``; components are NaN-neutralized upstream)."""
    n = comps_sorted[0].shape[0]
    neq = jnp.zeros((n - 1,), jnp.bool_)
    for c in comps_sorted:
        neq = jnp.logical_or(neq, c[1:] != c[:-1])
    return neq


def _group_scaffold(keys, key_kinds, mask):
    """The shared on-device group-discovery core of the SORTED lowering:
    stable lexicographic sort with invalid rows pushed last, then segment
    ids + boundaries. Returns ``(perm, valid, seg, boundary, groups)``."""
    n = mask.shape[0]
    idx = lax.iota(jnp.int32, n)
    ops = [jnp.logical_not(mask)]
    for k, kind in zip(keys, key_kinds):
        ops.extend(_key_components(k, kind))
    ops.append(idx)
    sorted_ops = lax.sort(tuple(ops), num_keys=len(ops))
    perm = sorted_ops[-1]
    valid = jnp.logical_not(sorted_ops[0])
    if n > 1:
        neq = _sorted_neq(sorted_ops[1:-1])
        boundary = jnp.concatenate(
            [valid[:1], jnp.logical_and(valid[1:], neq)])
    else:
        boundary = valid
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    groups = jnp.sum(boundary.astype(jnp.int32))
    return perm, valid, seg, boundary, groups


# ---------------------------------------------------------------------------
# Dense lowering: pack integer-like keys into one lexicographic slot id
# ---------------------------------------------------------------------------

def _dense_slots(keys, key_kinds, valid, S: int, axis=None):
    """Per-row dense slot ids + the fit verdict.

    Each key contributes a digit ``0`` for NULL (NaN) else ``k - lo + 1``
    — ascending slot order IS the host lexsort's group order (key 1
    major, nulls first). Returns ``(slot, ok, decoders)`` where
    ``decoders`` rebuilds per-key group values from a slot index.
    ``ok`` is a traced scalar: every float key integer-valued and the
    packed size within ``S``; when False the slot ids are garbage and the
    caller reroutes to the sorted program.

    With ``axis`` (the sharded lowering) the per-shard key extremes and
    fit verdict merge across shards (``pmin``/``pmax``), so every shard
    derives the SAME globally-consistent slot ids — the precondition for
    the cross-shard table merge."""
    acc = _acc_dtype()
    ok = jnp.asarray(True)
    sizes = []                       # traced digit counts, key order
    infos = []                       # (kind, lo_acc, dtype)
    for k, kind in zip(keys, key_kinds):
        a = jnp.asarray(k)
        af = (a.astype(jnp.int8) if kind == "b" else a).astype(acc)
        if kind == "f":
            nonnull = jnp.logical_and(valid, jnp.logical_not(jnp.isnan(af)))
            ok = jnp.logical_and(ok, jnp.all(jnp.where(
                nonnull, af == jnp.round(af), True)))
        else:
            nonnull = valid
        big = jnp.asarray(jnp.inf, acc)
        lo = jnp.min(jnp.where(nonnull, af, big))
        hi = jnp.max(jnp.where(nonnull, af, -big))
        if axis is not None:
            # global key range: ±inf identities of empty shards drop out
            lo = lax.pmin(lo, axis)
            hi = lax.pmax(hi, axis)
            any_nn = jnp.isfinite(lo)
        else:
            any_nn = jnp.any(nonnull)
        lo = jnp.where(any_nn, lo, jnp.zeros((), acc))
        hi = jnp.where(any_nn, hi, jnp.zeros((), acc) - 1)
        size = hi - lo + 2           # +1 digit offset, +1 null slot
        sizes.append(size)
        infos.append((kind, lo, a.dtype))
        # digits are computed in the float accumulator: key magnitudes
        # past its exact-integer window (2^53 under x64, 2^24 without)
        # would round and alias distinct keys — reroute instead
        exact = jnp.asarray(2.0 ** (53 if acc == jnp.float64 else 24), acc)
        ok = jnp.logical_and(ok, jnp.abs(lo) < exact)
        ok = jnp.logical_and(ok, jnp.abs(hi) < exact)
    total = sizes[0]
    for s in sizes[1:]:
        total = total * s
    if axis is not None:
        # the integrality verdict is per-shard evidence; the slot ids are
        # only sound when EVERY shard's keys pass (range/size terms are
        # already global via the merged lo/hi)
        ok = lax.pmin(ok.astype(jnp.int32), axis) > 0
    ok = jnp.logical_and(ok, jnp.isfinite(total))
    ok = jnp.logical_and(ok, total <= S)

    slot = jnp.zeros(valid.shape, jnp.int32)
    stride = jnp.asarray(1.0, acc)
    # build strides minor→major (last key = fastest digit)
    strides = [None] * len(keys)
    for i in range(len(keys) - 1, -1, -1):
        strides[i] = stride
        stride = stride * sizes[i]
    safe = jnp.where(ok, jnp.asarray(1.0, acc), jnp.zeros((), acc))
    for (kind, lo, _dt), st, k in zip(infos, strides, keys):
        a = jnp.asarray(k)
        af = (a.astype(jnp.int8) if kind == "b" else a).astype(acc)
        if kind == "f":
            digit = jnp.where(jnp.isnan(af), jnp.zeros((), acc),
                              af - lo + 1)
        else:
            digit = af - lo + 1
        # ok=False ⇒ clamp contributions to 0 so the int32 cast can't
        # overflow into UB before the verdict reroutes the plan
        slot = slot + (digit * st * safe).astype(jnp.int32)

    def make_decoder(kind, lo, dt, st, size):
        def decode(t_idx):
            tf = t_idx.astype(acc)
            digit = jnp.floor(tf / st) % size
            val = lo + digit - 1
            if kind == "f":
                return jnp.where(digit == 0,
                                 jnp.asarray(jnp.nan, acc), val).astype(dt)
            if kind == "b":
                return val.astype(jnp.int8).astype(dt)
            return val.astype(dt)
        return decode

    decoders = [make_decoder(kind, lo, dt, st, size)
                for (kind, lo, dt), st, size in zip(infos, strides, sizes)]
    return slot, ok, decoders


def _compact_index(present, S: int):
    """Gather-based table compaction: ``comp[j]`` = index of the j-th
    present slot. ``searchsorted`` over the presence prefix-sum is all
    gathers — fast on every backend, unlike an S-sized scatter."""
    cs = jnp.cumsum(present.astype(jnp.int32))
    return jnp.searchsorted(cs, lax.iota(jnp.int32, S) + 1, side="left")


def _build_dense_agg_program(key_kinds, agg_ops, val_kinds, S: int,
                             axis=None, world: int = 1):
    """The sort-free grouped lowering (see module docstring): dense slot
    ids, stacked segment reductions, gather compaction.

    Integer quantities — counts, integer sums, min/max over int columns,
    and the first/last row indices — reduce in INTEGER stacks: the float
    accumulator is float32 when x64 is off, and routing ints through it
    would silently round past 2^24 (host parity demands exact ints).

    With ``axis``/``world`` (the row-sharded lowering, arxiv 2112.09017
    reduction pattern) the SAME body runs per shard and the slot tables
    merge with ONE collective per stack — ``psum`` for the additive
    stacks (counts, sums — and with them the decomposable avg/variance
    (sum, count, Σ(v-μ)²) partials), ``pmin``/``pmax`` for the min/max
    stacks. ``first``/``last`` are not in the sharded surface (their
    row-index picks are shard-local); the caller gathers those plans."""
    acc = _acc_dtype()
    wide = jax.dtypes.canonicalize_dtype(jnp.int64)
    if axis is not None and any(fn in ("first", "last")
                                for fn, _, _ in agg_ops):
        raise AssertionError("first/last are not sharded-lowerable")

    def program(keys, vals, mask):
        n = mask.shape[0]
        idx = lax.iota(jnp.int32, n)
        valid = mask
        slot, ok, decoders = _dense_slots(keys, key_kinds, valid, S, axis)
        seg = jnp.where(valid, slot, S)          # invalid → dropped

        nonnull = {}

        def vwide(s_i):
            a = jnp.asarray(vals[s_i])
            return (a.astype(jnp.int8) if a.dtype == jnp.bool_
                    else a).astype(wide)

        for s_i, v in enumerate(vals):
            a = jnp.asarray(v)
            if val_kinds[s_i] == "f":
                nonnull[s_i] = jnp.logical_and(
                    valid, jnp.logical_not(jnp.isnan(a)))
            else:
                nonnull[s_i] = valid

        # ---- stacked additive scatters: every sum-like member in ONE
        # (n, C) segment_sum per domain (int/float) — scatter overhead
        # amortizes across the stacked columns. Counts and row indices
        # are bounded by the STATIC n, so whenever n sits inside the
        # accumulator's exact-integer window (2^53 / 2^24) they ride the
        # float stacks exactly — the common all-float plan then needs
        # only two scatters; the integer stacks exist for unbounded int
        # VALUES (sums, min/max), which must never round.
        stacks = {"ai": [], "af": [], "mf": [], "mi": [], "xi": []}
        index: dict[str, tuple[str, int]] = {}

        def want(stack, name, arr):
            if name not in index:
                index[name] = (stack, len(stacks[stack]))
                stacks[stack].append(arr)

        # counts/indices are bounded by the GLOBAL row count (n per shard
        # × world shards) — the exactness window must hold for the merged
        # totals, not just one shard's partials
        small_n = n * world < (1 << (53 if acc == jnp.float64 else 24))
        cstk = "af" if small_n else "ai"
        cdt = acc if small_n else wide
        want(cstk, "present", valid.astype(cdt))
        big_f = jnp.asarray(jnp.inf, acc)
        big_i = jnp.asarray(jnp.iinfo(wide).max, wide)
        small_i = jnp.asarray(jnp.iinfo(wide).min, wide)
        for fn, s_i, ig in agg_ops:
            if s_i < 0:
                continue
            nn = nonnull[s_i]
            # every referenced slot carries its non-null count: the
            # empty→NULL rule (all-null float groups) needs it for
            # min/max/first/last too, and one more stacked column is free
            want(cstk, f"cnt{s_i}", nn.astype(cdt))
            if fn in ("sum", "avg", "stddev", "variance", "stddev_pop",
                      "var_pop"):
                if val_kinds[s_i] != "f":
                    want("ai", f"sum{s_i}",
                         jnp.where(valid, vwide(s_i), jnp.zeros((), wide)))
                else:
                    vf = jnp.asarray(vals[s_i]).astype(acc)
                    want("af", f"sum{s_i}",
                         jnp.where(nn, vf, jnp.zeros((), acc)))
            elif fn in ("min", "max"):
                if val_kinds[s_i] == "f":
                    vf = jnp.asarray(vals[s_i]).astype(acc)
                    arr = (jnp.where(nn, vf, big_f) if fn == "min"
                           else jnp.where(nn, -vf, big_f))
                    want("mf", f"{fn}{s_i}", arr)
                elif fn == "min":
                    want("mi", f"min{s_i}",
                         jnp.where(valid, vwide(s_i), big_i))
                else:
                    want("xi", f"max{s_i}",
                         jnp.where(valid, vwide(s_i), small_i))
            elif fn == "first":
                gate = nn if ig else valid
                if small_n:
                    want("mf", f"fst{s_i}{ig}",
                         jnp.where(gate, idx.astype(acc), big_f))
                else:
                    want("mi", f"fst{s_i}{ig}",
                         jnp.where(gate, idx.astype(wide), big_i))
            elif fn == "last":
                gate = nn if ig else valid
                if small_n:
                    # ride the min stack via negation (indices are exact)
                    want("mf", f"lst{s_i}{ig}",
                         jnp.where(gate, -idx.astype(acc), big_f))
                else:
                    want("xi", f"lst{s_i}{ig}",
                         jnp.where(gate, idx.astype(wide),
                                   jnp.asarray(-1, wide)))

        reduced = {}
        for stack, red in (("ai", jax.ops.segment_sum),
                           ("af", jax.ops.segment_sum),
                           ("mf", jax.ops.segment_min),
                           ("mi", jax.ops.segment_min),
                           ("xi", jax.ops.segment_max)):
            if stacks[stack]:
                reduced[stack] = red(jnp.stack(stacks[stack], axis=1),
                                     seg, num_segments=S)
        if axis is not None:
            # THE cross-shard merge: one collective per populated stack
            # (additive → psum, min → pmin, max → pmax); after it every
            # shard holds the identical global slot tables and the rest
            # of the program computes replicated
            _merge = {"ai": lax.psum, "af": lax.psum, "mf": lax.pmin,
                      "mi": lax.pmin, "xi": lax.pmax}
            reduced = {stack: _merge[stack](r, axis)
                       for stack, r in reduced.items()}

        def table(name):
            stack, j = index[name]
            return reduced[stack][:, j]

        present = table("present") > 0
        groups = jnp.sum(present.astype(jnp.int32))

        def fsum(s_i):
            s = table(f"sum{s_i}")
            return s if val_kinds[s_i] == "f" else s.astype(acc)

        # ---- variance family second pass (only when requested): the
        # same two-pass Σ(v-μ)² the host path computes
        var_cols = []
        var_index = {}
        need_var = [s_i for fn, s_i, _ in agg_ops
                    if fn in ("stddev", "variance", "stddev_pop",
                              "var_pop")]
        if need_var:
            seg_c = jnp.clip(seg, 0, S - 1)
            for s_i in dict.fromkeys(need_var):
                nn = nonnull[s_i]
                vf = jnp.asarray(vals[s_i]).astype(acc)
                mu = fsum(s_i) / table(f"cnt{s_i}").astype(acc)
                d = jnp.where(nn, vf - jnp.take(mu, seg_c),
                              jnp.zeros((), acc))
                var_index[s_i] = len(var_cols)
                var_cols.append(d * d)
            ssd = jax.ops.segment_sum(
                jnp.stack(var_cols, axis=1), seg, num_segments=S)
            if axis is not None:
                # decomposable variance: the per-shard Σ(v-μ)² partials
                # (μ already global from the merged sum/count tables)
                # psum into the global second moment
                ssd = lax.psum(ssd, axis)

        comp = _compact_index(present, S)
        nan = jnp.asarray(jnp.nan, acc)

        key_outs = tuple(dec(comp) for dec in decoders)

        agg_outs = []
        for fn, s_i, ig in agg_ops:
            if fn == "count" and s_i < 0:
                agg_outs.append(jnp.take(table("present"), comp)
                                .astype(int_dtype()))
                continue
            vs = jnp.asarray(vals[s_i])
            cnt = jnp.take(table(f"cnt{s_i}"), comp)
            if fn == "count":
                agg_outs.append(cnt.astype(int_dtype()))
            elif fn == "sum":
                s = jnp.take(table(f"sum{s_i}"), comp)
                if val_kinds[s_i] != "f":
                    agg_outs.append(s.astype(int_dtype()))
                else:
                    agg_outs.append(jnp.where(cnt > 0, s, nan)
                                    .astype(vs.dtype))
            elif fn == "avg":
                agg_outs.append((jnp.take(fsum(s_i), comp)
                                 / cnt.astype(acc)).astype(float_dtype()))
            elif fn in ("stddev", "variance", "stddev_pop", "var_pop"):
                sd = jnp.take(ssd[:, var_index[s_i]], comp)
                cf = cnt.astype(acc)
                if fn in ("stddev", "variance"):
                    var = jnp.where(cnt > 1,
                                    sd / jnp.maximum(cf - 1, 1), nan)
                else:
                    var = jnp.where(cnt > 0, sd / jnp.maximum(cf, 1),
                                    nan)
                out = var if fn in ("variance", "var_pop") \
                    else jnp.sqrt(var)
                agg_outs.append(out.astype(float_dtype()))
            elif fn in ("min", "max"):
                m = jnp.take(table(f"{fn}{s_i}"), comp)
                if val_kinds[s_i] == "f":
                    if fn == "max":
                        m = -m
                    agg_outs.append(jnp.where(cnt > 0, m, nan)
                                    .astype(vs.dtype))
                else:
                    agg_outs.append(m.astype(vs.dtype))
            elif fn in ("first", "last"):
                tag = "fst" if fn == "first" else "lst"
                pos = jnp.take(table(f"{tag}{s_i}{ig}"), comp)
                if fn == "last" and index[f"{tag}{s_i}{ig}"][0] == "mf":
                    pos = -pos         # small-n: last rode the min stack
                pi = jnp.clip(pos, 0, n - 1).astype(jnp.int32)
                picked = jnp.take(vs, pi)
                if ig and val_kinds[s_i] == "f":
                    agg_outs.append(jnp.where(
                        cnt > 0, picked, jnp.asarray(jnp.nan, vs.dtype)))
                else:
                    agg_outs.append(picked)
            else:  # pragma: no cover - distinct aggs never lower dense
                raise AssertionError(fn)
        return key_outs, tuple(agg_outs), groups, ok

    return lambda: program


def _build_sharded_dense_agg_program(mesh, key_kinds, agg_ops, val_kinds,
                                     S: int):
    """The row-sharded dense lowering: the dense program body runs per
    shard with globally-consistent slot ids, and the slot tables merge
    with one collective per stack (see ``_build_dense_agg_program``).
    Outputs are replicated — every shard computes the identical final
    tables, so the group-count/fit-verdict sync stays ONE host read."""
    from jax.sharding import PartitionSpec as _P

    from ..parallel.mesh import DATA_AXIS, shard_map

    def build():
        program = _build_dense_agg_program(
            key_kinds, agg_ops, val_kinds, S, axis=DATA_AXIS,
            world=int(mesh.devices.size))()
        pd = _P(DATA_AXIS)
        # dqlint: ok(collective-guard): dispatch routes through
        # _PlanEntry(mesh=...), which wraps the jitted entry in
        # serialize_collectives — see _cached_plan.
        return shard_map(program, mesh=mesh, in_specs=(pd, pd, pd),
                         out_specs=_P())

    return build


# ---------------------------------------------------------------------------
# Sharded distinct: hash-partition all-to-all exchange + local unique
# ---------------------------------------------------------------------------

def _mix_hash(h, arr, kind):
    """Fold one key column into the per-row shard hash. Null-safe and
    sign-of-zero-safe like the host ``parallel.shard.hash_partition``:
    NaN (the engine's NULL) folds to one hash class, ``-0.0`` onto
    ``0.0`` (they compare equal, so they must exchange together)."""
    a = jnp.asarray(arr)
    prime = jnp.uint32(0x01000193)
    if kind == "f":
        nulls = jnp.isnan(a)
        z = jnp.where(a == 0, jnp.zeros_like(a), a)
        z = jnp.where(nulls, jnp.zeros_like(a), z)
        if a.dtype.itemsize == 8:
            bits = lax.bitcast_convert_type(z, jnp.int64)
            c = (bits & 0xFFFFFFFF).astype(jnp.uint32) \
                ^ (bits >> 32).astype(jnp.uint32)
        else:
            c = lax.bitcast_convert_type(z, jnp.int32).astype(jnp.uint32)
        h = (h * prime) ^ c
        return (h * prime) ^ nulls.astype(jnp.uint32)
    return (h * prime) ^ a.astype(jnp.uint32)


def _build_sharded_unique_program(mesh, key_kinds):
    """Distinct over a row-sharded frame: every row hash-partitions by
    key to an owner shard, ONE static-shape ``all_to_all`` exchanges the
    (keys, global row index, validity) blocks — each (src, dst) block is
    a full shard bucket with a per-row validity mask, so the plan is
    static whatever the key skew — and each shard runs the local sorted
    unique over its hash class, emitting first-occurrence GLOBAL row
    indices. The host concatenates + sorts the per-shard candidate sets
    (ascending global index IS first-occurrence order) in the engine's
    one counted sync."""
    from jax.sharding import PartitionSpec as _P

    from ..parallel.mesh import DATA_AXIS, shard_map

    D = int(mesh.devices.size)

    def build():
        def program(keys, mask):
            b = mask.shape[0]                       # per-shard slots
            me = lax.axis_index(DATA_AXIS).astype(jnp.int32)
            gidx = me * b + lax.iota(jnp.int32, b)  # global slot index
            h = jnp.full((b,), 0x811C9DC5, jnp.uint32)
            for k, kind in zip(keys, key_kinds):
                h = _mix_hash(h, k, kind)
            t = (h % jnp.uint32(D)).astype(jnp.int32)

            def xchg(blocked):     # (D*b, …): block d → shard d
                # dqlint: ok(collective-guard): dispatch is guarded by
                # _PlanEntry(mesh=...) via serialize_collectives
                return lax.all_to_all(blocked, DATA_AXIS, split_axis=0,
                                      concat_axis=0, tiled=True)

            def rep(x):            # every destination gets the full rows
                return jnp.broadcast_to(
                    x[None], (D,) + x.shape).reshape((D * b,))

            dest = lax.iota(jnp.int32, D)[:, None]
            send_ok = jnp.logical_and(mask[None, :], t[None, :] == dest)
            rmask = xchg(send_ok.reshape(D * b))
            rkeys = [xchg(rep(jnp.asarray(k))) for k in keys]
            rgidx = xchg(rep(gidx))

            n2 = D * b             # received rows (sparse validity)
            perm, valid, seg, _boundary, groups = _group_scaffold(
                rkeys, key_kinds, rmask)
            sorted_g = jnp.take(rgidx, perm)
            big = jnp.asarray(n2, jnp.int32)        # > any global index
            first_g = jax.ops.segment_min(
                jnp.where(valid, sorted_g, big), seg, num_segments=n2)
            cand = lax.sort((first_g,), num_keys=1)[0]
            # dqlint: ok(collective-guard): dispatch is guarded by
            # _PlanEntry(mesh=...) via serialize_collectives
            total = lax.psum(groups, DATA_AXIS)
            return cand, groups[None], total

        pd = _P(DATA_AXIS)
        # dqlint: ok(collective-guard): dispatch routes through
        # _PlanEntry(mesh=...), which wraps the jitted entry in
        # serialize_collectives — see _cached_plan.
        return shard_map(program, mesh=mesh, in_specs=(pd, pd),
                         out_specs=(pd, pd, _P()))

    return build


# ---------------------------------------------------------------------------
# Sorted lowering (arbitrary keys; distinct aggregates)
# ---------------------------------------------------------------------------

def _distinct_runs(seg, v, eligible, n):
    """Sorted-run scaffolding for count/sum DISTINCT: re-sort (segment,
    value) among eligible rows (ineligible ⇒ segment id n, dropped by the
    out-of-range rule of ``segment_sum``), then flag the first row of
    every (segment, value) run."""
    seg_k = jnp.where(eligible, seg, n)
    val_k = jnp.where(eligible, v, jnp.zeros_like(v))
    s2, v2 = lax.sort((seg_k, val_k), num_keys=2)
    live = s2 < n
    if n > 1:
        change = jnp.logical_or(s2[1:] != s2[:-1], v2[1:] != v2[:-1])
        first = jnp.concatenate([live[:1], jnp.logical_and(live[1:], change)])
    else:
        first = live
    return s2, v2, first


def _build_sorted_agg_program(key_kinds, agg_ops, val_kinds):
    """The sorted grouped lowering. ``agg_ops``: tuple of ``(fn, slot,
    ignore_nulls)`` — ``slot`` indexes the deduplicated value-column
    tuple, -1 for ``count(*)``."""
    acc = _acc_dtype()

    def program(keys, vals, mask):
        n = mask.shape[0]
        idx = lax.iota(jnp.int32, n)
        perm, valid, seg, boundary, groups = _group_scaffold(
            keys, key_kinds, mask)
        w_int = valid.astype(jnp.int32)
        big = jnp.asarray(n, jnp.int32)

        # first sorted position of each group → original row of the
        # group's first (stable order) member; keys gather from there
        first_pos = jax.ops.segment_min(jnp.where(valid, idx, big), seg,
                                        num_segments=n)
        fp = jnp.clip(first_pos, 0, n - 1)
        orig_first = jnp.take(perm, fp)
        key_outs = tuple(jnp.take(jnp.asarray(k), orig_first) for k in keys)

        last_pos = jax.ops.segment_max(
            jnp.where(valid, idx, jnp.asarray(-1, jnp.int32)), seg,
            num_segments=n)
        lp = jnp.clip(last_pos, 0, n - 1)

        # per-slot sorted values + null masks, computed once and shared
        sorted_vals = {}
        nonnull = {}
        for s_i, v in enumerate(vals):
            vs = jnp.take(jnp.asarray(v), perm)
            sorted_vals[s_i] = vs
            if val_kinds[s_i] == "f":
                nonnull[s_i] = jnp.logical_and(
                    valid, jnp.logical_not(jnp.isnan(vs)))
            else:
                nonnull[s_i] = valid

        nan = jnp.asarray(jnp.nan, acc)

        def seg_sum(x):
            return jax.ops.segment_sum(x, seg, num_segments=n)

        def moments(s_i):
            nn = nonnull[s_i]
            vf = sorted_vals[s_i].astype(acc)
            wz = nn.astype(acc)
            cnt = seg_sum(wz)
            s = seg_sum(jnp.where(nn, vf, jnp.zeros_like(vf)))
            return nn, vf, wz, cnt, s

        agg_outs = []
        for fn, s_i, ignore_nulls in agg_ops:
            if fn == "count" and s_i < 0:                # count(*)
                agg_outs.append(seg_sum(w_int).astype(int_dtype()))
                continue
            nn = nonnull[s_i]
            vs = sorted_vals[s_i]
            if fn == "count":
                agg_outs.append(
                    seg_sum(nn.astype(jnp.int32)).astype(int_dtype()))
            elif fn in ("sum", "avg", "stddev", "variance", "stddev_pop",
                        "var_pop"):
                _, vf, _, cnt, s = moments(s_i)
                if fn == "sum":
                    if val_kinds[s_i] != "f":
                        # integer sums stay exact integers (host parity:
                        # numpy accumulates int64, the frame stores
                        # int_dtype); int columns have no nulls so the
                        # empty→NULL rule can never fire for them
                        wide = jax.dtypes.canonicalize_dtype(jnp.int64)
                        agg_outs.append(jax.ops.segment_sum(
                            jnp.where(valid, vs,
                                      jnp.zeros_like(vs)).astype(wide),
                            seg, num_segments=n).astype(int_dtype()))
                    else:
                        # numpy reductions preserve the column dtype
                        agg_outs.append(jnp.where(
                            cnt > 0, s, nan).astype(vs.dtype))
                elif fn == "avg":
                    # 0/0 → NaN reproduces the empty→NULL rule directly
                    agg_outs.append((s / cnt).astype(float_dtype()))
                else:
                    mu = s / cnt
                    d = jnp.where(nn, vf - jnp.take(mu, seg),
                                  jnp.zeros((), acc))
                    ss = seg_sum(d * d)
                    if fn in ("stddev", "variance"):     # sample, n>1
                        var = jnp.where(cnt > 1,
                                        ss / jnp.maximum(cnt - 1, 1), nan)
                    else:                                # population, n>0
                        var = jnp.where(cnt > 0, ss / jnp.maximum(cnt, 1),
                                        nan)
                    out = var if fn in ("variance", "var_pop") \
                        else jnp.sqrt(var)
                    agg_outs.append(out.astype(float_dtype()))
            elif fn in ("min", "max"):
                red = jax.ops.segment_min if fn == "min" \
                    else jax.ops.segment_max
                if val_kinds[s_i] == "f":
                    fill = jnp.asarray(
                        jnp.inf if fn == "min" else -jnp.inf, vs.dtype)
                    m = red(jnp.where(nn, vs, fill), seg, num_segments=n)
                    cnt = seg_sum(nn.astype(jnp.int32))
                    agg_outs.append(jnp.where(
                        cnt > 0, m, jnp.asarray(jnp.nan, vs.dtype)))
                else:
                    # int/bool columns carry no nulls: every discovered
                    # group has >= 1 contributing row, so the reduction
                    # identity of masked-out rows can never surface
                    vi = vs.astype(jnp.int32) if vs.dtype == jnp.bool_ \
                        else vs
                    info = jnp.iinfo(vi.dtype)
                    fill = jnp.asarray(
                        info.max if fn == "min" else info.min, vi.dtype)
                    m = red(jnp.where(valid, vi, fill), seg,
                            num_segments=n)
                    agg_outs.append(m.astype(vs.dtype))
            elif fn in ("first", "last"):
                if ignore_nulls:
                    pos = (jax.ops.segment_min(
                        jnp.where(nn, idx, big), seg, num_segments=n)
                        if fn == "first" else
                        jax.ops.segment_max(
                            jnp.where(nn, idx, jnp.asarray(-1, jnp.int32)),
                            seg, num_segments=n))
                    has = seg_sum(nn.astype(jnp.int32)) > 0
                    picked = jnp.take(vs, jnp.clip(pos, 0, n - 1))
                    if val_kinds[s_i] == "f":
                        agg_outs.append(jnp.where(
                            has, picked, jnp.asarray(jnp.nan, vs.dtype)))
                    else:
                        # int/bool columns have no nulls: has is always
                        # true for a discovered group
                        agg_outs.append(picked)
                else:
                    agg_outs.append(jnp.take(vs, fp if fn == "first"
                                             else lp))
            elif fn in ("count_distinct", "sum_distinct"):
                # run detection in the column's OWN dtype: the float
                # accumulator is float32 without x64, where distinct
                # large ints would alias before the comparison
                vn = vs.astype(jnp.int8) if vs.dtype == jnp.bool_ else vs
                s2, v2, firstrun = _distinct_runs(seg, vn, nn, n)
                sid = jnp.where(s2 < n, s2, jnp.zeros_like(s2))
                # rows pushed past the live region carry sid 0 but
                # firstrun False / zero weight: they contribute nothing
                if fn == "count_distinct":
                    cd = jax.ops.segment_sum(
                        firstrun.astype(jnp.int32), sid, num_segments=n)
                    agg_outs.append(cd.astype(int_dtype()))
                elif val_kinds[s_i] != "f":
                    wide = jax.dtypes.canonicalize_dtype(jnp.int64)
                    sd = jax.ops.segment_sum(
                        jnp.where(firstrun, v2,
                                  jnp.zeros_like(v2)).astype(wide),
                        sid, num_segments=n)
                    agg_outs.append(sd.astype(int_dtype()))
                else:
                    wrun = jnp.where(firstrun, jnp.ones((), acc),
                                     jnp.zeros((), acc))
                    sd = jax.ops.segment_sum(wrun * v2.astype(acc), sid,
                                             num_segments=n)
                    cd = jax.ops.segment_sum(
                        firstrun.astype(jnp.int32), sid, num_segments=n)
                    agg_outs.append(jnp.where(
                        cd > 0, sd, nan).astype(float_dtype()))
            else:  # pragma: no cover - guarded by the eligibility check
                raise AssertionError(fn)
        return key_outs, tuple(agg_outs), groups

    return lambda: program


# ---------------------------------------------------------------------------
# Grouped aggregation entry point
# ---------------------------------------------------------------------------

def _run_plan(fn, args, before, sp):
    out = fn(*args)
    compiled = counters.get("grouped.compile") > before
    # plan_key: the cost-observatory join handle (attribute read, no
    # formatting — the noop contract holds on the disabled no-op span)
    sp.set(cache="compile" if compiled else "hit", plan_key=fn.key)
    if not compiled:
        counters.increment("grouped.hit")
    return out


def grouped_agg(frame, keys, agg_list):
    """Lower ``group_by(keys).agg(agg_list)`` to one device program.

    Returns the aggregated Frame — rows in lexicographic key order with
    the null group first, exactly like the host ``_group_plan`` path — or
    ``None`` when the plan is not device-lowerable (string keys,
    host-object aggregates, empty frame); the caller then takes the
    legacy numpy path and counts ``grouped.fallback``.

    The dense (sort-free) program runs first whenever the plan allows it;
    its fit verdict rides the same scalar sync as the group count, so the
    common case costs exactly ONE host sync. A range miss reroutes to the
    sorted program (one extra sync, ``grouped.dense_miss``).
    """
    from ..frame.frame import Frame

    data = frame._data                    # flush-on-read: pipeline settles
    mask = frame._mask
    n = frame.num_slots
    if n == 0:
        return None
    key_arrs, key_kinds = [], []
    for k in keys:
        arr = data.get(k)
        kind = _key_kind(arr) if arr is not None else None
        if kind is None:
            return None
        key_arrs.append(arr)
        key_kinds.append(kind)

    # value columns dedup into slots; aggregate ops reference slots so the
    # plan key stays structural (names never enter the key)
    slots: dict[str, int] = {}
    val_arrs: list = []
    val_kinds: list = []
    agg_ops = []
    for a in agg_list:
        if not agg_lowerable(a):
            return None
        if a.column is None:
            if a.fn != "count":
                return None
            agg_ops.append(("count", -1, False))
            continue
        arr = data.get(a.column)
        kind = _key_kind(arr) if arr is not None else None
        if kind is None:
            return None
        if a.column not in slots:
            slots[a.column] = len(val_arrs)
            val_arrs.append(arr)
            val_kinds.append(kind)
        agg_ops.append((a.fn, slots[a.column], bool(a.ignore_nulls)))

    struct = "|".join([
        dtype_tag(),
        ",".join(f"{k}:{_col_kind_spec(a)}"
                 for k, a in zip(key_kinds, key_arrs)),
        ",".join(f"{fn}@{s}{'!' if ig else ''}"
                 for fn, s, ig in agg_ops),
        ",".join(f"{k}:{_col_kind_spec(a)}"
                 for k, a in zip(val_kinds, val_arrs)),
    ])

    dense_ok = not any(fn in _DISTINCT_FNS for fn, _, _ in agg_ops)
    # Sharded lowering (frame rows laid out over the mesh): local
    # segment-reduce per shard + ONE cross-shard merge collective. The
    # surface is the dense program's decomposable aggregate set; plans
    # outside it (first/last — shard-local row picks — and the distinct
    # aggregates, which need a global sort) gather one level to the
    # single-device engine.
    shard = getattr(frame, "_shard", None)
    sharded = (shard is not None and dense_ok
               and not any(fn in ("first", "last")
                           for fn, _, _ in agg_ops))
    if shard is not None and not sharded:
        from ..parallel.shard import gather_arrays

        flat = gather_arrays(shard, jnp.asarray(mask, jnp.bool_),
                             *(list(key_arrs) + list(val_arrs)))
        mask = flat[0]
        key_arrs = list(flat[1:1 + len(key_arrs)])
        val_arrs = list(flat[1 + len(key_arrs):])
        shard = None

    b = n if sharded else bucket_size(n)
    keys_in = tuple(pad_rows(a, b, fresh=False) for a in key_arrs)
    vals_in = tuple(pad_rows(a, b, fresh=False) for a in val_arrs)
    mask_in = pad_rows(jnp.asarray(mask, jnp.bool_), b, fresh=False)
    args = (keys_in, vals_in, mask_in)

    S = min(_DENSE_MAX, max(2 * b, 16))

    # Plan-stats observatory gate (ONE flag read; disabled = nothing
    # else) — the grouped engine records HOST-KNOWN group counts, so its
    # selectivity evidence needs no deferred drain.
    stats_on = config.stats_enabled
    t_stats = time.perf_counter() if stats_on else 0.0
    c_stats = counters.get("grouped.compile") if stats_on else 0
    syncs = 0
    stats_key = f"G|{shard.tag()}|{struct}" if sharded else f"G|{struct}"
    # Adaptive lowering choice (cost-based optimizer + statstore): a
    # struct whose dense attempts repeatedly overflowed the slot-table
    # range skips straight to the sorted program, saving the doomed
    # dense dispatch AND its extra host sync. Advisory history — the
    # sorted program is bit-identical to the miss-reroute it replaces,
    # and fresh data that would fit again just re-earns its dense path
    # after the history entry evicts.
    skip_dense = False
    if (dense_ok and not sharded and stats_on
            and config.optimizer_enabled):
        from ..utils import statstore as _stats_store

        try:
            if _stats_store.STORE.miss_count(f"GD{S}|{struct}") >= 2:
                skip_dense = True
                counters.increment("optimizer.dense_skip")
        except Exception:
            pass
    # Adaptive lowering re-plan (sql/adaptive.py): the recorded output-
    # cardinality history for THESE key columns estimates the group
    # count; more estimated groups than the dense table has slots means
    # the dense program MUST miss (g groups need g slots), so the
    # doomed dispatch and its extra host sync are skipped for this
    # query — live estimate evidence, where the miss-history skip above
    # needs two recorded failures first. Bit-identical: the sorted
    # program is exactly the reroute a dense miss would have taken.
    if (dense_ok and not sharded and not skip_dense and stats_on
            and config.aqe_enabled):
        from ..sql import adaptive as _aqe
        from ..utils import statstore as _stats_store

        est_g = None
        try:
            ckey = cardinality_history_key("g", keys, key_arrs)
            if ckey is not None:
                est_g = _stats_store.STORE.est_rows(ckey, n)
        except Exception:
            est_g = None
        if est_g is not None and est_g > S \
                and _aqe.guard("grouped-lowering"):
            skip_dense = True
            _aqe.record(
                "grouped-lowering",
                f"est {est_g} groups > dense range {S}; sorted "
                "program directly",
                est_before=S, est_after=est_g)
    with _obs.TRACER.span(
            "frame.grouped.flush", cat="frame", op="group_by",
            keys=len(keys), aggs=len(agg_list), rows=n, bucket=b) as sp:
        g = -1
        run_dense = dense_ok and not skip_dense
        if sharded:
            before = counters.get("grouped.compile")
            fn = _cached_plan(
                f"GDH{S}|{shard.tag()}|{struct}",
                _build_sharded_dense_agg_program(
                    shard.mesh, tuple(key_kinds), tuple(agg_ops),
                    tuple(val_kinds), S),
                mesh=shard.mesh)
            fn.stats_key = stats_key
            try:
                _faults.inject("shard_merge")
                key_outs, agg_outs, groups, fit = _run_plan(
                    fn, args, before, sp)
                # ONE host sync: fit verdict + group count together
                counters.increment("frame.host_sync")
                syncs += 1
                fit_h, g_h = jax.device_get((fit, groups))
            except jax.errors.JaxRuntimeError as e:
                # shard_merge ladder: a device fault in the sharded
                # merge gathers to single-device grouped execution —
                # the query keeps its device lowering, minus one rung
                from ..parallel.shard import gather_arrays
                from ..utils.recovery import RECOVERY_LOG

                RECOVERY_LOG.record(
                    "shard_merge", "fallback", rung="gather",
                    cause=f"{type(e).__name__}: {e}",
                    detail="sharded grouped merge degraded to "
                           "single-device execution")
                counters.increment("grouped.shard_gather")
                flat = gather_arrays(shard, mask_in,
                                     *(list(keys_in) + list(vals_in)))
                args = (tuple(flat[1:1 + len(keys_in)]),
                        tuple(flat[1 + len(keys_in):]), flat[0])
            else:
                if bool(fit_h):
                    g = int(g_h)
                    sp.set(groups=g, lowering="sharded-dense",
                           shards=shard.devices)
                    if config.costprof_enabled:
                        # exchange-volume accounting (device-cost
                        # observatory): the merge collective reduces the
                        # stacked S-slot tables — static shapes, so the
                        # aggregate payload is sized without any sync
                        from ..parallel.shard import record_exchange

                        record_exchange(
                            "psum",
                            S * max(len(agg_ops), 1)
                            * _acc_dtype().itemsize * shard.devices)
                else:
                    # global key range overflowed the dense table: the
                    # sorted program is single-device — gather (same S
                    # bound would miss again, skip the dense retry)
                    counters.increment("grouped.dense_miss")
                    from ..parallel.shard import gather_arrays

                    flat = gather_arrays(shard, mask_in,
                                         *(list(keys_in)
                                           + list(vals_in)))
                    args = (tuple(flat[1:1 + len(keys_in)]),
                            tuple(flat[1 + len(keys_in):]), flat[0])
                    run_dense = False
        if g < 0 and run_dense:
            before = counters.get("grouped.compile")
            fn = _cached_plan(f"GD{S}|{struct}", _build_dense_agg_program(
                tuple(key_kinds), tuple(agg_ops), tuple(val_kinds), S))
            fn.stats_key = stats_key
            key_outs, agg_outs, groups, fit = _run_plan(
                fn, args, before, sp)
            # ONE host sync: the fit verdict + group count together
            counters.increment("frame.host_sync")
            syncs += 1
            fit_h, g_h = jax.device_get((fit, groups))
            if bool(fit_h):
                g = int(g_h)
                sp.set(groups=g, lowering="dense")
            else:
                counters.increment("grouped.dense_miss")
                if stats_on:
                    # miss history feeds the optimizer's dense-skip
                    # decision above (same struct key, next query)
                    from ..utils import statstore as _stats_store

                    try:
                        _stats_store.STORE.record_miss(f"GD{S}|{struct}")
                    except Exception:
                        pass
        if g < 0:
            before = counters.get("grouped.compile")
            fn = _cached_plan(f"GS|{struct}", _build_sorted_agg_program(
                tuple(key_kinds), tuple(agg_ops), tuple(val_kinds)))
            fn.stats_key = stats_key
            key_outs, agg_outs, groups = _run_plan(fn, args, before, sp)
            counters.increment("frame.host_sync")
            syncs += 1
            g = int(groups)
            sp.set(groups=g, lowering="sorted")
    if stats_on:
        _record_grouped_stats(
            stats_key, n, g, (time.perf_counter() - t_stats) * 1e3,
            counters.get("grouped.compile") - c_stats, syncs,
            card_key=cardinality_history_key("g", keys, key_arrs))

    # per-column eager slices, deliberately NOT compiler._unpad_tree: that
    # helper retraces per static slice length, which for the pipeline is
    # the (few-valued) frame length but here would be the DATA-DEPENDENT
    # group count — a retrace per distinct g costs far more than k+m
    # trivial slice dispatches
    out = {}
    for name, arr in zip(keys, key_outs):
        out[name] = arr[:g]
    for a, arr in zip(agg_list, agg_outs):
        out[a.name] = arr[:g]
    return Frame(out)


# ---------------------------------------------------------------------------
# Device sort (Frame.sort / SQL ORDER BY)
# ---------------------------------------------------------------------------

def _build_sort_program(key_specs):
    """``key_specs``: tuple of (kind, descending, nulls_first)."""

    def program(keys, mask):
        n = mask.shape[0]
        idx = lax.iota(jnp.int32, n)
        ops = [jnp.logical_not(mask)]
        for k, (kind, desc, nf) in zip(keys, key_specs):
            a = jnp.asarray(k)
            if kind == "b":
                a = a.astype(jnp.int8)
            if kind == "f":
                null = jnp.isnan(a)
                # flag False sorts first: nulls-first wants nulls=False
                ops.append(jnp.logical_not(null) if nf else null)
                a = jnp.where(null, jnp.zeros_like(a), a)
            ops.append(-a if desc else a)
        ops.append(idx)
        sorted_ops = lax.sort(tuple(ops), num_keys=len(ops))
        return sorted_ops[-1], jnp.sum(mask.astype(jnp.int32))

    return lambda: program


def device_sort(frame, names, ascending, nulls_first):
    """Device path for :meth:`Frame.sort`: numeric keys only, payload
    gathered with ``jnp.take`` so device columns never round-trip.

    On accelerators the permutation comes from one jitted ``lax.sort``
    program (one host sync: the valid-row count). On XLA:CPU — whose
    variadic sort is a scalar comparator loop several times slower than
    numpy's — the permutation is planned host-side from one batched pull
    of just the key columns + mask (the ``Frame.join`` "plan on host,
    materialize on device" split; still one sync, and strictly less host
    traffic than the legacy full to_pydict round-trip). ``None`` = take
    the host path."""
    from ..frame.frame import Frame

    data = frame._data
    n = frame.num_slots
    if n == 0:
        return None
    key_arrs, specs = [], []
    for name, asc, nf in zip(names, ascending, nulls_first):
        arr = data.get(name)
        kind = _key_kind(arr) if arr is not None else None
        if kind is None:
            return None
        if nf is None:
            nf = asc                  # Spark default: asc→first, desc→last
        key_arrs.append(arr)
        specs.append((kind, not asc, bool(nf)))

    mask = frame._mask
    if jax.default_backend() == "cpu":
        counters.increment("frame.host_sync")
        take = _host_sort_plan(key_arrs, specs, mask)
        return Frame(_gather_columns(data, jnp.asarray(take),
                                     host_idx=take))

    if getattr(frame, "_shard", None) is not None:
        # A total sort has no shard-local lowering (the permutation is
        # global); gather the sort inputs one level and run the
        # single-device program — the output frame is compact and
        # single-device either way.
        from ..parallel.shard import gather_arrays

        flat = gather_arrays(frame._shard, jnp.asarray(mask, jnp.bool_),
                             *key_arrs)
        mask = flat[0]
        key_arrs = list(flat[1:])

    key = "|".join([
        dtype_tag(), "S",
        ",".join(f"{k}{'v' if d else '^'}{'n' if f else '_'}:"
                 f"{_col_kind_spec(a)}"
                 for a, (k, d, f) in zip(key_arrs, specs)),
    ])
    b = bucket_size(n)
    before = counters.get("grouped.compile")
    fn = _cached_plan(key, _build_sort_program(tuple(specs)))
    keys_in = tuple(pad_rows(a, b, fresh=False) for a in key_arrs)
    mask_in = pad_rows(jnp.asarray(mask, jnp.bool_), b, fresh=False)

    with _obs.TRACER.span(
            "frame.grouped.flush", cat="frame", op="sort",
            keys=len(names), rows=n, bucket=b) as sp:
        perm, nvalid = _run_plan(fn, (keys_in, mask_in), before, sp)
        counters.increment("frame.host_sync")
        nv = int(nvalid)
    return Frame(_gather_columns(data, perm[:nv]))


def _gather_columns(data, take_dev, host_idx=None):
    """Materialize every column at the device index vector ``take_dev``.
    Host (string) columns need the indices host-side — one extra sync,
    only paid when such columns exist (or free when the caller already
    planned host-side)."""
    out = {}
    for name, arr in data.items():
        if _is_host_col(arr):
            if host_idx is None:
                counters.increment("frame.host_sync")
                host_idx = _host_index(take_dev)
            out[name] = _host_gather(arr, host_idx)
        else:
            out[name] = jnp.take(jnp.asarray(arr), take_dev, axis=0)
    return out


# ---------------------------------------------------------------------------
# Device distinct / dropDuplicates
# ---------------------------------------------------------------------------

def _build_unique_program(key_kinds):
    def program(keys, mask):
        n = mask.shape[0]
        perm, valid, seg, boundary, groups = _group_scaffold(
            keys, key_kinds, mask)
        big = jnp.asarray(n, jnp.int32)
        # stable sort ⇒ a group's first sorted member carries its minimum
        # original row index = the first occurrence; re-sorting those
        # indices restores first-occurrence output order (host parity)
        orig_first = jax.ops.segment_min(
            jnp.where(valid, perm, big), seg, num_segments=n)
        keep = lax.sort((orig_first,), num_keys=1)[0]
        return keep, groups

    return lambda: program


def device_unique(frame, key_names):
    """Device path for :meth:`Frame.distinct` (``key_names`` = all
    columns) and :meth:`Frame.drop_duplicates` (a subset): keep the first
    valid row per distinct key combination, in first-occurrence order.
    ``None`` = host path. NaN keys fold into one null group (the host
    behavior for scalar cells)."""
    from ..frame.frame import Frame

    data = frame._data
    n = frame.num_slots
    if n == 0:
        return None
    key_arrs, key_kinds = [], []
    for k in key_names:
        arr = data.get(k)
        if arr is None or _is_host_col(arr):
            return None
        a = jnp.asarray(arr)
        if a.ndim == 2:
            # vector cells group per component (distinct over an
            # assembled-features frame); NaN folds per component like the
            # scalar rule
            for j in range(a.shape[1]):
                comp = a[:, j]
                kind = _key_kind(comp)
                if kind is None:
                    return None
                key_arrs.append(comp)
                key_kinds.append(kind)
            continue
        kind = _key_kind(arr)
        if kind is None:
            return None
        key_arrs.append(arr)
        key_kinds.append(kind)

    mask = frame._mask
    card_key = (cardinality_history_key(
        "d", key_names, [data.get(k) for k in key_names])
        if config.stats_enabled else None)
    shard_store = getattr(frame, "_shard", None)
    if shard_store is not None:
        try:
            return _sharded_unique(frame, data, key_arrs, key_kinds,
                                   shard_store, card_key=card_key)
        except jax.errors.JaxRuntimeError as e:
            # shard_merge ladder: a device fault in the exchange program
            # gathers one level to the single-device unique below
            from ..parallel.shard import gather_arrays
            from ..utils.recovery import RECOVERY_LOG

            RECOVERY_LOG.record(
                "shard_merge", "fallback", rung="gather",
                cause=f"{type(e).__name__}: {e}",
                detail="sharded distinct degraded to single-device "
                       "execution")
            counters.increment("grouped.shard_gather")
            flat = gather_arrays(shard_store, jnp.asarray(mask, jnp.bool_),
                                 *key_arrs)
            mask = flat[0]
            key_arrs = list(flat[1:])

    key = "|".join([
        dtype_tag(), "U",
        ",".join(f"{k}:{_col_kind_spec(a)}"
                 for k, a in zip(key_kinds, key_arrs)),
    ])
    b = bucket_size(n)
    before = counters.get("grouped.compile")
    fn = _cached_plan(key, _build_unique_program(tuple(key_kinds)))
    fn.stats_key = key
    keys_in = tuple(pad_rows(a, b, fresh=False) for a in key_arrs)
    mask_in = pad_rows(jnp.asarray(mask, jnp.bool_), b, fresh=False)

    stats_on = config.stats_enabled
    t_stats = time.perf_counter() if stats_on else 0.0
    with _obs.TRACER.span(
            "frame.grouped.flush", cat="frame", op="distinct",
            keys=len(key_arrs), rows=n, bucket=b) as sp:
        keep, groups = _run_plan(fn, (keys_in, mask_in), before, sp)
        counters.increment("frame.host_sync")
        g = int(groups)
        sp.set(groups=g)
    if stats_on:
        _record_grouped_stats(
            key, n, g, (time.perf_counter() - t_stats) * 1e3,
            counters.get("grouped.compile") - before, 1,
            card_key=card_key)
    return Frame(_gather_columns(data, keep[:g]))


# --- BEGIN HOST FALLBACK (numpy allowed: object-array gathers + the -------
# CPU-backend sort permutation plan; nothing here touches device compute)
import numpy as np  # noqa: E402  (scoped to the host-fallback region)


def _host_index(take_dev):
    """Device index vector → host numpy (the string-payload gather sync)."""
    return np.asarray(take_dev)


def _host_gather(arr, host_idx):
    return np.asarray(arr, dtype=object)[host_idx]


def _host_sort_plan(key_arrs, specs, mask):
    """XLA:CPU sort permutation: ONE batched pull of the key columns +
    mask, then the SAME lexsort component construction as the legacy
    ``Frame.sort`` host path (``frame.frame.lexsort_keys`` — one shared
    definition, so null placement and direction semantics cannot drift).
    Returns the original row indices of the valid rows in sorted order
    (host int array)."""
    from ..frame.frame import lexsort_keys

    # dqlint: ok(host-sync): counted by the device-sort entry — the CPU
    # branch increments frame.host_sync immediately before planning here
    pulled = jax.device_get(tuple(key_arrs) + (mask,))
    m = np.asarray(pulled[-1], bool)
    vi = np.nonzero(m)[0]
    arrays = [np.asarray(k)[vi] for k in pulled[:-1]]
    order = np.lexsort(lexsort_keys(
        arrays, [not d for _k, d, _f in specs],
        [f for _k, _d, f in specs]))
    return vi[order]


def _sharded_unique(frame, data, key_arrs, key_kinds, store,
                    card_key=None):
    """Sharded :func:`device_unique`: dispatch the hash-partition
    exchange program (one counted host sync pulls the per-shard
    first-occurrence candidate sets + counts in one batch), merge-sort
    the candidates host-side (ascending global index = first-occurrence
    order, exactly the single-device output order), and gather the kept
    rows on device. Raises ``JaxRuntimeError`` through to the caller's
    shard_merge ladder."""
    from ..frame.frame import Frame

    mesh = store.mesh
    D = int(mesh.devices.size)
    n = frame.num_slots
    key = "|".join([
        dtype_tag(), f"USH{D}",
        ",".join(f"{k}:{_col_kind_spec(a)}"
                 for k, a in zip(key_kinds, key_arrs)),
    ])
    before = counters.get("grouped.compile")
    fn = _cached_plan(key, _build_sharded_unique_program(
        mesh, tuple(key_kinds)), mesh=mesh)
    fn.stats_key = key
    keys_in = tuple(jnp.asarray(a) for a in key_arrs)
    mask_in = jnp.asarray(frame._mask, jnp.bool_)
    stats_on = config.stats_enabled
    t_stats = time.perf_counter() if stats_on else 0.0
    with _obs.TRACER.span(
            "frame.grouped.flush", cat="frame", op="distinct",
            keys=len(key_arrs), rows=n, bucket=store.bucket,
            shards=D) as sp:
        _faults.inject("shard_merge")
        cand, cnts, total = _run_plan(fn, (keys_in, mask_in), before, sp)
        counters.increment("frame.host_sync")
        cand_h, cnts_h, g = jax.device_get((cand, cnts, total))
        g = int(g)
        sp.set(groups=g, lowering="sharded-exchange")
    if config.costprof_enabled:
        # exchange-volume accounting (device-cost observatory): the
        # hash-partition exchange ships FULL padded key blocks to every
        # owner shard — static shapes, sized without any sync
        from ..parallel.shard import record_exchange

        record_exchange(
            "all_to_all",
            sum(a.size * a.dtype.itemsize for a in keys_in) * D
            + mask_in.size * mask_in.dtype.itemsize * D)
    per = np.asarray(cand_h).reshape(D, -1)
    keep = np.sort(np.concatenate(
        [per[i, :int(cnts_h[i])] for i in range(D)])).astype(np.int64)
    if stats_on:
        _record_grouped_stats(
            key, n, g, (time.perf_counter() - t_stats) * 1e3,
            counters.get("grouped.compile") - before, 1,
            card_key=card_key)
    return Frame(_gather_columns(data, jnp.asarray(keep), host_idx=keep))
# --- END HOST FALLBACK ----------------------------------------------------
