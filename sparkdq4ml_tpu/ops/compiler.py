"""Fused expression-pipeline compiler: plan-keyed jit cache + bucketed padding.

The frame engine is eager by design (frame.py docstring: Spark's lazy DAG is
deliberately not replicated) — but in eager JAX every ``with_column`` /
``filter`` node dispatches as its *own* XLA computation, and the fusion the
design banks on only happens **inside** ``jax.jit``. BENCH_r05 showed the op
sweep pinned at interpreter-dispatch cost, not FLOPs. This module is the
missing compilation layer: chains of compilable frame ops coalesce (see
``Frame._defer``) and materialize as ONE jitted XLA program per *plan shape*.

Three pieces, mirroring the hierarchy lesson of Snap ML (PAPERS.md — keep the
hot loop in one compiled unit) and the graph-level-optimization approach of
"Memory Safe Computations with XLA Compiler" (PAPERS.md):

* **Structural plan key** — an ``Expr`` tree linearizes to a string of op
  kinds, referenced-column dtypes, and vector widths. Python literals in
  comparison/arithmetic positions are *hoisted out of the key* and passed as
  runtime scalar arguments, so ``price < 3`` and ``price < 4`` share one
  compiled program (``_lower`` rewrites the hoisted ``Lit`` into an
  :class:`_ArgLit` that broadcasts the runtime scalar at trace time).

* **Plan-keyed jit cache** — one ``jax.jit`` callable per plan key (bounded
  LRU). The program computes every pending column expression and the
  filter-mask AND in a single XLA computation, with buffer donation on the
  (padded) mask and on padded inputs of replaced columns.

* **Shape-bucketed row padding** — inputs pad up to the next power-of-two
  bucket with a ``False`` mask tail, so two CSV loads of different lengths
  hit the same compiled program instead of retracing; outputs slice back to
  the true row count.

Observability: ``pipeline.flush`` / ``pipeline.compile`` / ``pipeline.hit``
/ ``pipeline.fallback`` counters in :data:`utils.profiling.counters`, and a
``frame.pipeline.flush`` span (steps, bucket, rows, cache verdict) when
tracing is on. Disable the whole layer with
``.config("spark.pipeline.enabled", "false")`` (→ ``config.pipeline``),
which restores the exact per-op eager path.

Semantics are bit-identical to eager evaluation: the compiled program runs
the *same* ``Expr.eval`` methods (against a :class:`_TraceFrame` shim whose
columns are tracers), so every null rule, dtype promotion, and division
corner is the one the eager path implements. Anything outside the compilable
subset (strings, UDFs, row generators, array cells) never defers.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import logging
import math
import re
import threading
import time
import warnings
from collections import OrderedDict
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import config, float_dtype, int_dtype
from ..utils import faults as _faults
from ..utils import observability as _obs
from ..utils.profiling import counters
from . import expressions as E

__all__ = [
    "bucket_size", "pad_rows", "dtype_tag", "is_compilable",
    "run_pipeline", "clear_cache", "cache_len", "PipelineError",
    "plan_namespace", "plan_namespace_tag",
    "coalesce_scope", "run_batched", "coalesce_batch_bucket",
]


logger = logging.getLogger("sparkdq4ml_tpu.ops.compiler")


class PipelineError(RuntimeError):
    """Internal compile/run failure — callers fall back to eager replay."""


# ---------------------------------------------------------------------------
# Shape buckets
# ---------------------------------------------------------------------------

def bucket_size(n: int) -> int:
    """Row-slot bucket for ``n`` rows: the next power of two, floored at
    ``config.pipeline_min_bucket``. Two frames whose lengths land in the
    same bucket execute the same compiled program (the padded tail rides
    a ``False`` validity mask, so no masked reduction ever sees it).

    Above ``config.pipeline_exact_threshold`` the bucket IS ``n``: the
    pad-in + slice-out copies are O(n) per flush and at that scale cost
    more than the occasional retrace they avoid, while the small-frame
    regime (repeated queries over varying batch sizes) keeps full
    cross-length sharing."""
    lo = max(int(config.pipeline_min_bucket), 1)
    if n <= lo:
        return lo
    if n > int(config.pipeline_exact_threshold):
        return n
    return 1 << (n - 1).bit_length()


# ---------------------------------------------------------------------------
# Compilability — the subset of Expr that traces under jit
# ---------------------------------------------------------------------------

# Pure-jnp builtin scalar functions (device columns in, device column out).
# Everything else in _BUILTIN_FNS is host-side (strings/arrays) or needs a
# host-extracted literal in a non-trailing position.
_NUMERIC_FUNCS = frozenset({
    "abs", "sqrt", "exp", "log", "log10", "pow", "power", "floor", "ceil",
    "sign", "signum", "greatest", "least", "isnan", "coalesce", "sin",
    "cos", "tan", "asin", "acos", "atan", "atan2", "sinh", "cosh", "tanh",
    "degrees", "radians", "cbrt", "expm1", "log1p", "log2", "mod", "pmod",
    "hypot", "rint", "nanvl",
})
# round(col, d) is deliberately NOT compilable: its ``/ 10**d`` uses a
# compile-time-constant divisor, which XLA strength-reduces to a
# reciprocal multiply under jit — a 1-ULP divergence from the eager op.
# (Hoisted BinOp literals dodge this: a runtime-scalar divisor is not
# strength-reduced.) Bit-identical semantics outrank fusing one op.
_LIT_TAIL_FUNCS: frozenset = frozenset()

# (min, max) argument counts; None = unbounded. Wrong-arity calls must
# NOT defer — the eager path raises the TypeError at the call site, and
# deferring would postpone (or, pre-fix, swallow) that error.
_FUNC_ARITY = {
    "pow": (2, 2), "power": (2, 2), "atan2": (2, 2), "hypot": (2, 2),
    "mod": (2, 2), "pmod": (2, 2), "nanvl": (2, 2),
    "greatest": (1, None), "least": (1, None), "coalesce": (1, None),
    "round": (1, 2),
}


def _arity_ok(fn_name: str, n_args: int) -> bool:
    lo, hi = _FUNC_ARITY.get(fn_name, (1, 1))
    return n_args >= lo and (hi is None or n_args <= hi)


def _lit_compilable(v) -> bool:
    """Mirrors ``Lit.eval``'s type dispatch EXACTLY: only Python
    bool/int/float take the device path there (np.float64 passes as a
    float subclass; np.int64/np.bool_ do NOT subclass int/bool and fall
    to the host object-array branch, so they must not defer — and their
    repr could collide with the Python literal's plan key)."""
    return isinstance(v, (bool, int, float))


def _col_spec(arr) -> str:
    """Plan-key spec of a referenced base column: dtype + vector width
    (``f64``, ``f32x4``, …). Host object columns report ``h`` and are
    rejected by :func:`is_compilable`."""
    if isinstance(arr, np.ndarray) and arr.dtype == object:
        return "h"
    a = jnp.asarray(arr)
    w = f"x{a.shape[1]}" if a.ndim == 2 else ""
    return f"{np.dtype(a.dtype).str}{w}"


def schema_of(data: dict, pending_names: Sequence[str] = ()) -> dict:
    """name → key spec for the compilability walk: base device columns map
    to their dtype spec, host columns to ``h``, and columns produced by
    earlier pending steps to ``p`` (their dtype is determined by plan
    structure, so the spec carries no dtype)."""
    spec = {name: _col_spec(arr) for name, arr in data.items()}
    for name in pending_names:
        spec[name] = "p"
    return spec


class LazySchema:
    """``get``-only schema that resolves column specs ON DEMAND — the
    per-op ``_can_defer`` check runs once per deferred call, and eagerly
    spec-ing every stored column made deferral O(frame width) per op on
    wide frames; an expression only needs the handful of columns it
    references. Not used by :func:`_linearize` (which copies and mutates
    a real dict)."""

    def __init__(self, data: dict, pending_names: Sequence[str]):
        self._data = data
        self._pending = frozenset(pending_names)
        self._cache: dict = {}

    def get(self, name, default=None):
        if name in self._pending:
            return "p"
        try:
            return self._cache[name]
        except KeyError:
            pass
        arr = self._data.get(name)
        if arr is None:
            return default
        spec = self._cache[name] = _col_spec(arr)
        return spec


def _dtype_tag() -> str:
    """Engine dtype fingerprint prefixed to every plan key: expression
    eval bakes ``float_dtype()``/``int_dtype()`` into the program (e.g.
    ``/`` casts to the configured float), so a config flip (tests switch
    float32 ↔ float64) must miss the cache, not serve stale dtypes.

    Shared plan-key infrastructure: ``ops/segments.py`` (the grouped
    execution engine) prefixes its grouped/sort/unique plan keys with the
    same tag, and reuses :func:`bucket_size`/:func:`pad_rows` so both
    caches share one bucketing discipline."""
    return f"{np.dtype(float_dtype()).str}/{np.dtype(int_dtype()).str}"


# public aliases for the cross-module plan-cache contract (segments.py)
dtype_tag = _dtype_tag


def is_compilable(expr, schema: dict) -> bool:
    """True when ``expr`` evaluates entirely on device under jit: numeric
    column refs, numeric literals, arithmetic/comparison/boolean ops,
    numeric casts, CASE WHEN, IN over literal values, and the pure-jnp
    builtin functions. Strings, UDFs, row generators, subquery markers,
    and array-cell functions are not (they stay on the eager path)."""
    if isinstance(expr, E.Col):
        s = schema.get(expr.name)
        return s is not None and s != "h"
    if isinstance(expr, E.Lit):
        return _lit_compilable(expr.value)
    if isinstance(expr, E.Alias):
        return is_compilable(expr.child, schema)
    if isinstance(expr, E.BinOp):
        return (is_compilable(expr.left, schema)
                and is_compilable(expr.right, schema))
    if isinstance(expr, E.UnaryOp):
        return expr.op in ("-", "!", "isnull", "isnotnull") \
            and is_compilable(expr.child, schema)
    if isinstance(expr, E.Cast):
        try:
            dt = E.resolve_type_name(expr.type_name)
        except ValueError:
            return False
        if isinstance(dt, np.dtype) and dt == object:
            return False            # → string: host path
        return is_compilable(expr.child, schema)
    if isinstance(expr, E.InList):
        return (is_compilable(expr.child, schema)
                and all(isinstance(v, E.Lit)
                        and (_lit_compilable(v.value)
                             or E.InList._is_null_lit(v))
                        for v in expr.values))
    if isinstance(expr, E.CaseWhen):
        return (all(is_compilable(c, schema) and is_compilable(v, schema)
                    for c, v in expr.branches)
                and (expr.otherwise_expr is None
                     or is_compilable(expr.otherwise_expr, schema)))
    if isinstance(expr, E.Func):
        if not _arity_ok(expr.fn_name, len(expr.args)):
            return False
        if expr.fn_name in _LIT_TAIL_FUNCS:
            return (is_compilable(expr.args[0], schema)
                    and all(isinstance(a, E.Lit)
                            and _lit_compilable(a.value)
                            for a in expr.args[1:]))
        if expr.fn_name in _NUMERIC_FUNCS:
            return all(is_compilable(a, schema) for a in expr.args)
        return False
    return False


# ---------------------------------------------------------------------------
# Plan lowering: key string + literal hoisting (one traversal, lockstep)
# ---------------------------------------------------------------------------

class _ArgLit(E.Expr):
    """A hoisted literal: broadcasts the ``i``-th runtime scalar argument
    at its original ``Lit`` dtype. Exists only inside cached rewritten
    plans — never escapes the compiler."""

    def __init__(self, index: int, kind: str):
        self.index = index
        self.kind = kind            # "b" | "i" | "f"

    def eval(self, frame):
        val = _RUNTIME_LITS.lits[self.index]
        dt = (jnp.bool_ if self.kind == "b"
              else int_dtype() if self.kind == "i" else float_dtype())
        return jnp.full((frame.num_slots,), val, dt)

    def __str__(self):
        return f"?lit{self.index}"


class _HostConstLit(E.Expr):
    """A literal evaluated as a HOST numpy array: the lit-tail arguments
    of :data:`_LIT_TAIL_FUNCS` (e.g. ``round``'s digit count) are
    host-extracted inside the builtin (``int(np.asarray(d)[0])``), and
    under jit even a constant ``jnp.full`` is staged into a tracer that
    ``np.asarray`` rejects. Exists only inside rewritten plans."""

    def __init__(self, value):
        self.value = value

    def eval(self, frame):
        return np.full((frame.num_slots,), self.value)

    def __str__(self):
        return repr(self.value)


class _Lits(threading.local):
    lits: tuple = ()                # per-thread default (trace-time only)


_RUNTIME_LITS = _Lits()


def _lit_kind(v) -> str:
    if isinstance(v, (bool, np.bool_)):
        return "b"
    if isinstance(v, (int, np.integer)):
        return "i"
    return "f"


def _hoistable_lit(expr) -> Optional[E.Lit]:
    """The ``price < LITERAL`` case: a numeric (non-bool, non-NaN-sentinel)
    Lit in a BinOp/UnaryOp('-') operand position hoists to a runtime
    scalar. Bools and NaN stay in the key: NaN drives *static* null-rule
    branches elsewhere (InList), and bools are two values — hoisting buys
    nothing and loses constant-folding."""
    if isinstance(expr, E.Lit) and isinstance(expr.value, (int, float)) \
            and not isinstance(expr.value, bool) \
            and not (isinstance(expr.value, float)
                     and math.isnan(expr.value)):
        return expr
    return None


def _lower(expr, schema: dict, lits: list):
    """One traversal returning ``(key_fragment, rewritten_expr)``.

    ``lits`` collects the hoisted ``Lit`` nodes in traversal order; the
    rewritten tree holds matching :class:`_ArgLit` placeholders at the
    same positions. Key equality ⇒ identical traversal ⇒ later frames
    extract their literal values in exactly the cached program's order.
    """
    if isinstance(expr, E.Col):
        return f"C({expr.name!r}:{schema.get(expr.name)})", expr
    if isinstance(expr, E.Lit):
        return f"V({expr.value!r})", expr
    if isinstance(expr, E.Alias):
        k, ch = _lower(expr.child, schema, lits)
        return k, (expr if ch is expr.child else E.Alias(ch, expr._name))
    if isinstance(expr, E.BinOp):

        def operand(side):
            h = _hoistable_lit(side)
            if h is not None:
                idx = len(lits)
                lits.append(h)
                kind = _lit_kind(h.value)
                return f"L{kind}", _ArgLit(idx, kind)
            return _lower(side, schema, lits)

        lk, le = operand(expr.left)
        rk, re = operand(expr.right)
        return (f"B({expr.op},{lk},{rk})",
                expr if le is expr.left and re is expr.right
                else E.BinOp(expr.op, le, re))
    if isinstance(expr, E.UnaryOp):
        h = _hoistable_lit(expr.child) if expr.op == "-" else None
        if h is not None:
            idx = len(lits)
            lits.append(h)
            kind = _lit_kind(h.value)
            return (f"U(-,L{kind})",
                    E.UnaryOp("-", _ArgLit(idx, kind)))
        k, ch = _lower(expr.child, schema, lits)
        return (f"U({expr.op},{k})",
                expr if ch is expr.child else E.UnaryOp(expr.op, ch))
    if isinstance(expr, E.Cast):
        k, ch = _lower(expr.child, schema, lits)
        return (f"T({expr.type_name.lower()},{k})",
                expr if ch is expr.child else E.Cast(ch, expr.type_name))
    if isinstance(expr, E.InList):
        k, ch = _lower(expr.child, schema, lits)
        vals = ",".join("NULL" if E.InList._is_null_lit(v)
                        else repr(v.value) for v in expr.values)
        return (f"I({int(expr.negated)},{k},[{vals}])",
                expr if ch is expr.child
                else E.InList(ch, expr.values, expr.negated))
    if isinstance(expr, E.CaseWhen):
        parts = []
        branches = []
        changed = False
        for c, v in expr.branches:
            ck, ce = _lower(c, schema, lits)
            vk, ve = _lower(v, schema, lits)
            parts.append(f"{ck}:{vk}")
            changed = changed or ce is not c or ve is not v
            branches.append((ce, ve))
        if expr.otherwise_expr is not None:
            ok, oe = _lower(expr.otherwise_expr, schema, lits)
            changed = changed or oe is not expr.otherwise_expr
        else:
            ok, oe = "_", None
        return (f"W([{';'.join(parts)}],{ok})",
                expr if not changed else E.CaseWhen(branches, oe))
    if isinstance(expr, E.Func):
        lit_tail = expr.fn_name in _LIT_TAIL_FUNCS
        parts = []
        args = []
        changed = False
        for i, a in enumerate(expr.args):
            if lit_tail and i > 0:
                # host-extracted literal args (is_compilable guarantees
                # Lits here): evaluate as host numpy, bake into the key
                parts.append(f"V({a.value!r})")
                args.append(_HostConstLit(a.value))
                changed = True
                continue
            # numeric-builtin literal args hoist like BinOp operands:
            # pow(x, 2)/pow(x, 3) share one program, AND the exponent
            # stays a runtime scalar so XLA cannot strength-reduce
            # constant forms (pow(x, 2) → x*x) into 1-ULP divergence
            # from the eager op.
            h = _hoistable_lit(a)
            if h is not None:
                idx = len(lits)
                lits.append(h)
                kind = _lit_kind(h.value)
                parts.append(f"L{kind}")
                args.append(_ArgLit(idx, kind))
                changed = True
                continue
            ak, ae = _lower(a, schema, lits)
            parts.append(ak)
            changed = changed or ae is not a
            args.append(ae)
        return (f"F({expr.fn_name},{','.join(parts)})",
                expr if not changed else E.Func(expr.fn_name, args))
    raise PipelineError(f"non-compilable node reached _lower: {expr!r}")


def _referenced_base_cols(expr, schema: dict, out: list) -> None:
    """Column names an expression reads from the frame's STORED columns
    (names the step-evolved ``schema`` does not map to ``p``), in
    first-seen order — the compiled program's array inputs. A name read
    before a later step replaces it resolves to base here because the
    caller marks outputs ``p`` only after lowering the step that
    produces them."""
    if isinstance(expr, E.Col):
        if schema.get(expr.name) not in (None, "p") and expr.name not in out:
            out.append(expr.name)
        return
    for attr in ("left", "right", "child", "otherwise_expr"):
        v = getattr(expr, attr, None)
        if isinstance(v, E.Expr):
            _referenced_base_cols(v, schema, out)
    for v in getattr(expr, "args", None) or ():
        _referenced_base_cols(v, schema, out)
    for v in getattr(expr, "values", None) or ():
        _referenced_base_cols(v, schema, out)
    for c, v in getattr(expr, "branches", None) or ():
        _referenced_base_cols(c, schema, out)
        _referenced_base_cols(v, schema, out)


# ---------------------------------------------------------------------------
# The compiled program
# ---------------------------------------------------------------------------

class _TraceFrame:
    """Frame shim the compiled program evaluates expressions against: its
    columns are jit tracers and ``num_slots`` is the (static) bucket
    size, so ``Expr.eval`` runs unmodified — same nulls, same dtype
    promotion, same division corners as the eager path."""

    def __init__(self, env: dict, n: int):
        self._env = env
        self._n = n

    @property
    def num_slots(self) -> int:
        return self._n

    def _column_values(self, name: str):
        try:
            return self._env[name]
        except KeyError:
            raise KeyError(f"pipeline program has no column {name!r}; "
                           f"inputs: {sorted(self._env)}") from None


class _SchemaOverlay:
    """Mutable step-output overlay over a base schema (dict or
    :class:`LazySchema`) — _linearize marks produced columns ``p``
    without copying or eagerly materializing the base."""

    def __init__(self, base):
        self._base = base
        self._over: dict = {}

    def get(self, name, default=None):
        if name in self._over:
            return self._over[name]
        return self._base.get(name, default)

    def __setitem__(self, name, spec) -> None:
        self._over[name] = spec


def _linearize(steps, extra, base_schema):
    """THE single plan walk — used by both the cache probe and plan
    construction, so the key, the hoisted-literal order, and the
    rewritten trees can never drift apart (a divergence would make every
    lookup miss, or worse, bind literal values to the wrong _ArgLit
    slots). ``base_schema`` holds only the frame's stored columns; it
    evolves step-by-step (each step's outputs become ``p`` for LATER
    steps) so a step that reads a column *before* a later step replaces
    it keys on — and receives — the BASE column as a program input.

    Returns ``(key, lit_nodes, lowered_steps, lowered_extra, refs)``.
    """
    lits: list = []
    key_parts: list = []
    lowered_steps: list = []
    lowered_extra: list = []
    refs: list = []
    schema = _SchemaOverlay(base_schema)
    for step in steps:
        if step[0] == "with_column":
            k, ex = _lower(step[2], schema, lits)
            _referenced_base_cols(step[2], schema, refs)
            key_parts.append(f"W({step[1]!r})={k}")
            lowered_steps.append(("with_column", step[1], ex))
            schema[step[1]] = "p"
        elif step[0] == "with_columns":
            pairs = []
            ks = []
            for name, sub in step[1]:
                k, ex = _lower(sub, schema, lits)
                _referenced_base_cols(sub, schema, refs)
                ks.append(f"{name!r}={k}")
                pairs.append((name, ex))
            key_parts.append(f"WS({';'.join(ks)})")
            lowered_steps.append(("with_columns", tuple(pairs)))
            for name, _ in step[1]:
                schema[name] = "p"
        elif step[0] == "filter":
            k, ex = _lower(step[1], schema, lits)
            _referenced_base_cols(step[1], schema, refs)
            key_parts.append(f"F:{k}")
            lowered_steps.append(("filter", ex))
        else:
            raise PipelineError(f"unknown pipeline step {step[0]!r}")
    for name, sub in extra:
        k, ex = _lower(sub, schema, lits)
        _referenced_base_cols(sub, schema, refs)
        key_parts.append(f"O({name!r})={k}")
        lowered_extra.append((name, ex))
    key = _dtype_tag() + "|" + "|".join(key_parts)
    return key, lits, lowered_steps, lowered_extra, refs


class _Plan:
    """One cache entry: the jitted program plus its calling convention
    (see :func:`_linearize` for the key/lowering walk).

    With a :class:`~..parallel.shard.ShardedStore` layout the SAME body
    lowers as ONE ``shard_map``-wrapped program over the store's mesh —
    the compilable step surface is purely elementwise, so per-shard
    execution is bit-identical by construction and the program carries
    **zero cross-shard traffic** (the one extra output, the per-shard
    valid-row count, is shard-local too; the statstore drains it host-
    side later). Sharded plans key with the store's layout tag, so
    sharded and single-device programs coexist in this cache."""

    def __init__(self, steps, extra, base_schema, shard=None):
        key, lits, lowered_steps, lowered_extra, refs = _linearize(
            steps, extra, base_schema)
        replaced = {s[1] for s in steps if s[0] == "with_column"}
        for s in steps:
            if s[0] == "with_columns":
                replaced |= {name for name, _ in s[1]}
        # donate the padded inputs of columns the program both reads and
        # replaces (their old buffers die at flush); everything else rides
        # the kept dict and may alias the frame's own buffers.
        self.donated = tuple(r for r in refs if r in replaced)
        self.kept = tuple(r for r in refs if r not in replaced)
        self.extra_names = tuple(name for name, _ in lowered_extra)
        # produced columns + projection outputs — the term the cheap
        # pre-execution memory estimate (_est_flush_bytes) charges per row
        self.n_outputs = (
            sum(1 for s in lowered_steps if s[0] == "with_column")
            + sum(len(s[1]) for s in lowered_steps
                  if s[0] == "with_columns")
            + len(lowered_extra))
        self.key = key
        self.n_lits = len(lits)
        # whether this program ANDs a filter into the mask — the flushes
        # whose output mask carries a selectivity observation (statstore)
        self.has_filter = any(s[0] == "filter" for s in lowered_steps)
        # Introspection (observability.CACHES / EXPLAIN ANALYZE): per-plan
        # replay count and bucket histogram, updated under _CACHE_LOCK.
        self.hits = 0
        self.compiles = 0
        self.buckets: dict[int, int] = {}
        # Per-plan trace count: the compile-vs-hit verdict in run_pipeline
        # compares THIS plan's count across the call, not the global
        # pipeline.compile counter — a concurrent worker tracing a
        # different plan (the normal state of the serving thread-pool)
        # must not turn another plan's replay into a phantom "compile".
        self.traces = 0
        self._trace_lock = threading.Lock()

        donated_names = self.donated
        extra_pairs = tuple(lowered_extra)
        step_tuple = tuple(lowered_steps)
        # Abstract argument specs of the first real execution
        # (ShapeDtypeStructs + literal scalars) — the auditor's re-trace
        # surface (observability.ProgramHandle). None until first run.
        self.example: Optional[tuple] = None

        def body(kept, donated, mask, lit_args):
            # The pure program logic — shared by the jitted entry below
            # and the auditor's abstract re-trace (which must not count
            # as a compile nor bump the replay-verdict trace counter).
            _RUNTIME_LITS.lits = lit_args
            try:
                env = dict(kept)
                env.update(zip(donated_names, donated))
                fr = _TraceFrame(env, mask.shape[0])
                new_mask = mask
                changed = {}
                for st in step_tuple:
                    if st[0] == "with_column":
                        v = st[2].eval(fr)
                        env[st[1]] = v
                        changed[st[1]] = v
                    elif st[0] == "with_columns":
                        # Spark withColumns: every expression resolves
                        # against the *pre-step* frame state.
                        vals = {name: ex.eval(fr) for name, ex in st[1]}
                        env.update(vals)
                        changed.update(vals)
                    else:
                        # SQL three-valued logic — the SAME helper the
                        # eager Frame._filter_eager path calls
                        keep = E.predicate_keep_mask(st[1].eval(fr))
                        new_mask = jnp.logical_and(new_mask, keep)
                extras = {name: ex.eval(fr) for name, ex in extra_pairs}
                return changed, new_mask, extras
            finally:
                _RUNTIME_LITS.lits = ()

        if shard is not None:
            # ONE shard_map-wrapped program per flush: rows partition
            # over the data axis, literals replicate, and every output
            # (including the filter mask) stays row-sharded. The 4th
            # output is the per-shard post-filter valid count — shape
            # (1,) per shard → (devices,) global — so the statstore's
            # selectivity observation needs no eager cross-shard
            # reduction on the hot path.
            from jax.sharding import PartitionSpec as _P

            from ..parallel.mesh import (DATA_AXIS, serialize_collectives,
                                         shard_map)

            def sharded_body(kept, donated, mask, lit_args):
                changed, new_mask, extras = body(kept, donated, mask,
                                                 lit_args)
                valid = jnp.sum(new_mask, dtype=jnp.int32)[None]
                return changed, new_mask, extras, valid

            pd = _P(DATA_AXIS)
            sharded = shard_map(
                sharded_body, mesh=shard.mesh,
                in_specs=(pd, pd, pd, _P()),
                out_specs=(pd, pd, pd, pd))

            def program(kept, donated, mask, lit_args):
                counters.increment("pipeline.compile")
                with self._trace_lock:
                    self.traces += 1
                return sharded(kept, donated, mask, lit_args)

            self.trace_body = sharded
            # dispatch-to-completion under the process-wide collective
            # lock: the program is collective-free, but multi-device
            # executions on XLA:CPU share the rendezvous machinery and
            # the PR-6 discipline is "every mesh-bearing program
            # serializes" — sharded flushes are no exception.
            self.fn = serialize_collectives(jax.jit(program), shard.mesh)
            self.donates = False
            self.mesh = shard.mesh
            self.guarded = True
            return

        def program(kept, donated, mask, lit_args):
            # Body runs at trace time only → this counts XLA compiles.
            counters.increment("pipeline.compile")
            with self._trace_lock:
                self.traces += 1
            return body(kept, donated, mask, lit_args)

        self.trace_body = body
        self.mesh = None
        self.guarded = None

        # Buffer donation (replaced columns + mask) only pays on
        # accelerators, where the donated HBM buffer is reused for the
        # output; on XLA:CPU (unified memory) aliasing buys nothing and
        # measurably slows the call (~25% on the 20-op bench chain), so
        # the CPU path keeps the plain signature.
        if jax.default_backend() == "cpu":
            self.fn = jax.jit(program)
        else:
            self.fn = jax.jit(program, donate_argnums=(1, 2))
        self.donates = jax.default_backend() != "cpu"


_CACHE: "OrderedDict[str, _Plan]" = OrderedDict()
_CACHE_LOCK = threading.Lock()

# ---------------------------------------------------------------------------
# Cache namespaces (the serving layer's shared-plan-cache switch)
# ---------------------------------------------------------------------------

#: Plan-key namespace for the current execution context. Empty (the
#: default) means every caller shares one process-wide plan cache — the
#: structural keys make cross-tenant reuse safe by construction, so this
#: is the production configuration. The serving layer
#: (``serve/server.py``) sets a per-tenant namespace only when its
#: shared-plan-cache mode is OFF, which partitions the cache by tenant —
#: the control arm of the serving bench's shared-on vs shared-off
#: comparison. A contextvar, not a global: each worker thread/context
#: scopes its own queries without affecting concurrent ones.
_PLAN_NS: contextvars.ContextVar[str] = contextvars.ContextVar(
    "sparkdq4ml_plan_namespace", default="")


def plan_namespace_tag() -> str:
    """Key prefix for the active cache namespace (empty in shared mode).
    Prepended to pipeline plan keys here and to grouped-execution plan
    keys in ``ops/segments.py`` — both engines partition together."""
    ns = _PLAN_NS.get()
    return f"ns:{ns!r}|" if ns else ""


@contextlib.contextmanager
def plan_namespace(ns: str):
    """Scope plan-cache keys to namespace ``ns`` for the duration of the
    block (thread/context-local). ``ns=""`` is the shared namespace."""
    token = _PLAN_NS.set(str(ns))
    try:
        yield
    finally:
        _PLAN_NS.reset(token)


def clear_cache() -> None:
    """Drop every compiled plan (tests; conf flips) — the coalesced
    batched-dispatch cache too, since its entries close over base plans
    this cache just dropped."""
    with _CACHE_LOCK:
        _CACHE.clear()
        _BATCHED.clear()


def cache_len() -> int:
    with _CACHE_LOCK:
        return len(_CACHE)


def _lookup_plan(steps, extra, base_schema, shard=None):
    # Probe via the SAME _linearize walk that builds plans: key equality
    # guarantees the probe's lit order matches the cached program's
    # _ArgLit slots (the lowered trees are discarded on a hit).
    key, lits, _steps, _extra, _refs = _linearize(steps, extra, base_schema)
    if shard is not None:
        key = shard.tag() + "|" + key
    key = plan_namespace_tag() + key
    lit_values = tuple(
        # dqlint: ok(host-sync): hoisted literals are host scalars (numpy
        # or python) by Lit construction — never device arrays
        v.value.item() if hasattr(v.value, "item") else v.value
        for v in lits)
    with _CACHE_LOCK:
        plan = _CACHE.get(key)
        if plan is not None:
            _CACHE.move_to_end(key)
            return plan, lit_values
    plan = _Plan(steps, extra, base_schema, shard)
    plan.key = key                 # namespace rides the cached identity
    with _CACHE_LOCK:
        # Insert-if-absent: two threads can race past the probe and both
        # build this plan. Keeping the FIRST inserted object (instead of
        # overwriting) means every later hit/compile stat lands on the
        # one entry cache_report() sees — an overwrite would strand the
        # winner's stats on an evicted object (lost updates under the
        # 16-thread hammer test).
        existing = _CACHE.get(key)
        if existing is not None:
            _CACHE.move_to_end(key)
            return existing, lit_values
        _CACHE[key] = plan
        while len(_CACHE) > int(config.pipeline_cache_size):
            _CACHE.popitem(last=False)
            counters.increment("pipeline.evict")
    return plan, lit_values


# ---------------------------------------------------------------------------
# Padding + execution
# ---------------------------------------------------------------------------

def _pad(arr, b: int, fresh: bool):
    """Pad a device column to ``b`` row slots (zero tail). ``fresh``
    forces a copy even when no padding is needed — required for buffers
    the compiled call donates (the frame may share the original).
    Public as :data:`pad_rows` — the grouped engine (``ops/segments.py``)
    pads its key/value/mask inputs with the same helper."""
    a = jnp.asarray(arr)
    n = a.shape[0]
    if n == b:
        return jnp.copy(a) if fresh else a
    fill = jnp.zeros((b - n,) + a.shape[1:], a.dtype)
    return jnp.concatenate([a, fill], axis=0)


pad_rows = _pad


@functools.partial(jax.jit, static_argnums=1)
def _unpad_tree(tree, n: int):
    """Slice every padded output back to ``n`` rows in ONE dispatch —
    un-jitted per-array ``a[:n]`` slices cost a dispatch each (~1 ms × 11
    outputs on the 20-op bench chain, dominating the flush). A trivial
    memcpy program; its per-(shapes, n) retrace is not a pipeline
    compile."""
    return jax.tree_util.tree_map(lambda a: a[:n], tree)


def _flush_budget() -> Optional[int]:
    """Device-byte budget for ONE flush, or None (the production default,
    where the check costs one None check + one int check). Sources, in
    priority order: an injected ``oom`` fault (``utils.faults`` —
    deterministic shrunken budget, the chaos arm) and an explicit
    ``spark.audit.deviceBudget`` conf scaled by
    ``spark.audit.memoryFraction`` (the PR-9 static-bound threshold,
    promoted here from an audit-time annotation to a live pre-execution
    sensor). The allocator ``bytes_limit`` is deliberately NOT consulted
    on the hot path — reading it per flush is backend-API traffic the
    no-budget case must not pay."""
    shrunk = _faults.shrunk_budget("oom")
    if shrunk is not None:
        return shrunk
    budget = int(config.audit_device_budget)
    if budget > 0:
        return int(budget * float(config.audit_memory_fraction))
    return None


def flush_budget() -> Optional[int]:
    """Public read of the per-flush device-byte budget (None = no bound
    configured): the adaptive re-planner (``sql/adaptive.py``) re-checks
    a re-bucketed stage's static byte bound against the SAME budget the
    flush-time chunking ladder enforces, so the two layers can never
    disagree on what fits."""
    return _flush_budget()


def _est_flush_bytes(plan, data: dict, b: int) -> int:
    """Cheap, import-free over-approximation of the flush program's
    resident bytes at bucket ``b``: padded inputs + mask + 2× one
    engine-float column per produced output (value + one temporary). The
    precise instrument is the dqaudit jaxpr bound (``analysis/program``),
    but the flush hot path must never import the analysis package (the
    PR-9 hot-path pin), so the degrade decision uses this coarser mirror
    — linear in referenced columns, no tracing, only over-counts the
    per-row footprint."""
    total = b   # bool mask
    out_itemsize = np.dtype(float_dtype()).itemsize
    for name in plan.kept + plan.donated:
        a = data[name]
        width = a.shape[1] if getattr(a, "ndim", 1) == 2 else 1
        total += b * width * np.dtype(a.dtype).itemsize
    total += 2 * b * out_itemsize * max(plan.n_outputs, 1)
    return total


def _run_chunked(plan, lit_values, data: dict, mask, n: int,
                 budget: int, est: int):
    """Row-chunked execution of an over-budget flush — degrade to bounded
    memory BEFORE the allocator dies, instead of an OOM backtrace after.

    Sound because the compilable step surface is purely elementwise
    (strings/UDFs/aggregates never defer; a filter's mask AND is
    row-local), so slicing rows, replaying the SAME cached plan per
    slice, and concatenating is semantics-preserving — the chunk rows are
    a power of two, so all chunks but the tail share one compiled
    program. Counted ``pipeline.oom_chunked`` + a ``recovery.fallback``
    event at site ``oom`` (rung ``chunked``)."""
    counters.increment("pipeline.oom_chunked")
    # rows per chunk: scale the estimate down to the budget, snap to a
    # power of two (bucket reuse), floor at the bucket floor so even a
    # 1-byte injected budget makes progress
    per_row = max(1.0, est / float(max(n, 1)))
    m = max(1, int(budget / per_row))
    m = 1 << max(m.bit_length() - 1, 0)
    m = max(m, max(int(config.pipeline_min_bucket), 1))
    m = min(m, n)
    nchunks = -(-n // m)
    from ..utils.recovery import RECOVERY_LOG

    RECOVERY_LOG.record(
        "oom", "fallback", rung="chunked",
        detail=f"est {est} B > budget {budget} B; "
               f"{nchunks} chunk(s) of {m} rows")
    mask = jnp.asarray(mask, jnp.bool_)
    before = plan.traces
    stats_on = config.stats_enabled
    t_stats = time.perf_counter() if stats_on else 0.0
    pieces_changed: dict[str, list] = {}
    pieces_mask: list = []
    pieces_extras: dict[str, list] = {}
    bucket_counts: dict[int, int] = {}
    with _obs.span("frame.pipeline.flush", cat="frame", rows=n, bucket=m,
                   chunks=nchunks, oom_budget=budget, est_bytes=est,
                   plan_key=plan.key):
        # same chaos hook as the unchunked dispatch (one fire per FLUSH,
        # inside the flush span): an over-budget flush is still a flush,
        # and a scheduled pipeline_flush fault must reach the
        # Frame._flush ladder in the memory-constrained regime too
        _faults.inject("pipeline_flush")
        for start in range(0, n, m):
            rows = min(start + m, n) - start
            cb = bucket_size(rows)
            kept = {name: _pad(data[name][start:start + rows], cb,
                               fresh=False)
                    for name in plan.kept}
            donated = tuple(_pad(data[name][start:start + rows], cb,
                                 fresh=plan.donates)
                            for name in plan.donated)
            mask_in = _pad(mask[start:start + rows], cb,
                           fresh=plan.donates)
            if plan.example is None:
                # same idempotent recording as the unchunked path — a
                # plan whose FIRST execution is chunked must still be
                # enumerable by the PR-9 program auditor
                plan.example = (
                    {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                     for k, v in kept.items()},
                    tuple(jax.ShapeDtypeStruct(v.shape, v.dtype)
                          for v in donated),
                    jax.ShapeDtypeStruct(mask_in.shape, mask_in.dtype),
                    lit_values)
            with warnings.catch_warnings():
                # same unusable-donation suppression as the unchunked
                # dispatch — chunked compiles must not spam stderr
                warnings.filterwarnings(
                    "ignore", message=".*[Dd]onated.*",
                    category=UserWarning)
                changed, new_mask, extras = plan.fn(
                    kept, donated, mask_in, lit_values)
            if cb != rows:
                changed, new_mask, extras = _unpad_tree(
                    (changed, new_mask, extras), rows)
            bucket_counts[cb] = bucket_counts.get(cb, 0) + 1
            for k, v in changed.items():
                pieces_changed.setdefault(k, []).append(v)
            pieces_mask.append(new_mask)
            for k, v in extras.items():
                pieces_extras.setdefault(k, []).append(v)
    compiled = plan.traces - before
    if nchunks > compiled:
        counters.increment("pipeline.hit", nchunks - compiled)
    with _CACHE_LOCK:   # per-entry stats stay dispatch-coherent
        plan.compiles += compiled
        plan.hits += nchunks - compiled
        # per-BUCKET tallies (the tail chunk's smaller bucket included):
        # the retrace detector's expected_traces is len(buckets), so
        # folding the tail into m would misread the tail compile as a
        # retrace leak
        for cb, c in bucket_counts.items():
            plan.buckets[cb] = plan.buckets.get(cb, 0) + c

    def cat(vs):
        return vs[0] if len(vs) == 1 else jnp.concatenate(vs)

    new_data = dict(data)
    new_data.update({k: cat(vs) for k, vs in pieces_changed.items()})
    new_mask = cat(pieces_mask)
    if stats_on:
        # one record per flush (the chunked execution IS one logical
        # execution of this plan) — the heaviest plans are exactly the
        # history the est-rows/CBO store most needs
        _record_flush_stats(
            plan, data, m, n,
            (time.perf_counter() - t_stats) * 1e3, compiled > 0,
            new_mask, est=est)
    return (new_data, new_mask,
            {k: cat(vs) for k, vs in pieces_extras.items()})


def _record_flush_stats(plan, data, b: int, n: int,
                        wall_ms: float, compiled: bool, new_mask,
                        est=None, sel_scalar=None) -> None:
    """Plan-stats observatory hand-off (``utils/statstore.py``): one
    ``record_flush`` per execution of this plan (wall/compile digest,
    static byte estimate) and — when the flush carried a filter — a
    DEFERRED selectivity observation: ``sum(new_mask)`` is dispatched as
    one tiny async device reduction here and pulled in a batched,
    counted drain on the cold paths (report/EXPLAIN/save), never a sync
    on this path. Called only when ``spark.stats.enabled``; any failure
    is swallowed — statistics must never take a flush down."""
    from ..utils import statstore as _stats

    try:
        _stats.STORE.record_flush(
            plan.key, "pipeline", wall_ms=wall_ms, compiled=compiled,
            est_bytes=(est if est is not None
                       else _est_flush_bytes(plan, data, b)))
        if plan.has_filter:
            skey = _stats.selectivity_key(plan.key)
            if skey is not None:
                # sharded flushes hand over the program's own per-shard
                # valid counts — an eager sum over the sharded mask here
                # would dispatch a cross-shard collective on the hot path
                _stats.STORE.defer_rows(
                    skey, "filter", n,
                    sel_scalar if sel_scalar is not None
                    else jnp.sum(new_mask))
    except Exception:
        logger.debug("stats hand-off failed", exc_info=True)


def _record_dq_profile(steps, changed, new_mask, mask_in, b: int,
                       shard) -> None:
    """Data-quality observatory hand-off (``utils/dqprof.py``): enqueue
    deferred column-sketch reductions over this flush's outputs, plus
    per-rule pass/fail reductions for every ``with_column`` step whose
    expression is a registered DQ UDF — counted against the flush's
    INPUT mask, because the reference app fuses ``rule`` and
    ``WHERE rule > 0`` into one flush and the output mask has already
    swallowed the violations. Called only when
    ``spark.dq.profile.enabled``; any failure is swallowed — profiling
    must never take a flush down (dqprof degrades itself through the
    ``dq_profile`` fault ladder besides)."""
    from ..utils import dqprof as _dqprof

    try:
        from . import expressions as E
        from . import udf as _udf

        registry = _udf.default_registry()
        rules = []
        for step in steps:
            if step[0] == "with_column":
                pairs = [(step[1], step[2])]
            elif step[0] == "with_columns":
                pairs = list(step[1])
            else:
                continue
            for name, ex in pairs:
                if (isinstance(ex, E.UdfCall) and name in changed
                        and ex.udf_name in registry):
                    rules.append((ex.udf_name, name))
        _dqprof.observe_flush(changed, new_mask, b, shard=shard,
                              rules=rules, mask_in=mask_in)
    except Exception:
        logger.debug("dq-profile hand-off failed", exc_info=True)


#: Stage-boundary placement (cost-based optimizer, level >= 2): minimum
#: pending-step count for a chain to count as a "mega-stage" worth
#: probing, and the minimum recorded compile cost (statstore p50) of the
#: warm prefix for a split to pay — below it the two extra dispatches
#: cost more than the avoided recompile.
_SPLIT_MIN_STEPS = 6
_SPLIT_MIN_COMPILE_MS = 5.0


def _split_point(steps, extra, schema) -> Optional[int]:
    """Fused-stage boundary placement, informed by recorded compile-cost
    digests (ISSUE 14 / ``utils.statstore``): when a mega-stage's full
    plan is COLD (about to compile) but its first-half prefix is already
    compiled-and-cached with a recorded compile cost that dominates
    replay savings, split the flush at that boundary — the prefix
    replays as a cache hit and only the (smaller) tail compiles. The
    merge direction needs no hook: deferral already coalesces adjacent
    cheap stages into one program.

    Pure host-side planning: one ``_linearize`` walk plus two cache
    probes; only reached at ``spark.optimizer.level >= 2``. Returns the
    step index to split at, or None. Sound for ANY split point: the
    compilable step surface is purely elementwise-and-mask-AND, so
    running the same steps as two sequential programs is
    semantics-preserving (the row-chunked degrade's argument, applied
    along the step axis instead of the row axis)."""
    try:
        key, _lits, _s, _e, _r = _linearize(steps, tuple(extra), schema)
    except Exception:
        return None
    ns = plan_namespace_tag()
    parts = key.split("|")
    if len(parts) != 1 + len(steps) + len(extra):
        return None          # a key fragment embeds '|': stay unsplit
    with _CACHE_LOCK:
        if ns + key in _CACHE:
            return None      # warm mega-plan: replay beats any split
        k = len(steps) // 2
        prefix_key = ns + "|".join(parts[:1 + k])
        if prefix_key not in _CACHE:
            return None
    from ..utils import statstore as _stats

    cost = _stats.STORE.compile_ms_p50(prefix_key)
    if cost is None or cost < _SPLIT_MIN_COMPILE_MS:
        return None
    return k


def _history_bytes(key: str) -> Optional[int]:
    """Remembered resident-byte bound for a plan key (max of the static
    estimate and the measured peak across sessions) — the memory-aware
    chunking input the optimizer promotes from a fault-ladder rung to a
    planned decision. None = no history; never raises."""
    from ..utils import statstore as _stats

    try:
        return _stats.STORE.bytes_bound(key)
    except Exception:
        return None


def selectivity_key_for(where_steps, schema) -> Optional[str]:
    """The selectivity-entry key a flush of ``where_steps`` over
    ``schema`` would record under — computed WITHOUT executing anything
    (the same ``_linearize`` walk that builds plan keys, then the
    statstore's filter-part extraction). EXPLAIN uses this to address
    persisted history from a parsed query's WHERE clause on a fresh
    session. Returns None when the steps are not structurally
    compilable (those flushes take the eager path and record nothing)."""
    from ..utils import statstore as _stats

    try:
        key, _lits, _s, _e, _r = _linearize(tuple(where_steps), (),
                                            schema)
    except Exception:
        return None
    return _stats.selectivity_key(key)


def run_pipeline(data: dict, mask, n: int, steps, extra=(), shard=None):
    """Execute pending ``steps`` (+ ``extra`` projection expressions) over
    the base column dict as one compiled program.

    Returns ``(new_data, new_mask, extras)`` where ``new_data`` is a fresh
    column dict (replaced columns in place, new columns appended),
    ``new_mask`` the post-filter validity mask, and ``extras`` maps the
    requested projection names to their arrays — everything sliced back
    to ``n`` rows. Raises :class:`PipelineError` on any internal failure;
    callers must fall back to the eager path (never lose correctness to
    an optimization layer).

    ``shard`` (a ``parallel.shard.ShardedStore``) selects the sharded
    lowering: the frame's arrays are already laid out at the store's
    padded slot count, so ``n == slots``, no bucket padding or unpad
    slicing happens, and the plan dispatches as one ``shard_map``
    program under the collective guard — still zero counted host syncs.
    """
    counters.increment("pipeline.flush")
    # BASE schema only (lazy: only referenced columns get dtype probes) —
    # _lookup_plan/_Plan evolve it step-by-step so a column read before a
    # later step replaces it stays a base input.
    schema = LazySchema(data, ())
    try:
        b = n if shard is not None else bucket_size(n)
        # Stage-boundary placement (cost-based optimizer, level >= 2 —
        # default off): a cold mega-stage with a warm, compile-heavy
        # prefix splits into prefix-replay + tail-compile. Each half is
        # a full flush of this same entry point (its own stats, spans,
        # chunking, ladder).
        if (shard is None and n > 0
                and config.optimizer_enabled
                and int(config.optimizer_level) >= 2
                and len(steps) >= _SPLIT_MIN_STEPS):
            k = _split_point(steps, extra, schema)
            if k:
                counters.increment("optimizer.split")
                mid_data, mid_mask, _ = run_pipeline(
                    data, mask, n, steps[:k], ())
                return run_pipeline(mid_data, mid_mask, n, steps[k:],
                                    extra)
        plan, lit_values = _lookup_plan(steps, tuple(extra), schema, shard)
        # Pre-execution memory degrade (ISSUE 11 / arxiv 2206.14148):
        # when a device-byte budget is known (explicit
        # spark.audit.deviceBudget conf, or an injected `oom` fault
        # shrinking it) and the static estimate for this flush exceeds
        # it, execute row-chunked BEFORE the allocator can die — the
        # production default (no budget, no fault plan) costs one int
        # check and one None check.
        if n > 0:   # n==0 first, so a zero-row flush (where chunking is
            # meaningless) can never burn a one-shot injected oom fault
            budget = _flush_budget()
            if budget is not None:
                if shard is not None:
                    # per-SHARD resident bytes against the budget; an
                    # over-budget sharded flush degrades one rung to
                    # single-device row-chunked execution (gather first)
                    est = _est_flush_bytes(plan, data, shard.bucket)
                    if est > budget:
                        from ..parallel.shard import gather_arrays
                        from ..utils.recovery import RECOVERY_LOG

                        RECOVERY_LOG.record(
                            "shard_flush", "fallback", rung="chunked",
                            detail=f"per-shard est {est} B > budget "
                                   f"{budget} B; gathered to "
                                   "single-device chunked execution")
                        arrs = gather_arrays(
                            shard, mask, *(data[name] for name in
                                           plan.kept + plan.donated))
                        mask = arrs[0]
                        data = dict(data)
                        data.update(zip(plan.kept + plan.donated,
                                        arrs[1:]))
                        plan, lit_values = _lookup_plan(
                            steps, tuple(extra), schema)
                        est = _est_flush_bytes(plan, data, bucket_size(n))
                        return _run_chunked(plan, lit_values, data, mask,
                                            n, budget, est)
                else:
                    est = _est_flush_bytes(plan, data, b)
                    if (est <= budget and config.optimizer_enabled
                            and config.stats_enabled):
                        # memory-aware chunking as a PLANNED decision
                        # (ISSUE 14): a plan whose REMEMBERED byte bound
                        # (measured peaks included, persisted across
                        # sessions) exceeds the budget chunks up front
                        # even when the cheap static mirror under-counts
                        hist = _history_bytes(plan.key)
                        if hist is not None and hist > budget:
                            counters.increment("optimizer.mem_chunk")
                            est = hist
                    if est > budget:
                        return _run_chunked(plan, lit_values, data, mask,
                                            n, budget, est)
        before = plan.traces
        kept = {name: _pad(data[name], b, fresh=False)
                for name in plan.kept}
        # freshness only matters for buffers the call donates (the frame
        # may share the originals); _pad's zero fill is False for bool,
        # so the padded mask tail is invalid by construction
        donated = tuple(_pad(data[name], b, fresh=plan.donates)
                        for name in plan.donated)
        mask_in = _pad(jnp.asarray(mask, jnp.bool_), b, fresh=plan.donates)
        if plan.example is None:
            # Abstract specs only (shape/dtype metadata, no device read);
            # idempotent, so the benign cross-thread race needs no lock.
            plan.example = (
                {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for k, v in kept.items()},
                tuple(jax.ShapeDtypeStruct(v.shape, v.dtype)
                      for v in donated),
                jax.ShapeDtypeStruct(mask_in.shape, mask_in.dtype),
                lit_values)
        # Plan-stats observatory gate: ONE flag read; disabled mode pays
        # nothing else on this path (test-pinned, chaos-pin style).
        stats_on = config.stats_enabled
        t_stats = time.perf_counter() if stats_on else 0.0
        # Cross-request coalescing scope (serve/coalesce.py): the serving
        # worker arms it per job; everywhere else (and in serve's
        # disabled / light-load modes) it is None and the dispatch below
        # is byte-for-byte the per-request path — ONE None check,
        # test-pinned like the chaos hooks. Sharded flushes never
        # coalesce (they already serialize on the mesh).
        coal = _COALESCE.get()
        with warnings.catch_warnings():
            # donation of a replaced column whose output dtype differs
            # (int column replaced by a float expression) is unusable —
            # harmless, and the warning would spam every compile
            warnings.filterwarnings(
                "ignore", message=".*[Dd]onated.*", category=UserWarning)
            span_cm = (_obs.TRACER.span(
                "frame.pipeline.flush", cat="frame", steps=len(steps),
                outputs=len(extra), rows=n, bucket=b,
                # the cost-observatory join handle: EXPLAIN ANALYZE maps
                # this span's operator node to its cached CostProfile by
                # plan key (an attribute read, never formatting)
                plan_key=plan.key)
                if _obs.TRACER.enabled else None)
            # chaos hook at the dispatch boundary (one None check without
            # a plan): a due device_error raises HERE — inside the flush
            # span, so EXPLAIN ANALYZE attributes the fault to the
            # operator whose flush absorbed it — and escapes un-wrapped
            # for the Frame._flush recovery ladder below.
            shard_valid = None
            if span_cm is None:
                _faults.inject("pipeline_flush")
                if shard is not None:
                    _faults.inject("shard_flush")
                    changed, new_mask, extras, shard_valid = plan.fn(
                        kept, donated, mask_in, lit_values)
                elif coal is not None:
                    changed, new_mask, extras = coal.dispatch(
                        plan, b, kept, donated, mask_in, lit_values)
                else:
                    changed, new_mask, extras = plan.fn(
                        kept, donated, mask_in, lit_values)
                compiled = plan.traces > before
            else:
                with span_cm as sp:
                    _faults.inject("pipeline_flush")
                    if shard is not None:
                        _faults.inject("shard_flush")
                        changed, new_mask, extras, shard_valid = plan.fn(
                            kept, donated, mask_in, lit_values)
                        sp.set(shards=shard.devices)
                    elif coal is not None:
                        changed, new_mask, extras = coal.dispatch(
                            plan, b, kept, donated, mask_in, lit_values)
                        sp.set(coalesce=True)
                    else:
                        changed, new_mask, extras = plan.fn(
                            kept, donated, mask_in, lit_values)
                    compiled = plan.traces > before
                    sp.set(cache="compile" if compiled else "hit")
        if not compiled:
            counters.increment("pipeline.hit")
        with _CACHE_LOCK:     # per-entry stats for cache_report()
            if compiled:
                plan.compiles += 1
            else:
                plan.hits += 1
            plan.buckets[b] = plan.buckets.get(b, 0) + 1
        # Data-quality observatory gate (utils/dqprof.py): ONE flag
        # read; disabled mode pays nothing else on this path
        # (test-pinned, chaos-pin style). Runs on the PADDED bucket
        # arrays so sketch programs retrace per power-of-two bucket,
        # never per raw row count.
        if config.dq_profile_enabled:
            _record_dq_profile(steps, changed, new_mask, mask_in, b,
                               shard)
        if b != n:
            changed, new_mask, extras = _unpad_tree(
                (changed, new_mask, extras), n)
        if stats_on:
            # selectivity baseline = TRUE rows: a sharded frame's n is
            # the padded slot count, while its single-device twin (which
            # shares the layout-stripped selectivity entry) reports its
            # unpadded slots — mixing the two would skew the shared
            # history by the padding factor
            _record_flush_stats(
                plan, data, b, shard.rows if shard is not None else n,
                (time.perf_counter() - t_stats) * 1e3, compiled, new_mask,
                sel_scalar=shard_valid)
        new_data = dict(data)
        new_data.update(changed)
        return new_data, new_mask, extras
    except PipelineError:
        counters.increment("pipeline.fallback")
        raise
    except jax.errors.JaxRuntimeError:
        # A DEVICE fault (real or injected), not a compiler failure: it
        # escapes un-wrapped so the Frame._flush degradation ladder can
        # retry-then-degrade it through the recovery engine — wrapping it
        # as PipelineError would silently eat it as an eager fallback.
        raise
    except Exception as e:          # any jax/trace surprise → eager replay
        counters.increment("pipeline.fallback")
        raise PipelineError(str(e)) from e


# ---------------------------------------------------------------------------
# Cache introspection (observability.CACHES — see session.cache_report())
# ---------------------------------------------------------------------------

def cache_stats() -> dict:
    """Registry callback: size/capacity, hit/miss/eviction counters, and
    one entry per cached program (stable ``program_key``, replay count,
    bucket histogram) — the per-program lines EXPLAIN ANALYZE prints."""
    with _CACHE_LOCK:
        entries = [{"key": p.key[:160], "program_key": p.key,
                    "hits": p.hits,
                    "compiles": p.compiles, "buckets": dict(p.buckets),
                    "runtime_literals": p.n_lits}
                   for p in _CACHE.values()]
    return {
        "kind": "plan-keyed jit cache (fused expression pipeline)",
        "size": len(entries),
        "capacity": int(config.pipeline_cache_size),
        "hits": counters.get("pipeline.hit"),
        "misses": counters.get("pipeline.compile"),
        "evictions": counters.get("pipeline.evict"),
        "fallbacks": counters.get("pipeline.fallback"),
        "entries": entries,
    }


#: Numeric literal tokens of the plan-key grammar (``V(3)``/``V(3.5)``/
#: ``V(1e-06)``) — the positions literal hoisting should have emptied.
#: Bool (``V(True)``), NaN, and string literals stay distinct: the
#: compiler keys them deliberately (see ``_hoistable_lit``).
_NUM_LIT_RE = re.compile(r"V\((-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)\)")


def _bucket_variant(example, factor: int):
    """The example specs re-bucketed ``factor`` powers-of-two up — every
    padded input shares the row axis, so scaling the leading dim of each
    array spec is exactly "the same plan at a later shape bucket". The
    retrace detector compares TWO such variants (x2 vs x4) so both
    traces are fresh under the current config — never jax's possibly
    stale cached trace of the recorded shape."""
    kept, donated, mask, lits = example

    def up(s):
        shape = (s.shape[0] * factor,) + tuple(s.shape[1:])
        return jax.ShapeDtypeStruct(shape, s.dtype)

    return (({k: up(v) for k, v in kept.items()},
             tuple(up(v) for v in donated), up(mask), lits), {})


def program_handles() -> list:
    """Registry callback (observability.CACHES.register_programs): one
    :class:`~..utils.observability.ProgramHandle` per cached plan that
    has executed at least once. ``fn`` is the UN-counted trace body —
    re-tracing it is invisible to ``pipeline.compile`` and to the
    per-plan replay-verdict counter. ``expected_traces`` is the number
    of distinct shape buckets the plan served: a healthy plan compiles
    once per bucket, so ``observed > expected`` is a retrace leak."""
    with _CACHE_LOCK:
        plans = list(_CACHE.values())
    out = []
    for p in plans:
        if p.example is None:
            continue
        kept, donated, mask, lits = p.example
        out.append(_obs.ProgramHandle(
            "pipeline", p.key, p.trace_body,
            args=(kept, donated, mask, lits),
            variants={"bucket": [_bucket_variant(p.example, 2),
                                 _bucket_variant(p.example, 4)]},
            mesh=p.mesh, guarded=p.guarded,
            meta={"expected_traces": max(len(p.buckets), 1),
                  "observed_traces": p.traces,
                  # the literal-erased key: two plans colliding here are
                  # one program cached per literal VALUE — the hoisting
                  # regression the retrace detector's finalize pass
                  # closes (numeric V(...) tokens only; bool/NaN/string
                  # literals are deliberately key-resident)
                  "dedup_key": _NUM_LIT_RE.sub("V(#)", p.key),
                  "runtime_literals": p.n_lits}))
    return out


# ---------------------------------------------------------------------------
# Cross-request coalescing: vmapped batched dispatch (serve/coalesce.py)
# ---------------------------------------------------------------------------

#: Coalescing scope for the CURRENT execution context. None (the
#: default, and the only state outside an armed serving worker) keeps
#: ``run_pipeline``'s dispatch byte-for-byte the per-request path — one
#: None check, test-pinned. A serving worker whose job qualifies for
#: coalescing (conf-enabled, queue depth at/over ``minQueueDepth``,
#: deadline headroom) sets a sink whose ``dispatch()`` may rendezvous
#: this flush with concurrent same-plan flushes into ONE stacked device
#: program (see :func:`run_batched`). A contextvar, not a global: each
#: worker scopes its own job without affecting concurrent ones.
_COALESCE: contextvars.ContextVar = contextvars.ContextVar(
    "sparkdq4ml_coalesce", default=None)


@contextlib.contextmanager
def coalesce_scope(sink):
    """Route this context's unsharded pipeline flushes through ``sink``
    (an object with ``dispatch(plan, b, kept, donated, mask, lits)`` —
    the serving layer's :class:`~..serve.coalesce.Coalescer` member
    handle) for the duration of the block. ``sink=None`` restores the
    per-request path."""
    token = _COALESCE.set(sink)
    try:
        yield
    finally:
        _COALESCE.reset(token)


def coalesce_batch_bucket(n: int) -> int:
    """Member-count bucket for a coalesced batch: the next power of two,
    so a burst of 3 and a burst of 4 share one batched program (the pad
    member rides along and its outputs are discarded, exactly the row-
    padding argument applied to the member axis)."""
    return 1 << max(int(n) - 1, 0).bit_length()


class _BatchedPlan:
    """One coalesced-dispatch cache entry: ``jax.vmap`` of the base
    plan's UN-counted trace body over a new leading member axis, jitted
    once per (plan key, member-count bucket). The vmapped body is the
    auditor's re-trace surface (:func:`coalesce_program_handles`);
    the jitted entry counts its own traces for the retrace verdict —
    never the base plan's, whose replay stats stay per-request.

    The jitted entry takes the MEMBERS' argument tuples directly and
    does the stack, the vmapped body, and the per-member de-interleave
    inside ONE program: host-side ``jnp.stack`` per input array plus a
    separate split dispatch would cost a framework round-trip per array
    — more per-dispatch overhead than the solo flushes it replaces on
    dispatch-bound backends. XLA fuses the concatenates and slices into
    the body, so a coalesced flush is exactly one host->device call."""

    __slots__ = ("base", "batch", "key", "vbody", "fn", "hits",
                 "compiles", "traces", "buckets", "example",
                 "_trace_lock")

    def __init__(self, plan: _Plan, batch: int):
        self.base = plan
        self.batch = int(batch)
        self.key = f"coalesce[x{self.batch}]|{plan.key}"
        vbody = jax.vmap(plan.trace_body)
        self.vbody = vbody
        self.hits = 0
        self.compiles = 0
        self.traces = 0
        self.buckets: dict[int, int] = {}
        self.example: Optional[tuple] = None
        self._trace_lock = threading.Lock()
        n_don = len(plan.donated)
        n_lits = plan.n_lits
        kept_names = tuple(plan.kept)

        def program(members):
            with self._trace_lock:
                self.traces += 1
            kept_s = {name: jnp.stack([m[0][name] for m in members])
                      for name in kept_names}
            donated_s = tuple(jnp.stack([m[1][i] for m in members])
                              for i in range(n_don))
            mask_s = jnp.stack([m[2] for m in members])
            lits_s = tuple(jnp.stack([m[3][i] for m in members])
                           for i in range(n_lits))
            out = vbody(kept_s, donated_s, mask_s, lits_s)
            return [jax.tree_util.tree_map(lambda a, i=i: a[i], out)
                    for i in range(len(members))]

        # No donation even on accelerators: the member buffers must
        # survive for the degrade path's per-request replay.
        self.fn = jax.jit(program)


_BATCHED: "OrderedDict[tuple, _BatchedPlan]" = OrderedDict()
_BATCHED_EVICTIONS = 0


def _lookup_batched(plan: _Plan, batch: int) -> _BatchedPlan:
    global _BATCHED_EVICTIONS
    key = (plan.key, batch)
    with _CACHE_LOCK:
        bp = _BATCHED.get(key)
        if bp is not None:
            _BATCHED.move_to_end(key)
            return bp
    bp = _BatchedPlan(plan, batch)
    with _CACHE_LOCK:
        # same insert-if-absent discipline as _lookup_plan: the FIRST
        # inserted object keeps the stats every later dispatch lands on
        existing = _BATCHED.get(key)
        if existing is not None:
            _BATCHED.move_to_end(key)
            return existing
        _BATCHED[key] = bp
        while len(_BATCHED) > int(config.pipeline_cache_size):
            _BATCHED.popitem(last=False)
            _BATCHED_EVICTIONS += 1
    return bp


def est_member_bytes(plan: _Plan, kept: dict, donated, b: int) -> int:
    """Per-member resident-byte estimate of a coalesced flush, computed
    from the already-padded member inputs (the coalescer prices the
    STACKED batch as ``members × this`` against the admission budget —
    the same cheap static mirror as :func:`_est_flush_bytes`, fed from
    buffers instead of the frame dict)."""
    total = b   # bool mask
    out_itemsize = np.dtype(float_dtype()).itemsize
    for a in list(kept.values()) + list(donated):
        total += int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
    total += 2 * b * out_itemsize * max(plan.n_outputs, 1)
    return total


def run_batched(plan: _Plan, b: int, members):
    """Execute ``members`` — each ``(kept, donated, mask, lit_values)``,
    every one already padded to row bucket ``b`` by its own
    ``run_pipeline`` frame — as ONE stacked device dispatch of the
    vmapped plan body, and return the per-member ``(changed, new_mask,
    extras)`` list in member order.

    Inputs stack along a new leading member axis (hoisted literals
    included: each scalar slot becomes a ``(batch,)`` argument the
    vmapped ``_ArgLit`` broadcasts per member, so queries differing only
    in literal VALUES still share the one batched program). The member
    count pads up to :func:`coalesce_batch_bucket` by repeating member
    0, whose extra outputs are dropped at the de-interleave."""
    n = len(members)
    batch = coalesce_batch_bucket(n)
    if batch > n:
        members = list(members) + [members[0]] * (batch - n)
    # normalized pytree structure (dict / tuple / leaf / tuple per
    # member): a list-vs-tuple drift between callers must not retrace
    margs = tuple((dict(m[0]), tuple(m[1]), m[2], tuple(m[3]))
                  for m in members)
    bp = _lookup_batched(plan, batch)
    before = bp.traces
    out = bp.fn(margs)
    if bp.example is None:
        # abstract specs of the STACKED form the vmapped body consumes
        # (the auditor re-traces ``bp.vbody``, not the member-tuple
        # wrapper), idempotent (the benign cross-thread race needs no
        # lock) — literals are (batch,) ARRAY specs here, not the base
        # plan's host scalars: the batched calling convention
        m0 = margs[0]

        def stacked(v):
            a = jnp.asarray(v)
            return jax.ShapeDtypeStruct((batch,) + tuple(a.shape),
                                        a.dtype)

        bp.example = (
            {k: stacked(v) for k, v in m0[0].items()},
            tuple(stacked(v) for v in m0[1]),
            stacked(m0[2]),
            tuple(stacked(v) for v in m0[3]))
    compiled = bp.traces > before
    with _CACHE_LOCK:   # per-entry stats stay dispatch-coherent
        if compiled:
            bp.compiles += 1
        else:
            bp.hits += 1
        bp.buckets[b] = bp.buckets.get(b, 0) + 1
    return out[:n]


def coalesce_cache_stats() -> dict:
    """Registry callback (observability.CACHES): the coalesced-dispatch
    cache next to the per-request plan cache in ``cache_report()`` /
    ``/metrics`` — one entry per (plan key, member-count bucket), its
    program key carrying the ``coalesce[xN]`` batch-bucket tag."""
    with _CACHE_LOCK:
        entries = [{"key": bp.key[:160], "program_key": bp.key,
                    "hits": bp.hits, "compiles": bp.compiles,
                    "buckets": dict(bp.buckets), "batch": bp.batch,
                    "runtime_literals": bp.base.n_lits}
                   for bp in _BATCHED.values()]
        evicts = _BATCHED_EVICTIONS
    return {
        "kind": "coalesced batched-dispatch cache (vmapped plans)",
        "size": len(entries),
        "capacity": int(config.pipeline_cache_size),
        "hits": sum(e["hits"] for e in entries),
        "misses": sum(e["compiles"] for e in entries),
        "evictions": evicts,
        "entries": entries,
    }


def _coalesce_variant(example, factor: int):
    """The batched example specs scaled ``factor`` up along the MEMBER
    axis (every stacked input shares it, literal columns included) —
    "the same vmapped plan at a later batch bucket", the structural-
    stability probe the retrace detector compares x2 vs x4."""
    kept, donated, mask, lits = example

    def up(s):
        shape = (s.shape[0] * factor,) + tuple(s.shape[1:])
        return jax.ShapeDtypeStruct(shape, s.dtype)

    return (({k: up(v) for k, v in kept.items()},
             tuple(up(v) for v in donated), up(mask),
             tuple(up(v) for v in lits)), {})


def coalesce_program_handles() -> list:
    """Registry callback (observability.CACHES.register_programs): one
    ProgramHandle per executed batched plan, so dqaudit's program tier
    and the costprof observatory enumerate the coalesced hot path
    exactly like per-request plans — ``fn`` is the un-counted vmapped
    body; ``expected_traces`` is the row buckets served at this batch
    bucket (each is one legitimate trace of the one jitted entry)."""
    with _CACHE_LOCK:
        plans = list(_BATCHED.values())
    out = []
    for bp in plans:
        if bp.example is None:
            continue
        out.append(_obs.ProgramHandle(
            "coalesce", bp.key, bp.vbody,
            args=bp.example,
            variants={"bucket": [_coalesce_variant(bp.example, 2),
                                 _coalesce_variant(bp.example, 4)]},
            meta={"expected_traces": max(len(bp.buckets), 1),
                  "observed_traces": bp.traces,
                  # literal-erased like the pipeline handles; the
                  # coalesce[xN] tag stays, so batch buckets are
                  # distinct programs, not dedup collisions
                  "dedup_key": _NUM_LIT_RE.sub("V(#)", bp.key),
                  "runtime_literals": bp.base.n_lits}))
    return out


_obs.CACHES.register("pipeline", cache_stats)
_obs.CACHES.register_programs("pipeline", program_handles)
_obs.CACHES.register("coalesce", coalesce_cache_stats)
_obs.CACHES.register_programs("coalesce", coalesce_program_handles)
