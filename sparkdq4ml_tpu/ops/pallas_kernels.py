"""Pallas TPU kernels for the framework's hot ops.

Two data-touching operations dominate the pipeline (SURVEY.md §3.2/§3.3):

1. **The masked augmented Gramian** ``A = ZᵀZ, Z = [X, y, 1]·mask`` — the
   single matmul that is the entire data pass of a linear/logistic fit (the
   ``treeAggregate`` analogue; ``models/solvers.py:augmented_gram``). The
   Pallas version tiles rows HBM→VMEM and accumulates the ``(d+2, d+2)``
   block on the MXU across the grid, so arbitrarily many rows stream through
   a fixed VMEM footprint — the XLA path must materialize the masked ``Z``
   in HBM first; here the mask-multiply fuses into the same VMEM pass.

2. **The DQ rule chain** (`MinimumPriceDataQualityService` +
   `PriceCorrelationDataQualityService` + the two SQL filters,
   `DataQuality4MachineLearningApp.java:68-95`) — four elementwise passes in
   the reference (two UDF columns, two WHERE filters), fused here into ONE
   row-tiled VPU pass emitting both rule columns and the combined keep-mask.
   The rule-layer entry point is ``ops/rules.py:dq_rules_fused``, which
   dispatches here when enabled and to the equivalent XLA expression
   otherwise.

Both kernels are optional fast paths selected via ``config.pallas``:
``"on"`` (compiled, TPU), ``"auto"`` (compiled when the backend is TPU),
``"interpret"`` (CPU tests/CI — same kernel code through the Pallas
interpreter), ``"off"`` (default — plain XLA, which already fuses these
well). Dispatch falls back to XLA inside ``shard_map`` or ``vmap`` traces:
Pallas state-discharge has no vma rules, and the pallas_call batching rule
would break the grid-step-0 accumulator init.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..config import config
from .rules import (BAD_ROW_SENTINEL, CORRELATION_MAX_GUESTS,
                    CORRELATION_MAX_PRICE, MIN_PRICE)

# Row-tile height for the Gramian kernel: multiples of the f32 sublane (8);
# 512 rows × up-to-128 padded lanes ≈ 256 KB/input block in VMEM — far under
# the ~16 MB budget, large enough to keep the MXU busy.
BLOCK_ROWS = 512
# Lane-tile width for the Gramian OUTPUT: Mosaic's scoped-VMEM scratch for
# the accumulator scales with the output block (measured ~16× its padded
# bytes on v5e — a full (514, 514) f32 block wants 21 MB against the 16 MB
# stack limit). Tiling the output columns keeps the scratch bounded for any
# d; at d+2 ≤ 128 the grid degenerates to the untiled layout.
BLOCK_COLS = 128
# Row tiles for the elementwise DQ kernel: (DQ_BLOCK_ROWS, 128) f32 blocks,
# 5 buffers live (2 in + 3 out) ≈ 1.3 MB of VMEM.
DQ_BLOCK_ROWS = 512


def use_pallas() -> bool:
    """True when the configured mode selects the Pallas path."""
    mode = getattr(config, "pallas", "off")
    if mode == "on":
        return True
    if mode == "interpret":
        return True
    if mode == "auto":
        return jax.default_backend() == "tpu"
    return False


def _interpret() -> bool:
    return getattr(config, "pallas", "off") == "interpret"


def _unsupported_trace(*operands) -> bool:
    """True when dispatching a Pallas kernel here would be incorrect:

    * inside ``shard_map`` (operands carry varying-mesh-axes; the Pallas
      state-discharge machinery has no vma rules), or
    * inside ``vmap`` (the pallas_call batching rule prepends the batch axis
      to the grid, so ``pl.program_id(0)`` would index the batch, breaking
      the grid-step-0 accumulator init).

    Callers fall back to the identical-semantics XLA expression.
    """
    from jax._src.interpreters import batching

    # jax.typeof is the modern name; 0.4.x spells it get_aval (and its
    # avals carry no vma field — old shard_map tracks replication
    # elsewhere, so the getattr default covers it)
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        from jax.core import get_aval as typeof

    for op in operands:
        if isinstance(op, batching.BatchTracer):
            return True
        if "ShardMap" in type(op).__name__:   # 0.4.x shard_map tracer
            return True
        if getattr(typeof(op), "vma", frozenset()):
            return True
    return False


def dispatch_to_pallas(*operands) -> bool:
    """Single gate used by the XLA-level callers (solvers/rules)."""
    return use_pallas() and not _unsupported_trace(*operands)


# ---------------------------------------------------------------------------
# Masked augmented Gramian
# ---------------------------------------------------------------------------

def _gram_kernel(zl_ref, zr_ref, w_ref, out_ref):
    """One (col-tile, row-tile) step: out[:, j] += (Z·w)ᵀ Z[:, j] — the
    mask-multiply fused into the MXU pass. Row tiles are the INNER grid
    axis, so each output column block accumulates to completion before
    the next is touched."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    zw = zl_ref[:] * w_ref[:]  # broadcast (TILE, 1) mask over lanes
    # Contract the row (sublane) dimension: (TILE, D)ᵀ(TILE, Dt) → (D, Dt).
    out_ref[:] += jax.lax.dot_general(
        zw, zr_ref[:],
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=out_ref.dtype,
    )


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _masked_gram_call(Z, w, block_rows: int, interpret: bool):
    n, D = Z.shape
    bc = min(BLOCK_COLS, D)
    grid = (pl.cdiv(D, bc), pl.cdiv(n, block_rows))  # (cols OUTER, rows inner)
    return pl.pallas_call(
        _gram_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, D), lambda j, i: (i, 0)),
            pl.BlockSpec((block_rows, bc), lambda j, i: (i, j)),
            pl.BlockSpec((block_rows, 1), lambda j, i: (i, 0)),
        ],
        # One output column block per outer step, revisited by every row
        # tile (accumulator); VMEM scratch scales with (D, bc), not (D, D).
        out_specs=pl.BlockSpec((D, bc), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((D, D), Z.dtype),
        interpret=interpret,
    )(Z, Z, w)


def masked_gram_pallas(X: jnp.ndarray, y: jnp.ndarray, mask: jnp.ndarray,
                       block_rows: int = BLOCK_ROWS) -> jnp.ndarray:
    """Pallas equivalent of ``solvers.augmented_gram`` (same contract).

    ``A = ZᵀZ`` with ``Z = [X, y, 1]·mask``, shape ``(d+2, d+2)``. The mask
    enters once (Z·w against unweighted Z ⇒ ZᵀM Z for boolean M where
    w² = w); row padding added below carries zero weight.
    """
    D = X.shape[1] + 2
    n = X.shape[0]
    if n == 0:
        # A zero-step grid would never run the accumulator init.
        return jnp.zeros((D, D), X.dtype)
    w = mask.astype(X.dtype)
    ones = jnp.ones_like(y)
    Z = jnp.concatenate([X, y[:, None], ones[:, None]], axis=1)
    block = min(block_rows, max(8, -(-n // 8) * 8))
    pad = (-n) % block
    if pad:
        # Out-of-bounds block slots are undefined in Pallas; pad explicitly
        # with zero rows (zero weight ⇒ zero contribution to the Gramian).
        Z = jnp.concatenate([Z, jnp.zeros((pad, Z.shape[1]), Z.dtype)])
        w = jnp.concatenate([w, jnp.zeros((pad,), w.dtype)])
    return _masked_gram_call(Z, w[:, None], block, _interpret())


def _packed_gram_kernel(zl_ref, zr_ref, out_ref):
    """One (col-tile, row-tile) step of the pre-masked design:
    out[:, j] += Zᵀ Z[:, j]."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += jax.lax.dot_general(
        zl_ref[:], zr_ref[:],
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=out_ref.dtype,
    )


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _packed_gram_call(Z, block_rows: int, interpret: bool):
    n, D = Z.shape
    bc = min(BLOCK_COLS, D)
    grid = (pl.cdiv(D, bc), pl.cdiv(n, block_rows))
    return pl.pallas_call(
        _packed_gram_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, D), lambda j, i: (i, 0)),
            pl.BlockSpec((block_rows, bc), lambda j, i: (i, j)),
        ],
        out_specs=pl.BlockSpec((D, bc), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((D, D), Z.dtype),
        interpret=interpret,
    )(Z, Z)


def packed_gram_pallas(Z: jnp.ndarray,
                       block_rows: int = BLOCK_ROWS) -> jnp.ndarray:
    """Gramian of a pre-masked packed design ``Z = [X, y, 1]·mask``
    (``parallel/distributed.py:pack_design``): ``A = ZᵀZ``, rows streamed
    HBM→VMEM through a fixed footprint. Same contract as
    ``masked_gram_pallas`` with the mask-multiply already folded into ``Z``
    — one fewer input buffer."""
    n, D = Z.shape
    if n == 0:
        return jnp.zeros((D, D), Z.dtype)
    block = min(block_rows, max(8, -(-n // 8) * 8))
    pad = (-n) % block
    if pad:
        # Out-of-bounds block slots are undefined in Pallas; zero rows
        # contribute nothing to ZᵀZ.
        Z = jnp.concatenate([Z, jnp.zeros((pad, D), Z.dtype)])
    return _packed_gram_call(Z, block, _interpret())


# ---------------------------------------------------------------------------
# Fused DQ rule chain
# ---------------------------------------------------------------------------

def _dq_kernel(price_ref, guest_ref, pnm_ref, pcc_ref, keep_ref):
    """Fused DQ chain: both rule columns + combined keep mask, one VPU pass.

    Must match ``ops/rules.py`` exactly, including the null (NaN) asymmetry:
    ``minimum_price_rule`` propagates NaN; ``price_correlation_rule`` maps
    NaN in either input to the sentinel (the UDF2 null guard,
    `PriceCorrelationDataQualityUdf.java:12-14`).
    """
    price = price_ref[:]
    guest = guest_ref[:]
    sentinel = jnp.asarray(BAD_ROW_SENTINEL, price.dtype)
    pnm = jnp.where(price < MIN_PRICE, sentinel, price)
    bad2 = jnp.logical_and(guest < CORRELATION_MAX_GUESTS,
                           price > CORRELATION_MAX_PRICE)
    null2 = jnp.logical_or(jnp.isnan(price), jnp.isnan(guest))
    pcc = jnp.where(jnp.logical_or(bad2, null2), sentinel, price)
    pnm_ref[:] = pnm
    pcc_ref[:] = pcc
    # NaN pnm (null price) > 0 is False — the row drops, same as the SQL
    # WHERE in the reference chain.
    keep_ref[:] = jnp.logical_and(pnm > 0.0, pcc > 0.0)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _dq_rules_call(price2d, guest2d, block_rows: int, interpret: bool):
    rows, lanes = price2d.shape
    grid = (pl.cdiv(rows, block_rows),)
    spec = pl.BlockSpec((block_rows, lanes), lambda i: (i, 0))
    return pl.pallas_call(
        _dq_kernel,
        grid=grid,
        in_specs=[spec, spec],
        out_specs=(spec, spec, spec),
        out_shape=(
            jax.ShapeDtypeStruct(price2d.shape, price2d.dtype),
            jax.ShapeDtypeStruct(price2d.shape, price2d.dtype),
            jax.ShapeDtypeStruct(price2d.shape, jnp.bool_),
        ),
        interpret=interpret,
    )(price2d, guest2d)


def dq_rules_pallas(price: jnp.ndarray, guest: jnp.ndarray,
                    block_rows: int = DQ_BLOCK_ROWS):
    """Fused DQ pipeline: ``(price_no_min, price_correct_correl, keep)``.

    Semantically identical to applying ``minimum_price_rule``, filtering
    ``> 0``, then ``price_correlation_rule`` and filtering ``> 0`` (the
    reference's four-stage chain): because filtering is mask-composition,
    the two WHERE stages commute into one conjunction. Golden row counts
    (SURVEY.md §2.3: 40→24 / 27→20 / 1040→1024) are the regression tests.
    """
    dt = price.dtype if jnp.issubdtype(price.dtype, jnp.floating) else jnp.float32
    p = price.astype(dt)
    g = guest.astype(dt)
    n = p.shape[0]
    lanes = 128
    pad = (-n) % lanes
    if pad:
        # Padded slots: price=sentinel keeps them out of the keep-mask.
        p = jnp.concatenate([p, jnp.full((pad,), BAD_ROW_SENTINEL, dt)])
        g = jnp.concatenate([g, jnp.zeros((pad,), dt)])
    rows = p.shape[0] // lanes
    block = min(block_rows, max(8, -(-rows // 8) * 8))
    row_pad = (-rows) % block
    if row_pad:
        p = jnp.concatenate([p, jnp.full((row_pad * lanes,), BAD_ROW_SENTINEL, dt)])
        g = jnp.concatenate([g, jnp.zeros((row_pad * lanes,), dt)])
        rows += row_pad
    pnm, pcc, keep = _dq_rules_call(p.reshape(rows, lanes),
                                    g.reshape(rows, lanes), block, _interpret())
    return (pnm.reshape(-1)[:n], pcc.reshape(-1)[:n], keep.reshape(-1)[:n])
