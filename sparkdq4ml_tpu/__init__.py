"""sparkdq4ml_tpu: TPU-native framework with the capabilities of
net.jgp.labs.sparkdq4ml (see SURVEY.md). Columnar frame engine + DQ rule/UDF
layer + SQL subset + MLlib-convention estimators, distributed via
jax.sharding meshes and XLA collectives."""

from .config import config
from .frame import Frame, list_column, read_csv
from .ops import (col, lit, call_udf, callUDF, register_udf,
                  minimum_price_rule, price_correlation_rule,
                  register_builtin_rules)
from .session import TpuSession


def __getattr__(name):
    # Lazy serving-layer exports: importing the package must not pull in
    # the server machinery (pay-for-use contract; README § "Serving").
    if name in ("QueryServer", "TenantQuota", "QueryResult"):
        from . import serve as _serve

        return getattr(_serve, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__version__ = "0.1.0"
