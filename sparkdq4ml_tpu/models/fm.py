"""Factorization machines (MLlib ``org.apache.spark.ml.regression.FMRegressor``
/ ``classification.FMClassifier`` — shipped by the reference's mllib
dependency, pom.xml:29-32).

Model: ``ŷ(x) = b + xᵀw + ½ Σ_f [(xᵀV_f)² − (x²)ᵀ(V_f²)]`` — the rank-k
pairwise-interaction term is two MXU matmuls (the classic O(nk d) FM
identity), so the forward pass over all rows is three matmuls total.

TPU-first: loss + gradient via ``jax.value_and_grad`` over the batched
forward (squared loss for the regressor, logistic for the classifier),
optimized by a full-batch Adam ``lax.scan`` — one jitted program, zero
host round-trips; under a mesh the per-row loss reductions are psum'd
(MLlib instead runs minibatch gradient descent over RDD partitions).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype
from ..frame.frame import Frame
from .base import Estimator, Model, persistable
from ..parallel.mesh import serialize_collectives


class FmFit(NamedTuple):
    intercept: jnp.ndarray
    linear: jnp.ndarray       # (d,)
    factors: jnp.ndarray      # (d, k)
    loss_history: jnp.ndarray


def fm_forward(X, b, w, V):
    """Batched FM score: three matmuls (the O(nkd) identity)."""
    s = X @ V                                     # (n, k)
    s2 = (X * X) @ (V * V)                        # (n, k)
    return b + X @ w + 0.5 * jnp.sum(s * s - s2, axis=1)


def _fm_core(X, y, mask, n, *, factor_size, loss, reg_param, max_iter, lr,
             init_std, seed, fit_intercept, fit_linear, axis=None):
    dt = X.dtype
    d = X.shape[1]
    wm = mask.astype(dt)
    Xm = X * wm[:, None]
    ym = y * wm

    # shard count: replicated objective terms are pre-divided by it so the
    # psum in psum_value_and_grad restores them exactly once
    nshards = (jax.lax.psum(jnp.asarray(1.0, dt), axis)
               if axis is not None else jnp.asarray(1.0, dt))

    def objective(params):
        # LOCAL share of the loss: psum_value_and_grad sums value+grad
        # over the mesh (grad through a psum is unreliable on legacy
        # shard_map; see solvers.psum_value_and_grad)
        b, w, V = params
        pred = fm_forward(Xm, b, w, V)
        if loss == "squared":
            per_row = (pred - ym) ** 2
        else:   # logistic: labels 0/1, stable softplus form
            z = (2.0 * ym - wm) * pred
            per_row = jnp.logaddexp(0.0, -z)
        data_loss = jnp.sum(jnp.where(mask, per_row, 0.0)) / n
        # L2 on every parameter group (MLlib's regParam)
        return data_loss + reg_param * (
            jnp.sum(w * w) + jnp.sum(V * V) + b * b) / nshards

    from .solvers import adam_scan, psum_value_and_grad

    key = jax.random.PRNGKey(seed)
    V0 = init_std * jax.random.normal(key, (d, factor_size), dt)
    params0 = (jnp.asarray(0.0, dt), jnp.zeros((d,), dt), V0)

    def grad_mask(g):
        if not fit_intercept:
            g = (jnp.zeros_like(g[0]),) + g[1:]
        if not fit_linear:
            g = (g[0], jnp.zeros_like(g[1]), g[2])
        return g

    (b, w, V), history = adam_scan(psum_value_and_grad(objective, axis),
                                   params0, max_iter, lr,
                                   grad_mask=grad_mask)
    return FmFit(b, w, V, history)


@functools.lru_cache(maxsize=None)
def _fm_fit_fn(mesh, factor_size, loss, reg_param, max_iter, lr, init_std,
               seed, fit_intercept, fit_linear):
    def run(X, y, mask, axis=None):
        wm = mask.astype(X.dtype)
        n = jnp.sum(wm)
        if axis is not None:
            n = jax.lax.psum(n, axis)
        return _fm_core(X, y, mask, n, factor_size=factor_size, loss=loss,
                        reg_param=reg_param, max_iter=max_iter, lr=lr,
                        init_std=init_std, seed=seed,
                        fit_intercept=fit_intercept, fit_linear=fit_linear,
                        axis=axis)

    if mesh is None:
        return jax.jit(lambda X, y, m: run(X, y, m))

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    return serialize_collectives(jax.jit(shard_map(
        lambda X, y, m: run(X, y, m, DATA_AXIS), mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P())), mesh)


class _FMBase(Estimator):
    _persist_attrs = ('factor_size', 'reg_param', 'max_iter', 'step_size',
                      'init_std', 'fit_intercept', 'fit_linear', 'seed',
                      'features_col', 'label_col', 'prediction_col')

    def __init__(self, factor_size: int = 8, reg_param: float = 0.0,
                 max_iter: int = 100, step_size: float = 0.05,
                 init_std: float = 0.01, fit_intercept: bool = True,
                 fit_linear: bool = True, seed: int = 0,
                 features_col: str = "features", label_col: str = "label",
                 prediction_col: str = "prediction"):
        if factor_size < 1:
            raise ValueError("factor_size must be >= 1")
        self.factor_size = int(factor_size)
        self.reg_param = float(reg_param)
        self.max_iter = int(max_iter)
        self.step_size = float(step_size)
        self.init_std = float(init_std)
        self.fit_intercept = bool(fit_intercept)
        self.fit_linear = bool(fit_linear)
        self.seed = int(seed)
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col

    def set_factor_size(self, v):
        if v < 1:
            raise ValueError("factor_size must be >= 1")
        self.factor_size = int(v)
        return self

    def set_reg_param(self, v):
        self.reg_param = float(v)
        return self

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    def set_step_size(self, v):
        self.step_size = float(v)
        return self

    def set_init_std(self, v):
        self.init_std = float(v)
        return self

    def set_fit_intercept(self, v):
        self.fit_intercept = bool(v)
        return self

    def set_fit_linear(self, v):
        self.fit_linear = bool(v)
        return self

    def set_seed(self, v):
        self.seed = int(v)
        return self

    def set_features_col(self, v):
        self.features_col = v
        return self

    def set_label_col(self, v):
        self.label_col = v
        return self

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self

    setFactorSize = set_factor_size
    setRegParam = set_reg_param
    setMaxIter = set_max_iter
    setStepSize = set_step_size
    setInitStd = set_init_std
    setFitIntercept = set_fit_intercept
    setFitLinear = set_fit_linear
    setSeed = set_seed
    setFeaturesCol = set_features_col
    setLabelCol = set_label_col
    setPredictionCol = set_prediction_col

    _loss = "squared"

    def _fit_arrays(self, frame, mesh):
        from ..parallel.distributed import pad_and_shard_rows
        from ..parallel.mesh import normalize_mesh

        mesh = normalize_mesh(mesh)
        dt = np.dtype(float_dtype())
        X = np.asarray(frame._column_values(self.features_col), dt)
        if X.ndim == 1:
            X = X[:, None]
        y = np.asarray(frame._column_values(self.label_col), np.float64)
        mask = np.asarray(frame.mask)
        if mask.sum() == 0:
            raise ValueError(f"{type(self).__name__}: no valid rows")
        if not np.all(np.isfinite(X[mask])):
            raise ValueError("feature matrix has NaN/inf in valid rows")
        if not np.all(np.isfinite(y[mask])):
            raise ValueError("label column has NaN/inf in valid rows")
        self._validate_labels(y[mask])
        Xh = np.where(mask[:, None], X, 0.0)
        yh = np.where(mask, y, 0.0)
        Xd, yd, md = pad_and_shard_rows(mesh, Xh.astype(dt),
                                        yh.astype(dt), mask)
        fit_fn = _fm_fit_fn(mesh, self.factor_size, self._loss,
                            self.reg_param, self.max_iter, self.step_size,
                            self.init_std, self.seed, self.fit_intercept,
                            self.fit_linear)
        r = jax.block_until_ready(fit_fn(Xd, yd, md))
        return (float(r.intercept), np.asarray(r.linear, np.float64),
                np.asarray(r.factors, np.float64),
                np.asarray(r.loss_history, np.float64).tolist())

    def _validate_labels(self, yv):
        pass

    def _params_dict(self):
        return {k: getattr(self, k) for k in self._persist_attrs}


@persistable
class FMRegressor(_FMBase):
    """MLlib ``FMRegressor``: squared loss."""

    def fit(self, frame: Frame, mesh=None) -> "FMRegressionModel":
        b, w, V, hist = self._fit_arrays(frame, mesh)
        return FMRegressionModel(b, w, V, self._params_dict(), hist)


@persistable
class FMClassifier(_FMBase):
    """MLlib ``FMClassifier``: binary 0/1 labels, logistic loss."""

    _loss = "logistic"
    _persist_attrs = _FMBase._persist_attrs + ('probability_col',
                                               'raw_prediction_col')

    def __init__(self, probability_col: str = "probability",
                 raw_prediction_col: str = "rawPrediction", **kw):
        super().__init__(**kw)
        self.probability_col = probability_col
        self.raw_prediction_col = raw_prediction_col

    def _validate_labels(self, yv):
        if not np.all((yv == 0) | (yv == 1)):
            raise ValueError("FMClassifier requires binary 0/1 labels")

    def fit(self, frame: Frame, mesh=None) -> "FMClassificationModel":
        b, w, V, hist = self._fit_arrays(frame, mesh)
        return FMClassificationModel(b, w, V, self._params_dict(), hist)


class _FMModelBase(Model):
    _persist_attrs = ('intercept', 'linear', 'factors', '_params',
                      'loss_history')

    def __init__(self, intercept, linear, factors, params=None,
                 loss_history=None):
        self.intercept = float(intercept)
        self.linear = np.asarray(linear, np.float64)
        self.factors = np.asarray(factors, np.float64)
        self._params = dict(params or {})
        self.loss_history = list(loss_history or [])

    def _p(self, k, default=None):
        return self._params.get(k, default)

    @property
    def factor_size(self):
        return int(self.factors.shape[1])

    factorSize = factor_size

    def _score(self, X):
        Xd = jnp.asarray(X, float_dtype())
        if Xd.ndim == 1:
            Xd = Xd[:, None]
        return fm_forward(Xd, jnp.asarray(self.intercept, Xd.dtype),
                          jnp.asarray(self.linear, Xd.dtype),
                          jnp.asarray(self.factors, Xd.dtype))


@persistable
class FMRegressionModel(_FMModelBase):
    def transform(self, frame: Frame) -> Frame:
        pred = self._score(frame._column_values(
            self._p("features_col", "features")))
        return frame.with_column(self._p("prediction_col", "prediction"),
                                 pred)

    def predict(self, features) -> float:
        x = np.asarray(features, np.float64).reshape(1, -1)
        return float(np.asarray(self._score(x))[0])


@persistable
class FMClassificationModel(_FMModelBase):
    def transform(self, frame: Frame) -> Frame:
        p = self._params
        F = self._score(frame._column_values(
            p.get("features_col", "features")))
        prob1 = jax.nn.sigmoid(F)
        out = frame.with_column(p.get("raw_prediction_col", "rawPrediction"),
                                jnp.stack([-F, F], axis=1))
        out = out.with_column(p.get("probability_col", "probability"),
                              jnp.stack([1.0 - prob1, prob1], axis=1))
        return out.with_column(p.get("prediction_col", "prediction"),
                               (F > 0).astype(float_dtype()))

    def predict(self, features) -> float:
        x = np.asarray(features, np.float64).reshape(1, -1)
        return float(np.asarray(self._score(x))[0] > 0)
