"""Evaluators — the MLlib ``ml.evaluation`` surface CrossValidator needs
(BASELINE.json config: "CrossValidator grid (regParam × elasticNetParam)")."""

from __future__ import annotations

import numpy as np

from ..frame.frame import Frame


def _host_pair(labels, scores):
    """Device inputs pull to host in ONE batched, COUNTED transfer
    (``frame.host_sync``); numpy inputs pass through free. The curve
    helpers are public library surface — a caller handing them device
    arrays used to trigger an implicit, uncounted device→host transfer
    per numpy op, invisible to the sync audits the fused paths pin."""
    if not isinstance(labels, np.ndarray) or not isinstance(scores,
                                                            np.ndarray):
        import jax

        from ..utils.profiling import counters

        if any(hasattr(x, "devices") for x in (labels, scores)):
            counters.increment("frame.host_sync")
        labels, scores = jax.device_get((labels, scores))
    return np.asarray(labels), np.asarray(scores)


def threshold_sweep(labels: np.ndarray, scores: np.ndarray):
    """Cumulative (thresholds desc, tp, fp) at each DISTINCT score —
    the single O(n log n) sweep behind every ROC/PR curve and
    by-threshold metric (at threshold t, every row scoring ≥ t is
    predicted positive, so the last index of each tied run counts)."""
    labels, scores = _host_pair(labels, scores)
    order = np.argsort(-scores, kind="mergesort")
    y = (labels[order] == 1.0).astype(np.float64)
    s = scores[order]
    tp = np.cumsum(y)
    fp = np.cumsum(1.0 - y)
    boundary = np.r_[s[1:] != s[:-1], True]
    return s[boundary], tp[boundary], fp[boundary]


def pr_points(labels: np.ndarray, scores: np.ndarray):
    """(thresholds desc, precision, recall) at each distinct score."""
    labels, scores = _host_pair(labels, scores)
    thr, tp, fp = threshold_sweep(labels, scores)
    npos = max(float((labels == 1.0).sum()), 1.0)
    precision = tp / np.maximum(tp + fp, 1.0)
    recall = tp / npos
    return thr, precision, recall


def roc_points(labels: np.ndarray, scores: np.ndarray):
    """(FPR, TPR) arrays over descending score thresholds, O(n log n).

    Shared by the evaluators and the classifier summaries."""
    _, tps, fps = threshold_sweep(labels, scores)
    npos = max(tps[-1], 1.0) if len(tps) else 1.0
    nneg = max(fps[-1], 1.0) if len(fps) else 1.0
    tpr = np.r_[0.0, tps / npos]
    fpr = np.r_[0.0, fps / nneg]
    return fpr, tpr


def area_under_roc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Exact AUC (rank statistic with tie handling) via the trapezoid over
    the ROC boundary points — O(n log n)."""
    labels, scores = _host_pair(labels, scores)
    pos = labels == 1.0
    if pos.sum() == 0 or (~pos).sum() == 0:
        return float("nan")
    fpr, tpr = roc_points(labels, scores)
    return float(np.trapezoid(tpr, fpr))


def area_under_pr(labels: np.ndarray, scores: np.ndarray) -> float:
    """Precision-recall AUC over threshold boundaries, O(n log n)."""
    labels, scores = _host_pair(labels, scores)
    pos = labels == 1.0
    if pos.sum() == 0 or (~pos).sum() == 0:
        return float("nan")
    _, precision, recall = pr_points(labels, scores)
    return float(np.trapezoid(np.r_[1.0, precision], np.r_[0.0, recall]))


class Evaluator:
    def evaluate(self, frame: Frame) -> float:
        raise NotImplementedError

    def is_larger_better(self) -> bool:
        return True

    isLargerBetter = is_larger_better


class RegressionEvaluator(Evaluator):
    """Metrics: rmse (default), mse, mae, r2."""

    def __init__(self, metric_name: str = "rmse", label_col: str = "label",
                 prediction_col: str = "prediction"):
        if metric_name not in ("rmse", "mse", "mae", "r2", "var"):
            raise ValueError(f"unknown metric {metric_name!r}")
        self.metric_name = metric_name
        self.label_col = label_col
        self.prediction_col = prediction_col

    def set_metric_name(self, v: str):
        self.metric_name = v
        return self

    setMetricName = set_metric_name

    def is_larger_better(self) -> bool:
        return self.metric_name in ("r2", "var")

    isLargerBetter = is_larger_better

    def evaluate(self, frame: Frame) -> float:
        d = frame.to_pydict()
        y = d[self.label_col].astype(np.float64)
        p = d[self.prediction_col].astype(np.float64)
        return self.compute(y, p)

    def compute(self, y: np.ndarray, p: np.ndarray) -> float:
        if self.metric_name == "rmse":
            return float(np.sqrt(np.mean((y - p) ** 2)))
        if self.metric_name == "mse":
            return float(np.mean((y - p) ** 2))
        if self.metric_name == "mae":
            return float(np.mean(np.abs(y - p)))
        if self.metric_name == "var":
            # Spark RegressionMetrics.explainedVariance:
            # mean((p_i - mean(y))^2)
            return float(np.mean((p - y.mean()) ** 2))
        ss_res = float(np.sum((y - p) ** 2))
        ss_tot = float(np.sum((y - y.mean()) ** 2))
        return float("nan") if ss_tot == 0 else 1.0 - ss_res / ss_tot


class BinaryClassificationEvaluator(Evaluator):
    """Metrics: areaUnderROC (default), areaUnderPR. Reads the probability
    column when present (falls back to rawPrediction)."""

    def __init__(self, metric_name: str = "areaUnderROC",
                 label_col: str = "label",
                 raw_prediction_col: str = "rawPrediction"):
        if metric_name not in ("areaUnderROC", "areaUnderPR"):
            raise ValueError(f"unknown metric {metric_name!r}")
        self.metric_name = metric_name
        self.label_col = label_col
        self.raw_prediction_col = raw_prediction_col

    def set_metric_name(self, v: str):
        self.metric_name = v
        return self

    setMetricName = set_metric_name

    def evaluate(self, frame: Frame) -> float:
        d = frame.to_pydict()
        y = d[self.label_col].astype(np.float64)
        score_col = self.raw_prediction_col
        if score_col not in d and "probability" in d:
            score_col = "probability"
        s = d[score_col].astype(np.float64)
        return self.compute(y, s)

    def compute(self, y: np.ndarray, s: np.ndarray) -> float:
        if self.metric_name == "areaUnderROC":
            return area_under_roc(y, s)
        return area_under_pr(y, s)


class MulticlassClassificationEvaluator(Evaluator):
    """MLlib metrics: ``f1`` (the Spark default), ``accuracy``,
    ``weightedPrecision``, ``weightedRecall`` — per-class one-vs-rest
    scores weighted by true-class frequency."""

    _METRICS = ("f1", "accuracy", "weightedPrecision", "weightedRecall",
                "hammingLoss")

    def __init__(self, metric_name: str = "f1", label_col: str = "label",
                 prediction_col: str = "prediction"):
        if metric_name not in self._METRICS:
            raise ValueError(f"unknown metric {metric_name!r} "
                             f"(supported: {self._METRICS})")
        self.metric_name = metric_name
        self.label_col = label_col
        self.prediction_col = prediction_col

    def is_larger_better(self) -> bool:
        return self.metric_name != "hammingLoss"

    isLargerBetter = is_larger_better

    def evaluate(self, frame: Frame) -> float:
        d = frame.to_pydict()
        y = d[self.label_col].astype(np.float64)
        p = d[self.prediction_col].astype(np.float64)
        if self.metric_name == "accuracy":
            return float(np.mean(y == p))
        if self.metric_name == "hammingLoss":
            return float(np.mean(y != p))
        classes = np.unique(y)
        scores, weights = [], []
        for c in classes:
            tp = float(((p == c) & (y == c)).sum())
            fp = float(((p == c) & (y != c)).sum())
            fn = float(((p != c) & (y == c)).sum())
            prec = tp / max(tp + fp, 1.0)
            rec = tp / max(tp + fn, 1.0)
            if self.metric_name == "weightedPrecision":
                scores.append(prec)
            elif self.metric_name == "weightedRecall":
                scores.append(rec)
            else:
                scores.append(0.0 if prec + rec == 0
                              else 2 * prec * rec / (prec + rec))
            weights.append((y == c).mean())
        return float(np.average(scores, weights=weights))


class ClusteringEvaluator(Evaluator):
    """MLlib ``ClusteringEvaluator``: mean silhouette coefficient with
    squared-Euclidean distance (Spark's default and only 2.4-era metric).

    Device path: per-cluster means and squared norms make the per-point
    cluster distances one (n, k) matmul — the same ‖x−c‖² expansion the
    KMeans fit uses — instead of the naive O(n²) pairwise matrix, which is
    exactly Spark's optimization for this metric."""

    def __init__(self, features_col: str = "features",
                 prediction_col: str = "prediction",
                 metric_name: str = "silhouette"):
        if metric_name != "silhouette":
            raise ValueError(f"unknown metric {metric_name!r}")
        self.features_col = features_col
        self.prediction_col = prediction_col
        self.metric_name = metric_name

    def set_features_col(self, v):
        self.features_col = v
        return self

    setFeaturesCol = set_features_col

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self

    setPredictionCol = set_prediction_col

    def evaluate(self, frame: Frame) -> float:
        d = frame.to_pydict()
        X = np.asarray(d[self.features_col], np.float64)
        if X.ndim == 1:
            X = X[:, None]
        labels = np.asarray(d[self.prediction_col], np.float64).astype(int)
        uniq = np.unique(labels)
        k = len(uniq)
        if k < 2:
            return float("nan")
        remap = {c: i for i, c in enumerate(uniq)}
        lab = np.asarray([remap[c] for c in labels])
        n = len(lab)
        counts = np.bincount(lab, minlength=k).astype(np.float64)
        onehot = np.zeros((n, k))
        onehot[np.arange(n), lab] = 1.0
        sums = onehot.T @ X                              # (k, d)
        means = sums / counts[:, None]
        sq_sums = onehot.T @ np.sum(X * X, axis=1)       # (k,)
        # mean squared distance from point i to all of cluster c:
        #   E_c‖x_i − y‖² = ‖x_i‖² − 2·x_i·mean_c + E_c‖y‖²
        x_sq = np.sum(X * X, axis=1, keepdims=True)
        msd = x_sq - 2.0 * (X @ means.T) + (sq_sums / counts)[None, :]
        own = lab
        # a(i): mean distance to own cluster EXCLUDING self
        c_own = counts[own]
        a = np.where(c_own > 1,
                     (msd[np.arange(n), own] * c_own) / np.maximum(c_own - 1,
                                                                   1),
                     0.0)
        msd[np.arange(n), own] = np.inf
        b = msd.min(axis=1)                              # nearest other cluster
        s = np.where(c_own > 1,
                     (b - a) / np.maximum(np.maximum(a, b), 1e-300), 0.0)
        return float(s.mean())
