"""Feature-layer transformers.

``VectorAssembler`` packs input columns into one ``(n, d)`` feature-matrix
column (`DataQuality4MachineLearningApp.java:110-113`). TPU-first: the
"vector column" is literally the feature matrix in HBM, laid out densely so
the fit's Gramian is a single MXU matmul — there is no per-row vector object.

``StandardScaler`` / ``MinMaxScaler`` / ``MaxAbsScaler`` are the adjacent
MLlib feature estimators (same ``spark.ml.feature`` package the reference's
VectorAssembler comes from, pom.xml:29-32 mllib dependency). Statistics are
mask-weighted one-pass device reductions — filtered rows never leak into the
moments (SURVEY.md §7 "Masked-filter semantics") — and MLlib conventions are
kept: StandardScaler uses the *sample* (n−1) std, defaults
``with_mean=False, with_std=True``, and maps zero-variance features to 0;
MinMaxScaler maps constant features to ``(min+max)/2``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype, int_dtype
from .base import Estimator, Model, Transformer, persistable


@persistable
class VectorAssembler(Transformer):
    _persist_attrs = ('input_cols', 'output_col')
    def __init__(self, input_cols: Optional[Sequence[str]] = None,
                 output_col: str = "features"):
        self.input_cols = list(input_cols) if input_cols else []
        self.output_col = output_col

    def set_input_cols(self, cols: Sequence[str]) -> "VectorAssembler":
        self.input_cols = list(cols)
        return self

    setInputCols = set_input_cols

    def set_output_col(self, name: str) -> "VectorAssembler":
        self.output_col = name
        return self

    setOutputCol = set_output_col

    def get_input_cols(self):
        return list(self.input_cols)

    getInputCols = get_input_cols

    def get_output_col(self):
        return self.output_col

    getOutputCol = get_output_col

    def transform(self, frame):
        if not self.input_cols:
            raise ValueError("VectorAssembler: input_cols not set")
        dt = float_dtype()
        parts = []
        for name in self.input_cols:
            arr = jnp.asarray(frame._column_values(name), dt)
            parts.append(arr[:, None] if arr.ndim == 1 else arr)
        return frame.with_column(self.output_col, jnp.concatenate(parts, axis=1))


@persistable
class StringIndexer(Estimator):
    """MLlib ``StringIndexer``: map string categories to double indices,
    most-frequent-first (``frequencyDesc``; ties broken alphabetically, as
    Spark does). ``handle_invalid``: ``"error"`` (default) | ``"keep"``
    (unseen → numLabels) | ``"skip"`` (unseen → masked out on transform).

    The index *fit* is host-side (categories are host strings); the
    transformed column is a device array ready for VectorAssembler.
    """

    _persist_attrs = ('input_col', 'output_col', 'handle_invalid')

    def __init__(self, input_col: str = None, output_col: str = None,
                 handle_invalid: str = "error"):
        self.input_col = input_col
        self.output_col = output_col
        if handle_invalid not in ("error", "keep", "skip"):
            raise ValueError(f"handle_invalid={handle_invalid!r}")
        self.handle_invalid = handle_invalid

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def set_handle_invalid(self, v):
        self.handle_invalid = v
        return self

    setHandleInvalid = set_handle_invalid

    def fit(self, frame) -> "StringIndexerModel":
        col = frame._column_values(self.input_col)
        mask = np.asarray(frame.mask)
        values = [str(v) for v, m in zip(np.asarray(col, object), mask)
                  if m and v is not None]
        from collections import Counter

        counts = Counter(values)
        labels = sorted(counts, key=lambda k: (-counts[k], k))
        return StringIndexerModel(labels, self.input_col, self.output_col,
                                  self.handle_invalid)


@persistable
class StringIndexerModel(Model):
    _persist_attrs = ('labels', 'input_col', 'output_col', 'handle_invalid')

    def __init__(self, labels, input_col, output_col, handle_invalid="error"):
        self.labels = list(labels)
        self.input_col = input_col
        self.output_col = output_col
        self.handle_invalid = handle_invalid
        self._index = {l: i for i, l in enumerate(self.labels)}

    def _post_load(self):
        self.labels = list(self.labels)
        self._index = {l: i for i, l in enumerate(self.labels)}

    labelsArray = property(lambda self: [list(self.labels)])

    def transform(self, frame):
        col = np.asarray(frame._column_values(self.input_col), object)
        n_labels = len(self.labels)
        idx = np.empty(len(col), dtype=np.dtype(float_dtype()))
        invalid = np.zeros(len(col), bool)
        host_mask = np.asarray(frame.mask)
        for i, v in enumerate(col):
            j = self._index.get(str(v)) if v is not None else None
            if j is None:
                invalid[i] = True
                idx[i] = n_labels
            else:
                idx[i] = j
        if self.handle_invalid == "error" and bool((invalid & host_mask).any()):
            bad = sorted({str(col[i]) for i in np.nonzero(invalid & host_mask)[0]})
            raise ValueError(f"StringIndexer: unseen labels {bad}; set "
                             f"handle_invalid='keep' or 'skip'")
        out = frame.with_column(self.output_col, jnp.asarray(idx))
        if self.handle_invalid == "skip":
            out = out.filter(jnp.asarray(~invalid))
        return out


@persistable
class IndexToString(Transformer):
    """Inverse of StringIndexer: indices → label strings (host column)."""

    _persist_attrs = ('input_col', 'output_col', 'labels')

    def __init__(self, input_col: str = None, output_col: str = None,
                 labels=None):
        self.input_col = input_col
        self.output_col = output_col
        self.labels = list(labels) if labels is not None else None

    def transform(self, frame):
        idx = np.asarray(frame._column_values(self.input_col))
        labels = self.labels
        out = np.asarray([labels[int(i)] if 0 <= int(i) < len(labels) else None
                          for i in idx], dtype=object)
        return frame.with_column(self.output_col, out)


@persistable
class OneHotEncoder(Estimator):
    """MLlib ``OneHotEncoder``: index column → one-hot vector column.

    ``drop_last=True`` (Spark default) omits the last category so the
    encoding stays linearly independent with an intercept. The encode is a
    device comparison against an iota — one fused op, no host loop.
    """

    _persist_attrs = ('input_col', 'output_col', 'drop_last')

    def __init__(self, input_col: str = None, output_col: str = None,
                 drop_last: bool = True):
        self.input_col = input_col
        self.output_col = output_col
        self.drop_last = drop_last

    def set_drop_last(self, v: bool):
        self.drop_last = v
        return self

    setDropLast = set_drop_last

    def fit(self, frame) -> "OneHotEncoderModel":
        idx = frame._column_values(self.input_col)
        w = frame.mask
        size = int(np.asarray(jnp.max(jnp.where(w, jnp.asarray(idx), -1)))) + 1
        return OneHotEncoderModel(size, self.input_col, self.output_col,
                                  self.drop_last)


@persistable
class OneHotEncoderModel(Model):
    _persist_attrs = ('category_size', 'input_col', 'output_col', 'drop_last')
    def __init__(self, category_size, input_col, output_col, drop_last=True):
        self.category_size = int(category_size)
        self.input_col = input_col
        self.output_col = output_col
        self.drop_last = drop_last

    categorySizes = property(lambda self: [self.category_size])

    def transform(self, frame):
        idx = jnp.asarray(frame._column_values(self.input_col), int_dtype())
        width = self.category_size - (1 if self.drop_last else 0)
        eye = jnp.arange(width, dtype=int_dtype())
        onehot = (idx[:, None] == eye[None, :]).astype(float_dtype())
        return frame.with_column(self.output_col, onehot)


@persistable
class Bucketizer(Transformer):
    """MLlib ``Bucketizer``: continuous column → bucket index by split
    points (``splits`` of length b+1, monotonic; use ±inf for open ends).
    One device ``searchsorted``; values outside the splits raise unless
    ``handle_invalid='keep'`` (→ NaN) or ``'skip'`` (→ masked)."""

    _persist_attrs = ('splits', 'input_col', 'output_col', 'handle_invalid')

    def __init__(self, splits=None, input_col: str = None,
                 output_col: str = None, handle_invalid: str = "error"):
        self.splits = list(splits) if splits is not None else None
        self.input_col = input_col
        self.output_col = output_col
        self.handle_invalid = handle_invalid

    def set_splits(self, v):
        self.splits = list(v)
        return self

    setSplits = set_splits

    def transform(self, frame):
        s = np.asarray(self.splits, np.dtype(float_dtype()))
        if s.ndim != 1 or len(s) < 3 or not np.all(np.diff(s) > 0):
            raise ValueError("splits must be >=3 strictly increasing values")
        x = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        # right-closed last bucket, Spark semantics: x == splits[-1] falls in
        # the last bucket; outside [splits[0], splits[-1]] is invalid.
        idx = jnp.clip(jnp.searchsorted(jnp.asarray(s), x, side="right") - 1,
                       0, len(s) - 2).astype(float_dtype())
        # NaN is invalid too (it compares false to both bounds, and Spark
        # routes it through handleInvalid rather than into a bucket)
        invalid = jnp.logical_or(jnp.logical_or(x < s[0], x > s[-1]),
                                 jnp.isnan(x))
        if self.handle_invalid == "error":
            if bool(np.asarray(jnp.logical_and(invalid, frame.mask)).any()):
                raise ValueError("Bucketizer: values outside splits; set "
                                 "handle_invalid='keep' or 'skip'")
        elif self.handle_invalid == "keep":
            # Spark's 'keep': invalid values land in a special extra bucket
            # with index numBuckets (= len(splits) - 1)
            idx = jnp.where(invalid,
                            jnp.asarray(float(len(s) - 1), float_dtype()),
                            idx)
        out = frame.with_column(self.output_col, idx)
        if self.handle_invalid == "skip":
            out = out.filter(jnp.logical_not(invalid))
        return out


class _ScalerBase(Estimator):
    """Shared input/output-col builder surface for the feature scalers."""

    def __init__(self, input_col: str = "features",
                 output_col: str = "scaled_features"):
        self.input_col = input_col
        self.output_col = output_col

    def set_input_col(self, name: str):
        self.input_col = name
        return self

    setInputCol = set_input_col

    def set_output_col(self, name: str):
        self.output_col = name
        return self

    setOutputCol = set_output_col

    def _masked_feature_matrix(self, frame):
        """(n, d) feature matrix + (n,) mask weights on device."""
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        w = frame.mask.astype(X.dtype)
        return X, w


@jax.jit
def _masked_moments(X, w):
    """Mask-weighted count, mean, and sample variance — one fused pass."""
    n = jnp.sum(w)
    wc = w[:, None]
    mean = jnp.sum(X * wc, axis=0) / n
    centered = (X - mean) * wc
    var = jnp.sum(centered * centered, axis=0) / jnp.maximum(n - 1.0, 1.0)
    return n, mean, var


@jax.jit
def _masked_min_max(X, w):
    big = jnp.asarray(jnp.finfo(X.dtype).max, X.dtype)
    wc = w[:, None] > 0
    lo = jnp.min(jnp.where(wc, X, big), axis=0)
    hi = jnp.max(jnp.where(wc, X, -big), axis=0)
    return lo, hi


@persistable
class StandardScaler(_ScalerBase):
    """MLlib ``StandardScaler``: defaults ``with_mean=False, with_std=True``;
    sample (n−1) std; zero-variance features scale to 0.0."""

    _persist_attrs = ('input_col', 'output_col', 'with_mean', 'with_std')

    def __init__(self, input_col: str = "features",
                 output_col: str = "scaled_features",
                 with_mean: bool = False, with_std: bool = True):
        super().__init__(input_col, output_col)
        self.with_mean = with_mean
        self.with_std = with_std

    def set_with_mean(self, v: bool):
        self.with_mean = v
        return self

    setWithMean = set_with_mean

    def set_with_std(self, v: bool):
        self.with_std = v
        return self

    setWithStd = set_with_std

    def fit(self, frame) -> "StandardScalerModel":
        X, w = self._masked_feature_matrix(frame)
        _, mean, var = _masked_moments(X, w)
        return StandardScalerModel(np.asarray(mean), np.asarray(jnp.sqrt(var)),
                                   self.with_mean, self.with_std,
                                   self.input_col, self.output_col)


@persistable
class StandardScalerModel(Model):
    _persist_attrs = ('mean', 'std', 'with_mean', 'with_std', 'input_col', 'output_col')
    def __init__(self, mean, std, with_mean, with_std, input_col, output_col):
        self.mean = np.asarray(mean)
        self.std = np.asarray(std)
        self.with_mean = with_mean
        self.with_std = with_std
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        if self.with_mean:
            X = X - jnp.asarray(self.mean, X.dtype)
        if self.with_std:
            # MLlib: features with std == 0 map to 0.0 (scale factor 0).
            inv = np.where(self.std > 0, 1.0 / np.where(self.std > 0,
                                                        self.std, 1.0), 0.0)
            X = X * jnp.asarray(inv, X.dtype)
        return frame.with_column(self.output_col,
                                 X[:, 0] if squeeze else X)


@persistable
class MinMaxScaler(_ScalerBase):
    """MLlib ``MinMaxScaler``: rescale to [min, max] per feature; constant
    features map to ``(min+max)/2``."""

    _persist_attrs = ('input_col', 'output_col', 'min', 'max')

    def __init__(self, input_col: str = "features",
                 output_col: str = "scaled_features",
                 min: float = 0.0, max: float = 1.0):
        super().__init__(input_col, output_col)
        self.min = float(min)
        self.max = float(max)

    def set_min(self, v: float):
        self.min = float(v)
        return self

    setMin = set_min

    def set_max(self, v: float):
        self.max = float(v)
        return self

    setMax = set_max

    def fit(self, frame) -> "MinMaxScalerModel":
        X, w = self._masked_feature_matrix(frame)
        lo, hi = _masked_min_max(X, w)
        return MinMaxScalerModel(np.asarray(lo), np.asarray(hi),
                                 self.min, self.max,
                                 self.input_col, self.output_col)


@persistable
class MinMaxScalerModel(Model):
    _persist_attrs = ('original_min', 'original_max', 'min', 'max', 'input_col', 'output_col')
    def __init__(self, original_min, original_max, min, max,
                 input_col, output_col):
        self.original_min = np.asarray(original_min)
        self.original_max = np.asarray(original_max)
        self.min = min
        self.max = max
        self.input_col = input_col
        self.output_col = output_col

    originalMin = property(lambda self: self.original_min)
    originalMax = property(lambda self: self.original_max)

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        rng = self.original_max - self.original_min
        constant = rng == 0
        inv = np.where(constant, 0.0, 1.0 / np.where(constant, 1.0, rng))
        scaled = (X - jnp.asarray(self.original_min, X.dtype)) \
            * jnp.asarray(inv, X.dtype) * (self.max - self.min) + self.min
        half = 0.5 * (self.max + self.min)
        scaled = jnp.where(jnp.asarray(constant), jnp.asarray(half, X.dtype),
                           scaled)
        return frame.with_column(self.output_col,
                                 scaled[:, 0] if squeeze else scaled)


@persistable
class MaxAbsScaler(_ScalerBase):
    """MLlib ``MaxAbsScaler``: divide by per-feature max |x| (sparsity
    preserving); all-zero features stay 0."""

    _persist_attrs = ('input_col', 'output_col')

    def fit(self, frame) -> "MaxAbsScalerModel":
        X, w = self._masked_feature_matrix(frame)
        lo, hi = _masked_min_max(X, w)
        max_abs = np.maximum(np.abs(np.asarray(lo)), np.abs(np.asarray(hi)))
        return MaxAbsScalerModel(max_abs, self.input_col, self.output_col)


@persistable
class MaxAbsScalerModel(Model):
    _persist_attrs = ('max_abs', 'input_col', 'output_col')
    def __init__(self, max_abs, input_col, output_col):
        self.max_abs = np.asarray(max_abs)
        self.input_col = input_col
        self.output_col = output_col

    maxAbs = property(lambda self: self.max_abs)

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        inv = np.where(self.max_abs > 0,
                       1.0 / np.where(self.max_abs > 0, self.max_abs, 1.0), 0.0)
        X = X * jnp.asarray(inv, X.dtype)
        return frame.with_column(self.output_col, X[:, 0] if squeeze else X)


@persistable
class Imputer(Estimator):
    """MLlib ``Imputer``: replace missing values (NaN by default, or a
    configured ``missing_value`` sentinel) in numeric columns with the
    column's mean / median / mode, learned over valid rows only.

    Statistics are computed at the host boundary (median/mode are sort- and
    histogram-shaped, not device hot loops); the transform itself is a device
    ``jnp.where`` per column, fused by XLA with downstream ops.
    """

    _persist_attrs = ('input_cols', 'output_cols', 'strategy',
                      'missing_value')

    def __init__(self, input_cols: Optional[Sequence[str]] = None,
                 output_cols: Optional[Sequence[str]] = None,
                 strategy: str = "mean", missing_value: float = float("nan")):
        self.input_cols = list(input_cols) if input_cols else []
        self.output_cols = list(output_cols) if output_cols else []
        if strategy not in ("mean", "median", "mode"):
            raise ValueError(f"strategy={strategy!r} (mean|median|mode)")
        self.strategy = strategy
        self.missing_value = float(missing_value)

    def set_input_cols(self, v):
        self.input_cols = list(v)
        return self

    setInputCols = set_input_cols

    def set_output_cols(self, v):
        self.output_cols = list(v)
        return self

    setOutputCols = set_output_cols

    def set_strategy(self, v):
        if v not in ("mean", "median", "mode"):
            raise ValueError(f"strategy={v!r}")
        self.strategy = v
        return self

    setStrategy = set_strategy

    def set_missing_value(self, v):
        self.missing_value = float(v)
        return self

    setMissingValue = set_missing_value

    def _out_cols(self):
        return self.output_cols or self.input_cols

    def fit(self, frame) -> "ImputerModel":
        if not self.input_cols:
            raise ValueError("Imputer: input_cols not set")
        if self.output_cols and len(self.output_cols) != len(self.input_cols):
            raise ValueError("output_cols length must match input_cols")
        mask = np.asarray(frame.mask)
        surrogates = []
        for name in self.input_cols:
            x = np.asarray(frame._column_values(name), np.float64)[mask]
            miss = np.isnan(x) if np.isnan(self.missing_value) \
                else (x == self.missing_value)
            vals = x[~miss & ~np.isnan(x)]
            if len(vals) == 0:
                raise ValueError(f"Imputer: column {name!r} has no valid "
                                 "values to learn a surrogate from")
            if self.strategy == "mean":
                s = float(vals.mean())
            elif self.strategy == "median":
                s = float(np.median(vals))
            else:  # mode: most frequent, smallest on ties (Spark)
                uniq, cnt = np.unique(vals, return_counts=True)
                s = float(uniq[np.argmax(cnt)])
            surrogates.append(s)
        return ImputerModel(self.input_cols, self._out_cols(),
                            surrogates, self.missing_value)


@persistable
class ImputerModel(Model):
    _persist_attrs = ('input_cols', 'output_cols', 'surrogates',
                      'missing_value')

    def __init__(self, input_cols, output_cols, surrogates, missing_value):
        self.input_cols = list(input_cols)
        self.output_cols = list(output_cols)
        self.surrogates = [float(s) for s in surrogates]
        self.missing_value = float(missing_value)

    @property
    def surrogate_df(self):
        """The learned surrogates as a 1-row Frame (MLlib surrogateDF)."""
        from ..frame import Frame

        return Frame({c: [s] for c, s in zip(self.input_cols,
                                             self.surrogates)})

    surrogateDF = surrogate_df

    def transform(self, frame):
        for name, out, s in zip(self.input_cols, self.output_cols,
                                self.surrogates):
            x = jnp.asarray(frame._column_values(name), float_dtype())
            # NaN (the engine's null) is always missing — Spark imputes
            # nulls regardless of the configured missingValue sentinel
            miss = jnp.isnan(x)
            if not np.isnan(self.missing_value):
                miss = jnp.logical_or(miss, x == self.missing_value)
            frame = frame.with_column(out,
                                      jnp.where(miss, jnp.asarray(s, x.dtype),
                                                x))
        return frame


@persistable
class Normalizer(Transformer):
    """MLlib ``Normalizer``: scale each row of a vector column to unit
    p-norm (default p=2). Zero rows stay zero. Pure device elementwise —
    XLA fuses the norm and the divide into one kernel."""

    _persist_attrs = ('input_col', 'output_col', 'p')

    def __init__(self, input_col: str = "features",
                 output_col: str = "normalized_features", p: float = 2.0):
        self.input_col = input_col
        self.output_col = output_col
        if not p >= 1.0:
            raise ValueError("p must be >= 1")
        self.p = float(p)

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def set_p(self, v):
        if not v >= 1.0:
            raise ValueError("p must be >= 1")
        self.p = float(v)
        return self

    setP = set_p

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(X), axis=1, keepdims=True)
        elif self.p == 2.0:
            norm = jnp.sqrt(jnp.sum(X * X, axis=1, keepdims=True))
        elif self.p == 1.0:
            norm = jnp.sum(jnp.abs(X), axis=1, keepdims=True)
        else:
            norm = jnp.sum(jnp.abs(X) ** self.p, axis=1,
                           keepdims=True) ** (1.0 / self.p)
        out = jnp.where(norm > 0, X / jnp.where(norm > 0, norm, 1.0), X)
        return frame.with_column(self.output_col,
                                 out[:, 0] if squeeze else out)


@persistable
class Binarizer(Transformer):
    """MLlib ``Binarizer``: 1.0 where x > threshold else 0.0, on a scalar
    or vector column (NaN compares false → 0.0, as Spark's codegen does)."""

    _persist_attrs = ('threshold', 'input_col', 'output_col')

    def __init__(self, threshold: float = 0.0, input_col: str = None,
                 output_col: str = None):
        self.threshold = float(threshold)
        self.input_col = input_col
        self.output_col = output_col

    def set_threshold(self, v):
        self.threshold = float(v)
        return self

    setThreshold = set_threshold

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def transform(self, frame):
        x = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        out = jnp.where(x > self.threshold,
                        jnp.asarray(1.0, x.dtype), jnp.asarray(0.0, x.dtype))
        return frame.with_column(self.output_col, out)


@persistable
class PolynomialExpansion(Transformer):
    """MLlib ``PolynomialExpansion``: expand an (n, d) vector column into
    all monomials of total degree 1..``degree`` over the d features.

    The monomial *plan* (which feature-index multisets to multiply) is a
    tiny host-side enumeration; the expansion itself is one stacked device
    product per monomial, fused by XLA — the MXU-friendly dense layout is
    preserved (output is a single (n, D) matrix). Ordering: grouped by
    degree, lexicographic within a degree (MLlib interleaves; the *set* of
    monomials is identical, only column order differs — documented because
    downstream fits are order-insensitive)."""

    _persist_attrs = ('degree', 'input_col', 'output_col')

    def __init__(self, degree: int = 2, input_col: str = "features",
                 output_col: str = "poly_features"):
        if degree < 1:
            raise ValueError("degree must be >= 1")
        self.degree = int(degree)
        self.input_col = input_col
        self.output_col = output_col

    def set_degree(self, v):
        if v < 1:
            raise ValueError("degree must be >= 1")
        self.degree = int(v)
        return self

    setDegree = set_degree

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def transform(self, frame):
        from itertools import combinations_with_replacement

        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        d = X.shape[1]
        cols = []
        for deg in range(1, self.degree + 1):
            for combo in combinations_with_replacement(range(d), deg):
                term = X[:, combo[0]]
                for j in combo[1:]:
                    term = term * X[:, j]
                cols.append(term)
        return frame.with_column(self.output_col, jnp.stack(cols, axis=1))


@persistable
class QuantileDiscretizer(Estimator):
    """MLlib ``QuantileDiscretizer``: learn ``num_buckets`` quantile split
    points over the valid rows and return a :class:`Bucketizer` with open
    (±inf) outer splits. Exact quantiles (the reference engine's
    approxQuantile relative-error knob is unnecessary at this scale);
    duplicate quantiles collapse, so the fitted bucketizer may have fewer
    buckets, exactly like Spark."""

    _persist_attrs = ('num_buckets', 'input_col', 'output_col',
                      'handle_invalid')

    def __init__(self, num_buckets: int = 2, input_col: str = None,
                 output_col: str = None, handle_invalid: str = "error"):
        if num_buckets < 2:
            raise ValueError("num_buckets must be >= 2")
        self.num_buckets = int(num_buckets)
        self.input_col = input_col
        self.output_col = output_col
        self.handle_invalid = handle_invalid

    def set_num_buckets(self, v):
        if v < 2:
            raise ValueError("num_buckets must be >= 2")
        self.num_buckets = int(v)
        return self

    setNumBuckets = set_num_buckets

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def set_handle_invalid(self, v):
        self.handle_invalid = v
        return self

    setHandleInvalid = set_handle_invalid

    def fit(self, frame) -> "Bucketizer":
        mask = np.asarray(frame.mask)
        x = np.asarray(frame._column_values(self.input_col),
                       np.float64)[mask]
        x = x[~np.isnan(x)]
        if len(x) == 0:
            raise ValueError("QuantileDiscretizer: no valid rows to fit on")
        qs = np.quantile(x, np.linspace(0, 1, self.num_buckets + 1)[1:-1])
        inner = np.unique(qs)  # duplicate quantiles collapse (Spark)
        splits = [-float("inf"), *inner.tolist(), float("inf")]
        return Bucketizer(splits, self.input_col, self.output_col,
                          self.handle_invalid)


@persistable
class PCA(Estimator):
    """MLlib ``PCA``: learn the top-k principal components of a vector
    column. Fit is one masked covariance (a single MXU matmul over the
    row-sharded data, psum-reduced under a mesh) + a device ``eigh`` on the
    tiny (d, d) matrix. Transform follows MLlib exactly: rows are projected
    onto the components **without** mean subtraction (Spark's documented
    behavior — the components themselves come from the centered covariance,
    but ``transform`` multiplies raw rows)."""

    _persist_attrs = ('k', 'input_col', 'output_col')

    def __init__(self, k: int = None, input_col: str = "features",
                 output_col: str = "pca_features"):
        self.k = k
        self.input_col = input_col
        self.output_col = output_col

    def set_k(self, v):
        self.k = int(v)
        return self

    setK = set_k

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def fit(self, frame) -> "PCAModel":
        if not self.k or self.k < 1:
            raise ValueError("PCA: k must be a positive integer")
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        d = X.shape[1]
        if self.k > d:
            raise ValueError(f"k={self.k} exceeds the {d} input features")
        if int(np.asarray(frame.mask).sum()) == 0:
            raise ValueError("PCA: no valid rows to fit on")
        w = frame.mask.astype(X.dtype)
        n = jnp.sum(w)
        mean = jnp.sum(X * w[:, None], axis=0) / n
        C = (X - mean) * w[:, None]
        cov = (C.T @ C) / jnp.maximum(n - 1.0, 1.0)      # sample covariance
        vals, vecs = jnp.linalg.eigh(cov)                # ascending order
        vals = vals[::-1][: self.k]
        vecs = vecs[:, ::-1][:, : self.k]                # (d, k) columns
        # deterministic sign: largest-|.| element of each component positive
        vecs_np = np.asarray(vecs)
        signs = np.sign(vecs_np[np.argmax(np.abs(vecs_np), axis=0),
                                np.arange(self.k)])
        signs[signs == 0] = 1.0
        total = float(jnp.sum(jnp.clip(jnp.diagonal(cov), 0.0, None)))
        ev = np.clip(np.asarray(vals), 0.0, None)
        ratios = ev / total if total > 0 else np.zeros_like(ev)
        return PCAModel(vecs_np * signs, ratios, self.k,
                        self.input_col, self.output_col)


@persistable
class PCAModel(Model):
    _persist_attrs = ('pc', 'explained_variance', 'k', 'input_col',
                      'output_col')

    def __init__(self, pc, explained_variance, k, input_col, output_col):
        self.pc = np.asarray(pc)                         # (d, k)
        self.explained_variance = np.asarray(explained_variance)
        self.k = int(k)
        self.input_col = input_col
        self.output_col = output_col

    explainedVariance = property(lambda self: self.explained_variance)

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        return frame.with_column(self.output_col,
                                 X @ jnp.asarray(self.pc, X.dtype))
